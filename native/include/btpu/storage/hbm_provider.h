// HBM provider: the C ABI seam between the native worker and the device
// runtime that actually owns TPU HBM.
//
// On real TPU VMs the provider is implemented by the Python/JAX layer
// (blackbird_tpu/hbm.py registers ctypes callbacks: regions are device
// buffers, read/write are host<->device transfers). Tests and CPU-only dev
// use the built-in emulated provider (host memory). This mirrors the
// north-star's "TPU-HBM allocator behind the same region-descriptor
// contract" (BASELINE.json) without pretending libtpu exposes raw one-sided
// DMA to third parties.
//
// v2 adds scatter/gather batch transfers and a flush barrier. Device links
// are latency-bound per operation (one PJRT call per op), so the native data
// movers hand the provider ONE call per multi-shard transfer and the
// provider turns it into one host<->device transfer plus on-device
// scatter/gather. Writes may complete asynchronously; flush() blocks until
// every accepted write is durably in device memory.
//
// All functions return 0 on success, nonzero on failure.
#pragma once

#include <cstdint>
#include <string>

#include "btpu/common/error.h"

extern "C" {

// One element of a scatter/gather batch. `buf` is the host-side source
// (writes) or destination (reads).
typedef struct BtpuHbmIoVec {
  uint64_t region_id;
  uint64_t offset;
  void* buf;
  uint64_t len;
} BtpuHbmIoVec;

typedef struct BtpuHbmProviderV3 {
  void* ctx;
  // Allocates a device region of `size` bytes on `device_id` ("tpu:0").
  int (*alloc_region)(void* ctx, const char* device_id, uint64_t size, uint64_t* out_region_id);
  int (*free_region)(void* ctx, uint64_t region_id);
  // Host -> device and device -> host byte transfers within a region.
  int (*write)(void* ctx, uint64_t region_id, uint64_t offset, const void* src, uint64_t len);
  int (*read)(void* ctx, uint64_t region_id, uint64_t offset, void* dst, uint64_t len);
  // Bytes of free HBM remaining on the device (best effort; 0 = unknown).
  uint64_t (*available)(void* ctx, const char* device_id);
  // Scatter/gather batches: the whole batch is one logical transfer and the
  // provider is free to coalesce it into a single device op. May be null —
  // callers must fall back to per-op write/read (hbm_batch_io does).
  int (*write_batch)(void* ctx, const BtpuHbmIoVec* vecs, uint64_t n);
  int (*read_batch)(void* ctx, const BtpuHbmIoVec* vecs, uint64_t n);
  // Barrier: returns once all previously accepted writes are in device
  // memory. May be null when writes complete synchronously.
  int (*flush)(void* ctx);
  // v3: device-to-device copy between regions — THE ICI data path. When the
  // regions live on different chips the provider moves the bytes over the
  // interconnect with no host staging (JAX provider: a device_put between
  // committed device buffers, which XLA routes over ICI). May be null, and
  // may fail for layouts it cannot express (callers fall back to a staged
  // read+write through host memory — hbm_copy does).
  int (*copy)(void* ctx, uint64_t src_region, uint64_t src_offset, uint64_t dst_region,
              uint64_t dst_offset, uint64_t len);
} BtpuHbmProviderV3;

// v4 appends the CROSS-PROCESS device fabric: one-sided pulls between the
// device runtimes of different worker processes (JAX provider: a
// jax.experimental.transfer server per process — on TPU the bytes ride the
// chip fabric, never a host socket). The keystone orchestrates: it tells
// the source worker to OFFER a region range under a transfer id, then the
// destination worker to PULL it straight into its own region. All three
// entries may be null (no fabric — movers stage through the host lane).
typedef struct BtpuHbmProviderV4 {
  BtpuHbmProviderV3 base;
  // Address other processes' pulls can reach this provider's fabric server
  // at. Returns 0 and fills `buf` (NUL-terminated, `cap` bytes) or nonzero
  // when no fabric is available.
  int (*fabric_address)(void* ctx, char* buf, uint64_t cap);
  // Stages [offset, offset+len) of `region` for exactly one pull under
  // `transfer_id`. Returns once the range is offered (not once pulled).
  int (*fabric_offer)(void* ctx, uint64_t region_id, uint64_t offset, uint64_t len,
                      uint64_t transfer_id);
  // Pulls `len` bytes offered under `transfer_id` at `remote_fabric_addr`
  // into [offset, offset+len) of `region`. Blocks until the bytes are in
  // device memory.
  int (*fabric_pull)(void* ctx, const char* remote_fabric_addr, uint64_t transfer_id,
                     uint64_t region_id, uint64_t offset, uint64_t len);
} BtpuHbmProviderV4;

// v5 appends the HOST-VIEW escape hatch: when a region's device memory is
// CPU-addressable (the provider's host_view mode — CPU devices today,
// host-mapped HBM if a runtime ever exposes it), the provider hands the
// native side the region's stable base pointer ONCE and every subsequent
// read_at/write_at becomes a native memcpy with zero provider dispatch —
// the per-op ctypes/Python tax (the dominant cost of the cross-process
// staged device lane on dev boxes) disappears from the data path. Returns
// NULL for device-resident regions; may be null entirely.
typedef struct BtpuHbmProviderV5 {
  BtpuHbmProviderV4 base;
  void* (*host_view_base)(void* ctx, uint64_t region_id);
} BtpuHbmProviderV5;

// Installs the process-wide provider (Python calls this through ctypes).
// Passing NULL restores the built-in emulated provider. The version suffix
// makes a stale library/binding pair fail loudly at symbol lookup instead
// of reading past the end of a smaller struct. v3/v4 registration keeps
// working (newer entries default to null).
void btpu_register_hbm_provider_v3(const BtpuHbmProviderV3* provider);
void btpu_register_hbm_provider_v4(const BtpuHbmProviderV4* provider);
void btpu_register_hbm_provider_v5(const BtpuHbmProviderV5* provider);

}  // extern "C"

namespace btpu::storage {
// Returns the active provider (emulated one if none registered).
const BtpuHbmProviderV3& hbm_provider();
// True when the active provider is the built-in host-memory emulation.
bool hbm_provider_is_emulated();
// One batched transfer through the active provider, falling back to per-vec
// write/read when the provider has no batch entry points.
ErrorCode hbm_batch_io(const BtpuHbmIoVec* vecs, uint64_t n, bool is_write);
// Blocks until all accepted writes are durably in device memory.
ErrorCode hbm_flush();
// Device-to-device copy (ICI when cross-chip). Uses the provider's copy
// entry when present, else stages through a bounded host buffer.
ErrorCode hbm_copy(uint64_t src_region, uint64_t src_offset, uint64_t dst_region,
                   uint64_t dst_offset, uint64_t len);
// Host-view base pointer of a region (v5; nullptr when device-resident or
// the provider predates v5).
void* hbm_host_view_base(uint64_t region_id);
// Monotonic registration generation: bumped by every (un)register call.
// Consumers caching provider-derived pointers revalidate against it.
uint64_t hbm_provider_generation();
// Cross-process device fabric (v4; empty string / NOT_IMPLEMENTED without).
std::string hbm_fabric_address();
ErrorCode hbm_fabric_offer(uint64_t region_id, uint64_t offset, uint64_t len,
                           uint64_t transfer_id);
ErrorCode hbm_fabric_pull(const std::string& remote_addr, uint64_t transfer_id,
                          uint64_t region_id, uint64_t offset, uint64_t len);
}  // namespace btpu::storage
