// Watermark pressure: tier-aware eviction and tier demotion.
#include "btpu/keystone/keystone.h"

#include "keystone_internal.h"

#include <algorithm>
#include <optional>
#include <unordered_set>

#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/wire.h"
#include "btpu/ec/rs.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::keystone {

using coord::WatchEvent;

using namespace detail;

// ---- eviction -------------------------------------------------------------

double KeystoneService::tier_utilization(std::optional<StorageClass> cls) const {
  uint64_t capacity = 0;
  {
    SharedLock lock(registry_mutex_);
    for (const auto& [id, pool] : pools_) {
      if (!cls || pool.storage_class == *cls) capacity += pool.size;
    }
  }
  if (capacity == 0) return 0.0;
  // Allocated bytes, NOT capacity - free: pool allocators materialize
  // lazily, so an untouched pool reports no free bytes and capacity-free
  // would misread a near-empty tier as full (observed: spurious "eviction
  // pressure ... util 1" on a fresh HBM pool, with the health loop then
  // evicting live objects mid-benchmark).
  auto stats = adapter_.allocator().get_stats(cls);
  uint64_t used = 0;
  if (cls) {
    auto it = stats.allocated_per_class.find(*cls);
    used = it == stats.allocated_per_class.end() ? 0 : it->second;
  } else {
    used = stats.total_allocated_bytes;
  }
  return static_cast<double>(used) / static_cast<double>(capacity);
}

void KeystoneService::evict_for_pressure() {
  // Determine which tiers are over the watermark.
  std::vector<std::optional<StorageClass>> scopes;
  if (config_.tier_aware_eviction) {
    std::vector<StorageClass> classes;
    {
      SharedLock lock(registry_mutex_);
      for (const auto& [id, pool] : pools_) {
        if (std::find(classes.begin(), classes.end(), pool.storage_class) == classes.end())
          classes.push_back(pool.storage_class);
      }
    }
    // Fastest tier first: demotions out of a hot tier land in lower tiers,
    // and those are evaluated later in the same pass so they can shed the
    // cascade immediately instead of waiting a full health interval.
    std::sort(classes.begin(), classes.end(),
              [](StorageClass a, StorageClass b) { return tier_rank(a) < tier_rank(b); });
    for (auto c : classes) scopes.emplace_back(c);
  } else {
    scopes.emplace_back(std::nullopt);
  }

  for (const auto& scope : scopes) {
    if (tier_utilization(scope) < config_.high_watermark) continue;
    const double target = config_.high_watermark * (1.0 - config_.eviction_ratio);
    LOG_WARN << "eviction pressure on tier "
             << (scope ? storage_class_name(*scope) : "all") << " (util "
             << tier_utilization(scope) << " >= " << config_.high_watermark << ")";

    // LRU order over evictable objects in this scope. Shards are scanned
    // in ascending order, one shared lock at a time; LRU ranking happens
    // after the scan, so cross-shard ordering needs no global lock.
    std::vector<std::pair<std::chrono::steady_clock::time_point, ObjectKey>> candidates;
    for (size_t si = 0; si < shard_count_; ++si) {
      const ObjectShard& s = shards_[si];
      SharedLock lock(s.mutex);
      for (const auto& [key, info] : s.map) {
        if (info.soft_pin || info.state != ObjectState::kComplete) continue;
        // Inline objects hold no pool capacity: evicting one cannot relieve
        // allocator pressure (the loop's exit condition), so under the
        // global (non-tier-aware) scope they'd be destroyed for zero
        // benefit. Their growth is bounded by the inline budget instead.
        if (!info.copies.empty() && !info.copies.front().inline_data.empty()) continue;
        if (scope) {
          bool touches_tier = false;
          for (const auto& copy : info.copies) {
            for (const auto& shard : copy.shards) {
              if (shard.storage_class == *scope) touches_tier = true;
            }
          }
          if (!touches_tier) continue;
        }
        candidates.emplace_back(info.last_access.load(), key);
      }
    }
    std::sort(candidates.begin(), candidates.end());

    for (const auto& [ts, key] : candidates) {
      if (tier_utilization(scope) <= target) break;
      if (scope && config_.enable_tier_demotion) {
        const DemoteOutcome outcome = demote_object(key, *scope);
        if (outcome == DemoteOutcome::kDemoted) {
          ++counters_.objects_demoted;
          LOG_INFO << "demoted object " << key << " out of tier "
                   << storage_class_name(*scope);
          continue;
        }
        if (outcome == DemoteOutcome::kSkipped) continue;
      }
      ObjectShard& s = shard_for(key);
      WriterLock lock(s.mutex);
      auto it = s.map.find(key);
      if (it == s.map.end()) continue;
      // Fence-first (see gc): never free ranges a promoted leader still maps.
      if (unpersist_object(key) != ErrorCode::OK) continue;
      warn_if_error(free_object_locked(s, key, it->second), "evicted-object range free");
      s.map.erase(it);
      ++counters_.evicted;
      bump_view();
      lock.unlock();
      publish_cache_invalidation(key, 0);
      LOG_INFO << "evicted object " << key << " for tier pressure";
    }
  }
}

KeystoneService::DemoteOutcome KeystoneService::demote_object(const ObjectKey& key,
                                                              StorageClass from) {
  // Demotion never places new bytes onto a draining worker.
  const alloc::PoolMap live_pools = allocatable_pools_snapshot();

  // Lower tiers that actually have pools, nearest first. The ladder stops at
  // HDD: CUSTOM/unspecified pools are application-owned, never a backstop.
  std::vector<StorageClass> ladder;
  for (const auto& [id, pool] : live_pools) {
    const int rank = tier_rank(pool.storage_class);
    if (rank <= tier_rank(from) || rank > tier_rank(StorageClass::HDD)) continue;
    if (std::find(ladder.begin(), ladder.end(), pool.storage_class) == ladder.end())
      ladder.push_back(pool.storage_class);
  }
  if (ladder.empty()) return DemoteOutcome::kFailed;
  std::sort(ladder.begin(), ladder.end(),
            [](StorageClass a, StorageClass b) { return tier_rank(a) < tier_rank(b); });

  // Snapshot the object, then move bytes with NO metadata lock held — a
  // multi-hundred-MB transfer must not stall every put_start/get_workers.
  uint64_t size = 0;
  uint64_t epoch_snap = 0;
  WorkerConfig config;
  std::vector<CopyPlacement> old_copies;
  {
    const ObjectShard& s = shard_for(key);
    SharedLock lock(s.mutex);
    auto it = s.map.find(key);
    if (it == s.map.end() || it->second.state != ObjectState::kComplete)
      return DemoteOutcome::kSkipped;
    size = it->second.size;
    epoch_snap = it->second.epoch;
    config = it->second.config;
    old_copies = it->second.copies;
  }
  // Demotion moves whole objects. Only objects fully resident in the
  // pressured tier qualify — re-placing a mixed-tier object would drag its
  // healthy faster-tier replicas down the ladder too. Mixed objects keep
  // delete-eviction semantics (the caller's fallback).
  for (const auto& copy : old_copies) {
    for (const auto& shard : copy.shards) {
      if (shard.storage_class != from) return DemoteOutcome::kFailed;
    }
  }
  const bool coded = !old_copies.empty() && old_copies.front().ec_data_shards > 0;

  // Stage the replacement under a temporary allocator key; the old ranges
  // stay live the whole time, so concurrent readers are never broken.
  const ObjectKey staging_key = key + "\x01" "demote";
  alloc::AllocationRequest req = alloc::KeystoneAllocatorAdapter::to_allocation_request(
      staging_key, size, config);
  req.restrict_to_preferred = true;
  // The object is leaving its tier regardless; a node pin (often a node that
  // only hosts the hot tier) must not veto the move — without this, pinned
  // objects could never demote and would always fall through to deletion.
  req.preferred_node.clear();
  Result<std::vector<CopyPlacement>> placed = ErrorCode::INSUFFICIENT_SPACE;
  for (StorageClass target_class : ladder) {
    req.preferred_classes = {target_class};
    auto attempt = adapter_.allocator().allocate(req, live_pools);
    if (attempt.ok()) {
      placed = std::move(attempt).value().copies;
      break;
    }
  }
  if (!placed.ok()) return DemoteOutcome::kFailed;

  // Stream from the first readable copy into the staged placements.
  // DeviceLocation shards are readable here by construction: workers only
  // advertise TransportKind::HBM descriptors (which yield DeviceLocation
  // placements, range_allocator.cpp) on an in-process LOCAL data plane
  // (worker.cpp), so a keystone seeing them shares the provider's process.
  // Cross-process HBM pools register callback-backed regions instead.
  bool moved = false;
  const CopyPlacement* moved_src = nullptr;
  bool used_unchecked = false;
  if (coded) {
    // Coded objects move SHARD-VERBATIM: the staged allocation reused the
    // object's (k, m) config, so it has the identical geometry and every
    // shard (data and parity alike) copies bytes straight across with no
    // decode. The mover invariant still holds: the object CRC accumulates
    // over the data shards' valid bytes AS they stream, and a mismatch
    // aborts the move — the object stays put (kSkipped, never the delete
    // fallback: the bytes are still parity-recoverable by client reads).
    const CopyPlacement& src = old_copies.front();
    const size_t k = src.ec_data_shards;
    const uint64_t L = src.shards.empty() ? 0 : src.shards.front().length;
    uint32_t crc = 0;
    constexpr uint64_t kChunk = 8ull << 20;
    std::vector<uint8_t> buf(static_cast<size_t>(std::min<uint64_t>(L, kChunk)));
    auto stream_one = [&](const ShardPlacement& s, const ShardPlacement& d,
                          uint64_t crc_bytes) -> ErrorCode {
      for (uint64_t off = 0; off < s.length; off += kChunk) {
        const uint64_t n = std::min(kChunk, s.length - off);
        BTPU_RETURN_IF_ERROR(
            transport::shard_io(*data_client_, s, off, buf.data(), n, /*is_write=*/false));
        if (off < crc_bytes)
          crc = crc32c(buf.data(), std::min(n, crc_bytes - off), crc);
        BTPU_RETURN_IF_ERROR(
            transport::shard_io(*data_client_, d, off, buf.data(), n, /*is_write=*/true));
      }
      return ErrorCode::OK;
    };
    if (placed.value().size() == 1 &&
        placed.value().front().shards.size() == src.shards.size()) {
      moved = true;
      for (size_t i = 0; i < src.shards.size() && moved; ++i) {
        const uint64_t start = i * L;
        const uint64_t crc_bytes =
            i < k && start < size ? std::min<uint64_t>(L, size - start) : 0;
        if (stream_one(src.shards[i], placed.value().front().shards[i], crc_bytes) !=
            ErrorCode::OK)
          moved = false;
      }
      if (moved && src.content_crc != 0 && crc != src.content_crc) {
        LOG_WARN << "demotion of coded " << key
                 << " aborted: source failed crc verification (still "
                    "parity-recoverable in place)";
        warn_if_error(adapter_.free_object(staging_key), "demote staging free");
        return DemoteOutcome::kSkipped;
      }
    }
    if (!moved) {
      // A transiently unreadable shard (hung worker, death inside the
      // heartbeat TTL) or a staging-geometry surprise must NEVER funnel a
      // parity-recoverable object into the caller's delete fallback.
      warn_if_error(adapter_.free_object(staging_key), "demote staging free");
      return DemoteOutcome::kSkipped;
    }
  } else {
    const alloc::PoolMap fabric_pools = memory_pools();
    for (const auto& src : old_copies) {
      used_unchecked = false;
      if (copy_object_bytes(*data_client_, src, placed.value(), size, &fabric_pools,
                            &counters_.fabric_moves, &used_unchecked) == ErrorCode::OK) {
        moved = true;
        moved_src = &src;
        break;
      }
    }
  }
  if (!moved) {
    warn_if_error(adapter_.free_object(staging_key), "demote staging free");
    return DemoteOutcome::kFailed;
  }

  // Swap the placements in only if the object didn't change underneath us.
  ObjectShard& s = shard_for(key);
  WriterLock lock(s.mutex);
  auto it = s.map.find(key);
#if defined(BTPU_SCHED)
  // PLANTED MUTANT — ABA/lost-update class (the race the epoch exists to
  // kill): splice the staged placements in WITHOUT re-checking the epoch,
  // so a remove+re-put that landed during the unlocked byte move gets its
  // placements clobbered by the old object's staging. The SchedMutants
  // matrix detects it as a read-back mismatch within the seed budget.
  const bool skip_epoch_check = sched::mutant_enabled("demote_skip_epoch_check");
#else
  constexpr bool skip_epoch_check = false;
#endif
  if (it == s.map.end() || (!skip_epoch_check && it->second.epoch != epoch_snap)) {
    lock.unlock();
    warn_if_error(adapter_.free_object(staging_key), "demote staging free");
    return DemoteOutcome::kSkipped;
  }
  warn_if_error(adapter_.free_object(key), "demoted-object allocation free");
  if (auto ec = adapter_.allocator().rename_object(staging_key, key); ec != ErrorCode::OK) {
    // Unreachable in practice (staging exists, key was just freed); treat the
    // object as lost rather than leave metadata pointing at freed ranges.
    LOG_ERROR << "demotion rename failed for " << key << ": " << to_string(ec);
    warn_if_error(adapter_.free_object(staging_key), "demote staging free");
    s.map.erase(it);
    warn_if_error(unpersist_object(key), "evicted-object unpersist");
    ++counters_.objects_lost;
    bump_view();
    lock.unlock();
    // A deletion like any other: caching clients must hear about it.
    publish_cache_invalidation(key, 0);
    return DemoteOutcome::kSkipped;
  }
  it->second.copies = std::move(placed).value();
  if (!moved_src) moved_src = &old_copies.front();  // coded path: shard-verbatim
  for (auto& copy : it->second.copies) {
    copy.content_crc = old_copies.front().content_crc;
    carry_shard_crcs(*moved_src, copy);
  }
  it->second.epoch = next_epoch_.fetch_add(1);
  const uint64_t new_epoch = it->second.epoch;
  // Fabric/device moves carry stamps without the staged lane's CRC gate:
  // scrub them.
  if (used_unchecked) queue_scrub_target(key);
  if (auto ec = persist_object(key, it->second); ec != ErrorCode::OK) {
    // The move already landed locally; the durable record still names the old
    // (now released) placements. Don't claim the demotion — kSkipped keeps
    // the pressure loop honest — and queue the key for the health loop's
    // re-persist: a never-again-mutated key would otherwise keep its stale
    // record forever.
    LOG_ERROR << "demotion of " << key << " not durably recorded: " << to_string(ec);
    mark_persist_dirty(key);
    bump_view();
    lock.unlock();
    publish_cache_invalidation(key, new_epoch);
    return DemoteOutcome::kSkipped;
  }
  bump_view();
  lock.unlock();
  // The bytes moved (old ranges are freed and reusable): cached placements
  // and cached bytes alike must revalidate against the new epoch.
  publish_cache_invalidation(key, new_epoch);
  return DemoteOutcome::kDemoted;
}

}  // namespace btpu::keystone
