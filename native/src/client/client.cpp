#include "btpu/client/client.h"

#include <atomic>
#include <thread>

#include "btpu/common/log.h"
#include "btpu/common/thread_pool.h"
#include "btpu/common/trace.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::client {

void ClientOptions::set_keystone_endpoints(const std::string& list) {
  keystone_address.clear();
  keystone_fallbacks.clear();
  size_t pos = 0;
  while (pos <= list.size()) {
    const size_t next = list.find(',', pos);
    const std::string part = list.substr(pos, next - pos);
    if (!part.empty()) {
      if (keystone_address.empty()) {
        keystone_address = part;
      } else {
        keystone_fallbacks.push_back(part);
      }
    }
    if (next == std::string::npos) break;
    pos = next + 1;
  }
}

ObjectClient::ObjectClient(ClientOptions options)
    : options_(std::move(options)), data_(transport::make_transport_client()) {
  rpc_ = std::make_unique<rpc::KeystoneRpcClient>(options_.keystone_address);
}

ObjectClient::ObjectClient(ClientOptions options, keystone::KeystoneService* embedded)
    : options_(std::move(options)),
      embedded_(embedded),
      data_(transport::make_transport_client()) {}

ObjectClient::~ObjectClient() = default;

ErrorCode ObjectClient::connect() {
  if (embedded_) return ErrorCode::OK;
  auto ec = rpc_->connect();
  // Initial connect participates in failover too: the configured primary
  // may already be a dead or standby keystone.
  const size_t endpoints = 1 + options_.keystone_fallbacks.size();
  for (size_t i = 0; i + 1 < endpoints && ec != ErrorCode::OK; ++i) {
    rotate_keystone();
    ec = rpc_->connect();
  }
  return ec;
}

void ObjectClient::rotate_keystone() {
  const size_t endpoints = 1 + options_.keystone_fallbacks.size();
  keystone_index_ = (keystone_index_ + 1) % endpoints;
  const std::string& address = keystone_index_ == 0
                                   ? options_.keystone_address
                                   : options_.keystone_fallbacks[keystone_index_ - 1];
  LOG_WARN << "keystone failover: switching to " << address;
  rpc_ = std::make_unique<rpc::KeystoneRpcClient>(address);
  rpc_->connect();
}

Result<bool> ObjectClient::object_exists(const ObjectKey& key) {
  if (embedded_) return embedded_->object_exists(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.object_exists(key); });
}

Result<std::vector<CopyPlacement>> ObjectClient::get_workers(const ObjectKey& key) {
  if (embedded_) return embedded_->get_workers(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.get_workers(key); });
}

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size) {
  return put(key, data, size, options_.default_config);
}

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size,
                            const WorkerConfig& config) {
  TRACE_SPAN("client.put");
  Result<std::vector<CopyPlacement>> placed = ErrorCode::INTERNAL_ERROR;
  {
    TRACE_SPAN("client.put.start_rpc");
    placed = embedded_
                 ? embedded_->put_start(key, size, config)
                 : rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
                     return r.put_start(key, size, config);
                   });
  }
  if (!placed.ok()) return placed.error();

  const auto* bytes = static_cast<const uint8_t*>(data);
  TRACE_SPAN("client.put.transfer");
  for (const auto& copy : placed.value()) {
    if (auto ec = transfer_copy_put(copy, bytes, size); ec != ErrorCode::OK) {
      // Roll back the reservation (reference blackbird_client.cpp:104-107).
      LOG_WARN << "put " << key << " transfer failed (" << to_string(ec) << "), cancelling";
      if (embedded_) {
        embedded_->put_cancel(key);
      } else {
        rpc_failover(/*idempotent=*/false,
                     [&](rpc::KeystoneRpcClient& r) { return r.put_cancel(key); });
      }
      return ec;
    }
  }
  if (embedded_) return embedded_->put_complete(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.put_complete(key); });
}

Result<std::vector<uint8_t>> ObjectClient::get(const ObjectKey& key) {
  TRACE_SPAN("client.get");
  auto copies = get_workers(key);
  if (!copies.ok()) return copies.error();
  uint64_t size = 0;
  if (!copies.value().empty()) {
    for (const auto& shard : copies.value().front().shards) size += shard.length;
  }
  std::vector<uint8_t> buffer(size);
  ErrorCode last = ErrorCode::NO_COMPLETE_WORKER;
  for (const auto& copy : copies.value()) {
    uint64_t copy_size = 0;
    for (const auto& shard : copy.shards) copy_size += shard.length;
    if (copy_size != size) buffer.resize(copy_size);
    if (auto ec = transfer_copy_get(copy, buffer.data(), copy_size); ec == ErrorCode::OK) {
      return buffer;
    } else {
      last = ec;
      LOG_WARN << "get " << key << " copy " << copy.copy_index << " failed ("
               << to_string(ec) << "), trying next replica";
    }
  }
  return last;
}

Result<uint64_t> ObjectClient::get_into(const ObjectKey& key, void* buffer,
                                        uint64_t buffer_size) {
  TRACE_SPAN("client.get");
  auto copies = get_workers(key);
  if (!copies.ok()) return copies.error();
  ErrorCode last = ErrorCode::NO_COMPLETE_WORKER;
  for (const auto& copy : copies.value()) {
    uint64_t copy_size = 0;
    for (const auto& shard : copy.shards) copy_size += shard.length;
    if (copy_size > buffer_size) return ErrorCode::BUFFER_OVERFLOW;
    if (auto ec = transfer_copy_get(copy, static_cast<uint8_t*>(buffer), copy_size);
        ec == ErrorCode::OK) {
      return copy_size;
    } else {
      last = ec;
    }
  }
  return last;
}

ErrorCode ObjectClient::remove(const ObjectKey& key) {
  if (embedded_) return embedded_->remove_object(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_object(key); });
}

Result<uint64_t> ObjectClient::remove_all() {
  if (embedded_) return embedded_->remove_all_objects();
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_all_objects(); });
}

Result<ClusterStats> ObjectClient::cluster_stats() {
  if (embedded_) return embedded_->get_cluster_stats();
  return rpc_failover(/*idempotent=*/true,
                      [&](rpc::KeystoneRpcClient& r) { return r.get_cluster_stats(); });
}

Result<ViewVersionId> ObjectClient::ping() {
  if (embedded_) return embedded_->get_view_version();
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.ping(); });
}

// One shard transfer; `buf` already points at the shard's slice of the
// object buffer (running-offset math lives in the copy-level loop).
// Location dispatch lives in transport::shard_io, shared with keystone's
// repair/demotion data movers.
ErrorCode ObjectClient::shard_io(const ShardPlacement& shard, uint8_t* buf, bool is_write) {
  return transport::shard_io(*data_, shard, 0, buf, shard.length, is_write);
}

namespace {
// Shared transfer pool: persistent threads amortized across all clients in
// the process (per-op thread spawn costs ~100us, see thread_pool.h).
ThreadPool& transfer_pool() {
  static ThreadPool pool(8);
  return pool;
}

// Below this many bytes per shard, parallel dispatch costs more than the
// transfer itself: run inline.
constexpr uint64_t kInlineShardBytes = 128 * 1024;

// Runs `count` shard jobs, parallel when worthwhile. Returns first error.
ErrorCode run_parallel(size_t count, size_t parallelism, uint64_t bytes_per_shard,
                       const std::function<ErrorCode(size_t)>& job) {
  if (count == 0) return ErrorCode::OK;
  if (count == 1 || parallelism <= 1 || bytes_per_shard < kInlineShardBytes) {
    for (size_t i = 0; i < count; ++i) {
      if (auto ec = job(i); ec != ErrorCode::OK) return ec;
    }
    return ErrorCode::OK;
  }
  std::atomic<uint32_t> first_error{static_cast<uint32_t>(ErrorCode::OK)};
  transfer_pool().run_batch(count, [&](size_t i) {
    if (first_error.load() != static_cast<uint32_t>(ErrorCode::OK)) return;
    if (auto ec = job(i); ec != ErrorCode::OK) {
      uint32_t expected = static_cast<uint32_t>(ErrorCode::OK);
      first_error.compare_exchange_strong(expected, static_cast<uint32_t>(ec));
    }
  });
  return static_cast<ErrorCode>(first_error.load());
}
}  // namespace

ErrorCode ObjectClient::transfer_copy_put(const CopyPlacement& copy, const uint8_t* data,
                                          uint64_t size) {
  // Running-offset layout: shard i covers [offsets[i], offsets[i]+len).
  std::vector<uint64_t> offsets(copy.shards.size());
  uint64_t off = 0;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    offsets[i] = off;
    off += copy.shards[i].length;
  }
  if (off != size) return ErrorCode::INVALID_PARAMETERS;
  const uint64_t per_shard = copy.shards.empty() ? 0 : size / copy.shards.size();
  return run_parallel(copy.shards.size(), options_.io_parallelism, per_shard, [&](size_t i) {
    return shard_io(copy.shards[i], const_cast<uint8_t*>(data) + offsets[i], /*is_write=*/true);
  });
}

ErrorCode ObjectClient::transfer_copy_get(const CopyPlacement& copy, uint8_t* data,
                                          uint64_t size) {
  std::vector<uint64_t> offsets(copy.shards.size());
  uint64_t off = 0;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    offsets[i] = off;
    off += copy.shards[i].length;
  }
  if (off != size) return ErrorCode::INVALID_PARAMETERS;
  const uint64_t per_shard = copy.shards.empty() ? 0 : size / copy.shards.size();
  return run_parallel(copy.shards.size(), options_.io_parallelism, per_shard, [&](size_t i) {
    return shard_io(copy.shards[i], data + offsets[i], /*is_write=*/false);
  });
}

}  // namespace btpu::client
