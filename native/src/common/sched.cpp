// Schedule-exploration scheduler (see sched.h for the model). The whole
// file is compiled only under BTPU_SCHED; release builds get an empty TU.
//
// Implementation shape: while a Run is armed, enrolled threads serialize on
// a token — exactly one is in St::kRunning at a time, everyone else is
// parked on a per-thread condition variable under one scheduler mutex. At
// every preemption point the running thread returns the token and a policy
// (seeded PCT priorities, or the DFS choice stack) picks the next holder.
// Blocking operations never block for real: a contended annotated mutex
// becomes a deterministic try_lock/park loop, a CondVarAny wait parks in
// the scheduler until a notify (or, for timed waits, until the scheduler
// chooses to fire the virtual timeout — wall time never passes).
//
// The scheduler's own primitives are deliberately the RAW std types: going
// through the annotated/hooked wrappers would recurse straight back into
// the scheduler (scripts/btpu_lint.py mutex-annotated-only allowlists this
// file for exactly that reason).
#include "btpu/common/sched.h"

#if defined(BTPU_SCHED)

#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>

#include "btpu/common/env.h"

namespace btpu::sched {

std::atomic<bool> g_armed{false};

ThreadState*& self_slot() noexcept {
  thread_local ThreadState* s = nullptr;
  return s;
}

struct ThreadState {
  enum class St : uint8_t {
    kRunnable,       // wants the token
    kRunning,        // holds the token
    kBlockedMutex,   // parked until on_unlock(wait_addr)
    kBlockedCv,      // parked until on_notify(cv_addr)
    kBlockedCvTimed, // parked, but the scheduler may fire the timeout
    kFinished,
  };

  uint32_t id{0};
  St st{St::kRunnable};
  const void* wait_addr{nullptr};
  Point point{Point::kYield};
  // CondVar protocol state (valid while cv_armed).
  bool cv_armed{false};
  const void* cv_addr{nullptr};
  bool cv_notified{false};
  bool cv_timed{false};
  bool cv_timeout_fired{false};
  uint64_t priority{0};
  std::condition_variable parked;
};

namespace {

uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Global {
  std::mutex mu;
  std::condition_variable any_cv;  // run-teardown + adoption rendezvous
  bool run_active{false};
  RunOptions opts;
  std::vector<std::unique_ptr<ThreadState>> threads;
  uint32_t enrolled{0};
  bool started{false};
  int running{-1};  // id of the token holder, -1 = idle
  uint64_t steps{0};
  uint64_t progress{0};  // bumps on every grant/state change (watchdog)
  uint32_t pending_adopt{0};
  uint32_t next_adopt_id{0};
  uint32_t hang_ms{5000};
  // PCT state.
  std::vector<uint64_t> change_steps;  // sorted step indices
  uint64_t low_priority_next{0};       // descending: preempted-at-change-point
  // DFS state (valid when opts.mode == kDfs).
  const std::vector<uint32_t>* dfs_prefix{nullptr};
  std::vector<uint32_t> dfs_chosen;
  std::vector<uint32_t> dfs_alts;
  // Async-signal-safe failure banner, formatted at arm time.
  char banner[192]{};
  struct sigaction prev_sig[3]{};
  bool sig_installed{false};
};

Global& g() {
  static Global* instance = new Global();  // leaked: hooks may race teardown
  return *instance;
}

const int kBannerSignals[3] = {SIGABRT, SIGSEGV, SIGBUS};

void banner_handler(int sig, siginfo_t*, void*) {
  Global& gl = g();
  (void)!::write(2, gl.banner, ::strnlen(gl.banner, sizeof(gl.banner)));
  for (int i = 0; i < 3; ++i) {
    if (kBannerSignals[i] == sig) {
      ::sigaction(sig, &gl.prev_sig[i], nullptr);
      break;
    }
  }
  ::raise(sig);
}

ThreadState* find_locked(Global& gl, uint32_t id) {
  for (auto& t : gl.threads)
    if (t->id == id) return t.get();
  return nullptr;
}

bool is_candidate(const ThreadState& t) {
  return t.st == ThreadState::St::kRunnable || t.st == ThreadState::St::kBlockedCvTimed;
}

const char* st_name(ThreadState::St st) {
  switch (st) {
    case ThreadState::St::kRunnable: return "runnable";
    case ThreadState::St::kRunning: return "running";
    case ThreadState::St::kBlockedMutex: return "blocked-mutex";
    case ThreadState::St::kBlockedCv: return "blocked-cv";
    case ThreadState::St::kBlockedCvTimed: return "blocked-cv-timed";
    case ThreadState::St::kFinished: return "finished";
  }
  return "?";
}

const char* point_name(Point p) {
  switch (p) {
    case Point::kLock: return "lock";
    case Point::kLockShared: return "lock-shared";
    case Point::kUnlock: return "unlock";
    case Point::kCvWait: return "cv-wait";
    case Point::kCvNotify: return "cv-notify";
    case Point::kAtomic: return "atomic";
    case Point::kYield: return "yield";
  }
  return "?";
}

[[noreturn]] void die_locked(Global& gl, const char* why) {
  std::fprintf(stderr, "%s", gl.banner);
  std::fprintf(stderr, "BTPU_SCHED: %s (seed=%llu, step=%llu)\n", why,
               static_cast<unsigned long long>(gl.opts.seed),
               static_cast<unsigned long long>(gl.steps));
  for (const auto& t : gl.threads) {
    std::fprintf(stderr, "  thread %u: %s at %s addr=%p\n", t->id, st_name(t->st),
                 point_name(t->point), t->wait_addr);
  }
  std::fflush(stderr);
  std::abort();
}

// Picks the next token holder among the candidates; nullptr = idle. Called
// with gl.mu held; consumes one DFS decision when >1 candidate.
ThreadState* choose_locked(Global& gl) {
  std::vector<ThreadState*> cand;
  for (auto& t : gl.threads)
    if (is_candidate(*t)) cand.push_back(t.get());
  if (cand.empty()) return nullptr;
  std::sort(cand.begin(), cand.end(),
            [](const ThreadState* a, const ThreadState* b) { return a->id < b->id; });
  if (cand.size() == 1) return cand.front();
  if (gl.opts.mode == Mode::kDfs) {
    const size_t decision = gl.dfs_chosen.size();
    uint32_t idx = 0;
    if (gl.dfs_prefix && decision < gl.dfs_prefix->size()) idx = (*gl.dfs_prefix)[decision];
    if (idx >= cand.size()) {
      // The replayed prefix saw MORE candidates here than this run does:
      // the fixture is nondeterministic across replays, and silently
      // redirecting the branch would corrupt the enumeration while still
      // reporting complete=true — the exact silent-truncation lie the DFS
      // mode exists to never tell. Convict loudly instead.
      die_locked(gl, "DFS prefix index out of range — fixture is nondeterministic "
                     "between replayed schedules");
    }
    gl.dfs_chosen.push_back(idx);
    gl.dfs_alts.push_back(static_cast<uint32_t>(cand.size()));
    return cand[idx];
  }
  // PCT: highest priority runs (ties impossible in practice — splitmix64).
  ThreadState* best = cand.front();
  for (ThreadState* t : cand)
    if (t->priority > best->priority) best = t;
  return best;
}

void grant_locked(Global& gl, ThreadState* t) {
  ++gl.progress;
  if (t->st == ThreadState::St::kBlockedCvTimed) {
    // Chosen while un-notified: the virtual timeout fires NOW.
    t->cv_timeout_fired = true;
    t->cv_armed = false;
  }
  t->st = ThreadState::St::kRunning;
  gl.running = static_cast<int>(t->id);
  t->parked.notify_one();
  gl.any_cv.notify_all();
}

// One scheduling step charged to the RUNNING thread `me`: PCT priority
// change points apply here; the step budget is the livelock detector.
void bump_step_locked(Global& gl, ThreadState* me) {
  ++gl.steps;
  if (gl.steps > gl.opts.max_steps)
    die_locked(gl, "step budget exceeded — livelock or an unbounded scheduled loop");
  if (gl.opts.mode == Mode::kPct &&
      std::binary_search(gl.change_steps.begin(), gl.change_steps.end(), gl.steps)) {
    me->priority = gl.low_priority_next--;
  }
}

// Deterministic-start rendezvous: a decision must not race a declared
// spawn, or the runnable set (and the whole schedule) would depend on how
// fast the OS starts the new thread. Bounded so a spawn that dies before
// adopting cannot wedge the run.
void wait_adoptions_locked(Global& gl, std::unique_lock<std::mutex>& lk) {
  if (gl.pending_adopt == 0) return;
  gl.any_cv.wait_for(lk, std::chrono::milliseconds(2000),
                     [&gl] { return gl.pending_adopt == 0; });
}

void park_until_running(Global& gl, std::unique_lock<std::mutex>& lk, ThreadState* me) {
  uint64_t last_progress = gl.progress;
  uint32_t stale_adopt_windows = 0;
  while (me->st != ThreadState::St::kRunning) {
    if (me->parked.wait_for(lk, std::chrono::milliseconds(gl.hang_ms)) ==
        std::cv_status::timeout) {
      if (me->st == ThreadState::St::kRunning) break;
      if (gl.progress != last_progress) {
        last_progress = gl.progress;
        stale_adopt_windows = 0;
        continue;  // someone is making progress; keep waiting
      }
      // No progress for a full hang window. If a candidate exists but the
      // token is idle that is a scheduler bug — self-heal and note it;
      // otherwise every controllable thread is blocked: deadlock verdict.
      if (gl.running == -1 && gl.started) {
        if (ThreadState* next = choose_locked(gl)) {
          grant_locked(gl, next);
          continue;
        }
        if (gl.pending_adopt == 0)
          die_locked(gl, "deadlock — every enrolled thread is blocked and nothing can wake them");
        // A declared spawn that never adopts (thread ctor threw, body died
        // early) must become a verdict too, or it gates this watchdog off
        // forever and a real deadlock hangs silently.
        if (++stale_adopt_windows >= 3)
          die_locked(gl, "declared spawn never adopted — pending_adopt stuck with no progress");
      } else if (!gl.started) {
        die_locked(gl, "enrollment barrier never completed — fewer threads enrolled than "
                       "RunOptions.threads promised");
      }
      last_progress = gl.progress;
    }
  }
}

// The running thread offers the token at a preemption point.
void yield_point_locked(Global& gl, std::unique_lock<std::mutex>& lk, ThreadState* me,
                        Point p, const void* addr) {
  bump_step_locked(gl, me);
  me->point = p;
  me->wait_addr = addr;
  me->st = ThreadState::St::kRunnable;
  gl.running = -1;
  wait_adoptions_locked(gl, lk);
  if (me->st == ThreadState::St::kRunning) {
    // An external (unenrolled) unlock/notify saw the idle token and granted
    // it to us while we waited on the adoption rendezvous — we already hold
    // it; choosing again here would double-grant (or, with no other
    // candidate, null-deref): the exactly-one-runner invariant lives here.
    return;
  }
  ThreadState* next = choose_locked(gl);
  if (next == me) {
    me->st = ThreadState::St::kRunning;
    gl.running = static_cast<int>(me->id);
    ++gl.progress;
    return;
  }
  grant_locked(gl, next);  // never null: me is still a candidate
  park_until_running(gl, lk, me);
}

void enroll_locked(Global& gl, std::unique_lock<std::mutex>& lk, ThreadState* me) {
  me->priority = splitmix64(gl.opts.seed ^ (0x51edULL + me->id));
  gl.threads.emplace_back(me);
  self_slot() = me;
  ++gl.enrolled;
  ++gl.progress;  // enrollment is progress: keeps the watchdog off slow spawns
  me->st = ThreadState::St::kRunnable;
  if (!gl.started) {
    if (gl.opts.threads == 0 || gl.enrolled >= gl.opts.threads) {
      gl.started = true;
      ThreadState* first = choose_locked(gl);
      if (first) grant_locked(gl, first);
    }
  } else if (gl.running == -1) {
    ThreadState* next = choose_locked(gl);
    if (next) grant_locked(gl, next);
  }
  park_until_running(gl, lk, me);
}

void retire_locked(Global& gl, ThreadState* me) {
  me->st = ThreadState::St::kFinished;
  me->cv_armed = false;
  self_slot() = nullptr;
  if (gl.running == static_cast<int>(me->id)) gl.running = -1;
  ++gl.progress;
  ThreadState* next = choose_locked(gl);
  if (next && gl.running == -1) grant_locked(gl, next);
  gl.any_cv.notify_all();
}

void arm(const RunOptions& options) {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (gl.run_active) die_locked(gl, "nested sched::Run — one run at a time per process");
  gl.opts = options;
  gl.threads.clear();
  gl.enrolled = 0;
  gl.started = false;
  gl.running = -1;
  gl.steps = 0;
  gl.progress = 0;
  gl.pending_adopt = 0;
  gl.next_adopt_id = options.threads == 0 ? 1000 : options.threads + 1000;
  gl.hang_ms = env_u32("BTPU_SCHED_HANG_MS", options.hang_ms);
  gl.opts.max_steps = env_u64("BTPU_SCHED_MAX_STEPS", options.max_steps);
  gl.change_steps.clear();
  gl.dfs_prefix = nullptr;
  gl.dfs_chosen.clear();
  gl.dfs_alts.clear();
  if (options.mode == Mode::kPct) {
    // d-1 priority-change points sampled from the estimated step range.
    uint64_t x = splitmix64(options.seed);
    for (uint32_t i = 1; i < options.pct_depth; ++i) {
      x = splitmix64(x);
      gl.change_steps.push_back(1 + x % std::max<uint32_t>(options.pct_steps, 1));
    }
    std::sort(gl.change_steps.begin(), gl.change_steps.end());
    gl.low_priority_next = options.pct_depth;  // below every splitmix priority
  }
  if (options.mode == Mode::kDfs) {
    // BTPU_SCHED_SEED is inert in DFS mode (the "seed" is just the schedule
    // ordinal) — telling the operator to set it would send them down a dead
    // runbook path; the deterministic enumeration itself is the replay.
    std::snprintf(gl.banner, sizeof(gl.banner),
                  "\nBTPU_SCHED: failure under DFS schedule ordinal %llu — re-run the "
                  "same fixture; the enumeration is deterministic\n",
                  static_cast<unsigned long long>(options.seed));
  } else {
    std::snprintf(gl.banner, sizeof(gl.banner),
                  "\nBTPU_SCHED: failure under schedule control — BTPU_SCHED_SEED=%llu "
                  "(mode=pct) replays this interleaving\n",
                  static_cast<unsigned long long>(options.seed));
  }
  struct sigaction sa {};
  sa.sa_sigaction = banner_handler;
  sa.sa_flags = SA_SIGINFO;
  sigemptyset(&sa.sa_mask);
  for (int i = 0; i < 3; ++i) ::sigaction(kBannerSignals[i], &sa, &gl.prev_sig[i]);
  gl.sig_installed = true;
  gl.run_active = true;
  g_armed.store(true, std::memory_order_seq_cst);
}

void disarm() {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  // Every enrolled thread — including adopted detached ones — must retire
  // before control-flow leaves the run; scheduling keeps running meanwhile,
  // driven by the threads themselves.
  const auto all_done = [&gl] {
    if (gl.pending_adopt != 0) return false;
    for (const auto& t : gl.threads)
      if (t->st != ThreadState::St::kFinished) return false;
    return true;
  };
  uint64_t last_progress = gl.progress;
  uint32_t stale_adopt_windows = 0;
  while (!all_done()) {
    if (gl.any_cv.wait_for(lk, std::chrono::milliseconds(gl.hang_ms)) ==
        std::cv_status::timeout) {
      if (gl.progress != last_progress) {
        last_progress = gl.progress;
        stale_adopt_windows = 0;
        continue;
      }
      if (gl.pending_adopt != 0) {
        // No progress AND a declared spawn that never adopted: bounded
        // patience, then a verdict — an infinite wait here would hang the
        // Run destructor with no banner (the one failure mode worse than
        // aborting).
        if (++stale_adopt_windows >= 3)
          die_locked(gl, "teardown: declared spawn never adopted — pending_adopt stuck");
        continue;
      }
      if (gl.running == -1) {
        if (ThreadState* next = choose_locked(gl)) {
          grant_locked(gl, next);
          continue;
        }
        die_locked(gl, "teardown deadlock — enrolled threads never retired");
      }
      last_progress = gl.progress;
    }
  }
  g_armed.store(false, std::memory_order_seq_cst);
  gl.run_active = false;
  gl.threads.clear();
  if (gl.sig_installed) {
    for (int i = 0; i < 3; ++i) ::sigaction(kBannerSignals[i], &gl.prev_sig[i], nullptr);
    gl.sig_installed = false;
  }
}

}  // namespace

// ---- hook entry points -----------------------------------------------------

void preempt(Point p, const void* addr) noexcept {
  ThreadState* me = self_slot();
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active || me == nullptr) return;
  yield_point_locked(gl, lk, me, p, addr);
}

void acquire(Point p, const void* addr, bool (*try_fn)(void*), void* m) noexcept {
  ThreadState* me = self_slot();
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active || me == nullptr) {
    lk.unlock();
    // Raced a disarm: fall back to a plain blocking acquire via try-spin
    // (the caller already committed to the scheduled path).
    while (!try_fn(m)) ::usleep(100);
    return;
  }
  for (;;) {
    // The decision point sits BEFORE the acquisition attempt: whoever runs
    // next may take the lock first — that is the interleaving under test.
    yield_point_locked(gl, lk, me, p, addr);
    if (try_fn(m)) return;  // nonblocking probe; scheduler lock held is fine
    me->point = p;
    me->wait_addr = addr;
    me->st = ThreadState::St::kBlockedMutex;
    gl.running = -1;
    ++gl.progress;
    ThreadState* next = choose_locked(gl);
    if (next) grant_locked(gl, next);
    park_until_running(gl, lk, me);
  }
}

void on_unlock(const void* addr) noexcept {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active) return;
  bool woke = false;
  for (auto& t : gl.threads) {
    if (t->st == ThreadState::St::kBlockedMutex && t->wait_addr == addr) {
      t->st = ThreadState::St::kRunnable;
      woke = true;
    }
  }
  if (woke) ++gl.progress;
  ThreadState* me = self_slot();
  if (me != nullptr && me->st == ThreadState::St::kRunning) {
    yield_point_locked(gl, lk, me, Point::kUnlock, addr);
  } else if (gl.running == -1 && gl.started) {
    // An unenrolled thread released a lock enrolled threads were parked on
    // while the token was idle: hand it to whoever the policy picks.
    if (ThreadState* next = choose_locked(gl)) grant_locked(gl, next);
  }
}

CvWaitTicket cv_register(const void* cv_addr, bool timed) noexcept {
  ThreadState* me = self_slot();
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active || me == nullptr) return CvWaitTicket{};
  me->cv_armed = true;
  me->cv_addr = cv_addr;
  me->cv_notified = false;
  me->cv_timed = timed;
  me->cv_timeout_fired = false;
  return CvWaitTicket{me};
}

bool cv_park(CvWaitTicket t) noexcept {
  ThreadState* me = static_cast<ThreadState*>(t.rep);
  if (me == nullptr) return true;
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active) return true;
  bump_step_locked(gl, me);
  if (me->cv_notified) {  // notify landed between register and park
    me->cv_armed = false;
    return true;
  }
  me->point = Point::kCvWait;
  me->wait_addr = me->cv_addr;
  me->st = me->cv_timed ? ThreadState::St::kBlockedCvTimed : ThreadState::St::kBlockedCv;
  gl.running = -1;
  ++gl.progress;
  wait_adoptions_locked(gl, lk);
  if (me->st != ThreadState::St::kRunning) {
    // Same external-grant window as yield_point_locked: an unenrolled
    // notify during the adoption rendezvous may have woken AND granted us
    // already — only choose a successor if we are genuinely parked.
    ThreadState* next = choose_locked(gl);
    if (next) grant_locked(gl, next);
    park_until_running(gl, lk, me);
  }
  me->cv_armed = false;
  return me->cv_notified && !me->cv_timeout_fired;
}

void on_notify(const void* cv_addr, bool all) noexcept {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active) return;
  // notify_one targets the lowest-id waiter — deterministic by design (the
  // DFS bound does not enumerate notify targets; documented in §10).
  std::vector<ThreadState*> waiters;
  for (auto& t : gl.threads) {
    if (t->cv_armed && t->cv_addr == cv_addr && !t->cv_notified)
      waiters.push_back(t.get());
  }
  std::sort(waiters.begin(), waiters.end(),
            [](const ThreadState* a, const ThreadState* b) { return a->id < b->id; });
  if (!all && waiters.size() > 1) waiters.resize(1);
  bool woke = false;
  for (ThreadState* w : waiters) {
    w->cv_notified = true;
    if (w->st == ThreadState::St::kBlockedCv || w->st == ThreadState::St::kBlockedCvTimed) {
      w->st = ThreadState::St::kRunnable;
      woke = true;
    }
  }
  if (woke) ++gl.progress;
  ThreadState* me = self_slot();
  if (me != nullptr && me->st == ThreadState::St::kRunning) {
    yield_point_locked(gl, lk, me, Point::kCvNotify, cv_addr);
  } else if (gl.running == -1 && gl.started) {
    if (ThreadState* next = choose_locked(gl)) grant_locked(gl, next);
  }
}

// ---- enrollment ------------------------------------------------------------

Enroll::Enroll(uint32_t id) noexcept {
  if (!armed()) return;
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active || self_slot() != nullptr) return;
  if (find_locked(gl, id) != nullptr) die_locked(gl, "duplicate sched::Enroll id");
  auto* t = new ThreadState();
  t->id = id;
  active_ = true;
  enroll_locked(gl, lk, t);
}

Enroll::~Enroll() {
  if (!active_) return;
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  ThreadState* me = self_slot();
  if (!gl.run_active || me == nullptr) return;
  retire_locked(gl, me);
}

void decl_spawn() noexcept {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active) return;
  ++gl.pending_adopt;
}

AdoptScope::AdoptScope() noexcept {
  if (!armed()) return;
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  if (!gl.run_active || gl.pending_adopt == 0 || self_slot() != nullptr) return;
  --gl.pending_adopt;
  auto* t = new ThreadState();
  t->id = gl.next_adopt_id++;
  active_ = true;
  gl.any_cv.notify_all();  // decision points rendezvous on pending_adopt
  enroll_locked(gl, lk, t);
}

AdoptScope::~AdoptScope() {
  if (!active_) return;
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  ThreadState* me = self_slot();
  if (!gl.run_active || me == nullptr) return;
  retire_locked(gl, me);
}

// ---- run control -----------------------------------------------------------

Run::Run(const RunOptions& options) { arm(options); }
Run::~Run() { disarm(); }

uint64_t current_seed() noexcept {
  Global& gl = g();
  std::unique_lock<std::mutex> lk(gl.mu);
  return gl.run_active ? gl.opts.seed : 0;
}

ExploreResult explore_dfs(const ExploreOptions& options,
                          const std::function<void()>& fixture) {
  ExploreResult result;
  const uint64_t max_schedules =
      options.max_schedules != 0 ? options.max_schedules
                                 : env_u64("BTPU_SCHED_DFS_MAX", 200000);
  std::vector<uint32_t> prefix;
  for (;;) {
    RunOptions ro;
    ro.mode = Mode::kDfs;
    ro.threads = options.threads;
    ro.seed = result.schedules + 1;  // schedule ordinal, printed on failure
    ro.max_steps = options.max_steps;
    std::vector<uint32_t> chosen, alts;
    {
      Run run(ro);
      {
        Global& gl = g();
        std::unique_lock<std::mutex> lk(gl.mu);
        gl.dfs_prefix = &prefix;
      }
      fixture();
      Global& gl = g();
      std::unique_lock<std::mutex> lk(gl.mu);
      // disarm() has not run yet (Run is alive); the choice log is intact.
      chosen = gl.dfs_chosen;
      alts = gl.dfs_alts;
      gl.dfs_prefix = nullptr;
    }
    ++result.schedules;
    result.max_decisions = std::max<uint64_t>(result.max_decisions, chosen.size());
    // Backtrack: deepest decision with an unexplored sibling.
    size_t i = chosen.size();
    while (i > 0 && chosen[i - 1] + 1 >= alts[i - 1]) --i;
    if (i == 0) {
      result.complete = true;
      break;
    }
    prefix.assign(chosen.begin(), chosen.begin() + static_cast<ptrdiff_t>(i));
    ++prefix.back();
    if (result.schedules >= max_schedules) {
      result.complete = false;  // truncated: callers MUST fail on this
      break;
    }
  }
  return result;
}

bool mutant_enabled(const char* name) noexcept {
  static const char* armed_mutant = env_str("BTPU_SCHED_MUTANT");
  return armed_mutant != nullptr && std::strcmp(armed_mutant, name) == 0;
}

}  // namespace btpu::sched

#endif  // BTPU_SCHED
