// RAM tier (host DRAM / CXL-style memory): flat allocation, direct mapping.
//
// Parity target: reference src/worker/storage/ram_backend.cpp (malloc pool,
// reserve/commit lifecycle) and cxl_memory_backend.cpp (mmap'd device
// memory with anonymous fallback) — both collapse to one backend here since
// the only difference is where the bytes live; the worker may hand us
// transport-owned memory (shm segment) via set_external_region.
#include <cstdlib>
#include <cstring>

#include "backend_base.h"
#include "btpu/common/log.h"

namespace btpu::storage {

class RamBackend : public OffsetBackendBase {
 public:
  explicit RamBackend(BackendConfig config) : OffsetBackendBase(std::move(config)) {}
  ~RamBackend() override { shutdown(); }

  // Adopt caller-owned memory (e.g. a shm segment) instead of mallocing.
  void set_external_region(void* base) { external_base_ = base; }

  ErrorCode initialize() override {
    if (base_) return ErrorCode::INVALID_STATE;
    if (external_base_) {
      base_ = static_cast<uint8_t*>(external_base_);
      owned_ = false;
    } else {
      base_ = static_cast<uint8_t*>(std::malloc(config_.capacity));
      if (!base_) return ErrorCode::OUT_OF_MEMORY;
      owned_ = true;
    }
    return init_allocator();
  }

  void shutdown() override {
    if (base_ && owned_) std::free(base_);
    base_ = nullptr;
  }

  void* base_address() const override { return base_; }

  ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    if (len > config_.capacity || offset > config_.capacity - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    std::memcpy(base_ + offset, src, len);
    return ErrorCode::OK;
  }

  ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    if (len > config_.capacity || offset > config_.capacity - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    std::memcpy(dst, base_ + offset, len);
    return ErrorCode::OK;
  }

 private:
  uint8_t* base_{nullptr};
  void* external_base_{nullptr};
  bool owned_{false};
};

std::unique_ptr<StorageBackend> make_ram_backend(const BackendConfig& config) {
  return std::make_unique<RamBackend>(config);
}

std::unique_ptr<StorageBackend> create_ram_backend_with_region(const BackendConfig& config,
                                                               void* region) {
  auto backend = std::make_unique<RamBackend>(config);
  backend->set_external_region(region);
  return backend;
}

}  // namespace btpu::storage
