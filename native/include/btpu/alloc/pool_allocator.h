// Per-pool free-range allocator.
//
// Parity target: reference include/blackbird/allocation/range_allocator.h:36-69
// and src/allocation/range_allocator.cpp:12-146 (PoolAllocator): a free-range
// map offset->length with best-fit/first-fit carve, merge-on-free, and
// conversion of ranges into absolute remote addresses. Two deliberate changes:
//   * best-fit runs on a size-ordered secondary index (O(log n)) instead of
//     the reference's linear map scan (range_allocator.cpp:133-146);
//   * the region key comes from the pool's generic RemoteDescriptor rather
//     than UCX-specific fields, and validation happens in the constructor
//     (throws std::invalid_argument, matching reference ctor behavior).
#pragma once

#include <map>
#include <optional>
#include <string_view>

#include "btpu/alloc/allocator.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/common/types.h"

namespace btpu::alloc {

class PoolAllocator {
 public:
  // Validates the pool descriptor; throws std::invalid_argument when the pool
  // has zero size, an unspecified transport, an empty endpoint, or a
  // non-hex rkey (parity: reference PoolAllocator ctor + to_memory_location
  // strict rkey validation, range_allocator.cpp:12-35,125-131).
  // `poolsan_track` registers the pool with btpu::poolsan (shadow extent
  // map + generations + red zones + quarantine) — set by the keystone-side
  // RangeAllocator, the one authority on placement carve/free. Backend-
  // internal reservation allocators stay untracked: they share the pool id
  // with the keystone's view of the same region, and two shadows over one
  // address space would convict each other's carves.
  explicit PoolAllocator(const MemoryPool& pool, bool poolsan_track = false);

  // Carved offsets honor the pool's advertised alignment (MemoryPool::
  // alignment): the chosen block is padded up to the boundary and the
  // leading gap returns to the free map. Tracked pools additionally carve
  // a trailing red zone when the pool has room (dropped, never failing the
  // allocation, when it does not) and stamp a fresh generation.
  std::optional<Range> allocate(uint64_t size, bool prefer_best_fit = true);
  // Carves a SPECIFIC range out of the free map (keystone restart replay of
  // persisted placements). Fails when any byte of it is already allocated.
  bool allocate_at(const Range& range);
  // `who` is poolsan report context (the owning object key when known).
  // Tracked pools park the extent in the bounded quarantine FIFO instead
  // of reusing it immediately; a convicted free (double free, wild free)
  // is REFUSED — the free map stays intact.
  void free(const Range& range, std::string_view who = {});

  uint64_t total_free() const;
  uint64_t largest_free_block() const;
  // 1 - largest_free_block/total_free; 0 when empty or unfragmented
  // (parity: reference AllocatorStats fragmentation definition,
  // allocator_interface.h:15-22).
  double fragmentation_ratio() const;
  bool can_allocate(uint64_t size) const;
  size_t free_range_count() const;

  const MemoryPoolId& pool_id() const noexcept { return pool_id_; }
  StorageClass storage_class() const noexcept { return storage_class_; }
  const NodeId& node_id() const noexcept { return node_id_; }
  const TopoCoord& topo() const noexcept { return topo_; }
  uint64_t pool_size() const noexcept { return pool_size_; }
  const RemoteDescriptor& remote() const noexcept { return remote_; }

  // Converts a carved range into the absolute remote location a client dials:
  // remote_base + offset, with the region key parsed from rkey_hex.
  MemoryLocation to_memory_location(const Range& range) const;

 private:
  MemoryPoolId pool_id_;
  StorageClass storage_class_;
  NodeId node_id_;
  TopoCoord topo_;
  RemoteDescriptor remote_;
  uint64_t rkey_{0};
  uint64_t pool_size_;
  uint64_t alignment_{0};  // 0/1 = unaligned

  // Pool-sanitizer shadow (null = untracked: release builds, BTPU_POOLSAN=0,
  // or backend-internal allocators). Leaf state with its own mutex; the
  // only lock edge is mutex_ -> shadow (allocate stamps/drains under
  // mutex_; free consults the shadow BEFORE taking mutex_).
  poolsan::ShadowPtr shadow_;

  mutable Mutex mutex_;
  // offset -> length / length -> offset views of the free map.
  std::map<uint64_t, uint64_t> free_by_offset_ BTPU_GUARDED_BY(mutex_);
  std::multimap<uint64_t, uint64_t> free_by_size_ BTPU_GUARDED_BY(mutex_);

  void insert_free(uint64_t offset, uint64_t length) BTPU_REQUIRES(mutex_);
  void erase_free(std::map<uint64_t, uint64_t>::iterator it) BTPU_REQUIRES(mutex_);
  // The carve search (best-fit via the size index or first-fit by offset),
  // factored out so allocate() can retry after a quarantine drain. Returns
  // the carved start offset, or nullopt when no block fits.
  std::optional<uint64_t> carve(uint64_t size, bool prefer_best_fit)
      BTPU_REQUIRES(mutex_);
  // allocate_at's exact carve, factored out so IT can retry after a
  // quarantine drain too (record re-apply frees then re-adopts ranges).
  bool carve_exact(const Range& range) BTPU_REQUIRES(mutex_);
  // free() minus the locking: merge-with-neighbors insert, shared with the
  // quarantine-release path (which already holds mutex_).
  void free_locked(uint64_t offset, uint64_t length) BTPU_REQUIRES(mutex_);
};

}  // namespace btpu::alloc
