// LOCAL transport: process-global region registry + memcpy. The hermetic
// in-process fake SURVEY.md §4 calls for; also the embedded-cluster fast path.
#include <atomic>
#include <cstring>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <unordered_map>

#include "btpu/common/crc32c.h"
#include "btpu/common/log.h"
#include "btpu/common/pool_span.h"
#include "btpu/transport/transport.h"

namespace btpu::transport {

namespace {

struct LocalRegion {
  uint8_t* base{nullptr};  // null for virtual regions
  uint64_t len{0};
  uint64_t remote_base{0};  // advertised == (uintptr_t)base; 0 for virtual
  RegionReadFn read_fn;
  RegionWriteFn write_fn;
  std::string tag;  // pool id at registration — the poolsan shadow lookup key
};

struct LocalRegistry {
  // Reader-writer lock: the access path (every LOCAL one-sided op) takes a
  // shared lock for its rkey lookup; registration/teardown take it unique.
  SharedMutex mutex;
  std::unordered_map<uint64_t, LocalRegion> by_rkey BTPU_GUARDED_BY(mutex);
  std::mt19937_64 rng BTPU_GUARDED_BY(mutex){0x6274707545ull};  // deterministic for debuggability

  static LocalRegistry& instance() {
    static LocalRegistry r;
    return r;
  }
};

class LocalTransportServer : public TransportServer {
 public:
  TransportKind kind() const noexcept override { return TransportKind::LOCAL; }

  ErrorCode start(const std::string&, uint16_t) override { return ErrorCode::OK; }
  void stop() override {
    auto& reg = LocalRegistry::instance();
    WriterLock lock(reg.mutex);
    for (uint64_t rkey : my_rkeys_) reg.by_rkey.erase(rkey);
    my_rkeys_.clear();
  }

  Result<RemoteDescriptor> register_region(void* base, uint64_t len,
                                           const std::string& tag) override {
    if (!base || len == 0) return ErrorCode::INVALID_PARAMETERS;
    auto& reg = LocalRegistry::instance();
    WriterLock lock(reg.mutex);
    uint64_t rkey = reg.rng() | 1;  // nonzero
    while (reg.by_rkey.contains(rkey)) rkey = reg.rng() | 1;
    const uint64_t remote_base = reinterpret_cast<uint64_t>(base);
    reg.by_rkey[rkey] = {static_cast<uint8_t*>(base), len, remote_base, nullptr, nullptr, tag};
    my_rkeys_.push_back(rkey);
    RemoteDescriptor d;
    d.transport = TransportKind::LOCAL;
    d.endpoint = "local:" + tag;
    d.remote_base = remote_base;
    d.rkey_hex = rkey_to_hex(rkey);
    return d;
  }

  Result<RemoteDescriptor> register_virtual_region(uint64_t len, const std::string& tag,
                                                   RegionReadFn read_fn,
                                                   RegionWriteFn write_fn) override {
    if (len == 0 || !read_fn || !write_fn) return ErrorCode::INVALID_PARAMETERS;
    auto& reg = LocalRegistry::instance();
    WriterLock lock(reg.mutex);
    uint64_t rkey = reg.rng() | 1;
    while (reg.by_rkey.contains(rkey)) rkey = reg.rng() | 1;
    reg.by_rkey[rkey] = {nullptr, len, 0, std::move(read_fn), std::move(write_fn), tag};
    my_rkeys_.push_back(rkey);
    RemoteDescriptor d;
    d.transport = TransportKind::LOCAL;
    d.endpoint = "local:" + tag;
    d.remote_base = 0;
    d.rkey_hex = rkey_to_hex(rkey);
    return d;
  }

  ErrorCode unregister_region(const RemoteDescriptor& desc) override {
    uint64_t rkey = 0;
    try {
      rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    } catch (...) {
      return ErrorCode::INVALID_PARAMETERS;
    }
    auto& reg = LocalRegistry::instance();
    WriterLock lock(reg.mutex);
    reg.by_rkey.erase(rkey);
    std::erase(my_rkeys_, rkey);
    return ErrorCode::OK;
  }

 private:
  std::vector<uint64_t> my_rkeys_;
};

}  // namespace

// TSan exemption, scoped to local_access's stack: LOCAL transport emulates
// ONE-SIDED RMA (the reference's ucp_get_nbx/ucp_put_nbx into worker
// memory) with a same-address-space memcpy. One-sided reads racing remote
// writes are the modeled hardware behavior — a reader that raced a
// concurrent reallocation gets garbage bytes, which every consumer
// discards through an epoch re-check or a CRC gate before acting (repair
// re-checks the object epoch before publishing; scrub heals only behind a
// final stamp match; client verify fails over). The suppression is
// declared in each sanitized EXECUTABLE (native/exe/tsan_rma_suppression.h
// — TSan reads the default-suppressions hook during .preinit, before this
// shared library's symbols are guaranteed registered), while TSan keeps
// full power over the actual shared-state code (registries, object map,
// allocator), where a report IS a bug.

// Bounds+rkey-checked access used by the mux client (local kind). The flat
// path resolves through poolspan::resolve — the one sanctioned base+offset
// chokepoint — so stale-generation / quarantined-extent accesses are
// convicted here exactly like on the TCP serving engines.
ErrorCode local_access(uint64_t remote_addr, uint64_t rkey, void* buf, uint64_t len,
                       bool is_write, uint32_t* crc_out, uint64_t extent_gen) {
  auto& reg = LocalRegistry::instance();
  uint8_t* target = nullptr;
  RegionReadFn read_fn;
  RegionWriteFn write_fn;
  uint64_t offset = 0;
  // Held across the post-lock copy below. The registry lock only proves the
  // extent live at RESOLVE time; a concurrent free may quarantine it while
  // the memcpy runs — the sanctioned one-sided RMA race (CRC gate judges the
  // stale bytes). The pin keeps an armed poolsan from turning that race into
  // a use-after-poison trap: it defers the freed extent's byte-level poison
  // (never the conviction) until the copy is out (poolsan.h "access pins").
  poolsan::AccessPin pin;
  {
    SharedLock lock(reg.mutex);
    auto it = reg.by_rkey.find(rkey);
    if (it == reg.by_rkey.end()) return ErrorCode::MEMORY_ACCESS_ERROR;
    const LocalRegion& region = it->second;
    if (remote_addr < region.remote_base || len > region.len ||
        remote_addr - region.remote_base > region.len - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    offset = remote_addr - region.remote_base;
    if (region.base) {
      // Pin BEFORE the proof: a free landing in between is convicted by the
      // resolve; one landing after it finds the pin already open.
      pin = poolsan::AccessPin(region.base, region.tag.c_str(), region.len);
      auto span = poolspan::resolve(region.base, region.len, offset, len, extent_gen,
                                    is_write ? poolspan::Access::kWrite
                                             : poolspan::Access::kRead,
                                    region.tag.c_str());
      if (!span.ok()) return span.error();
      target = span.value().data();
    } else {
      read_fn = region.read_fn;
      write_fn = region.write_fn;
    }
  }
  if (target) {
    if (is_write) {
      if (crc_out) {
        *crc_out = crc32c_copy(target, buf, len);  // fused: hash while moving
      } else {
        std::memcpy(target, buf, len);
      }
    } else if (crc_out) {
      *crc_out = crc32c_copy(buf, target, len);  // fused: hash while moving
    } else {
      std::memcpy(buf, target, len);
    }
    return ErrorCode::OK;
  }
  const ErrorCode ec = is_write ? write_fn(offset, buf, len) : read_fn(offset, buf, len);
  // Callback-backed regions consume/fill `buf` opaquely; hash is a second pass.
  if (ec == ErrorCode::OK && crc_out) *crc_out = crc32c(buf, len);
  return ec;
}

std::unique_ptr<TransportServer> make_local_transport_server() {
  return std::make_unique<LocalTransportServer>();
}

}  // namespace btpu::transport
