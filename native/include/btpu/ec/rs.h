// Systematic Reed-Solomon erasure coding over GF(2^8).
//
// An object is split into k equal data shards; m parity shards are computed
// such that ANY k of the k+m shards reconstruct the data (tolerates any m
// losses). Systematic: the k data shards are stored verbatim, so reads that
// find all data shards never pay a decode.
//
// No reference counterpart — blackbird only replicates (WorkerConfig
// .replication_factor, types.h:161); EC gives the same worker-loss
// tolerance at (k+m)/k storage overhead instead of (1+m)x. Parity rows use
// a Cauchy matrix (every square submatrix of a Cauchy matrix is invertible,
// which is exactly the any-k-of-n property).
//
// Limits: 1 <= k, 1 <= m, k + m <= 128 (x_j = k+j and y_i = i must be
// distinct elements of GF(256) with x_j != y_i).
#pragma once

#include <cstddef>
#include <cstdint>

namespace btpu::ec {

inline constexpr size_t kMaxTotalShards = 128;

// parity[j][0..len) = sum_i C(j,i) * data[i][0..len)  (GF(256) arithmetic).
// data: k pointers, parity: m pointers, all buffers len bytes. Returns
// false (parity untouched) when the geometry is out of range.
bool rs_encode(const uint8_t* const* data, size_t k, uint8_t* const* parity, size_t m,
               size_t len);

// Reconstructs missing DATA shards from any k present shards.
//   present[i] for i in [0, k+m): shard i's bytes, or nullptr if lost.
//   out[i]: for each i < k with present[i] == nullptr, a len-byte buffer
//           that receives the reconstructed shard (ignored otherwise).
// Returns false when fewer than k shards are present (or parameters are out
// of range). Missing PARITY shards are not rebuilt here; re-encode from the
// (now complete) data instead.
bool rs_reconstruct(const uint8_t* const* present, size_t k, size_t m, size_t len,
                    uint8_t* const* out);

}  // namespace btpu::ec
