// Transport layer tests: one-sided read/write with bounds+rkey validation
// over LOCAL, TCP (pooled endpoints), and SHM.
// Parity notes: the reference only exercises its transport via manual demo
// binaries (clients/ucx_client.cpp); here the contract is unit-tested.
#include <atomic>
#include <algorithm>
#include <chrono>
#include <sys/wait.h>
#include <unistd.h>
#include <cstdlib>
#include <cstring>
#include <thread>

#include "btest.h"
#include "btpu/common/crc32c.h"
#include "btpu/net/net.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::transport;

namespace {

uint64_t parse_rkey(const RemoteDescriptor& d) { return std::stoull(d.rkey_hex, nullptr, 16); }

void run_roundtrip_suite(TransportServer& server, TransportClient& client) {
  std::vector<uint8_t> region(64 * 1024, 0);
  void* base = region.data();
  if (void* owned = server.alloc_region(region.size(), "pool-x")) base = owned;

  auto reg = server.register_region(base, 64 * 1024, "pool-x");
  BT_ASSERT_OK(reg);
  const RemoteDescriptor desc = reg.value();
  const uint64_t rkey = parse_rkey(desc);

  // Write a pattern at offset 4096, read it back.
  std::vector<uint8_t> src(8192);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i * 31 + 7);
  BT_EXPECT(client.write(desc, desc.remote_base + 4096, rkey, src.data(), src.size()) ==
            ErrorCode::OK);
  std::vector<uint8_t> dst(8192, 0);
  BT_EXPECT(client.read(desc, desc.remote_base + 4096, rkey, dst.data(), dst.size()) ==
            ErrorCode::OK);
  BT_EXPECT(std::memcmp(src.data(), dst.data(), src.size()) == 0);

  // Sub-range read from within the written window.
  std::vector<uint8_t> sub(100, 0);
  BT_EXPECT(client.read(desc, desc.remote_base + 4096 + 50, rkey, sub.data(), 100) ==
            ErrorCode::OK);
  BT_EXPECT(std::memcmp(src.data() + 50, sub.data(), 100) == 0);

  // Out-of-bounds and past-the-end are rejected.
  BT_EXPECT(client.read(desc, desc.remote_base + 64 * 1024 - 10, rkey, sub.data(), 100) ==
            ErrorCode::MEMORY_ACCESS_ERROR);
  // Bad rkey rejected (shm validates bounds only — access control is file
  // permissions — so skip the rkey probe there).
  if (desc.transport != TransportKind::SHM) {
    BT_EXPECT(client.read(desc, desc.remote_base, rkey ^ 0x1234, sub.data(), 10) ==
              ErrorCode::MEMORY_ACCESS_ERROR);
  }

  // Zero-length transfers are no-ops.
  BT_EXPECT(client.write(desc, desc.remote_base, rkey, src.data(), 0) == ErrorCode::OK);

  // Concurrent transfers (exercises the tcp connection pool).
  std::vector<std::thread> threads;
  std::atomic<int> failures{0};
  for (int t = 0; t < 8; ++t) {
    threads.emplace_back([&, t] {
      std::vector<uint8_t> buf(1024, static_cast<uint8_t>(t));
      std::vector<uint8_t> back(1024);
      const uint64_t off = 16384 + static_cast<uint64_t>(t) * 2048;
      for (int i = 0; i < 25; ++i) {
        if (client.write(desc, desc.remote_base + off, rkey, buf.data(), buf.size()) !=
            ErrorCode::OK)
          ++failures;
        if (client.read(desc, desc.remote_base + off, rkey, back.data(), back.size()) !=
            ErrorCode::OK)
          ++failures;
        if (std::memcmp(buf.data(), back.data(), buf.size()) != 0) ++failures;
      }
    });
  }
  for (auto& th : threads) th.join();
  BT_EXPECT_EQ(failures.load(), 0);

  BT_EXPECT(server.unregister_region(desc) == ErrorCode::OK);
  if (desc.transport == TransportKind::LOCAL) {
    // After unregister the rkey is dead.
    BT_EXPECT(client.read(desc, desc.remote_base, rkey, sub.data(), 10) ==
              ErrorCode::MEMORY_ACCESS_ERROR);
  }
  server.stop();
}

}  // namespace

BTEST(Transport, LocalRoundtrip) {
  auto server = make_transport_server(TransportKind::LOCAL);
  auto client = make_transport_client();
  BT_ASSERT(server && client);
  BT_ASSERT(server->start("", 0) == ErrorCode::OK);
  run_roundtrip_suite(*server, *client);
}

BTEST(Transport, TcpRoundtrip) {
  auto server = make_transport_server(TransportKind::TCP);
  auto client = make_transport_client();
  BT_ASSERT(server && client);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  run_roundtrip_suite(*server, *client);
}

BTEST(Transport, TcpStagedLaneEngagesSameHost) {
  // Same-host TCP rides the shm-staged lane: payloads move through the
  // client-created segment, only headers cross the socket — including for
  // VIRTUAL regions, whose callbacks target the shared segment directly
  // (the out-of-process device-tier data path).
  auto server = make_transport_server(TransportKind::TCP);
  auto client = make_transport_client();
  BT_ASSERT(server && client);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);

  std::vector<uint8_t> flat_region(256 * 1024, 0);
  auto flat = server->register_region(flat_region.data(), flat_region.size(), "flat");
  BT_ASSERT_OK(flat);

  std::vector<uint8_t> store(256 * 1024, 0);  // backing for a virtual region
  auto virt = server->register_virtual_region(
      store.size(), "virt",
      [&](uint64_t off, void* dst, uint64_t len) {
        std::memcpy(dst, store.data() + off, len);
        return ErrorCode::OK;
      },
      [&](uint64_t off, const void* src, uint64_t len) {
        std::memcpy(store.data() + off, src, len);
        return ErrorCode::OK;
      });
  BT_ASSERT_OK(virt);

  const uint64_t staged_before = tcp_staged_op_count();
  std::vector<uint8_t> payload(100 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 31);
  std::vector<uint8_t> back(payload.size(), 0);

  for (const auto& desc : {flat.value(), virt.value()}) {
    const uint64_t rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    BT_EXPECT(client->write(desc, desc.remote_base + 512, rkey, payload.data(),
                            payload.size()) == ErrorCode::OK);
    std::fill(back.begin(), back.end(), 0);
    BT_EXPECT(client->read(desc, desc.remote_base + 512, rkey, back.data(),
                           back.size()) == ErrorCode::OK);
    BT_EXPECT(std::memcmp(payload.data(), back.data(), payload.size()) == 0);
  }
  // Both regions' bytes really are in place server-side.
  BT_EXPECT(std::memcmp(flat_region.data() + 512, payload.data(), payload.size()) == 0);
  BT_EXPECT(std::memcmp(store.data() + 512, payload.data(), payload.size()) == 0);
  // All four ops (2 writes + 2 reads) used the staged lane.
  BT_EXPECT(tcp_staged_op_count() >= staged_before + 4);

  // Bounds violations fail cleanly over the staged lane too.
  const auto& desc = flat.value();
  const uint64_t rkey = std::stoull(desc.rkey_hex, nullptr, 16);
  BT_EXPECT(client->read(desc, desc.remote_base + flat_region.size() - 8, rkey,
                         back.data(), 64) == ErrorCode::MEMORY_ACCESS_ERROR);
  server->stop();
}

BTEST(Transport, ShmRoundtrip) {
  auto server = make_transport_server(TransportKind::SHM);
  auto client = make_transport_client();
  BT_ASSERT(server && client);
  BT_ASSERT(server->start("", 0) == ErrorCode::OK);
  run_roundtrip_suite(*server, *client);
}

BTEST(Transport, TcpSurvivesServerRestart) {
  // Pooled connections go stale when a worker restarts; the client must
  // retry on a fresh connection transparently.
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(4096, 0);
  auto reg = server->register_region(region.data(), region.size(), "p");
  BT_ASSERT_OK(reg);
  auto desc = reg.value();
  const uint64_t rkey = parse_rkey(desc);
  auto client = make_transport_client();

  uint8_t v = 42;
  BT_EXPECT(client->write(desc, desc.remote_base, rkey, &v, 1) == ErrorCode::OK);
  server->stop();

  // Restart on the same port with the same region re-registered.
  auto hp = net::parse_host_port(desc.endpoint);
  BT_ASSERT(hp.has_value());
  auto server2 = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server2->start("127.0.0.1", hp->port) == ErrorCode::OK);
  auto reg2 = server2->register_region(region.data(), region.size(), "p");
  BT_ASSERT_OK(reg2);
  auto desc2 = reg2.value();

  uint8_t back = 0;
  BT_EXPECT(client->read(desc2, desc2.remote_base, parse_rkey(desc2), &back, 1) ==
            ErrorCode::OK);
  BT_EXPECT_EQ(int(back), 42);
  server2->stop();
}

BTEST(Transport, TcpBatchPipelinesAcrossEndpoints) {
  // A batch spanning two workers moves in one pipelined pass; per-op rkey
  // violations land on their op without sinking the rest of the batch.
  auto server_a = make_transport_server(TransportKind::TCP);
  auto server_b = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server_a->start("127.0.0.1", 0) == ErrorCode::OK);
  BT_ASSERT(server_b->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region_a(32 * 1024), region_b(32 * 1024);
  auto reg_a = server_a->register_region(region_a.data(), region_a.size(), "a");
  auto reg_b = server_b->register_region(region_b.data(), region_b.size(), "b");
  BT_ASSERT_OK(reg_a);
  BT_ASSERT_OK(reg_b);
  const auto desc_a = reg_a.value();
  const auto desc_b = reg_b.value();
  auto client = make_transport_client();

  std::vector<uint8_t> src(48 * 1024);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i * 13 + 5);
  // Three writes: two good (one per worker), one with a bad rkey.
  WireOp writes[3] = {
      {&desc_a, desc_a.remote_base, parse_rkey(desc_a), src.data(), 16 * 1024},
      {&desc_b, desc_b.remote_base, parse_rkey(desc_b), src.data() + 16 * 1024, 16 * 1024},
      {&desc_a, desc_a.remote_base, parse_rkey(desc_a) ^ 0xbad, src.data() + 32 * 1024,
       16 * 1024},
  };
  BT_EXPECT(client->write_batch(writes, 3) == ErrorCode::MEMORY_ACCESS_ERROR);
  BT_EXPECT(writes[0].status == ErrorCode::OK);
  BT_EXPECT(writes[1].status == ErrorCode::OK);
  BT_EXPECT(writes[2].status == ErrorCode::MEMORY_ACCESS_ERROR);

  std::vector<uint8_t> dst(32 * 1024, 0);
  WireOp reads[2] = {
      {&desc_a, desc_a.remote_base, parse_rkey(desc_a), dst.data(), 16 * 1024},
      {&desc_b, desc_b.remote_base, parse_rkey(desc_b), dst.data() + 16 * 1024, 16 * 1024},
  };
  BT_EXPECT(client->read_batch(reads, 2) == ErrorCode::OK);
  BT_EXPECT(std::memcmp(src.data(), dst.data(), 32 * 1024) == 0);
  server_a->stop();
  server_b->stop();
}

BTEST(Transport, TcpBatchSplitsWideOps) {
  // One op wider than the pipeline chunk size round-trips intact (the batch
  // engine splits it across several pooled connections internally).
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  const uint64_t len = 9ull << 20;  // > 2 chunks
  std::vector<uint8_t> region(len);
  auto reg = server->register_region(region.data(), region.size(), "wide");
  BT_ASSERT_OK(reg);
  const auto desc = reg.value();
  std::vector<uint8_t> src(len);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i >> 12 ^ i);
  WireOp put{&desc, desc.remote_base, parse_rkey(desc), src.data(), len};
  BT_EXPECT(make_transport_client()->write_batch(&put, 1) == ErrorCode::OK);
  std::vector<uint8_t> dst(len, 0);
  WireOp get{&desc, desc.remote_base, parse_rkey(desc), dst.data(), len};
  BT_EXPECT(make_transport_client()->read_batch(&get, 1) == ErrorCode::OK);
  BT_EXPECT(std::memcmp(src.data(), dst.data(), len) == 0);
  server->stop();
}

BTEST(Transport, TcpWantCrcCoversStagedAndMultiChunkReads) {
  // The want_crc contract over real TCP: per-chunk CRCs (an op wider than
  // kChunkBytes splits internally) must combine to the whole op's crc32c,
  // on both the staged (same-host shm segment, fused copy) and streaming
  // lanes. A fold/ordering bug here would surface in production as
  // spurious CHECKSUM_MISMATCH on every verified read past 4 MiB.
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  const uint64_t len = 9ull << 20;  // > 2 chunks
  std::vector<uint8_t> region(len);
  auto reg = server->register_region(region.data(), region.size(), "crc");
  BT_ASSERT_OK(reg);
  const auto desc = reg.value();
  std::vector<uint8_t> src(len);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i * 131 >> 4 ^ i);
  WireOp put{&desc, desc.remote_base, parse_rkey(desc), src.data(), len};
  BT_EXPECT(make_transport_client()->write_batch(&put, 1) == ErrorCode::OK);

  auto client = make_transport_client();
  // Staged lane (default on same host): wide op, per-chunk fused copies.
  std::vector<uint8_t> dst(len, 0);
  WireOp get{&desc, desc.remote_base, parse_rkey(desc), dst.data(), len};
  get.want_crc = true;
  const uint64_t staged_before = tcp_staged_op_count();
  BT_EXPECT(client->read_batch(&get, 1) == ErrorCode::OK);
  BT_EXPECT(tcp_staged_op_count() > staged_before);
  BT_EXPECT(dst == src);
  BT_EXPECT_EQ(get.crc, crc32c(src.data(), len));
  // Small op (single chunk) keeps the contract too.
  WireOp small{&desc, desc.remote_base + 12345, parse_rkey(desc), dst.data(), 70000};
  small.want_crc = true;
  BT_EXPECT(client->read_batch(&small, 1) == ErrorCode::OK);
  BT_EXPECT_EQ(small.crc, crc32c(src.data() + 12345, 70000));
  server->stop();

  // Streaming lane (staged lane disabled): the segmented drain hashes as
  // segments land; same combined result.
  setenv("BTPU_STAGED_DATA", "0", 1);
  auto server2 = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server2->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region2(len);
  auto reg2 = server2->register_region(region2.data(), region2.size(), "crc2");
  BT_ASSERT_OK(reg2);
  const auto desc2 = reg2.value();
  WireOp put2{&desc2, desc2.remote_base, parse_rkey(desc2), src.data(), len};
  BT_EXPECT(make_transport_client()->write_batch(&put2, 1) == ErrorCode::OK);
  std::fill(dst.begin(), dst.end(), 0);
  WireOp get2{&desc2, desc2.remote_base, parse_rkey(desc2), dst.data(), len};
  get2.want_crc = true;
  BT_EXPECT(make_transport_client()->read_batch(&get2, 1) == ErrorCode::OK);
  BT_EXPECT(dst == src);
  BT_EXPECT_EQ(get2.crc, crc32c(src.data(), len));
  unsetenv("BTPU_STAGED_DATA");
  server2->stop();
}

BTEST(Transport, WantCrcFusesIntoWritesAcrossLanes) {
  // Put-path mirror of the read fusion: a write with want_crc must return
  // the crc32c of the bytes it moved — fused with the staging copy on the
  // staged lane, folded across chunks when the op splits, post-send on the
  // streaming lane, and fused with the memcpy on SHM/LOCAL. The client
  // stamps shard CRCs straight from these, so a wrong value here would
  // poison every later verified read of the object.
  const uint64_t len = 9ull << 20;  // > 2 chunks on the TCP lane
  std::vector<uint8_t> src(len);
  for (size_t i = 0; i < src.size(); ++i) src[i] = static_cast<uint8_t>(i * 37 >> 3 ^ i);
  const uint32_t expect = crc32c(src.data(), len);

  {  // TCP staged (default same-host) — wide op, per-chunk fused copies.
    auto server = make_transport_server(TransportKind::TCP);
    BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
    std::vector<uint8_t> region(len);
    auto reg = server->register_region(region.data(), region.size(), "wcrc");
    BT_ASSERT_OK(reg);
    const auto desc = reg.value();
    WireOp put{&desc, desc.remote_base, parse_rkey(desc), src.data(), len};
    put.want_crc = true;
    BT_EXPECT(make_transport_client()->write_batch(&put, 1) == ErrorCode::OK);
    BT_EXPECT_EQ(put.crc, expect);
    BT_EXPECT(region == src);
    // Single-chunk op at an offset keeps the contract.
    WireOp small{&desc, desc.remote_base + 4321, parse_rkey(desc), src.data(), 70000};
    small.want_crc = true;
    BT_EXPECT(make_transport_client()->write_batch(&small, 1) == ErrorCode::OK);
    BT_EXPECT_EQ(small.crc, crc32c(src.data(), 70000));
    server->stop();
  }
  {  // TCP streaming lane (staged lane disabled): hash rides post-send.
    setenv("BTPU_STAGED_DATA", "0", 1);
    auto server = make_transport_server(TransportKind::TCP);
    BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
    std::vector<uint8_t> region(len);
    auto reg = server->register_region(region.data(), region.size(), "wcrc2");
    BT_ASSERT_OK(reg);
    const auto desc = reg.value();
    WireOp put{&desc, desc.remote_base, parse_rkey(desc), src.data(), len};
    put.want_crc = true;
    BT_EXPECT(make_transport_client()->write_batch(&put, 1) == ErrorCode::OK);
    BT_EXPECT_EQ(put.crc, expect);
    BT_EXPECT(region == src);
    unsetenv("BTPU_STAGED_DATA");
    server->stop();
  }
  {  // SHM: fused with the segment memcpy.
    auto server = make_transport_server(TransportKind::SHM);
    BT_ASSERT(server->start("", 0) == ErrorCode::OK);
    void* base = server->alloc_region(len, "wcrc3");
    BT_ASSERT(base != nullptr);
    auto reg = server->register_region(base, len, "wcrc3");
    BT_ASSERT_OK(reg);
    const auto desc = reg.value();
    WireOp put{&desc, desc.remote_base, parse_rkey(desc), src.data(), len};
    put.want_crc = true;
    BT_EXPECT(make_transport_client()->write_batch(&put, 1) == ErrorCode::OK);
    BT_EXPECT_EQ(put.crc, expect);
    BT_EXPECT(std::memcmp(base, src.data(), len) == 0);
    server->stop();
  }
}

BTEST(Transport, TcpStagedPipelineChunksRoundtripUnevenSizes) {
  // The staged lane moves a sub-op through the segment in pipe chunks
  // (client drains chunk N while the server stages chunk N+1). Sizes chosen
  // to hit every boundary shape: below one pipe chunk, exactly one, a
  // multiple, and a multi-chunk op with a short odd tail — each must
  // roundtrip byte-exact with the fused CRC equal to the whole-range hash,
  // in both directions, on flat AND virtual (callback-backed) regions.
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  constexpr uint64_t kRegion = 4ull << 20;
  std::vector<uint8_t> flat_region(kRegion, 0);
  auto flat = server->register_region(flat_region.data(), kRegion, "pipe");
  BT_ASSERT_OK(flat);
  std::vector<uint8_t> store(kRegion, 0);
  auto virt = server->register_virtual_region(
      kRegion, "pipe-virt",
      [&](uint64_t off, void* dst, uint64_t len) {
        std::memcpy(dst, store.data() + off, len);
        return ErrorCode::OK;
      },
      [&](uint64_t off, const void* src, uint64_t len) {
        std::memcpy(store.data() + off, src, len);
        return ErrorCode::OK;
      });
  BT_ASSERT_OK(virt);
  auto client = make_transport_client();

  for (uint64_t len : {uint64_t{70'000}, uint64_t{256} << 10, uint64_t{512} << 10,
                       (uint64_t{3} << 20) + 12'345}) {
    std::vector<uint8_t> src(len);
    for (size_t i = 0; i < src.size(); ++i)
      src[i] = static_cast<uint8_t>((i * 151 + len) >> 2 ^ i);
    const uint32_t expect = crc32c(src.data(), len);
    for (const auto& desc : {flat.value(), virt.value()}) {
      WireOp put{&desc, desc.remote_base + 777, parse_rkey(desc), src.data(), len};
      put.want_crc = true;
      BT_EXPECT(client->write_batch(&put, 1) == ErrorCode::OK);
      BT_EXPECT_EQ(put.crc, expect);
      std::vector<uint8_t> dst(len, 0);
      WireOp get{&desc, desc.remote_base + 777, parse_rkey(desc), dst.data(), len};
      get.want_crc = true;
      BT_EXPECT(client->read_batch(&get, 1) == ErrorCode::OK);
      BT_EXPECT_EQ(get.crc, expect);
      BT_EXPECT(dst == src);
    }
    BT_EXPECT(std::memcmp(flat_region.data() + 777, src.data(), len) == 0);
    BT_EXPECT(std::memcmp(store.data() + 777, src.data(), len) == 0);
  }
  // A bounds violation on a pipelined op fails cleanly AND leaves the
  // connection stream aligned (every chunk status is drained): the next op
  // on the pooled connection still works.
  const auto& desc = flat.value();
  std::vector<uint8_t> buf(2ull << 20);
  WireOp bad{&desc, desc.remote_base + kRegion - 4096, parse_rkey(desc), buf.data(),
             buf.size()};
  BT_EXPECT(make_transport_client()->read_batch(&bad, 1) == ErrorCode::MEMORY_ACCESS_ERROR);
  WireOp ok{&desc, desc.remote_base + 777, parse_rkey(desc), buf.data(), 70'000};
  ok.want_crc = true;
  BT_EXPECT(make_transport_client()->read_batch(&ok, 1) == ErrorCode::OK);
  BT_EXPECT_EQ(ok.crc, crc32c(flat_region.data() + 777, 70'000));
  server->stop();
}

BTEST(Transport, TcpStagedLaneFourConcurrentClients) {
  // >= 4 client threads against ONE worker through the staged lane (the
  // multi-client contention shape the sharded pool/counters exist for):
  // every thread runs verified batched writes+reads of its own disjoint
  // window, all bytes and fused CRCs must come back exact, and the staged
  // lane must actually have carried the ops.
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  constexpr int kThreads = 4;
  constexpr uint64_t kWindow = 768ull << 10;  // > pipe chunk: chunked subs too
  std::vector<uint8_t> region(kThreads * kWindow, 0);
  auto reg = server->register_region(region.data(), region.size(), "mt");
  BT_ASSERT_OK(reg);
  const auto desc = reg.value();
  const uint64_t rkey = parse_rkey(desc);
  const uint64_t staged_before = tcp_staged_op_count();

  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      auto client = make_transport_client();
      const uint64_t base = desc.remote_base + static_cast<uint64_t>(t) * kWindow;
      std::vector<uint8_t> src(kWindow);
      std::vector<uint8_t> dst(kWindow);
      for (int round = 0; round < 6; ++round) {
        for (size_t i = 0; i < src.size(); ++i)
          src[i] = static_cast<uint8_t>(i * (t + 3) + round * 17);
        const uint32_t expect = crc32c(src.data(), src.size());
        WireOp put{&desc, base, rkey, src.data(), kWindow};
        put.want_crc = true;
        if (client->write_batch(&put, 1) != ErrorCode::OK || put.crc != expect) {
          ++failures;
          continue;
        }
        std::fill(dst.begin(), dst.end(), 0);
        WireOp get{&desc, base, rkey, dst.data(), kWindow};
        get.want_crc = true;
        if (client->read_batch(&get, 1) != ErrorCode::OK || get.crc != expect ||
            dst != src)
          ++failures;
      }
    });
  }
  for (auto& th : threads) th.join();
  BT_EXPECT_EQ(failures.load(), 0);
  BT_EXPECT(tcp_staged_op_count() > staged_before);
  server->stop();
}

BTEST(Transport, TcpBatchFailsFastOnDeadEndpoint) {
  // One unreachable endpoint in a batch must not sink the ops aimed at the
  // live one, and every op to the dead endpoint shares one connect attempt
  // (the per-batch memoization; a preempted worker otherwise costs
  // N x connect-timeout serially).
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(8192, 9);
  auto reg = server->register_region(region.data(), region.size(), "live");
  BT_ASSERT_OK(reg);
  const auto live = reg.value();

  // A port with no listener: loopback connects fail immediately (RST).
  RemoteDescriptor dead;
  dead.transport = TransportKind::TCP;
  {
    uint16_t free_port = 0;
    auto probe = net::tcp_listen("127.0.0.1", 0, &free_port);
    BT_ASSERT_OK(probe);
    dead.endpoint = "127.0.0.1:" + std::to_string(free_port);
  }  // listener closed: the port is dead

  auto client = make_transport_client();
  std::vector<uint8_t> dst(4 * 1024, 0);
  const auto t0 = std::chrono::steady_clock::now();
  WireOp ops[4] = {
      {&dead, 0x1000, 1, dst.data(), 1024},
      {&live, live.remote_base, parse_rkey(live), dst.data() + 1024, 1024},
      {&dead, 0x2000, 1, dst.data() + 2048, 1024},
      {&live, live.remote_base + 1024, parse_rkey(live), dst.data() + 3072, 1024},
  };
  BT_EXPECT(client->read_batch(ops, 4) != ErrorCode::OK);
  const auto elapsed = std::chrono::steady_clock::now() - t0;
  BT_EXPECT(ops[0].status != ErrorCode::OK);
  BT_EXPECT(ops[1].status == ErrorCode::OK);
  BT_EXPECT(ops[2].status != ErrorCode::OK);
  BT_EXPECT(ops[3].status == ErrorCode::OK);
  BT_EXPECT_EQ(int(dst[1024]), 9);  // live reads actually landed
  BT_EXPECT_EQ(int(dst[3072]), 9);
  // Far below any connect-timeout multiple (loopback refusals are instant;
  // the bound guards against serial timeout stacking on regression).
  BT_EXPECT(std::chrono::duration_cast<std::chrono::milliseconds>(elapsed).count() < 2000);
  server->stop();
}

BTEST(Transport, BatchHonorsConcurrencyCap) {
  // max_concurrency=1 serializes the pipeline; the batch must still complete
  // correctly (the cap is a resource bound, not a semantic change).
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(64 * 1024);
  for (size_t i = 0; i < region.size(); ++i) region[i] = static_cast<uint8_t>(i * 3 + 1);
  auto reg = server->register_region(region.data(), region.size(), "cap");
  BT_ASSERT_OK(reg);
  const auto desc = reg.value();
  std::vector<uint8_t> dst(64 * 1024, 0);
  std::vector<WireOp> ops;
  for (size_t j = 0; j < 8; ++j)
    ops.push_back({&desc, desc.remote_base + j * 8192, parse_rkey(desc), dst.data() + j * 8192,
                   8192});
  BT_EXPECT(make_transport_client()->read_batch(ops.data(), ops.size(), 1) == ErrorCode::OK);
  BT_EXPECT(std::memcmp(region.data(), dst.data(), region.size()) == 0);
  server->stop();
}

BTEST(Transport, FaultyClientBatchAppliesPerOpFaults) {
  // The fault injector inherits the default per-op batch loop, so the n-th
  // op of a batch fails exactly as the n-th single op would.
  auto server = make_transport_server(TransportKind::LOCAL);
  BT_ASSERT(server->start("", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(4096, 7);
  auto reg = server->register_region(region.data(), region.size(), "f");
  BT_ASSERT_OK(reg);
  const auto desc = reg.value();
  FaultSpec spec;
  spec.fail_nth_read = 2;
  auto client = make_faulty_transport_client(make_transport_client(), spec);
  std::vector<uint8_t> dst(3 * 64, 0);
  WireOp reads[3] = {
      {&desc, desc.remote_base, parse_rkey(desc), dst.data(), 64},
      {&desc, desc.remote_base + 64, parse_rkey(desc), dst.data() + 64, 64},
      {&desc, desc.remote_base + 128, parse_rkey(desc), dst.data() + 128, 64},
  };
  BT_EXPECT(client->read_batch(reads, 3) == ErrorCode::NETWORK_ERROR);
  BT_EXPECT(reads[0].status == ErrorCode::OK);
  BT_EXPECT(reads[1].status == ErrorCode::NETWORK_ERROR);
  BT_EXPECT(reads[2].status == ErrorCode::OK);
  server->stop();
}

BTEST(Transport, RkeyHexRoundtrip) {
  BT_EXPECT_EQ(rkey_to_hex(0xdeadbeefull), "deadbeef");
  BT_EXPECT_EQ(std::stoull(rkey_to_hex(0x1234567890abcdefull), nullptr, 16),
               0x1234567890abcdefull);
}

// ---- PVM lane (same-host one-sided via process_vm_readv/writev) -----------

BTEST(Transport, PvmSelfProcessServesRegisteredRegionsOneCopy) {
  // Own-process endpoints are the ONE-COPY fast path, gated on the self
  // registry: a writable region the worker registered serves direct fused
  // copies; an unregistered endpoint (stale placement, or a minted string
  // nobody vouched for) must decline writes — pvm_access falls back and
  // the staged lane's rkey check judges it. Retirement closes the lane
  // again BEFORE the memory is freed.
  std::vector<uint8_t> region(8192, 7);
  RemoteDescriptor desc;
  desc.transport = TransportKind::TCP;
  desc.remote_base = 0x4000;
  std::vector<uint8_t> out(64, 0);
  uint8_t val = 0xAB;
  {
    // Unregistered (no generation token): the registry is authoritative for
    // writable self regions — both directions decline (a stale placement
    // must fail over cleanly, not read recycled heap), and the caller falls
    // back to the staged lane.
    RemoteDescriptor unvouched = desc;
    unvouched.pvm_endpoint = pvm_make_endpoint(region.data(), region.size());
    BT_EXPECT(!unvouched.pvm_endpoint.empty());
    BT_EXPECT(!pvm_access(unvouched, 0x4000, &val, 1, /*is_write=*/true, nullptr));
    BT_EXPECT(!pvm_access(unvouched, 0x4000, out.data(), out.size(), false, nullptr));
  }

  const uint64_t gen = pvm_register_self_region(region.data(), region.size());
  BT_EXPECT(gen != 0);
  desc.pvm_endpoint = pvm_make_endpoint(region.data(), region.size(),
                                        /*writable=*/true, gen);
  BT_EXPECT(!desc.pvm_endpoint.empty());
  const uint64_t ops_before = pvm_op_count();
  uint32_t crc = 0;
  BT_EXPECT(pvm_access(desc, 0x4000 + 100, out.data(), out.size(), false, &crc));
  BT_EXPECT(std::all_of(out.begin(), out.end(), [](uint8_t b) { return b == 7; }));
  BT_EXPECT_EQ(crc, crc32c(out.data(), out.size()));
  BT_EXPECT(pvm_access(desc, 0x4000 + 500, &val, 1, /*is_write=*/true, nullptr));
  BT_EXPECT_EQ(int(region[500]), 0xAB);
  BT_EXPECT(pvm_op_count() >= ops_before + 2);
  // Bounds still enforced against the advertised window.
  BT_EXPECT(!pvm_access(desc, 0x4000 + region.size() - 4, out.data(), 64, false, nullptr));

  // Address reuse: a NEW registration at the same base (revived worker whose
  // pool mmap landed on the old address) mints a new generation — the OLD
  // placement's endpoint must mismatch and decline, never address the
  // replacement pool's bytes.
  pvm_retire_self_region(region.data());
  const uint64_t gen2 = pvm_register_self_region(region.data(), region.size());
  BT_EXPECT(gen2 != gen);
  BT_EXPECT(!pvm_access(desc, 0x4000, &val, 1, /*is_write=*/true, nullptr));
  BT_EXPECT(!pvm_access(desc, 0x4000, out.data(), out.size(), false, nullptr));

  pvm_retire_self_region(region.data());
  BT_EXPECT(!pvm_access(desc, 0x4000, &val, 1, /*is_write=*/true, nullptr));
}

BTEST(Transport, PvmCrossProcessRoundtripAndBounds) {
  // Real cross-process: a forked child holds the region (inherited mapping,
  // same vaddr, COW pages) and the parent reads AND writes one-sided with
  // zero child involvement. The child does NO allocation after fork — other
  // test threads may hold the malloc lock at fork time, and a child that
  // mallocs would deadlock.
  constexpr size_t kLen = 256 * 1024;
  std::vector<uint8_t> region(kLen);
  for (size_t i = 0; i < kLen; ++i) region[i] = static_cast<uint8_t>(i * 13 + 5);
  int ack[2];
  BT_ASSERT(::pipe(ack) == 0);
  const pid_t child = ::fork();
  BT_ASSERT(child >= 0);
  if (child == 0) {
    ::close(ack[1]);  // else the parent's close never EOFs the pipe
    // Touch one page so the child has its own COW copy SOMEWHERE — reads
    // still see the pattern, and the parent's one-sided write must land in
    // THIS process's view to flip the exit code.
    region[0] = region[0];
    char c;
    (void)!::read(ack[0], &c, 1);  // park until the parent finishes
    _exit(region[1000] == 0xEE ? 0 : 9);
  }
  ::close(ack[0]);

  RemoteDescriptor desc;
  desc.transport = TransportKind::TCP;  // primary is irrelevant to the lane
  desc.remote_base = 0x1000;            // placements rarely start at 0
  desc.pvm_endpoint = pvm_make_endpoint_for_pid(child, region.data(), kLen);
  BT_EXPECT(!desc.pvm_endpoint.empty());

  std::vector<uint8_t> out(4096, 0);
  uint32_t crc = 0;
  BT_EXPECT(pvm_access(desc, 0x1000 + 512, out.data(), out.size(), false, &crc));
  bool match = true;
  for (size_t i = 0; i < out.size(); ++i)
    if (out[i] != static_cast<uint8_t>((i + 512) * 13 + 5)) match = false;
  BT_EXPECT(match);
  BT_EXPECT_EQ(crc, crc32c(out.data(), out.size()));
  BT_EXPECT(pvm_op_count() >= 1);

  // One-sided write: flip a byte in the child's region, child verifies.
  uint8_t val = 0xEE;
  BT_EXPECT(pvm_access(desc, 0x1000 + 1000, &val, 1, /*is_write=*/true, nullptr));

  // Bounds: past-the-end and before-base are declined (fallback, not UB).
  BT_EXPECT(!pvm_access(desc, 0x1000 + kLen - 10, out.data(), 100, false, nullptr));
  BT_EXPECT(!pvm_access(desc, 0x500, out.data(), 16, false, nullptr));

  // Read-only endpoints (host-view device regions: the backing pointer is
  // provider-generation-dependent) serve one-sided READS but decline
  // writes — those take the staged path, which revalidates the pointer.
  RemoteDescriptor ro = desc;
  ro.pvm_endpoint = pvm_make_endpoint_for_pid(child, region.data(), kLen,
                                              /*writable=*/false);
  BT_EXPECT(pvm_access(ro, 0x1000 + 64, out.data(), 64, false, nullptr));
  BT_EXPECT(!pvm_access(ro, 0x1000 + 64, out.data(), 64, /*is_write=*/true, nullptr));

  ::close(ack[1]);  // release the child; it checks the written byte
  int status = 0;
  BT_ASSERT(::waitpid(child, &status, 0) == child);
  BT_EXPECT(WIFEXITED(status) && WEXITSTATUS(status) == 0);

  // Dead pid: the endpoint now names a reaped process — declined cleanly.
  BT_EXPECT(!pvm_access(desc, 0x1000, out.data(), 16, false, nullptr));
}

BTEST(Transport, PvmRejectsForeignBootAndGarbage) {
  RemoteDescriptor desc;
  desc.remote_base = 0;
  std::vector<uint8_t> out(16, 0);
  desc.pvm_endpoint = "deadbeef00000000000000000000dead:1:12345:1000:10000";
  BT_EXPECT(!pvm_access(desc, 0, out.data(), 16, false, nullptr));
  desc.pvm_endpoint = "not-an-endpoint";
  BT_EXPECT(!pvm_access(desc, 0, out.data(), 16, false, nullptr));
  desc.pvm_endpoint = "";
  BT_EXPECT(!pvm_access(desc, 0, out.data(), 16, false, nullptr));
}
