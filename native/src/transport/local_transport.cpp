// LOCAL transport: process-global region registry + memcpy. The hermetic
// in-process fake SURVEY.md §4 calls for; also the embedded-cluster fast path.
#include <atomic>
#include <cstring>
#include <mutex>
#include <random>
#include <unordered_map>

#include "btpu/common/log.h"
#include "btpu/transport/transport.h"

namespace btpu::transport {

namespace {

struct LocalRegion {
  uint8_t* base;
  uint64_t len;
  uint64_t remote_base;  // advertised == (uintptr_t)base
};

struct LocalRegistry {
  std::mutex mutex;
  std::unordered_map<uint64_t, LocalRegion> by_rkey;
  std::mt19937_64 rng{0x6274707545ull};  // deterministic for debuggability

  static LocalRegistry& instance() {
    static LocalRegistry r;
    return r;
  }
};

class LocalTransportServer : public TransportServer {
 public:
  TransportKind kind() const noexcept override { return TransportKind::LOCAL; }

  ErrorCode start(const std::string&, uint16_t) override { return ErrorCode::OK; }
  void stop() override {
    auto& reg = LocalRegistry::instance();
    std::lock_guard<std::mutex> lock(reg.mutex);
    for (uint64_t rkey : my_rkeys_) reg.by_rkey.erase(rkey);
    my_rkeys_.clear();
  }

  Result<RemoteDescriptor> register_region(void* base, uint64_t len,
                                           const std::string& tag) override {
    if (!base || len == 0) return ErrorCode::INVALID_PARAMETERS;
    auto& reg = LocalRegistry::instance();
    std::lock_guard<std::mutex> lock(reg.mutex);
    uint64_t rkey = reg.rng() | 1;  // nonzero
    while (reg.by_rkey.contains(rkey)) rkey = reg.rng() | 1;
    const uint64_t remote_base = reinterpret_cast<uint64_t>(base);
    reg.by_rkey[rkey] = {static_cast<uint8_t*>(base), len, remote_base};
    my_rkeys_.push_back(rkey);
    RemoteDescriptor d;
    d.transport = TransportKind::LOCAL;
    d.endpoint = "local:" + tag;
    d.remote_base = remote_base;
    d.rkey_hex = rkey_to_hex(rkey);
    return d;
  }

  ErrorCode unregister_region(const RemoteDescriptor& desc) override {
    uint64_t rkey = 0;
    try {
      rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    } catch (...) {
      return ErrorCode::INVALID_PARAMETERS;
    }
    auto& reg = LocalRegistry::instance();
    std::lock_guard<std::mutex> lock(reg.mutex);
    reg.by_rkey.erase(rkey);
    std::erase(my_rkeys_, rkey);
    return ErrorCode::OK;
  }

 private:
  std::vector<uint64_t> my_rkeys_;
};

}  // namespace

// Bounds+rkey-checked access used by the mux client (local kind).
ErrorCode local_access(uint64_t remote_addr, uint64_t rkey, void* buf, uint64_t len,
                       bool is_write) {
  auto& reg = LocalRegistry::instance();
  uint8_t* target = nullptr;
  {
    std::lock_guard<std::mutex> lock(reg.mutex);
    auto it = reg.by_rkey.find(rkey);
    if (it == reg.by_rkey.end()) return ErrorCode::MEMORY_ACCESS_ERROR;
    const LocalRegion& region = it->second;
    if (remote_addr < region.remote_base || remote_addr + len > region.remote_base + region.len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    target = region.base + (remote_addr - region.remote_base);
  }
  if (is_write) {
    std::memcpy(target, buf, len);
  } else {
    std::memcpy(buf, target, len);
  }
  return ErrorCode::OK;
}

std::unique_ptr<TransportServer> make_local_transport_server() {
  return std::make_unique<LocalTransportServer>();
}

}  // namespace btpu::transport
