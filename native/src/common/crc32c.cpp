#include "btpu/common/crc32c.h"

#include "btpu/common/thread_annotations.h"

#include <algorithm>
#include <array>
#include <cstring>
#include <mutex>
#include <shared_mutex>
#include <unordered_map>

#if defined(__x86_64__)
#include <nmmintrin.h>
#include <wmmintrin.h>
#endif

namespace btpu {

namespace {

// Table fallback (single-slice; the hardware path is the one that matters).
struct Crc32cTable {
  std::array<uint32_t, 256> t{};
  Crc32cTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int b = 0; b < 8; ++b) c = (c >> 1) ^ (0x82f63b78u & (0u - (c & 1)));
      t[i] = c;
    }
  }
};

const Crc32cTable& table() {
  static const Crc32cTable tbl;
  return tbl;
}

// ---- GF(2) crc combine (zlib's crc32_combine algorithm, Castagnoli poly).
// crc(X || Y) = shift(crc(X), len(Y)) ^ crc(Y): lets independent chains run
// in parallel and merge afterwards. Operates on RAW (pre-final-xor) crcs.

uint32_t gf2_matrix_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  while (vec) {
    if (vec & 1) sum ^= *mat;
    vec >>= 1;
    ++mat;
  }
  return sum;
}

void gf2_matrix_square(uint32_t* square, const uint32_t* mat) {
  for (int n = 0; n < 32; ++n) square[n] = gf2_matrix_times(mat, mat[n]);
}

// Advances `crc` over len2 zero bytes (then xor the second chain's raw crc).
uint32_t crc32c_shift(uint32_t crc, size_t len2) {
  if (len2 == 0) return crc;
  uint32_t even[32], odd[32];
  odd[0] = 0x82f63b78u;  // reflected CRC32C polynomial
  uint32_t row = 1;
  for (int n = 1; n < 32; ++n) {
    odd[n] = row;
    row <<= 1;
  }
  gf2_matrix_square(even, odd);  // 2 zero bits
  gf2_matrix_square(odd, even);  // 4 zero bits
  do {
    gf2_matrix_square(even, odd);  // 8, 32, 128... zero bits
    if (len2 & 1) crc = gf2_matrix_times(even, crc);
    len2 >>= 1;
    if (len2 == 0) break;
    gf2_matrix_square(odd, even);
    if (len2 & 1) crc = gf2_matrix_times(odd, crc);
    len2 >>= 1;
  } while (len2);
  return crc;
}

#if defined(__x86_64__)
// The crc32 instruction has ~3-cycle latency but 1/cycle throughput: one
// serial chain caps at ~5 GB/s. Three independent chains saturate the unit
// (~3x), merged per fixed-size triplet with a PRECOMPUTED shift operator —
// applying a cached 32-row matrix is 32 xors, vs the ~30us exponentiation
// crc32c_shift pays for an arbitrary length.
constexpr size_t kLane = 4096;

struct ShiftOp {
  uint32_t mat[32];
};

const ShiftOp& lane_shift() {
  static const ShiftOp op = [] {
    ShiftOp s{};
    // Operator for "append kLane zero bytes" = the matrix moving crc(X) to
    // crc(X || 0^kLane): derive one column at a time via crc32c_shift.
    for (int bit = 0; bit < 32; ++bit) s.mat[bit] = crc32c_shift(1u << bit, kLane);
    return s;
  }();
  return op;
}

// One kernel, two modes: kStore=false is the plain 3-lane hash; kStore=true
// fuses a copy into the same pass (each load feeds a store AND the crc32
// unit — a single serial crc chain would throttle the fused pass to the
// instruction's ~5 GB/s latency bound, below memcpy + separate crc).
template <bool kStore>
__attribute__((target("sse4.2"))) uint32_t crc32c_hw_kernel(uint8_t* dst, const uint8_t* src,
                                                            size_t len, uint32_t crc) {
  const ShiftOp& shift = lane_shift();
  while (len >= 3 * kLane) {
    const uint8_t* sa = src;
    const uint8_t* sb = src + kLane;
    const uint8_t* sc = src + 2 * kLane;
    uint32_t a = crc, b = 0, c = 0;
    for (size_t i = 0; i < kLane; i += 8) {
      uint64_t va, vb, vc;
      __builtin_memcpy(&va, sa + i, 8);
      __builtin_memcpy(&vb, sb + i, 8);
      __builtin_memcpy(&vc, sc + i, 8);
      if constexpr (kStore) {
        __builtin_memcpy(dst + i, &va, 8);
        __builtin_memcpy(dst + kLane + i, &vb, 8);
        __builtin_memcpy(dst + 2 * kLane + i, &vc, 8);
      }
      a = static_cast<uint32_t>(_mm_crc32_u64(a, va));
      b = static_cast<uint32_t>(_mm_crc32_u64(b, vb));
      c = static_cast<uint32_t>(_mm_crc32_u64(c, vc));
    }
    crc = gf2_matrix_times(shift.mat, gf2_matrix_times(shift.mat, a) ^ b) ^ c;
    src += 3 * kLane;
    if constexpr (kStore) dst += 3 * kLane;
    len -= 3 * kLane;
  }
  while (len >= 8) {
    uint64_t v;
    __builtin_memcpy(&v, src, 8);
    if constexpr (kStore) {
      __builtin_memcpy(dst, &v, 8);
      dst += 8;
    }
    crc = static_cast<uint32_t>(_mm_crc32_u64(crc, v));
    src += 8;
    len -= 8;
  }
  while (len--) {
    if constexpr (kStore) *dst++ = *src;
    crc = _mm_crc32_u8(crc, *src++);
  }
  return crc;
}

uint32_t crc32c_hw(const uint8_t* p, size_t len, uint32_t crc) {
  return crc32c_hw_kernel<false>(nullptr, p, len, crc);
}

bool have_sse42() {
  static const bool yes = __builtin_cpu_supports("sse4.2");
  return yes;
}

// ---- PCLMUL-folded kernel -------------------------------------------------
// The crc32 instruction serializes on one port: three interleaved chains
// saturate it at ~8 B/cycle, which the 3-lane kernel above reaches. Going
// past that ceiling needs carryless-multiply folding: 8 independent 16-byte
// accumulators, each folded 128 bytes ahead per step (2 clmuls), reduced at
// the end by per-accumulator 128-bit folds and a final crc32-instruction
// pass over the surviving 16 bytes (the fold invariant keeps the remaining
// bytes CRC-equivalent to the whole message, so no Barrett reduction is
// needed). Measured ~23 GB/s vs ~16 for the 3-lane kernel; the fused copy
// variant stores each loaded vector once (~15 GB/s cache-resident).
//
// Constants: in the REFLECTED domain a clmul of two reflected operands
// yields the reflected product shifted down one bit, so the fold-by-T
// constant is reflect64(x^(T-1) mod P) — derived at startup by stepping the
// reflected LFSR (one step = one zero bit appended) from reflect32(x^0),
// then validated implicitly by the differential unit tests.

static uint32_t lfsr_step(uint32_t v) {
  return (v >> 1) ^ (0x82f63b78u & (0u - (v & 1)));
}

static uint64_t fold_constant(uint64_t t_bits) {
  uint32_t v = 0x80000000u;  // reflect32(x^0)
  for (uint64_t i = 0; i < t_bits; ++i) v = lfsr_step(v);
  return static_cast<uint64_t>(v) << 32;  // as a reflected 64-bit operand
}

constexpr int kPclAcc = 8;                      // 16-byte accumulators
constexpr size_t kPclBlock = kPclAcc * 16;      // bytes folded per step
// Below this, fold setup + reduction outweigh the per-byte win.
constexpr size_t kPclMin = 2 * kPclBlock + 16;

struct PclConstants {
  __m128i fold_block;  // fold by kPclBlock bytes
  __m128i fold_128;    // fold by 16 bytes (accumulator reduction)
  PclConstants() {
    fold_block = _mm_set_epi64x(
        static_cast<long long>(fold_constant(kPclBlock * 8 - 1)),
        static_cast<long long>(fold_constant(kPclBlock * 8 + 64 - 1)));
    fold_128 = _mm_set_epi64x(static_cast<long long>(fold_constant(127)),
                              static_cast<long long>(fold_constant(191)));
  }
};

const PclConstants& pcl_constants() {
  static const PclConstants k;
  return k;
}

template <bool kStore>
__attribute__((target("pclmul,sse4.2"))) uint32_t crc32c_pcl_kernel(uint8_t* dst,
                                                                    const uint8_t* src,
                                                                    size_t len, uint32_t crc) {
  const PclConstants& k = pcl_constants();
  __m128i x[kPclAcc];
  for (int i = 0; i < kPclAcc; ++i) {
    x[i] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 16 * i));
    if constexpr (kStore)
      _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + 16 * i), x[i]);
  }
  x[0] = _mm_xor_si128(x[0], _mm_cvtsi64_si128(static_cast<long long>(crc)));
  src += kPclBlock;
  if constexpr (kStore) dst += kPclBlock;
  len -= kPclBlock;
  while (len >= kPclBlock) {
    for (int i = 0; i < kPclAcc; ++i) {
      const __m128i y = _mm_loadu_si128(reinterpret_cast<const __m128i*>(src + 16 * i));
      if constexpr (kStore)
        _mm_storeu_si128(reinterpret_cast<__m128i*>(dst + 16 * i), y);
      x[i] = _mm_xor_si128(
          _mm_xor_si128(_mm_clmulepi64_si128(x[i], k.fold_block, 0x00),
                        _mm_clmulepi64_si128(x[i], k.fold_block, 0x11)),
          y);
    }
    src += kPclBlock;
    if constexpr (kStore) dst += kPclBlock;
    len -= kPclBlock;
  }
  for (int i = 1; i < kPclAcc; ++i) {
    x[i] = _mm_xor_si128(_mm_xor_si128(_mm_clmulepi64_si128(x[i - 1], k.fold_128, 0x00),
                                       _mm_clmulepi64_si128(x[i - 1], k.fold_128, 0x11)),
                         x[i]);
  }
  uint32_t c = 0;
  c = static_cast<uint32_t>(
      _mm_crc32_u64(c, static_cast<uint64_t>(_mm_cvtsi128_si64(x[kPclAcc - 1]))));
  c = static_cast<uint32_t>(
      _mm_crc32_u64(c, static_cast<uint64_t>(_mm_extract_epi64(x[kPclAcc - 1], 1))));
  // Tail (< one block): the plain crc32-instruction kernel finishes it.
  return crc32c_hw_kernel<kStore>(dst, src, len, c);
}

bool have_pclmul() {
  static const bool yes =
      __builtin_cpu_supports("pclmul") && __builtin_cpu_supports("sse4.2");
  return yes;
}
#endif

}  // namespace

uint32_t crc32c(const void* data, size_t len, uint32_t seed) {
  const auto* p = static_cast<const uint8_t*>(data);
  uint32_t crc = ~seed;
#if defined(__x86_64__)
  if (len >= kPclMin && have_pclmul()) return ~crc32c_pcl_kernel<false>(nullptr, p, len, crc);
  if (have_sse42()) return ~crc32c_hw(p, len, crc);
#endif
  const auto& t = table().t;
  for (size_t i = 0; i < len; ++i) crc = (crc >> 8) ^ t[(crc ^ p[i]) & 0xff];
  return ~crc;
}

uint32_t crc32c_copy(void* dst, const void* src, size_t len, uint32_t seed) {
  auto* d = static_cast<uint8_t*>(dst);
  const auto* s = static_cast<const uint8_t*>(src);
#if defined(__x86_64__)
  // Large copies: tile as memcpy-then-hash over cache-resident blocks
  // rather than the store-interleaved kernels. The stores contend with the
  // fold/crc pipeline badly enough on common microarchitectures that one
  // "fused" pass runs ~30% BELOW two passes over an L2-resident tile
  // (measured: 256 KiB fused ~10 GB/s vs tiled ~14, while memcpy alone
  // does ~24 and hash-only ~20). Small copies stay truly fused — the
  // per-tile fixed costs dominate there and everything is L1-resident.
  constexpr size_t kTile = 64 * 1024;
  if (len >= kTile / 2 && have_sse42()) {
    uint32_t crc = seed;
    size_t pos = 0;
    while (pos < len) {
      const size_t n = std::min(kTile, len - pos);
      std::memcpy(d + pos, s + pos, n);
      // Hash the DESTINATION: cache-hot, and it describes the bytes
      // actually delivered even if the (possibly shared) source moves
      // underneath.
      crc = crc32c(d + pos, n, crc);
      pos += n;
    }
    return crc;
  }
  if (len >= kPclMin && have_pclmul()) return ~crc32c_pcl_kernel<true>(d, s, len, ~seed);
  if (have_sse42()) return ~crc32c_hw_kernel<true>(d, s, len, ~seed);
#endif
  std::memcpy(d, s, len);
  // Hash the DESTINATION: cache-hot, and it describes the bytes actually
  // delivered even if the (possibly shared) source moves underneath.
  return crc32c(d, len, seed);
}

uint32_t crc32c_combine(uint32_t crc_a, uint32_t crc_b, uint64_t len_b) {
  if (len_b == 0) return crc_a;
  // The pre/post conditioning cancels through the linear operator, so the
  // identity holds directly on final values:
  //   crc(X || Y) = shift_{|Y|}(crc(X)) ^ crc(Y).
  // Cached operator per length: building one costs a matrix exponentiation,
  // applying one is 32 xors — and shard/chunk lengths repeat heavily, so in
  // steady state every lookup is a read. Reader-writer lock: N client
  // threads folding per-chunk CRCs share the hit path instead of convoying
  // on one mutex per fold.
  static SharedMutex ops_mutex;
  static std::unordered_map<uint64_t, std::array<uint32_t, 32>> ops;
  std::array<uint32_t, 32> op{};
  bool found = false;
  {
    SharedLock lock(ops_mutex);
    if (auto it = ops.find(len_b); it != ops.end()) {
      op = it->second;
      found = true;
    }
  }
  if (!found) {
    // Exponentiate OUTSIDE the lock (tens of us): a new length must not
    // stall concurrent folds of known lengths. A racing duplicate insert
    // computes the same matrix, so either copy winning is fine.
    std::array<uint32_t, 32> m{};
    for (int bit = 0; bit < 32; ++bit)
      m[static_cast<size_t>(bit)] = crc32c_shift(1u << bit, len_b);
    WriterLock lock(ops_mutex);
    if (ops.size() >= 256) ops.clear();  // degenerate workloads only
    ops.emplace(len_b, m);
    op = m;
  }
  return gf2_matrix_times(op.data(), crc_a) ^ crc_b;
}

}  // namespace btpu
