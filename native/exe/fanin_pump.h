// Shared connection fan-in pump for the exe-side harnesses: N nonblocking
// sockets against one data-plane endpoint, each holding exactly one raw
// kOpRead in flight, driven by a single poll loop. Used by `bb-wire
// --fanin` (the bench row) and `bb-soak --fanin` (the kill/revive chaos
// fleet) so a protocol or drain fix lands ONCE — the two pumps diverging
// silently is how a bench stops measuring what the soak exercises.
#pragma once

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <unistd.h>

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

#include "btpu/net/net.h"
#include "btpu/transport/data_wire.h"

namespace btpu::exe {

struct FaninConn {
  net::Socket sock;
  uint64_t recvd{0};  // of the current response (4-byte status + op_len)
};

struct FaninStats {
  uint64_t completed{0};
  size_t dead{0};
};

// Opens up to `want` nonblocking connections; stops early on connect
// failure (fd limit, mid-kill) or when `stop` says so — the caller runs
// with whatever fleet stood up.
inline std::vector<FaninConn> fanin_connect(const std::string& host, uint16_t port,
                                            size_t want,
                                            const std::function<bool()>& stop) {
  std::vector<FaninConn> conns;
  conns.reserve(want);
  for (size_t i = 0; i < want; ++i) {
    if (stop && stop()) break;
    auto s = net::tcp_connect(host, port, 2000);
    if (!s.ok()) break;
    FaninConn c;
    c.sock = std::move(s).value();
    const int fl = ::fcntl(c.sock.fd(), F_GETFL, 0);
    ::fcntl(c.sock.fd(), F_SETFL, fl | O_NONBLOCK);
    conns.push_back(std::move(c));
  }
  return conns;
}

// One read op: rotating-stride offset keeps requests spread across the
// region (4099 is coprime with power-of-two region sizes). 53 bytes into
// an idle socket: never fills the send buffer.
inline bool fanin_send(FaninConn& c, size_t idx, uint64_t remote_base, uint64_t rkey,
                       uint64_t region_len, uint64_t op_len) {
  const uint64_t off = (idx * 4099) % (region_len - op_len);
  transport::datawire::DataRequestHeader hdr{transport::datawire::kOpRead,
                                             remote_base + off, rkey, op_len, 0, 0, 0, 0};
  return net::write_all(c.sock.fd(), &hdr, sizeof(hdr)) == ErrorCode::OK;
}

// Primes one op per connection, then pumps poll->drain->resend until
// `quit(stats)` says stop. Dead connections (peer reset, kill wave) are
// closed and counted, never retried here — rebuild policy is the
// caller's (the bench runs one fleet; the soak rebuilds per chaos wave).
inline FaninStats fanin_pump(std::vector<FaninConn>& conns, uint64_t remote_base,
                             uint64_t rkey, uint64_t region_len, uint64_t op_len,
                             const std::function<bool(const FaninStats&)>& quit) {
  FaninStats st;
  for (size_t i = 0; i < conns.size(); ++i) {
    if (!fanin_send(conns[i], i, remote_base, rkey, region_len, op_len)) {
      conns[i].sock.close();
      ++st.dead;
    }
  }
  const uint64_t resp_len = 4 + op_len;
  std::vector<pollfd> fds(conns.size());
  std::vector<uint8_t> sink(64 * 1024);
  while (!quit(st)) {
    for (size_t i = 0; i < conns.size(); ++i)
      fds[i] = {conns[i].sock.valid() ? conns[i].sock.fd() : -1, POLLIN, 0};
    const int rc = ::poll(fds.data(), static_cast<nfds_t>(fds.size()), 100);
    if (rc <= 0) continue;
    for (size_t i = 0; i < conns.size(); ++i) {
      if (!conns[i].sock.valid()) continue;
      if (!(fds[i].revents & (POLLIN | POLLERR | POLLHUP))) continue;
      for (;;) {
        const uint64_t want = std::min<uint64_t>(resp_len - conns[i].recvd, sink.size());
        const ssize_t n = ::read(conns[i].sock.fd(), sink.data(), want);
        if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          conns[i].sock.close();  // dead conn: drop it, keep the fleet running
          ++st.dead;
          break;
        }
        if (n < 0) break;  // EAGAIN: come back on the next poll round
        conns[i].recvd += static_cast<uint64_t>(n);
        if (conns[i].recvd == resp_len) {
          ++st.completed;
          conns[i].recvd = 0;
          if (!fanin_send(conns[i], i + st.completed, remote_base, rkey, region_len,
                          op_len)) {
            conns[i].sock.close();
            ++st.dead;
            break;
          }
        }
      }
    }
  }
  return st;
}

}  // namespace btpu::exe
