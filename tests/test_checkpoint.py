"""Sharded-array checkpoint/restore through the object store: save on one
mesh layout, restore on another (resharding), replicated-shard dedup."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from blackbird_tpu import EmbeddedCluster
from blackbird_tpu.checkpoint import load_sharded, remove_checkpoint, save_sharded
from blackbird_tpu.parallel import make_mesh
from typing import Any, Generator


@pytest.fixture()
def store() -> Generator[Any, None, None]:
    with EmbeddedCluster(workers=4, pool_bytes=64 << 20) as cluster:
        yield cluster.client()


def test_save_and_restore_same_sharding(store: Any) -> None:
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 16 * 32, dtype=np.float32).reshape(8 * 16, 32), sharding
    )
    save_sharded(store, "ckpt/a", arr)
    back = load_sharded(store, "ckpt/a", sharding=sharding)
    assert back.sharding == sharding
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_restore_onto_different_mesh_layout(store: Any) -> None:
    mesh8 = make_mesh(8)
    arr = jax.device_put(
        np.random.default_rng(5).normal(size=(64, 48)).astype(np.float32),
        NamedSharding(mesh8, P("workers", None)),
    )
    save_sharded(store, "ckpt/reshard", arr)

    # Restore sharded over the SECOND axis on a 4-device mesh.
    mesh4 = make_mesh(4)
    target = NamedSharding(mesh4, P(None, "workers"))
    back = load_sharded(store, "ckpt/reshard", sharding=target)
    assert back.sharding == target
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))

    # And to a plain host array.
    host = load_sharded(store, "ckpt/reshard")
    np.testing.assert_array_equal(host, np.asarray(arr))


def _shard_keys(store: Any, prefix: str) -> list[str]:
    import json

    meta = json.loads(bytes(store.get(prefix + "/meta")))
    return [s["key"] for s in meta["shards"]]


def test_replicated_sharding_stores_one_copy(store: Any) -> None:
    mesh = make_mesh(8)
    replicated = NamedSharding(mesh, P())  # same bytes on every device
    arr = jax.device_put(np.arange(1024, dtype=np.int32), replicated)
    save_sharded(store, "ckpt/rep", arr)
    keys = _shard_keys(store, "ckpt/rep")
    assert len(keys) == 1  # deduplicated: one object for all 8 replicas
    assert store.exists(keys[0])
    back = load_sharded(store, "ckpt/rep", sharding=replicated)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_remove_checkpoint_cleans_all_objects(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.zeros((32, 8), dtype=np.float32), NamedSharding(mesh, P("workers", None))
    )
    save_sharded(store, "ckpt/tmp", arr)
    assert store.exists("ckpt/tmp/meta")
    keys = _shard_keys(store, "ckpt/tmp")
    # An orphan from an interrupted save: written, listed in no meta.
    store.put("ckpt/tmp/shard/999-1000", b"orphan")
    remove_checkpoint(store, "ckpt/tmp")
    assert not store.exists("ckpt/tmp/meta")
    for key in keys:
        assert not store.exists(key)
    assert not store.exists("ckpt/tmp/shard/999-1000")


def test_list_checkpoints_discovers_prefixes(store: Any) -> None:
    from blackbird_tpu.checkpoint import list_checkpoints

    mesh = make_mesh(8)
    arr = jax.device_put(np.zeros(64, dtype=np.float32), NamedSharding(mesh, P()))
    save_sharded(store, "ckpt/step999", arr)
    save_sharded(store, "ckpt/step1000", arr)
    save_sharded(store, "other/x", arr)
    assert list_checkpoints(store, "ckpt/") == ["ckpt/step1000", "ckpt/step999"]
    assert sorted(list_checkpoints(store)) == ["ckpt/step1000", "ckpt/step999", "other/x"]
    # Resume pattern: latest step by PARSED step number (lexicographic max
    # would wrongly pick step999 over step1000).
    latest = max(list_checkpoints(store, "ckpt/"),
                 key=lambda p: int(p.rsplit("step", 1)[1]))
    assert latest == "ckpt/step1000"


def test_int_dtypes_and_odd_shapes(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.random.default_rng(9).integers(-1000, 1000, size=(17, 13, 5),
                                          dtype=np.int16),
        NamedSharding(mesh, P(None)),
    )
    save_sharded(store, "ckpt/odd", arr)
    np.testing.assert_array_equal(load_sharded(store, "ckpt/odd"), np.asarray(arr))


def test_resave_replaces_and_reclaims_stale_shards(store: Any) -> None:
    mesh = make_mesh(8)
    arr8 = jax.device_put(
        np.arange(64 * 8, dtype=np.float32).reshape(64, 8),
        NamedSharding(mesh, P("workers", None)),
    )
    save_sharded(store, "ckpt/resave", arr8)
    first_keys = set(_shard_keys(store, "ckpt/resave"))
    assert len(first_keys) == 8

    # Re-save the (different) array replicated: 1 shard; the 8 old shard
    # objects must be reclaimed, and loads must see the NEW bytes.
    arr_new = jax.device_put(
        np.ones((64, 8), dtype=np.float32), NamedSharding(mesh, P())
    )
    save_sharded(store, "ckpt/resave", arr_new)
    second_keys = set(_shard_keys(store, "ckpt/resave"))
    assert len(second_keys) == 1
    for stale in first_keys - second_keys:
        assert not store.exists(stale)
    np.testing.assert_array_equal(
        load_sharded(store, "ckpt/resave"), np.asarray(arr_new)
    )


def test_scalar_and_zero_d_arrays(store: Any) -> None:
    step = jax.numpy.asarray(12345, dtype=jax.numpy.int32)  # 0-d
    save_sharded(store, "ckpt/step", step)
    assert int(load_sharded(store, "ckpt/step")) == 12345


def test_save_overwrites_orphaned_objects(store: Any) -> None:
    """A crashed previous save can leave shard/meta objects that no readable
    meta lists (or a meta listing shards never written). A fresh save must
    win over both without raising."""
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 4 * 4, dtype=np.float32).reshape(8 * 4, 4), sharding
    )
    # Orphan 1: a shard object under the prefix with stale bytes and no meta.
    index_map = arr.sharding.devices_indices_map(arr.shape)
    from blackbird_tpu.checkpoint import _box_name, _index_to_boxes

    some_box = _box_name(_index_to_boxes(next(iter(index_map.values()))))
    store.put(f"ckpt/orphan/shard/{some_box}", b"\x00" * 64)
    save_sharded(store, "ckpt/orphan", arr)
    np.testing.assert_array_equal(load_sharded(store, "ckpt/orphan"), np.asarray(arr))

    # Orphan 2: meta lists a shard that was never written (partial save);
    # the guarded pre-put remove must absorb the missing object.
    import json

    meta = json.loads(bytes(store.get("ckpt/orphan/meta")))
    meta["shards"].append(
        {"key": "ckpt/orphan/shard/never-written", "boxes": [[0, 1], [0, 4]],
         "shape": [1, 4]}
    )
    store.remove("ckpt/orphan/meta")
    store.put("ckpt/orphan/meta", json.dumps(meta).encode())
    save_sharded(store, "ckpt/orphan", arr)  # must not raise
    np.testing.assert_array_equal(load_sharded(store, "ckpt/orphan"), np.asarray(arr))


def test_each_object_has_single_writer(store: Any) -> None:
    """Multi-host safety invariant (single-process proxy): every shard box
    is written by exactly one owner device, so replicated shards never
    double-put. With 8 devices replicating one box, a save must issue
    exactly one put for it (verified via a counting client wrapper)."""
    mesh = make_mesh(8)
    replicated = NamedSharding(mesh, P())
    arr = jax.device_put(np.arange(256, dtype=np.int32), replicated)

    puts = []

    class Counting:
        def __init__(self, inner: Any) -> None:
            self._inner = inner

        def put(self, key: str, data: Any, **kw: Any) -> None:
            puts.append(key)
            return self._inner.put(key, data, **kw)

        def __getattr__(self, name: str) -> Any:
            return getattr(self._inner, name)

    save_sharded(Counting(store), "ckpt/single", arr)
    shard_puts = [k for k in puts if "/shard/" in k]
    assert len(shard_puts) == 1, shard_puts


def test_checkpoint_onto_ici_device_mesh() -> None:
    """Sharded checkpoint whose bytes live ON the device mesh: save with
    preferred_class=HBM_TPU against an ICI cluster (one JAX device pool per
    chip), then restore under a different sharding. Ties together the
    checkpoint layer, keystone placement, and the ICI device tier."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blackbird_tpu import EmbeddedCluster, StorageClass
    from blackbird_tpu.hbm import JaxHbmProvider
    from blackbird_tpu.native import TransportKind
    from blackbird_tpu.parallel import make_mesh

    provider = JaxHbmProvider(page_bytes=64 * 1024).register()
    try:
        with EmbeddedCluster(workers=8, pool_bytes=8 << 20,
                             storage_class=StorageClass.HBM_TPU,
                             transport=TransportKind.ICI) as cluster:
            client = cluster.client()
            mesh = make_mesh(8)
            arr = jax.device_put(
                np.arange(8 * 64 * 16, dtype=np.float32).reshape(8 * 64, 16),
                NamedSharding(mesh, P("workers", None)),
            )
            save_sharded(client, "ckpt/mesh", arr,
                         preferred_class=StorageClass.HBM_TPU)

            # Every shard object landed on the device tier.
            import json as _json

            meta = _json.loads(bytes(client.get("ckpt/mesh/meta")))
            for shard in meta["shards"]:
                for copy in client.placements(shard["key"]):
                    for s in copy["shards"]:
                        assert s["location"]["kind"] == "device", shard["key"]

            back = load_sharded(client, "ckpt/mesh",
                                sharding=NamedSharding(mesh, P(None, "workers")))
            np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
    finally:
        JaxHbmProvider.unregister()


def test_erasure_coded_checkpoint_roundtrip(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.arange(8192, dtype=np.float32).reshape(64, 128),
        NamedSharding(mesh, P("workers", None)),
    )
    save_sharded(store, "ckpt/ec", arr, ec=(2, 1))
    # Every shard object is one coded copy; the meta stays replicated.
    for obj in store.list("ckpt/ec/shard/"):
        copies = store.placements(obj["key"])
        assert len(copies) == 1 and copies[0]["ec"]["data_shards"] == 2
    # Meta is stored as a degenerate (1, m) code: m+1 single-shard copies
    # on distinct workers — the same loss tolerance as the coded shards.
    meta_ec = store.placements("ckpt/ec/meta")[0]["ec"]
    assert meta_ec["data_shards"] == 1 and meta_ec["parity_shards"] == 1
    back = load_sharded(store, "ckpt/ec", sharding=NamedSharding(mesh, P(None, "workers")))
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
