// Mmap disk tier: one pre-sized backing file mapped read/write, so disk bytes
// are directly addressable (and transport-registrable) host memory.
//
// Parity target: reference src/worker/storage/mmap_disk_backend.cpp
// (create_backing_file :279-298, setup_mmap + MADV_RANDOM :300-325, internal
// PoolAllocator :219-229). Bytes persist across restarts in the backing file.
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <filesystem>

#include "backend_base.h"
#include "btpu/common/log.h"
#include "btpu/common/pool_span.h"

namespace btpu::storage {

class MmapDiskBackend : public OffsetBackendBase {
 public:
  explicit MmapDiskBackend(BackendConfig config) : OffsetBackendBase(std::move(config)) {}
  ~MmapDiskBackend() override { shutdown(); }

  ErrorCode initialize() override {
    if (base_) return ErrorCode::INVALID_STATE;
    if (config_.path.empty()) return ErrorCode::MISSING_REQUIRED_FIELD;

    std::error_code fs_ec;
    std::filesystem::create_directories(
        std::filesystem::path(config_.path).parent_path(), fs_ec);

    int fd = ::open(config_.path.c_str(), O_CREAT | O_RDWR | O_CLOEXEC, 0644);
    if (fd < 0) {
      LOG_ERROR << "mmap backend: open " << config_.path << ": " << std::strerror(errno);
      return ErrorCode::INITIALIZATION_FAILED;
    }
    if (::ftruncate(fd, static_cast<off_t>(config_.capacity)) != 0) {
      ::close(fd);
      return ErrorCode::INSUFFICIENT_SPACE;
    }
    void* base =
        ::mmap(nullptr, config_.capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) {
      LOG_ERROR << "mmap backend: mmap failed: " << std::strerror(errno);
      return ErrorCode::INITIALIZATION_FAILED;
    }
    ::madvise(base, config_.capacity, MADV_RANDOM);
    base_ = static_cast<uint8_t*>(base);
    return init_allocator();
  }

  void shutdown() override {
    if (base_) {
      ::msync(base_, config_.capacity, MS_ASYNC);
      ::munmap(base_, config_.capacity);
      base_ = nullptr;
    }
  }

  void* base_address() const override { return base_; }
  bool persistent() const override { return true; }

  ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    auto span = poolspan::resolve(base_, config_.capacity, offset, len, 0,
                                  poolspan::Access::kWrite, config_.pool_id.c_str());
    if (!span.ok()) return span.error();
    std::memcpy(span.value().data(), src, len);
    return ErrorCode::OK;
  }

  ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    auto span = poolspan::resolve(base_, config_.capacity, offset, len, 0,
                                  poolspan::Access::kRead, config_.pool_id.c_str());
    if (!span.ok()) return span.error();
    std::memcpy(dst, span.value().data(), len);
    return ErrorCode::OK;
  }

 private:
  uint8_t* base_{nullptr};
};

std::unique_ptr<StorageBackend> make_mmap_disk_backend(const BackendConfig& config) {
  return std::make_unique<MmapDiskBackend>(config);
}

}  // namespace btpu::storage
