// Pool-sanitizer tests (btpu/common/poolsan.h; docs/CORRECTNESS.md §12):
// shadow state + generations + red zones + quarantine, the stale-descriptor
// lifecycle through BOTH TCP serving engines, the alloc/free churn hammer,
// and the planted-mutant matrix (overrun / stale_read / double_free — each
// must be CONVICTED deterministically, 3/3 forked replays).
//
// Everything here is inert in release builds (poolsan compiled out): each
// test opens with a compiled_in() gate and prints a skip notice — the
// sanitizer trees (asan/tsan/sched, `make check`'s poolsan-smoke leg) run
// the real thing.
#include <sys/wait.h>
#include <unistd.h>

#if defined(__SANITIZE_ADDRESS__) && __has_include(<sanitizer/asan_interface.h>)
#include <sanitizer/asan_interface.h>
#endif

#include <atomic>
#include <cstring>
#include <thread>
#include <vector>

#include "btest.h"
#include "btpu/alloc/pool_allocator.h"
#include "btpu/client/embedded.h"
#include "btpu/common/env.h"
#include "btpu/common/pool_span.h"
#include "btpu/common/poolsan.h"
#include "btpu/storage/backend.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::alloc;

namespace {

bool poolsan_ready(const char* test) {
  if (poolsan::compiled_in() && poolsan::armed()) return true;
  std::printf("        [skip] %s: poolsan not compiled in/armed (release tree)\n", test);
  return false;
}

// Scoped env var (tests arm knobs/mutants live; poolsan reads env per call).
struct ScopedEnv {
  std::string name;
  ScopedEnv(const char* n, const char* v) : name(n) { ::setenv(n, v, 1); }
  ~ScopedEnv() { ::unsetenv(name.c_str()); }
};

MemoryPool make_pool(const std::string& id, uint64_t size, const RemoteDescriptor& remote) {
  MemoryPool p;
  p.id = id;
  p.node_id = "node-ps";
  p.size = size;
  p.storage_class = StorageClass::RAM_CPU;
  p.remote = remote;
  return p;
}

// A registered region + tracked allocator over one buffer: the minimal
// serving-side fixture (LOCAL or TCP kind).
struct TrackedRegion {
  std::vector<uint8_t> bytes;
  std::unique_ptr<transport::TransportServer> server;
  RemoteDescriptor desc;
  std::unique_ptr<PoolAllocator> pa;
  std::string pool_id;

  ~TrackedRegion() {
    pa.reset();  // shadow released before the host unbinds/frees
    poolsan::unbind_host(pool_id);
    if (server) server->stop();
  }
};

std::unique_ptr<TrackedRegion> make_tracked(TransportKind kind, const std::string& pool_id,
                                            uint64_t size) {
  auto t = std::make_unique<TrackedRegion>();
  t->pool_id = pool_id;
  t->bytes.assign(size, 0);
  t->server = transport::make_transport_server(kind);
  if (t->server->start("127.0.0.1", 0) != ErrorCode::OK) return nullptr;
  auto reg = t->server->register_region(t->bytes.data(), size, pool_id);
  if (!reg.ok()) return nullptr;
  t->desc = std::move(reg).value();
  poolsan::bind_host(pool_id, t->bytes.data(), size);
  t->pa = std::make_unique<PoolAllocator>(make_pool(pool_id, size, t->desc),
                                          /*poolsan_track=*/true);
  return t;
}

ShardPlacement placement_for(const TrackedRegion& t, const Range& r) {
  ShardPlacement s;
  s.pool_id = t.pool_id;
  s.worker_id = "node-ps";
  s.remote = t.desc;
  s.storage_class = StorageClass::RAM_CPU;
  s.length = r.length;
  s.location = t.pa->to_memory_location(r);
  return s;
}

ErrorCode batch_io(transport::TransportClient& client, const ShardPlacement& shard,
                   uint8_t* buf, uint64_t len, bool is_write) {
  transport::WireOp op;
  if (!transport::make_wire_op(shard, 0, buf, len, op)) return ErrorCode::INTERNAL_ERROR;
  return is_write ? client.write_batch(&op, 1) : client.read_batch(&op, 1);
}

// The stale-descriptor lifecycle against ONE serving engine: a client
// caches a placement, the extent is freed (remove/GC shape), and re-reads
// MUST fail STALE_EXTENT-class — never return another object's bytes —
// both while quarantined and after the space is reused under a new
// generation.
void stale_lifecycle_against(TransportKind kind, const std::string& pool_id) {
  auto t = make_tracked(kind, pool_id, 1 << 20);
  BT_ASSERT(t != nullptr);
  auto client = transport::make_transport_client();

  auto r1 = t->pa->allocate(4096);
  BT_ASSERT(r1.has_value());
  ShardPlacement stale = placement_for(*t, *r1);
  const auto* mem = std::get_if<MemoryLocation>(&stale.location);
  BT_ASSERT(mem != nullptr && mem->extent_gen != 0);  // generation stamped

  std::vector<uint8_t> data(4096, 0xAB);
  BT_EXPECT_OK(batch_io(*client, stale, data.data(), data.size(), /*is_write=*/true));
  std::vector<uint8_t> back(4096, 0);
  BT_EXPECT_OK(batch_io(*client, stale, back.data(), back.size(), /*is_write=*/false));
  BT_EXPECT(back == data);

  const auto before = poolsan::counters();
  t->pa->free(*r1, "poolsan-test");

  // Quarantined: the read is convicted, and the buffer keeps its sentinel
  // (the engine answered an error, not bytes).
  std::vector<uint8_t> probe(4096, 0x11);
  const ErrorCode quarantined = batch_io(*client, stale, probe.data(), probe.size(), false);
  BT_EXPECT(quarantined == ErrorCode::STALE_EXTENT);
  BT_EXPECT(probe == std::vector<uint8_t>(4096, 0x11));

  // Drain the quarantine and reuse the space under a NEW generation + new
  // bytes: the stale generation stamp convicts, the neighbor's bytes are
  // never served.
  {
    ScopedEnv q("BTPU_POOLSAN_QUARANTINE_BYTES", "1");  // next free drains all
    auto churn = t->pa->allocate(64);
    BT_ASSERT(churn.has_value());
    t->pa->free(*churn, "churn");
  }
  auto r2 = t->pa->allocate(4096);
  BT_ASSERT(r2.has_value());
  std::vector<uint8_t> fresh(4096, 0xEE);
  ShardPlacement live = placement_for(*t, *r2);
  BT_EXPECT_OK(batch_io(*client, live, fresh.data(), fresh.size(), /*is_write=*/true));

  const ErrorCode reused = batch_io(*client, stale, probe.data(), probe.size(), false);
  BT_EXPECT(reused == ErrorCode::STALE_EXTENT);
  BT_EXPECT(probe == std::vector<uint8_t>(4096, 0x11));  // 0xEE never leaked

  const auto after = poolsan::counters();
  BT_EXPECT(after.stale_generation >= before.stale_generation + 2);
  BT_EXPECT(after.convictions > before.convictions);
  t->pa->free(*r2, "cleanup");
}

}  // namespace

BTEST(Poolsan, ShadowGenerationAndQuarantineBasics) {
  if (!poolsan_ready("ShadowGenerationAndQuarantineBasics")) return;
  auto t = make_tracked(TransportKind::LOCAL, "ps-basics", 1 << 20);
  BT_ASSERT(t != nullptr);

  auto a = t->pa->allocate(4096);
  auto b = t->pa->allocate(4096);
  BT_ASSERT(a.has_value() && b.has_value());
  const auto la = t->pa->to_memory_location(*a);
  const auto lb = t->pa->to_memory_location(*b);
  BT_EXPECT(la.extent_gen != 0 && lb.extent_gen != 0);
  BT_EXPECT(la.extent_gen != lb.extent_gen);  // fresh generation per carve

  // Free parks in quarantine (bytes counted, capacity still reachable).
  const uint64_t free_before = t->pa->total_free();
  t->pa->free(*a, "basics");
  BT_EXPECT(poolsan::counters().quarantine_bytes >= 4096);
  // Quarantined spans (usable + red zone) count as free: no capacity lost.
  BT_EXPECT(t->pa->total_free() >= free_before + 4096);

  // Resolve through the chokepoint: live extent OK, quarantined convicted.
  auto live = poolspan::resolve(t->bytes.data(), t->bytes.size(), b->offset, b->length,
                                lb.extent_gen, poolspan::Access::kRead, t->pool_id.c_str());
  BT_EXPECT_OK(live.error());
  auto dead = poolspan::resolve(t->bytes.data(), t->bytes.size(), a->offset, a->length,
                                la.extent_gen, poolspan::Access::kRead, t->pool_id.c_str());
  BT_EXPECT(dead.error() == ErrorCode::STALE_EXTENT);

  // A wrong-generation stamp on a LIVE extent is convicted too (ABA).
  auto aba = poolspan::resolve(t->bytes.data(), t->bytes.size(), b->offset, b->length,
                               lb.extent_gen + 17, poolspan::Access::kRead,
                               t->pool_id.c_str());
  BT_EXPECT(aba.error() == ErrorCode::STALE_EXTENT);

  // Cross-extent overrun at the access site.
  auto over = poolspan::resolve(t->bytes.data(), t->bytes.size(), b->offset, b->length + 1,
                                0, poolspan::Access::kRead, t->pool_id.c_str());
  BT_EXPECT(over.error() == ErrorCode::MEMORY_ACCESS_ERROR);

  // Capacity is never lost to the quarantine: a pool-sized carve drains it.
  t->pa->free(*b, "basics");
  auto big = t->pa->allocate((1 << 20) - 8192);
  BT_EXPECT(big.has_value());
  if (big) t->pa->free(*big, "basics");
}

BTEST(Poolsan, DoubleFreeIsRefusedAndConvicted) {
  if (!poolsan_ready("DoubleFreeIsRefusedAndConvicted")) return;
  auto t = make_tracked(TransportKind::LOCAL, "ps-dfree", 1 << 20);
  BT_ASSERT(t != nullptr);
  auto a = t->pa->allocate(8192);
  BT_ASSERT(a.has_value());
  const auto before = poolsan::counters();
  const uint64_t free_after_first = [&] {
    t->pa->free(*a, "first");
    return t->pa->total_free();
  }();
  t->pa->free(*a, "second");  // the classic double free: REFUSED
  BT_EXPECT_EQ(poolsan::counters().double_free, before.double_free + 1);
  BT_EXPECT_EQ(t->pa->total_free(), free_after_first);  // free map untouched
}

// Access pin: while a pin is open (an in-flight RMA copy), a free's state
// flip is immediate — the next resolve convicts — but the byte-level
// quarantine poison is deferred, so the bytes the pool already vouched for
// stay readable until the LAST pin drops. This is what keeps the sanctioned
// one-sided-read-vs-free race (docs/BYTE_PATHS.md) from turning into a
// use-after-poison abort under the armed asan tree.
BTEST(Poolsan, AccessPinDefersPoisonUntilLastUnpin) {
  if (!poolsan_ready("AccessPinDefersPoisonUntilLastUnpin")) return;
  auto t = make_tracked(TransportKind::LOCAL, "ps-pin", 1 << 20);
  BT_ASSERT(t != nullptr);
  auto a = t->pa->allocate(4096);
  BT_ASSERT(a.has_value());
  const auto la = t->pa->to_memory_location(*a);

  std::vector<uint8_t> data(4096, 0xAB);
  {
    auto span = poolspan::resolve(t->bytes.data(), t->bytes.size(), a->offset, a->length,
                                  la.extent_gen, poolspan::Access::kWrite,
                                  t->pool_id.c_str());
    BT_ASSERT_OK(span);
    std::memcpy(span.value().data(), data.data(), data.size());
  }

  {
    poolsan::AccessPin outer(t->bytes.data(), t->pool_id.c_str(), t->bytes.size());
    poolsan::AccessPin inner(t->bytes.data(), t->pool_id.c_str(), t->bytes.size());
    t->pa->free(*a, "pinned-free");

    // Detection never weakens: a resolve arriving after the free convicts.
    auto dead = poolspan::resolve(t->bytes.data(), t->bytes.size(), a->offset, a->length,
                                  la.extent_gen, poolspan::Access::kRead,
                                  t->pool_id.c_str());
    BT_EXPECT(dead.error() == ErrorCode::STALE_EXTENT);

    // The in-flight copy window: bytes stay readable (an asan tree would
    // abort right here on the deferred-but-applied poison) and still carry
    // the extent's last contents.
    std::vector<uint8_t> copy(4096, 0);
    std::memcpy(copy.data(), t->bytes.data() + a->offset, copy.size());
    BT_EXPECT(copy == data);

    // One pin down, one still open: the fill stays deferred.
    inner = poolsan::AccessPin();
    std::memcpy(copy.data(), t->bytes.data() + a->offset, copy.size());
    BT_EXPECT(copy == data);
  }

  // Last pin dropped: the quarantine fill applied. On the asan tree reading
  // the bytes now would abort, so probe the poison state instead; on the
  // gcc tree the pattern canary must be in place (verified by the drain).
#if defined(__SANITIZE_ADDRESS__) && __has_include(<sanitizer/asan_interface.h>)
  BT_EXPECT(__asan_region_is_poisoned(t->bytes.data() + a->offset, 4096) != nullptr);
#else
  BT_EXPECT(t->bytes[a->offset] != 0xAB);  // pattern-filled, old bytes gone
#endif

  // The quarantine canary survives its normal verification on the way out
  // (a deferred-then-applied fill must read back as a well-formed canary).
  const auto before = poolsan::counters();
  {
    ScopedEnv q("BTPU_POOLSAN_QUARANTINE_BYTES", "1");
    auto churn = t->pa->allocate(64);
    BT_ASSERT(churn.has_value());
    t->pa->free(*churn, "drain");
  }
  BT_EXPECT_EQ(poolsan::counters().redzone_smash, before.redzone_smash);
}

BTEST(Poolsan, StaleDescriptorThreadEngine) {
  if (!poolsan_ready("StaleDescriptorThreadEngine")) return;
  // Pin the thread-per-connection fallback explicitly.
  ScopedEnv eng("BTPU_IOURING_NET", "0");
  stale_lifecycle_against(TransportKind::TCP, "ps-tcp-thread");
}

BTEST(Poolsan, StaleDescriptorUringEngine) {
  if (!poolsan_ready("StaleDescriptorUringEngine")) return;
  if (!transport::uring_runtime_available()) {
    std::printf("        [skip] io_uring unavailable on this kernel\n");
    return;
  }
  ScopedEnv eng("BTPU_IOURING_NET", "1");
  stale_lifecycle_against(TransportKind::TCP, "ps-tcp-uring");
}

BTEST(Poolsan, StaleDescriptorLocalLane) {
  if (!poolsan_ready("StaleDescriptorLocalLane")) return;
  stale_lifecycle_against(TransportKind::LOCAL, "ps-local");
}

// Cluster-level lifecycle: a client that captured placements before a
// remove must get STALE_EXTENT-class failures when it replays them against
// the data plane — the exact cached-RemoteDescriptor bug class.
BTEST(Poolsan, ClusterRemoveConvictsCapturedPlacements) {
  if (!poolsan_ready("ClusterRemoveConvictsCapturedPlacements")) return;
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(1, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();
  WorkerConfig cfg;
  cfg.replication_factor = 1;

  std::vector<uint8_t> data(128 * 1024);
  for (size_t i = 0; i < data.size(); ++i) data[i] = static_cast<uint8_t>(i * 31 + 7);
  BT_ASSERT(client->put("ps/victim", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto placements = client->get_workers("ps/victim");
  BT_ASSERT_OK(placements);
  BT_ASSERT(!placements.value().empty() && !placements.value()[0].shards.empty());
  const ShardPlacement stale = placements.value()[0].shards[0];

  BT_EXPECT(client->remove("ps/victim") == ErrorCode::OK);
  // Refill the pool so the victim's extent is likely reused with new bytes.
  std::vector<uint8_t> other(128 * 1024, 0x42);
  BT_ASSERT(client->put("ps/squatter", other.data(), other.size(), cfg) == ErrorCode::OK);

  auto raw = transport::make_transport_client();
  std::vector<uint8_t> probe(stale.length, 0x11);
  const ErrorCode ec = batch_io(*raw, stale, probe.data(), probe.size(), /*is_write=*/false);
  BT_EXPECT(ec == ErrorCode::STALE_EXTENT || ec == ErrorCode::MEMORY_ACCESS_ERROR);
  BT_EXPECT(probe == std::vector<uint8_t>(stale.length, 0x11));  // no neighbor bytes
  cluster.stop();
}

// Quarantine-reuse hammer: alloc/free churn with live readers. The
// invariant under the sanitizer is NO false positives — every read of an
// extent its thread still owns succeeds byte-exact — while quarantine
// cycling runs flat out. tsan runs this in the sanitizer suite; the
// Sched.PoolsanQuarantineChurn fixture explores the interleavings.
BTEST(Poolsan, QuarantineReuseHammer) {
  if (!poolsan_ready("QuarantineReuseHammer")) return;
  ScopedEnv q("BTPU_POOLSAN_QUARANTINE_BYTES", "16384");  // cycle hard
  auto t = make_tracked(TransportKind::LOCAL, "ps-hammer", 1 << 20);
  BT_ASSERT(t != nullptr);
  const auto before = poolsan::counters();

  constexpr int kThreads = 4;
  constexpr int kIters = 250;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  threads.reserve(kThreads);
  for (int ti = 0; ti < kThreads; ++ti) {
    threads.emplace_back([&, ti] {
      auto client = transport::make_transport_client();
      for (int i = 0; i < kIters; ++i) {
        const uint64_t len = 512 + static_cast<uint64_t>((ti * 131 + i * 17) % 2048);
        auto r = t->pa->allocate(len);
        if (!r) continue;  // transient pressure is fine; convictions are not
        std::vector<uint8_t> data(len, static_cast<uint8_t>(ti * 16 + (i & 15) + 1));
        ShardPlacement shard = placement_for(*t, *r);
        transport::WireOp op;
        if (!transport::make_wire_op(shard, 0, data.data(), len, op) ||
            client->write_batch(&op, 1) != ErrorCode::OK) {
          failures.fetch_add(1);
        } else {
          std::vector<uint8_t> back(len, 0);
          transport::WireOp rop;
          (void)transport::make_wire_op(shard, 0, back.data(), len, rop);
          if (client->read_batch(&rop, 1) != ErrorCode::OK || back != data)
            failures.fetch_add(1);
        }
        t->pa->free(*r, "hammer");
      }
    });
  }
  for (auto& th : threads) th.join();
  BT_EXPECT_EQ(failures.load(), 0);  // zero false positives under churn
  const auto after = poolsan::counters();
  BT_EXPECT_EQ(after.convictions, before.convictions);
}

// ---- planted-mutant matrix (BTPU_POOLSAN_MUTANT; PR 11 pattern) -----------
// Each mutant re-injects one historical bug class in a FORKED child and
// must be convicted deterministically on every replay. Child exit protocol:
// 42 = convicted via counters/refusal, 7 = the bug went UNDETECTED (fails
// the test), anything else (asan abort on the poisoned red zone) = native
// conviction.

namespace {

constexpr int kConvicted = 42;
constexpr int kUndetected = 7;

// Runs `scenario` in a forked child 3x with the given mutant armed; every
// replay must convict (exit 42, or die under asan's poison).
void run_mutant_replays(const char* mutant, int (*scenario)()) {
  for (int replay = 0; replay < 3; ++replay) {
    const pid_t pid = ::fork();
    BT_ASSERT(pid >= 0);
    if (pid == 0) {
      ::setenv("BTPU_POOLSAN_MUTANT", mutant, 1);
      ::_exit(scenario());
    }
    int status = 0;
    BT_ASSERT(::waitpid(pid, &status, 0) == pid);
    const bool convicted_by_counter = WIFEXITED(status) && WEXITSTATUS(status) == kConvicted;
    const bool convicted_by_sanitizer =
        WIFSIGNALED(status) ||
        (WIFEXITED(status) && WEXITSTATUS(status) != 0 && WEXITSTATUS(status) != kUndetected);
    if (!(convicted_by_counter || convicted_by_sanitizer)) {
      std::printf("        mutant %s replay %d NOT convicted (status 0x%x)\n", mutant,
                  replay, status);
    }
    BT_EXPECT(convicted_by_counter || convicted_by_sanitizer);
  }
}

// Mutant 1: a backend write_at smears one byte past the extent
// (ram_backend.cpp). gcc trees convict the smashed red-zone canary at free;
// asan trees trap the store in the poisoned red zone.
int scenario_overrun() {
  const uint64_t kPool = 1 << 20;
  auto region = std::make_unique<std::vector<uint8_t>>(kPool, 0);
  storage::BackendConfig cfg;
  cfg.pool_id = "ps-mut-overrun";
  cfg.capacity = kPool;
  auto backend = storage::create_ram_backend_with_region(cfg, region->data());
  if (!backend || backend->initialize() != ErrorCode::OK) return kUndetected;

  auto server = transport::make_transport_server(TransportKind::LOCAL);
  if (server->start("", 0) != ErrorCode::OK) return kUndetected;
  auto reg = server->register_region(region->data(), kPool, cfg.pool_id);
  if (!reg.ok()) return kUndetected;
  poolsan::bind_host(cfg.pool_id, region->data(), kPool);
  PoolAllocator pa(make_pool(cfg.pool_id, kPool, reg.value()), /*poolsan_track=*/true);

  auto r = pa.allocate(4096);
  if (!r) return kUndetected;
  std::vector<uint8_t> data(4096, 0x77);
  // The mutant smears data[4096] into the red zone (asan: traps HERE).
  if (backend->write_at(r->offset, data.data(), data.size()) != ErrorCode::OK)
    return kUndetected;
  const auto before = poolsan::counters();
  pa.free(*r, "mut-overrun");        // gcc: canary verify convicts here
  (void)poolsan::scrub_canaries();   // and the scrub hook would, too
  const bool convicted = poolsan::counters().redzone_smash > before.redzone_smash;
  poolsan::unbind_host(cfg.pool_id);
  return convicted ? kConvicted : kUndetected;
}

// Mutant 2: the client memoizes placements and never revalidates across a
// remove (client.cpp get_workers). The reuse read MUST surface a
// STALE_EXTENT-class failure, never another object's bytes.
int scenario_stale_read() {
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(1, 8 << 20));
  if (cluster.start() != ErrorCode::OK) return kUndetected;
  auto client = cluster.make_client();
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  std::vector<uint8_t> data(128 * 1024, 0xA5);
  if (client->put("mut/stale", data.data(), data.size(), cfg) != ErrorCode::OK)
    return kUndetected;
  auto first = client->get("mut/stale");  // memoizes the placements
  if (!first.ok() || first.value() != data) return kUndetected;
  if (client->remove("mut/stale") != ErrorCode::OK) return kUndetected;
  std::vector<uint8_t> other(128 * 1024, 0x42);
  if (client->put("mut/squatter", other.data(), other.size(), cfg) != ErrorCode::OK)
    return kUndetected;

  const auto before = poolsan::counters();
  auto reread = client->get("mut/stale");  // mutant replays the stale memo
  const bool convicted = !reread.ok() &&
                         poolsan::counters().stale_generation > before.stale_generation;
  const bool leaked = reread.ok() && reread.value() == other;  // neighbor bytes!
  cluster.stop();
  if (leaked) return kUndetected;
  return convicted ? kConvicted : kUndetected;
}

// Mutant 3: RangeAllocator::free releases the first range twice. The
// shadow refuses the second free; the pool stays consistent (a follow-up
// put/get round-trips byte-exact).
int scenario_double_free() {
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(1, 8 << 20));
  if (cluster.start() != ErrorCode::OK) return kUndetected;
  auto client = cluster.make_client();
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  std::vector<uint8_t> data(128 * 1024, 0x3C);
  if (client->put("mut/dfree", data.data(), data.size(), cfg) != ErrorCode::OK)
    return kUndetected;
  const auto before = poolsan::counters();
  if (client->remove("mut/dfree") != ErrorCode::OK) return kUndetected;  // double-frees
  if (poolsan::counters().double_free <= before.double_free) {
    cluster.stop();
    return kUndetected;
  }
  // The refused free kept the free map intact: the pool still round-trips.
  std::vector<uint8_t> again(128 * 1024);
  for (size_t i = 0; i < again.size(); ++i) again[i] = static_cast<uint8_t>(i * 13 + 5);
  bool ok = client->put("mut/after", again.data(), again.size(), cfg) == ErrorCode::OK;
  if (ok) {
    auto back = client->get("mut/after");
    ok = back.ok() && back.value() == again;
  }
  cluster.stop();
  return ok ? kConvicted : kUndetected;
}

}  // namespace

BTEST(PoolsanMutants, MutantOverrunConvicted3of3) {
  if (!poolsan_ready("MutantOverrunConvicted3of3")) return;
  run_mutant_replays("overrun", scenario_overrun);
}

BTEST(PoolsanMutants, MutantStaleReadConvicted3of3) {
  if (!poolsan_ready("MutantStaleReadConvicted3of3")) return;
  run_mutant_replays("stale_read", scenario_stale_read);
}

BTEST(PoolsanMutants, MutantDoubleFreeConvicted3of3) {
  if (!poolsan_ready("MutantDoubleFreeConvicted3of3")) return;
  run_mutant_replays("double_free", scenario_double_free);
}
