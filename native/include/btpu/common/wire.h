// Binary wire format for RPC payloads and coordination-store values.
//
// Role parity: the reference serializes RPC structs with YLT struct_pack
// (types.h:19-21, rpc_service.cpp:360-385). YLT is not a dependency here;
// this is our own compact encoding (fixed-width scalars in native byte order;
// a static_assert pins the build to little-endian hosts, which covers every
// TPU VM / x86 / ARM deployment target):
//   scalars    little-endian fixed width
//   string     u32 length + bytes
//   vector<T>  u32 count + elements
//   variant    u8 alternative index + alternative
//   Result<T>  u8 {0=value,1=error} + payload
//   struct     u32 body length + fields (see below)
// Decode is bounds-checked everywhere; a truncated or corrupt frame yields
// false, never UB.
//
// Cross-version evolution (wire format v2): every composite struct is
// size-prefixed and decoded tail-tolerantly — a decoder reads the fields it
// knows, defaults any fields missing from the body (older peer), and skips
// any bytes past the fields it knows (newer peer). Top-level RPC messages
// get the same tail tolerance from the frame length instead of a prefix.
// The evolution rule this buys: APPEND-ONLY — new fields go at the end of a
// struct, never in the middle, and existing field types never change. Under
// that rule a mixed-version fleet (rolling upgrade) interoperates in both
// directions; test_rpc.cpp proves both with hand-framed newer/older peers.
#pragma once

#include <bit>
#include <cstring>
#include <limits>
#include <stdexcept>
#include <string>
#include <type_traits>
#include <vector>

#include "btpu/common/types.h"

namespace btpu::wire {

static_assert(std::endian::native == std::endian::little,
              "btpu wire format requires a little-endian host");

class Writer {
 public:
  std::vector<uint8_t>& buffer() noexcept { return buf_; }
  std::vector<uint8_t> take() noexcept { return std::move(buf_); }
  size_t size() const noexcept { return buf_.size(); }

  void put_bytes(const void* p, size_t n) {
    const auto* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }

  template <typename T>
    requires std::is_arithmetic_v<T> || std::is_enum_v<T>
  void put(T v) {
    put_bytes(&v, sizeof(T));
  }

  void put_string(std::string_view s) {
    if (s.size() > std::numeric_limits<uint32_t>::max())
      throw std::length_error("wire: string exceeds u32 length prefix");
    put<uint32_t>(static_cast<uint32_t>(s.size()));
    put_bytes(s.data(), s.size());
  }

 private:
  std::vector<uint8_t> buf_;
};

// WireReader: THE bounds-checked cursor every raw decode surface in the
// tree parses through (RPC frame trailers, 0xEE control-error frames, the
// packed TCP data-plane headers, WAL/persist record envelopes — and, via
// the Reader subclass below, the whole struct codec). Contract:
//   - every read is validated against the remaining bytes FIRST; a short
//     buffer returns false and moves nothing (truncation is an error, not
//     UB — there is no way to read past `size`);
//   - every accessor is BTPU_NODISCARD, so an unchecked read of hostile
//     bytes is a compile error under -Werror=unused-result;
//   - length/count fields read through length_u32/length_u64, which
//     sanity-cap the value against an explicit ceiling AND the remaining
//     bytes, so a hostile 2^32 count can neither over-allocate nor wrap
//     any downstream `pos + n` arithmetic (cursor math is index-based and
//     checked, never pointer-bumped);
//   - peeks never advance, so probe-then-dispatch decoders (the record
//     envelope) cannot desynchronize the cursor.
class WireReader {
 public:
  WireReader(const void* data, size_t size)
      : data_(static_cast<const uint8_t*>(data)), size_(size) {}
  explicit WireReader(const std::vector<uint8_t>& v) : WireReader(v.data(), v.size()) {}

  size_t remaining() const noexcept { return size_ - pos_; }
  size_t consumed() const noexcept { return pos_; }
  bool exhausted() const noexcept { return pos_ == size_; }
  const uint8_t* cursor() const noexcept { return data_ + pos_; }

  BTPU_NODISCARD bool bytes(void* out, size_t n) noexcept {
    if (remaining() < n) return false;
    std::memcpy(out, data_ + pos_, n);
    pos_ += n;
    return true;
  }

  BTPU_NODISCARD bool u8(uint8_t& out) noexcept { return bytes(&out, 1); }
  BTPU_NODISCARD bool u16(uint16_t& out) noexcept { return bytes(&out, 2); }
  BTPU_NODISCARD bool u32(uint32_t& out) noexcept { return bytes(&out, 4); }
  BTPU_NODISCARD bool u64(uint64_t& out) noexcept { return bytes(&out, 8); }

  // Borrow `n` bytes in place (no copy); the view aliases the input buffer.
  BTPU_NODISCARD bool view(const uint8_t*& out, size_t n) noexcept {
    if (remaining() < n) return false;
    out = data_ + pos_;
    pos_ += n;
    return true;
  }

  BTPU_NODISCARD bool skip(size_t n) noexcept {
    if (remaining() < n) return false;
    pos_ += n;
    return true;
  }

  // Probe without consuming: the envelope/dispatch decoders look before
  // they leap. A short buffer returns false, same as the consuming reads.
  BTPU_NODISCARD bool peek_u8(uint8_t& out) const noexcept { return peek(&out, 1, 0); }
  BTPU_NODISCARD bool peek_u64(uint64_t& out) const noexcept { return peek(&out, 8, 0); }
  BTPU_NODISCARD bool peek_u8_at(uint8_t& out, size_t off) const noexcept {
    return peek(&out, 1, off);
  }

  // Length/count fields from untrusted input: the value must fit BOTH the
  // caller's semantic ceiling and the bytes actually present (each counted
  // element/byte costs >= `min_unit` bytes of input). Rejecting here keeps
  // hostile counts from reaching reserve()/resize() at all.
  BTPU_NODISCARD bool length_u32(uint32_t& out, uint64_t cap, size_t min_unit = 1) noexcept {
    uint32_t n = 0;
    if (!u32(n) || n > cap) return false;
    if (min_unit > 0 && static_cast<uint64_t>(n) > remaining() / min_unit) return false;
    out = n;
    return true;
  }
  BTPU_NODISCARD bool length_u64(uint64_t& out, uint64_t cap, size_t min_unit = 1) noexcept {
    uint64_t n = 0;
    if (!u64(n) || n > cap) return false;
    if (min_unit > 0 && n > remaining() / min_unit) return false;
    out = n;
    return true;
  }

 private:
  BTPU_NODISCARD bool peek(void* out, size_t n, size_t off) const noexcept {
    if (remaining() < off || remaining() - off < n) return false;
    std::memcpy(out, data_ + pos_ + off, n);
    return true;
  }

  const uint8_t* data_;
  size_t size_;
  size_t pos_{0};
};

// Reader: the struct-codec cursor — WireReader's checked core plus the
// typed get<T>/get_string surface the encode/decode overload set uses.
class Reader : public WireReader {
 public:
  using WireReader::WireReader;

  BTPU_NODISCARD bool get_bytes(void* out, size_t n) { return bytes(out, n); }

  template <typename T>
    requires std::is_arithmetic_v<T> || std::is_enum_v<T>
  BTPU_NODISCARD bool get(T& out) {
    return bytes(&out, sizeof(T));
  }

  BTPU_NODISCARD bool get_string(std::string& out) {
    uint32_t n = 0;
    if (!length_u32(n, std::numeric_limits<uint32_t>::max())) return false;
    const uint8_t* p = nullptr;
    if (!view(p, n)) return false;
    out.assign(reinterpret_cast<const char*>(p), n);
    return true;
  }
};

// ---- encode/decode overload set ------------------------------------------

template <typename T>
  requires std::is_arithmetic_v<T> || std::is_enum_v<T>
inline void encode(Writer& w, const T& v) { w.put(v); }
template <typename T>
  requires std::is_arithmetic_v<T> || std::is_enum_v<T>
BTPU_NODISCARD inline bool decode(Reader& r, T& v) { return r.get(v); }

inline void encode(Writer& w, const std::string& s) { w.put_string(s); }
BTPU_NODISCARD inline bool decode(Reader& r, std::string& s) { return r.get_string(s); }

// bool gets an explicit one-byte encoding: raw memcpy into a bool from
// untrusted bytes would create an invalid value representation (UB).
inline void encode(Writer& w, const bool& v) { w.put<uint8_t>(v ? 1 : 0); }
BTPU_NODISCARD inline bool decode(Reader& r, bool& v) {
  uint8_t b = 0;
  if (!r.get(b) || b > 1) return false;
  v = (b == 1);
  return true;
}

template <typename T>
void encode(Writer& w, const std::vector<T>& v);
template <typename T>
BTPU_NODISCARD bool decode(Reader& r, std::vector<T>& v);

template <typename T>
void encode(Writer& w, const Result<T>& res) {
  if (res.ok()) {
    w.put<uint8_t>(0);
    encode(w, res.value());
  } else {
    w.put<uint8_t>(1);
    w.put(res.error());
  }
}

template <typename T>
BTPU_NODISCARD bool decode(Reader& r, Result<T>& out) {
  uint8_t tag = 0;
  if (!r.get(tag)) return false;
  if (tag == 0) {
    T v{};
    if (!decode(r, v)) return false;
    out = Result<T>(std::move(v));
    return true;
  }
  if (tag != 1) return false;  // only {0=value, 1=error} are legal
  ErrorCode ec{};
  if (!r.get(ec)) return false;
  // An "error" arm carrying OK would make ok()==false yet error()==OK,
  // which silently passes `error() != OK` checks — reject the frame.
  if (ec == ErrorCode::OK) return false;
  out = Result<T>(ec);
  return true;
}

// Struct field helpers: encode_fields(w, a, b, c) / decode_fields(r, a, b, c).
inline void encode_fields(Writer&) {}
template <typename T, typename... Rest>
void encode_fields(Writer& w, const T& first, const Rest&... rest) {
  encode(w, first);
  encode_fields(w, rest...);
}
BTPU_NODISCARD inline bool decode_fields(Reader&) { return true; }
template <typename T, typename... Rest>
BTPU_NODISCARD bool decode_fields(Reader& r, T& first, Rest&... rest) {
  return decode(r, first) && decode_fields(r, rest...);
}

// Tail-tolerant variant: a clean end-of-input at a field boundary leaves the
// remaining fields defaulted (older peer omitted them); a partial field is
// still an error (corruption, not version skew).
BTPU_NODISCARD inline bool decode_fields_tail(Reader&) { return true; }
template <typename T, typename... Rest>
BTPU_NODISCARD bool decode_fields_tail(Reader& r, T& first, Rest&... rest) {
  if (r.exhausted()) {
    first = T{};
    return decode_fields_tail(r, rest...);
  }
  return decode(r, first) && decode_fields_tail(r, rest...);
}

// Composite structs on the wire: [u32 body length][fields]. Decoding reads
// the known fields out of the body (missing trailing fields default) and
// skips whatever a newer peer appended after them.
template <typename... Fields>
void encode_struct(Writer& w, const Fields&... fields) {
  auto& buf = w.buffer();
  const size_t at = buf.size();
  w.put<uint32_t>(0);
  encode_fields(w, fields...);
  if (buf.size() - at - 4 > std::numeric_limits<uint32_t>::max())
    throw std::length_error("wire: struct exceeds u32 body length");
  const uint32_t len = static_cast<uint32_t>(buf.size() - at - 4);
  std::memcpy(buf.data() + at, &len, sizeof(len));
}

template <typename... Fields>
BTPU_NODISCARD bool decode_struct(Reader& r, Fields&... fields) {
  uint32_t len = 0;
  if (!r.get(len) || r.remaining() < len) return false;
  Reader body(r.cursor(), len);
  if (!decode_fields_tail(body, fields...)) return false;
  return r.skip(len);
}

// ---- data-model overloads -------------------------------------------------
// All composites are size-prefixed (encode_struct) so appended fields are
// version-tolerant even when the struct is nested inside vectors/messages.

inline void encode(Writer& w, const TopoCoord& t) { encode_struct(w, t.slice_id, t.host_id, t.chip_id); }
BTPU_NODISCARD inline bool decode(Reader& r, TopoCoord& t) { return decode_struct(r, t.slice_id, t.host_id, t.chip_id); }

inline void encode(Writer& w, const RemoteDescriptor& d) {
  encode_struct(w, d.transport, d.endpoint, d.remote_base, d.rkey_hex, d.fabric_addr,
                d.pvm_endpoint, d.data_wire_version);
}
BTPU_NODISCARD inline bool decode(Reader& r, RemoteDescriptor& d) {
  // `pvm_endpoint` appended after fabric_addr; old frames leave it "".
  // `data_wire_version` appended after that; old frames leave it 0
  // (pre-versioned peer — the tcp client refuses those, see types.h).
  return decode_struct(r, d.transport, d.endpoint, d.remote_base, d.rkey_hex, d.fabric_addr,
                       d.pvm_endpoint, d.data_wire_version);
}

// `extent_gen` appended (poolsan generation stamp); old frames leave it 0
// (unstamped — generation validation is skipped, see types.h).
inline void encode(Writer& w, const MemoryLocation& m) {
  encode_struct(w, m.remote_addr, m.rkey, m.size, m.extent_gen);
}
BTPU_NODISCARD inline bool decode(Reader& r, MemoryLocation& m) {
  return decode_struct(r, m.remote_addr, m.rkey, m.size, m.extent_gen);
}

inline void encode(Writer& w, const FileLocation& f) { encode_struct(w, f.file_path, f.file_offset); }
BTPU_NODISCARD inline bool decode(Reader& r, FileLocation& f) { return decode_struct(r, f.file_path, f.file_offset); }

inline void encode(Writer& w, const DeviceLocation& d) {
  encode_struct(w, d.device_id, d.region_id, d.offset, d.size);
}
BTPU_NODISCARD inline bool decode(Reader& r, DeviceLocation& d) {
  return decode_struct(r, d.device_id, d.region_id, d.offset, d.size);
}

inline void encode(Writer& w, const LocationDetail& loc) {
  w.put<uint8_t>(static_cast<uint8_t>(loc.index()));
  std::visit([&w](const auto& alt) { encode(w, alt); }, loc);
}
BTPU_NODISCARD inline bool decode(Reader& r, LocationDetail& loc) {
  uint8_t idx = 0;
  if (!r.get(idx)) return false;
  switch (idx) {
    case 0: { MemoryLocation m; if (!decode(r, m)) return false; loc = m; return true; }
    case 1: { FileLocation f; if (!decode(r, f)) return false; loc = f; return true; }
    case 2: { DeviceLocation d; if (!decode(r, d)) return false; loc = d; return true; }
    default: return false;
  }
}

inline void encode(Writer& w, const ShardPlacement& s) {
  encode_struct(w, s.pool_id, s.worker_id, s.remote, s.storage_class, s.length, s.location);
}
BTPU_NODISCARD inline bool decode(Reader& r, ShardPlacement& s) {
  return decode_struct(r, s.pool_id, s.worker_id, s.remote, s.storage_class, s.length, s.location);
}

inline void encode(Writer& w, const CopyPlacement& c) {
  encode_struct(w, c.copy_index, c.shards, c.ec_data_shards, c.ec_parity_shards,
                c.ec_object_size, c.content_crc, c.shard_crcs, c.inline_data,
                c.cache_version, c.cache_gen, c.cache_lease_ms);
}
BTPU_NODISCARD inline bool decode(Reader& r, CopyPlacement& c) {
  return decode_struct(r, c.copy_index, c.shards, c.ec_data_shards, c.ec_parity_shards,
                       c.ec_object_size, c.content_crc, c.shard_crcs, c.inline_data,
                       c.cache_version, c.cache_gen, c.cache_lease_ms);
}

inline void encode(Writer& w, const PutSlot& s) {
  encode_struct(w, s.slot_key, s.copies);
}
BTPU_NODISCARD inline bool decode(Reader& r, PutSlot& s) {
  return decode_struct(r, s.slot_key, s.copies);
}

inline void encode(Writer& w, const WorkerConfig& c) {
  encode_struct(w, static_cast<uint64_t>(c.replication_factor),
                static_cast<uint64_t>(c.max_workers_per_copy), c.enable_soft_pin,
                c.preferred_node, c.preferred_classes, c.ttl_ms, c.enable_locality_awareness,
                c.prefer_contiguous, static_cast<uint64_t>(c.min_shard_size), c.preferred_slice,
                static_cast<uint64_t>(c.ec_data_shards),
                static_cast<uint64_t>(c.ec_parity_shards), c.preferred_host);
}
BTPU_NODISCARD inline bool decode(Reader& r, WorkerConfig& c) {
  // `preferred_host` was appended after the EC fields shipped; decode_struct's
  // tail tolerance defaults it to -1 for records from older peers.
  uint64_t rf = 0, mw = 0, ms = 0, eck = 0, ecm = 0;
  if (!decode_struct(r, rf, mw, c.enable_soft_pin, c.preferred_node, c.preferred_classes,
                     c.ttl_ms, c.enable_locality_awareness, c.prefer_contiguous, ms,
                     c.preferred_slice, eck, ecm, c.preferred_host))
    return false;
  c.replication_factor = rf;
  c.max_workers_per_copy = mw;
  c.min_shard_size = ms;
  c.ec_data_shards = eck;
  c.ec_parity_shards = ecm;
  return true;
}

inline void encode(Writer& w, const ClusterStats& s) {
  encode_struct(w, s.total_workers, s.total_memory_pools, s.total_objects, s.total_capacity,
                s.used_capacity, s.avg_utilization, s.inline_bytes);
}
BTPU_NODISCARD inline bool decode(Reader& r, ClusterStats& s) {
  return decode_struct(r, s.total_workers, s.total_memory_pools, s.total_objects,
                       s.total_capacity, s.used_capacity, s.avg_utilization, s.inline_bytes);
}

inline void encode(Writer& w, const MemoryPool& p) {
  encode_struct(w, p.id, p.node_id, p.base_addr, p.size, p.used, p.storage_class, p.remote,
                p.topo, p.alignment, p.fabric_addr);
}
BTPU_NODISCARD inline bool decode(Reader& r, MemoryPool& p) {
  // `alignment` and `fabric_addr` were appended after v1 shipped;
  // decode_struct's tail tolerance defaults them for older records.
  return decode_struct(r, p.id, p.node_id, p.base_addr, p.size, p.used, p.storage_class,
                       p.remote, p.topo, p.alignment, p.fabric_addr);
}

inline void encode(Writer& w, const ObjectSummary& o) {
  encode_struct(w, o.key, o.size, o.complete_copies, o.soft_pin);
}
BTPU_NODISCARD inline bool decode(Reader& r, ObjectSummary& o) {
  return decode_struct(r, o.key, o.size, o.complete_copies, o.soft_pin);
}

inline void encode(Writer& w, const BatchPutStartItem& i) {
  encode_struct(w, i.key, i.data_size, i.config, i.content_crc);
}
BTPU_NODISCARD inline bool decode(Reader& r, BatchPutStartItem& i) {
  return decode_struct(r, i.key, i.data_size, i.config, i.content_crc);
}

inline void encode(Writer& w, const CopyShardCrcs& c) { encode_struct(w, c.copy_index, c.crcs); }
BTPU_NODISCARD inline bool decode(Reader& r, CopyShardCrcs& c) { return decode_struct(r, c.copy_index, c.crcs); }

template <typename T>
void encode(Writer& w, const std::vector<T>& v) {
  if (v.size() > std::numeric_limits<uint32_t>::max())
    throw std::length_error("wire: vector exceeds u32 count prefix");
  w.put<uint32_t>(static_cast<uint32_t>(v.size()));
  for (const auto& e : v) encode(w, e);
}

template <typename T>
BTPU_NODISCARD bool decode(Reader& r, std::vector<T>& v) {
  uint32_t n = 0;
  if (!r.get(n)) return false;
  // Guard against hostile counts: each element costs >= 1 byte on the wire.
  if (n > r.remaining()) return false;
  v.clear();
  v.reserve(n);
  for (uint32_t i = 0; i < n; ++i) {
    T e{};
    if (!decode(r, e)) return false;
    v.push_back(std::move(e));
  }
  return true;
}

// ---- request/response structs --------------------------------------------
// X-macro: each RPC struct lists its fields once. Messages are bounded by
// the RPC frame, so they decode tail-tolerantly without a length prefix:
// fields an older peer omitted default, bytes a newer peer appended are
// ignored (the frame decoder never requires exhaustion — from_bytes_lax).
#define BTPU_WIRE_STRUCT(Type, ...)                                   \
  inline void encode(Writer& w, const Type& m) {                      \
    auto& [__VA_ARGS__] = m;                                          \
    encode_fields(w, __VA_ARGS__);                                    \
  }                                                                   \
  BTPU_NODISCARD inline bool decode(Reader& r, Type& m) {             \
    auto& [__VA_ARGS__] = m;                                          \
    return decode_fields_tail(r, __VA_ARGS__);                        \
  }

#define BTPU_WIRE_EMPTY(Type)                       \
  inline void encode(Writer&, const Type&) {}       \
  BTPU_NODISCARD inline bool decode(Reader&, Type&) { return true; }

BTPU_WIRE_STRUCT(ObjectExistsRequest, f0)
BTPU_WIRE_STRUCT(ObjectExistsResponse, f0, f1)
BTPU_WIRE_STRUCT(GetWorkersRequest, f0)
BTPU_WIRE_STRUCT(GetWorkersResponse, f0, f1)
BTPU_WIRE_STRUCT(PutStartRequest, f0, f1, f2, f3)
BTPU_WIRE_STRUCT(PutStartResponse, f0, f1)
BTPU_WIRE_STRUCT(PutCompleteRequest, f0, f1, f2)
BTPU_WIRE_STRUCT(PutCompleteResponse, f0)
BTPU_WIRE_STRUCT(PutCancelRequest, f0)
BTPU_WIRE_STRUCT(PutCancelResponse, f0)
BTPU_WIRE_STRUCT(RemoveObjectRequest, f0)
BTPU_WIRE_STRUCT(RemoveObjectResponse, f0)
BTPU_WIRE_EMPTY(RemoveAllObjectsRequest)
BTPU_WIRE_STRUCT(RemoveAllObjectsResponse, f0, f1)
BTPU_WIRE_STRUCT(DrainWorkerRequest, f0)
BTPU_WIRE_STRUCT(DrainWorkerResponse, f0, f1)
BTPU_WIRE_EMPTY(GetClusterStatsRequest)
BTPU_WIRE_STRUCT(GetClusterStatsResponse, f0, f1)
BTPU_WIRE_EMPTY(GetViewVersionRequest)
BTPU_WIRE_STRUCT(GetViewVersionResponse, f0, f1)
BTPU_WIRE_STRUCT(ListObjectsRequest, f0, f1)
BTPU_WIRE_STRUCT(ListObjectsResponse, f0, f1)
BTPU_WIRE_EMPTY(ListPoolsRequest)
BTPU_WIRE_STRUCT(ListPoolsResponse, f0, f1)
BTPU_WIRE_STRUCT(BatchObjectExistsRequest, f0)
BTPU_WIRE_STRUCT(BatchObjectExistsResponse, f0, f1)
BTPU_WIRE_STRUCT(BatchGetWorkersRequest, f0)
BTPU_WIRE_STRUCT(BatchGetWorkersResponse, f0, f1)
BTPU_WIRE_STRUCT(BatchPutStartRequest, f0)
BTPU_WIRE_STRUCT(BatchPutStartResponse, f0, f1)
BTPU_WIRE_STRUCT(BatchPutCompleteRequest, f0, f1, f2)
BTPU_WIRE_STRUCT(BatchPutCompleteResponse, f0, f1)
BTPU_WIRE_STRUCT(BatchPutCancelRequest, f0)
BTPU_WIRE_STRUCT(BatchPutCancelResponse, f0, f1)
BTPU_WIRE_STRUCT(PutStartPooledRequest, f0, f1, f2, f3)
BTPU_WIRE_STRUCT(PutStartPooledResponse, f0, f1)
BTPU_WIRE_STRUCT(PutCommitSlotRequest, f0, f1, f2, f3, f4, f5, f6, f7)
BTPU_WIRE_STRUCT(PutCommitSlotResponse, f0, f1)
BTPU_WIRE_STRUCT(PutInlineRequest, f0, f1, f2, f3)
BTPU_WIRE_STRUCT(PutInlineResponse, f0)
BTPU_WIRE_STRUCT(PingRequest, f0)
BTPU_WIRE_STRUCT(PingResponse, f0, f1)

#undef BTPU_WIRE_STRUCT
#undef BTPU_WIRE_EMPTY

// Convenience: serialize a whole message to bytes / parse from bytes.
template <typename T>
std::vector<uint8_t> to_bytes(const T& msg) {
  Writer w;
  encode(w, msg);
  return w.take();
}

template <typename T>
BTPU_NODISCARD bool from_bytes(const std::vector<uint8_t>& bytes, T& out) {
  Reader r(bytes);
  return decode(r, out) && r.exhausted();
}

// Message-boundary parse: tolerates trailing bytes a newer peer appended
// after the fields this build knows. Use for RPC frames; from_bytes stays
// strict for contexts where trailing garbage means corruption.
template <typename T>
BTPU_NODISCARD bool from_bytes_lax(const std::vector<uint8_t>& bytes, T& out) {
  Reader r(bytes);
  return decode(r, out);
}

}  // namespace btpu::wire
