// Domain-partitioned error codes.
//
// Parity target: reference include/blackbird/common/error/error_domain.h:14-38 and
// error_codes.h:15-79 — each subsystem owns a 1000-code block and enumerator names
// match the reference API so client code ports unchanged. Implementation is ours.
#pragma once

#include <cstdint>
#include <string_view>

// BTPU_NODISCARD: an error or decode verdict the caller MUST look at.
// Applied at the TYPE level to ErrorCode and Result<T> below — which makes
// every function returning them warn-on-discard automatically, including
// ones written next year — and at the DECLARATION level to bool-returning
// decode/parse/validate functions, whose bool carries the same "did this
// fail" weight but whose type cannot. The whole tree builds with
// -Werror=unused-result (Makefile/CMake), so a dropped ErrorCode is a
// compile error, not a latent bug. Deliberate discards spell it out with
// a (void) cast and a comment saying why ignoring is correct.
// scripts/btpu_lint.py enforces both the type-level attributes and the
// per-declaration sweep.
#if defined(__has_cpp_attribute)
#if __has_cpp_attribute(nodiscard)
#define BTPU_NODISCARD [[nodiscard]]
#endif
#endif
#ifndef BTPU_NODISCARD
#define BTPU_NODISCARD
#endif

namespace btpu {

enum class Domain : uint32_t {
  SUCCESS = 0,
  SYSTEM = 1000,
  STORAGE = 2000,
  NETWORK = 3000,
  COORDINATION = 4000,
  DATA = 5000,
  CLIENT = 6000,
  CONFIG = 7000,
};

constexpr uint32_t domain_base(Domain d) noexcept { return static_cast<uint32_t>(d); }

enum class BTPU_NODISCARD ErrorCode : uint32_t {
  OK = 0,

  // System (1000-1999)
  INTERNAL_ERROR = domain_base(Domain::SYSTEM),
  INITIALIZATION_FAILED,
  INVALID_STATE,
  OPERATION_TIMEOUT,
  RESOURCE_EXHAUSTED,
  NOT_IMPLEMENTED,
  // Appended (wire append-only rule): the request's end-to-end deadline
  // budget was spent — retrying is pointless unless the caller extends it.
  DEADLINE_EXCEEDED,
  // Appended: the server shed the request under overload before doing any
  // work. Safe to retry for EVERY method (mutations included — shed happens
  // before dispatch), after the backoff hint that rides the rejection.
  RETRY_LATER,

  // Storage (2000-2999)
  BUFFER_OVERFLOW = domain_base(Domain::STORAGE),
  OUT_OF_MEMORY,
  MEMORY_POOL_NOT_FOUND,
  MEMORY_POOL_ALREADY_EXISTS,
  INVALID_MEMORY_POOL,
  ALLOCATION_FAILED,
  INSUFFICIENT_SPACE,
  MEMORY_ACCESS_ERROR,
  // Appended (wire append-only rule): a pool access through a descriptor
  // whose extent has since been freed/quarantined/reused — the placement's
  // generation stamp no longer matches the extent's (btpu::poolsan). The
  // access was convicted at the resolve site instead of served as a
  // neighbor object's bytes; the caller must re-fetch placements.
  STALE_EXTENT,

  // Network (3000-3999)
  NETWORK_ERROR = domain_base(Domain::NETWORK),
  CONNECTION_FAILED,
  TRANSFER_FAILED,
  TRANSPORT_ERROR,  // generalizes the reference's UCX_ERROR to any transport
  INVALID_ADDRESS,
  REMOTE_ENDPOINT_ERROR,
  RPC_FAILED,

  // Coordination (4000-4999)
  COORD_ERROR = domain_base(Domain::COORDINATION),  // reference: ETCD_ERROR
  COORD_KEY_NOT_FOUND,
  COORD_TRANSACTION_FAILED,
  COORD_LEASE_ERROR,
  COORD_WATCH_ERROR,
  LEADER_ELECTION_FAILED,
  SERVICE_REGISTRATION_FAILED,
  NOT_LEADER,  // mutation sent to a standby keystone; retry against the leader
  // Fencing-token mismatch: a mutation carried an election epoch older than
  // the current leader's — the writer was deposed (split-brain window) and
  // must step down instead of retrying.
  FENCED,

  // Data (5000-5999)
  OBJECT_NOT_FOUND = domain_base(Domain::DATA),
  OBJECT_ALREADY_EXISTS,
  INVALID_KEY,
  INVALID_WORKER,
  WORKER_NOT_READY,
  NO_COMPLETE_WORKER,
  WORKER_DRAIN_INCOMPLETE,  // some copies could not migrate; worker kept, retry
  DATA_CORRUPTION,
  CHECKSUM_MISMATCH,

  // Client (6000-6999)
  CLIENT_ERROR = domain_base(Domain::CLIENT),
  CLIENT_NOT_FOUND,
  CLIENT_ALREADY_EXISTS,
  CLIENT_DISCONNECTED,
  SESSION_EXPIRED,
  INVALID_CLIENT_STATE,
  // Appended (wire append-only rule): an async op/batch was cancelled
  // before its remaining stages ran (client op core, btpu/client/op_core.h).
  OPERATION_CANCELLED,

  // Config (7000-7999)
  CONFIG_ERROR = domain_base(Domain::CONFIG),
  INVALID_CONFIGURATION,
  INVALID_PARAMETERS,
  MISSING_REQUIRED_FIELD,
  VALUE_OUT_OF_RANGE,
};

constexpr Domain error_domain(ErrorCode code) noexcept {
  const auto v = static_cast<uint32_t>(code);
  if (v < 1000) return Domain::SUCCESS;
  return static_cast<Domain>((v / 1000) * 1000);
}

constexpr bool is_ok(ErrorCode code) noexcept { return code == ErrorCode::OK; }

// Short symbolic name, e.g. "OBJECT_NOT_FOUND".
std::string_view to_string(ErrorCode code) noexcept;
// One-line human description.
std::string_view describe(ErrorCode code) noexcept;
std::string_view domain_name(Domain d) noexcept;

}  // namespace btpu
