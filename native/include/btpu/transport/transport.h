// Pluggable one-sided data-plane transport.
//
// Parity target: reference include/blackbird/transport/ucx_engine.h:17-55 +
// src/transport/ucx_engine.cpp (worker side: register_memory -> {remote_addr,
// rkey} descriptor, listener) and the client-side UCX helpers inside
// src/client/blackbird_client.cpp:128-202 (endpoint create, put/get, wait).
// The reference hard-codes UCX in four places; here the transport is an
// interface with three wire implementations:
//   * LOCAL — same-process registry, memcpy (hermetic tests, embedded cluster)
//   * TCP   — sockets; dev fallback and the DCN inter-slice path. The client
//     side pools connections per endpoint, fixing the reference's
//     per-transfer endpoint creation + busy-wait spin
//     (blackbird_client.cpp:162-200, flagged in SURVEY §7 hard parts).
//   * SHM   — POSIX shared memory for same-host zero-copy (the TPU-VM-local
//     tier; clients map the worker's region and address it directly).
// The HBM tier registers DeviceLocation regions served by the HBM provider
// (see storage/hbm_backend.h) rather than flat remote addresses.
//
// Contract notes (mirrors UCX semantics the reference relies on):
//   * register_region advertises {endpoint, remote_base, rkey}; placements
//     embed absolute remote_addr = remote_base + allocator offset
//     (reference range_allocator.cpp:125-131);
//   * clients read/write with no per-op worker involvement;
//   * every access is validated against the registered region bounds + rkey.
#pragma once

#include <atomic>
#include <functional>
#include <memory>

// The transport interfaces are stateless, but every implementation guards
// registries/pools/staging with the annotated mutexes; pulling the
// annotation macros in here keeps all transport TUs on one idiom.
#include "btpu/common/deadline.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/common/types.h"

namespace btpu::transport {

// Accessors for regions without a flat host mapping (io_uring files, HBM
// device memory): the transport server forwards one-sided ops to these.
using RegionReadFn = std::function<ErrorCode(uint64_t offset, void* dst, uint64_t len)>;
using RegionWriteFn = std::function<ErrorCode(uint64_t offset, const void* src, uint64_t len)>;
// Device-fabric hooks for callback-backed device regions (hbm_provider v4):
// offer stages a range for one cross-process pull under a transfer id; pull
// fetches an offered range from a remote fabric address straight into this
// region — on TPU the bytes ride the chip fabric, never this transport.
using RegionOfferFn =
    std::function<ErrorCode(uint64_t offset, uint64_t len, uint64_t transfer_id)>;
using RegionPullFn = std::function<ErrorCode(const std::string& remote_fabric_addr,
                                             uint64_t transfer_id, uint64_t offset,
                                             uint64_t len)>;

// Worker side: owns registered regions and (for wire transports) a listener.
class TransportServer {
 public:
  virtual ~TransportServer() = default;

  virtual TransportKind kind() const noexcept = 0;
  // Starts the listener (no-op for LOCAL/SHM). port 0 picks ephemeral.
  virtual ErrorCode start(const std::string& host, uint16_t port) = 0;
  virtual void stop() = 0;
  // Registers [base, base+len) for one-sided remote access. `tag` names the
  // region (pool id) — SHM uses it as the segment name.
  virtual Result<RemoteDescriptor> register_region(void* base, uint64_t len,
                                                   const std::string& tag) = 0;
  virtual ErrorCode unregister_region(const RemoteDescriptor& desc) = 0;
  // Transports whose regions must live in transport-owned memory (SHM
  // segments) allocate it here; nullptr means caller-owned memory is fine
  // and the caller should malloc/mmap itself, then register_region it.
  virtual void* alloc_region(uint64_t len, const std::string& tag) {
    (void)len;
    (void)tag;
    return nullptr;
  }
  // Registers a callback-backed region (addresses are offsets starting at the
  // descriptor's remote_base = 0). Supported by LOCAL and TCP; SHM regions
  // are memory by definition.
  virtual Result<RemoteDescriptor> register_virtual_region(uint64_t len, const std::string& tag,
                                                           RegionReadFn read_fn,
                                                           RegionWriteFn write_fn) {
    (void)len;
    (void)tag;
    (void)read_fn;
    (void)write_fn;
    return ErrorCode::NOT_IMPLEMENTED;
  }
  // Attaches device-fabric hooks to an already-registered (virtual) region.
  // Transports that cannot serve fabric commands ignore this (the keystone
  // falls back to the staged host lane).
  virtual ErrorCode attach_fabric(const RemoteDescriptor& desc, RegionOfferFn offer_fn,
                                  RegionPullFn pull_fn) {
    (void)desc;
    (void)offer_fn;
    (void)pull_fn;
    return ErrorCode::NOT_IMPLEMENTED;
  }
  // Exposes a virtual region's backing FILE to the transport (disk tiers:
  // region offset == file offset on the flat backing file). The uring data
  // plane then serves reads by submitting the disk read on the SAME ring
  // as the socket ops — no callback thread, no staging segment. `odirect`
  // flags an O_DIRECT fd (the engine 512-aligns its window). The fd is
  // BORROWED: the backend keeps it open until after the transport stops.
  // Transports without a ring engine ignore this (callback path serves).
  virtual ErrorCode attach_direct_io(const RemoteDescriptor& desc, int fd, bool odirect) {
    (void)desc;
    (void)fd;
    (void)odirect;
    return ErrorCode::NOT_IMPLEMENTED;
  }
  // Diagnostics: live data-plane connections (uring engine) or live
  // serving threads (thread-per-connection fallback). Tests use it to pin
  // the fan-in shape (thousands of conns, zero per-conn threads) and the
  // fallback's thread-reaping bound.
  virtual size_t debug_connection_count() const { return 0; }
};

// One wire-level one-sided transfer in a batch. Always flat addressing
// (MemoryLocation-style, including virtual regions); device shards batch
// through shard_io_batch instead.
// Dialect of the tcp data plane's raw packed framing (DataRequestHeader /
// StagedFrame — no length prefix, so no tail tolerance). Bump on ANY layout
// change to those headers. Advertised in RemoteDescriptor::data_wire_version
// at region registration; the tcp client refuses a POSITIVE mismatch
// (v != 0 && v != ours) before the first byte goes out, so a mixed-version
// client/worker pair fails fast with REMOTE_ENDPOINT_ERROR instead of
// desyncing the stream. 0 (pre-versioned metadata: legacy peers, WAL-restored
// placements) is served on the documented both-sides-ship-together contract.
// v2: trace_id/span_id appended to DataRequestHeader (29 -> 45 bytes).
// v3: extent_gen (poolsan generation stamp) appended (45 -> 53 bytes).
inline constexpr uint32_t kTcpDataWireVersion = 3;

struct WireOp {
  const RemoteDescriptor* remote{nullptr};
  uint64_t addr{0};
  uint64_t rkey{0};
  uint8_t* buf{nullptr};
  uint64_t len{0};
  ErrorCode status{ErrorCode::OK};  // per-op result, set by the batch call
  // Ops with want_crc get `crc` = crc32c of the op's bytes, computed by
  // the transport WHILE they move (per-segment during socket drains, fused
  // with the staging-segment copy in both directions) instead of by a
  // second client pass — verified reads check and puts stamp their shard
  // CRCs with ~no extra sweep of the bytes.
  bool want_crc{false};
  uint32_t crc{0};
  // End-to-end deadline for this op (default infinite). Stamped by
  // make_wire_op from the ambient per-op deadline on the CALLING thread
  // (fan-out worker threads read it from here, never from the thread-local).
  // The TCP engine propagates the remaining budget on every request header
  // it issues, skips sub-ops whose budget is already spent
  // (DEADLINE_EXCEEDED locally), and the serving side aborts chunks whose
  // budget expired in flight.
  Deadline deadline{};
  // Distributed-trace context, stamped alongside the deadline (same
  // calling-thread rule — fan-out threads must never read the ambient
  // thread-local). Propagated on every TCP request header this op issues;
  // 0 = untraced.
  uint64_t trace_id{0};
  uint64_t span_id{0};
  // Pool-sanitizer generation stamp of the extent this op addresses
  // (copied from MemoryLocation::extent_gen by make_wire_op). Rides every
  // TCP request header and the local/shm/pvm resolve paths; the serving
  // side validates it against the pool's shadow state in -DBTPU_POOLSAN
  // trees. 0 = unstamped.
  uint64_t extent_gen{0};
};

// Client side: one-sided read/write against any advertised descriptor.
// Thread-safe; concurrent calls proceed in parallel (pooled connections).
class TransportClient {
 public:
  virtual ~TransportClient() = default;
  virtual ErrorCode read(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey,
                         void* dst, uint64_t len) = 0;
  virtual ErrorCode write(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey,
                          const void* src, uint64_t len) = 0;

  // Batched one-sided ops. The mux implementation pipelines TCP ops: every
  // request is issued before any response is awaited, so a batch of n
  // transfers costs ~one round-trip of latency instead of n and needs no
  // fan-out threads (the reference instead paid a std::async thread per
  // shard, blackbird_client.cpp:250-267). Every op is attempted; per-op
  // results land in op.status and the first failure is returned.
  // `max_concurrency` caps in-flight requests (connections per batch);
  // 0 = transport default.
  virtual ErrorCode read_batch(WireOp* ops, size_t n, size_t max_concurrency = 0);
  virtual ErrorCode write_batch(WireOp* ops, size_t n, size_t max_concurrency = 0);

  // Device-fabric commands against a worker's callback-backed device region
  // (RegionOfferFn/RegionPullFn on the server side). The command rides the
  // control lane; the PAYLOAD rides the device fabric between the two
  // worker processes. NOT_IMPLEMENTED = no fabric on this transport — the
  // caller stages through the host lane instead.
  virtual ErrorCode fabric_offer(const RemoteDescriptor& remote, uint64_t addr, uint64_t rkey,
                                 uint64_t len, uint64_t transfer_id) {
    (void)remote, (void)addr, (void)rkey, (void)len, (void)transfer_id;
    return ErrorCode::NOT_IMPLEMENTED;
  }
  virtual ErrorCode fabric_pull(const RemoteDescriptor& remote, uint64_t addr, uint64_t rkey,
                                uint64_t len, uint64_t transfer_id,
                                const std::string& src_fabric_addr) {
    (void)remote, (void)addr, (void)rkey, (void)len, (void)transfer_id,
        (void)src_fabric_addr;
    return ErrorCode::NOT_IMPLEMENTED;
  }
};

// Factory: server for one kind; mux client that routes on descriptor kind.
std::unique_ptr<TransportServer> make_transport_server(TransportKind kind);
std::unique_ptr<TransportClient> make_transport_client();

// Fault injection for hermetic failure-path tests (the reference has no
// fault injection of any kind, SURVEY §5): wraps a client and fails the
// n-th read/write exactly once with the given error, and/or persistently
// fails every op aimed at one endpoint (a dead replica/worker), and/or
// injects LATENCY (fixed + jitter per op) so slow-worker scenarios — the
// tail-at-scale failure mode — are testable, not just hard errors.
struct FaultSpec {
  uint32_t fail_nth_write{0};  // 1-based op count; 0 = never fail
  uint32_t fail_nth_read{0};
  std::string fail_endpoint;   // every op on this endpoint fails; "" = off
  ErrorCode error{ErrorCode::NETWORK_ERROR};
  // Injected latency: every matching op sleeps latency_ms plus uniform
  // [0, latency_jitter_ms] BEFORE executing. latency_endpoint narrows the
  // injection to one endpoint ("" = all ops) — "one slow worker" is
  // latency_endpoint = that worker's pool endpoint.
  uint32_t latency_ms{0};
  uint32_t latency_jitter_ms{0};
  std::string latency_endpoint;
  // Dynamic override (chaos harnesses): when set, the value read per op
  // REPLACES latency_ms, so a soak's chaos thread can spike and clear a
  // worker's latency mid-run without swapping transports under I/O.
  std::shared_ptr<const std::atomic<uint32_t>> latency_override_ms;
};
std::unique_ptr<TransportClient> make_faulty_transport_client(
    std::unique_ptr<TransportClient> inner, FaultSpec spec);

// One shard-range transfer dispatched on the placement's location kind:
// MemoryLocation through `client`'s one-sided path, DeviceLocation through
// the in-process HBM provider (HBM-kind placements only exist for pools in
// this process). `in_off` is a byte offset within the shard. Single home for
// this dispatch — shared by the client SDK and keystone's repair/demotion
// data movers so new location kinds cannot diverge between them.
ErrorCode shard_io(TransportClient& client, const ShardPlacement& shard, uint64_t in_off,
                   uint8_t* buf, uint64_t len, bool is_write);

// Reads or writes [obj_off, obj_off+len) of one copy through its shards
// (running-offset walk; partial-shard access offsets into the registered
// region). Shared by the client SDK's split-replica reads and keystone's
// repair/demotion movers.
ErrorCode copy_range_io(TransportClient& client, const CopyPlacement& copy, uint64_t obj_off,
                        uint8_t* buf, uint64_t len, bool is_write);

// Flattens one wire shard access into a WireOp. Returns false for location
// kinds with no flat client data path (FileLocation is worker-served;
// DeviceLocation batches through shard_io_batch).
bool make_wire_op(const ShardPlacement& shard, uint64_t in_off, uint8_t* buf, uint64_t len,
                  WireOp& op);

// Appends WireOps covering [obj_off, obj_off+len) of one copy (the
// running-offset walk of copy_range_io, emitting ops instead of moving
// bytes; buf points at the range start). Returns false when a shard in
// range is not flat-addressable.
bool append_range_wire_ops(const CopyPlacement& copy, uint64_t obj_off, uint64_t len,
                           uint8_t* buf, std::vector<WireOp>& ops);

// One element of a multi-shard transfer (buf already points at this shard's
// slice of the object buffer).
struct ShardJob {
  const ShardPlacement* shard{nullptr};
  uint64_t in_off{0};
  uint8_t* buf{nullptr};
  uint64_t len{0};
};

// Moves every job in one logical transfer. DeviceLocation jobs are coalesced
// into a single HBM-provider batch call (device links pay per-op latency —
// one PJRT call per batch instead of per shard, see hbm_provider.h v2);
// every other location kind goes through shard_io one by one. Callers that
// want wire-transport parallelism should fan the non-device jobs out
// themselves (client.cpp run_parallel does) and pass only device jobs here.
ErrorCode shard_io_batch(TransportClient& client, const ShardJob* jobs, size_t n,
                         bool is_write);

// Formats/parses rkey hex (shared by transports and allocator tests).
std::string rkey_to_hex(uint64_t rkey);

// Number of data-plane ops this process served through the same-host
// shm-staged TCP lane (diagnostics: benches + tests assert the lane engages).
uint64_t tcp_staged_op_count() noexcept;
// Lane accounting for the copies-per-byte scoreboard (bb-bench / bench.py):
// bytes moved over the staged lane (2 user-space copies per byte), and
// ops/bytes over the plain streaming socket lane (1 user-space copy client-
// side plus the kernel socket path). The pvm lane's counterparts live in
// pvm_op_count/pvm_byte_count below (1 copy per byte).
uint64_t tcp_staged_byte_count() noexcept;
uint64_t tcp_stream_op_count() noexcept;
uint64_t tcp_stream_byte_count() noexcept;
// Server-side stream lane: reads answered straight off registered pool
// pages (single gather write, ZERO worker-side staging copies) — by the
// uring engine's pool-direct sends and the fallback server's write_iov2
// path alike. The pair proves the one-copy claim for remote gets: total
// user-space copies = the client's fused drain, nothing on the worker.
uint64_t tcp_pool_direct_op_count() noexcept;
uint64_t tcp_pool_direct_byte_count() noexcept;
// SEND_ZC completions by kernel verdict (engine only, REPORT_USAGE
// notifs): sent = transmitted straight from pool pages, copied = the
// kernel privately copied first (loopback always lands here — sustained
// copied on a real NIC means the ZC lane is a net loss; alert on it, see
// docs/OPERATIONS.md). Both 0 when ZC is off (BTPU_IOURING_ZC=0, payloads
// under BTPU_ZC_THRESHOLD, kernels without SEND_ZC, or the fallback
// server).
uint64_t tcp_zerocopy_sent_count() noexcept;
uint64_t tcp_zerocopy_copied_count() noexcept;
// Live io_uring event-loop threads serving TCP data planes in this process
// (0 = every server is on the thread-per-connection fallback). Defined in
// net/uring_engine.cpp.
size_t uring_active_loop_count() noexcept;
// Whether a TCP server started NOW would run the uring engine: env gate
// (BTPU_FORCE_NO_URING) + a runtime io_uring probe. Tests and benches use
// it to know which engine they are measuring.
bool uring_runtime_available();

// Shared data-path worker pool (tcp_transport.cpp): runs fn(0..n-1) across
// the pool plus the calling thread and returns when all calls completed.
// Used for shard-parallel striped fetches and parallel memory-lane copies;
// degrades to the caller's inline loop on single-core machines
// (wire_parallel_capacity() == 0).
void wire_parallel_for(size_t n, const std::function<void(size_t)>& fn);
size_t wire_parallel_capacity() noexcept;
// The size the pool runs (or would run) at, WITHOUT instantiating it —
// the metrics/capi accessor: a /metrics scrape on a control-plane-only
// process must not spawn data-path worker threads as a side effect.
size_t wire_pool_threads_resolved() noexcept;

// PVM lane (same-host one-sided via process_vm_readv/writev — see
// pvm_transport.cpp). Workers advertise `pvm_make_endpoint(base, len)` on
// every host-addressable region; the client mux calls `pvm_access` first
// and falls back to the primary transport when it returns false (other
// host, dead/restarted pid, denied syscall, out-of-window address).
// `writable=false` marks regions whose backing pointer the server may swap
// (HBM host views): clients then one-sided READ only — writes take the
// staged path, which revalidates through the provider.
// `self_gen` (from pvm_register_self_region) bakes the self-registry
// generation into the endpoint as `:sN`; 0 omits the token.
std::string pvm_make_endpoint(const void* base, uint64_t len, bool writable = true,
                              uint64_t self_gen = 0);
// Names another live process's region (tests; the serving process normally
// advertises itself via pvm_make_endpoint).
std::string pvm_make_endpoint_for_pid(long pid, const void* base, uint64_t len,
                                      bool writable = true, uint64_t self_gen = 0);
// Self-region registry: a worker that advertises a WRITABLE host region in
// its own process registers it here, which upgrades same-process accesses
// to a direct fused one-pass copy (zero syscalls, CRC folded in). The
// returned generation must ride the advertised endpoint (pvm_make_endpoint
// self_gen) — it is what keeps a stale placement from addressing a NEW
// region whose mmap reused the same base. Retire BEFORE freeing the
// region's memory — retirement blocks until in-flight direct copies drain,
// and unregistered/mismatched regions simply fall back to the
// syscall/staged lanes, so skipping registration is safe but slower.
uint64_t pvm_register_self_region(const void* base, uint64_t len);
void pvm_retire_self_region(const void* base);
// `extent_gen` is the placement's poolsan generation stamp (0 = unstamped);
// the same-process direct lane validates it against the pool's shadow
// state. On a poolsan conviction the lane sets *fail_out (STALE_EXTENT /
// MEMORY_ACCESS_ERROR) and returns false — the caller must FAIL the op
// with that code instead of falling back to a slower lane that would only
// re-convict the same stale descriptor.
bool pvm_access(const RemoteDescriptor& remote, uint64_t remote_addr, void* buf, uint64_t len,
                bool is_write, uint32_t* crc_out, uint64_t extent_gen = 0,
                ErrorCode* fail_out = nullptr);
// Ops/bytes this process completed over the PVM lane (diagnostics, like
// tcp_staged_op_count).
uint64_t pvm_op_count() noexcept;
uint64_t pvm_byte_count() noexcept;

}  // namespace btpu::transport
