// Storage-backend micro-benchmark: reserve/commit/free ops/sec + write/read
// bandwidth per tier. (Role of reference examples/benchmark_disk_backends.cpp,
// extended to every tier including HBM.)
#include <chrono>
#include <cstdio>
#include <filesystem>

#include "btpu/storage/backend.h"

using namespace btpu;
using namespace btpu::storage;
using Clock = std::chrono::steady_clock;

static void bench_tier(StorageClass cls, const std::string& dir) {
  BackendConfig config;
  config.pool_id = "bench";
  config.node_id = "local";
  config.storage_class = cls;
  config.capacity = 256 << 20;
  if (!dir.empty()) config.path = dir + "/" + std::string(storage_class_name(cls)) + ".dat";

  auto backend = create_storage_backend(config);
  if (!backend || backend->initialize() != ErrorCode::OK) {
    std::printf("%-10s unavailable\n", storage_class_name(cls).data());
    return;
  }

  // Lifecycle ops/sec (4 KiB shards, like the reference's micro-harness).
  constexpr int kOps = 2000;
  auto t0 = Clock::now();
  for (int i = 0; i < kOps; ++i) {
    auto token = backend->reserve_shard(4096);
    (void)backend->commit_shard(token.value());  // bench loop: timing only
    (void)backend->free_shard(token.value().offset, 4096);  // bench loop: timing only
  }
  const double ops_sec = kOps / std::chrono::duration<double>(Clock::now() - t0).count();

  // Bandwidth (4 MiB blocks).
  std::vector<uint8_t> block(4 << 20, 0xAB);
  constexpr int kBlocks = 32;
  t0 = Clock::now();
  for (int i = 0; i < kBlocks; ++i)
    (void)backend->write_at(static_cast<uint64_t>(i) * block.size(), block.data(), block.size());  // bench loop: timing only
  const double write_gbps = kBlocks * double(block.size()) /
                            std::chrono::duration<double>(Clock::now() - t0).count() / 1e9;
  t0 = Clock::now();
  for (int i = 0; i < kBlocks; ++i)
    (void)backend->read_at(static_cast<uint64_t>(i) * block.size(), block.data(), block.size());  // bench loop: timing only
  const double read_gbps = kBlocks * double(block.size()) /
                           std::chrono::duration<double>(Clock::now() - t0).count() / 1e9;

  std::printf("%-10s %10.0f lifecycle-ops/s   write %6.2f GB/s   read %6.2f GB/s\n",
              storage_class_name(cls).data(), ops_sec, write_gbps, read_gbps);
  backend->shutdown();
}

int main() {
  auto dir = std::filesystem::temp_directory_path() / "btpu_backend_bench";
  std::filesystem::create_directories(dir);
  std::printf("tier       lifecycle          bandwidth (4MiB blocks)\n");
  bench_tier(StorageClass::RAM_CPU, "");
  bench_tier(StorageClass::HBM_TPU, "");  // emulated unless a provider is registered
  bench_tier(StorageClass::HDD, dir.string());
  bench_tier(StorageClass::SSD, dir.string());
  bench_tier(StorageClass::NVME, dir.string());
  std::filesystem::remove_all(dir);
  return 0;
}
