#include "btpu/coord/mem_coordinator.h"

#include <algorithm>

#include "btpu/common/log.h"

namespace btpu::coord {

// ---- key scheme -----------------------------------------------------------

std::string workers_prefix(const std::string& c) { return "/btpu/clusters/" + c + "/workers/"; }
std::string worker_key(const std::string& c, const std::string& w) {
  return workers_prefix(c) + w;
}
std::string pools_prefix(const std::string& c) {
  return "/btpu/clusters/" + c + "/memory_pools/";
}
std::string pool_key(const std::string& c, const std::string& w, const std::string& p) {
  return pools_prefix(c) + w + "/" + p;
}
std::string heartbeat_prefix(const std::string& c) {
  return "/btpu/clusters/" + c + "/heartbeat/";
}
std::string heartbeat_key(const std::string& c, const std::string& w) {
  return heartbeat_prefix(c) + w;
}
std::string services_prefix(const std::string& s) { return "/btpu/services/" + s + "/"; }
std::string objects_prefix(const std::string& c) { return "/btpu/clusters/" + c + "/objects/"; }
std::string object_record_key(const std::string& c, const std::string& key) {
  return objects_prefix(c) + key;
}

// ---- MemCoordinator -------------------------------------------------------

MemCoordinator::MemCoordinator() {
  expiry_thread_ = std::thread([this] { expiry_loop(); });
}

MemCoordinator::~MemCoordinator() {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    stopping_ = true;
  }
  expiry_cv_.notify_all();
  if (expiry_thread_.joinable()) expiry_thread_.join();
}

void MemCoordinator::expiry_loop() {
  std::unique_lock<std::mutex> lock(mutex_);
  while (!stopping_) {
    expiry_cv_.wait_for(lock, std::chrono::milliseconds(20));
    if (stopping_) break;

    const auto now = Clock::now();
    std::vector<LeaseId> expired;
    for (const auto& [id, lease] : leases_) {
      if (lease.deadline <= now) expired.push_back(id);
    }
    for (LeaseId id : expired) {
      auto it = leases_.find(id);
      if (it == leases_.end()) continue;
      auto keys = it->second.keys;
      leases_.erase(it);
      LOG_DEBUG << "lease " << id << " expired (" << keys.size() << " keys)";
      for (const auto& key : keys) {
        // Only delete entries still owned by this lease: a key refreshed via
        // a later put_with_ttl belongs to the new lease and must survive
        // (heartbeat refresh pattern).
        auto entry = data_.find(key);
        if (entry == data_.end() || entry->second.lease != id) continue;
        // del_locked unlocks while firing watch callbacks.
        del_locked(key, lock);
      }
      // A leader whose lease expired loses the election.
      for (auto& [election, candidates] : elections_) {
        auto dead = std::find_if(candidates.begin(), candidates.end(),
                                 [&](const Candidate& c) { return c.lease == id; });
        if (dead != candidates.end()) {
          const bool was_leader = dead == candidates.begin();
          candidates.erase(dead);
          if (was_leader) promote_next_locked(election, lock);
        }
      }
    }
  }
}

void MemCoordinator::notify(WatchEvent::Type type, const std::string& key,
                            const std::string& value) {
  std::vector<WatchCallback> to_call;
  {
    std::lock_guard<std::mutex> lock(mutex_);
    for (const auto& w : watches_) {
      if (key.rfind(w.prefix, 0) == 0) to_call.push_back(w.cb);
    }
  }
  WatchEvent ev{type, key, value};
  for (auto& cb : to_call) cb(ev);
}

ErrorCode MemCoordinator::del_locked(const std::string& key, std::unique_lock<std::mutex>& lock) {
  auto it = data_.find(key);
  if (it == data_.end()) return ErrorCode::COORD_KEY_NOT_FOUND;
  data_.erase(it);
  std::vector<WatchCallback> to_call;
  for (const auto& w : watches_) {
    if (key.rfind(w.prefix, 0) == 0) to_call.push_back(w.cb);
  }
  if (!to_call.empty()) {
    lock.unlock();
    WatchEvent ev{WatchEvent::Type::kDelete, key, ""};
    for (auto& cb : to_call) cb(ev);
    lock.lock();
  }
  return ErrorCode::OK;
}

Result<std::string> MemCoordinator::get(const std::string& key) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = data_.find(key);
  if (it == data_.end()) return ErrorCode::COORD_KEY_NOT_FOUND;
  return it->second.value;
}

ErrorCode MemCoordinator::put(const std::string& key, const std::string& value) {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    data_[key] = Entry{value, 0};
  }
  notify(WatchEvent::Type::kPut, key, value);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::put_with_ttl(const std::string& key, const std::string& value,
                                       int64_t ttl_ms) {
  auto lease = lease_grant(ttl_ms);
  if (!lease.ok()) return lease.error();
  return put_with_lease(key, value, lease.value());
}

ErrorCode MemCoordinator::put_with_lease(const std::string& key, const std::string& value,
                                         LeaseId lease) {
  {
    std::lock_guard<std::mutex> lock(mutex_);
    auto it = leases_.find(lease);
    if (it == leases_.end()) return ErrorCode::COORD_LEASE_ERROR;
    it->second.keys.push_back(key);
    data_[key] = Entry{value, lease};
  }
  notify(WatchEvent::Type::kPut, key, value);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::del(const std::string& key) {
  std::unique_lock<std::mutex> lock(mutex_);
  return del_locked(key, lock);
}

Result<std::vector<KeyValue>> MemCoordinator::get_with_prefix(const std::string& prefix) {
  std::lock_guard<std::mutex> lock(mutex_);
  std::vector<KeyValue> out;
  for (auto it = data_.lower_bound(prefix); it != data_.end(); ++it) {
    if (it->first.rfind(prefix, 0) != 0) break;
    out.push_back({it->first, it->second.value});
  }
  return out;
}

Result<LeaseId> MemCoordinator::lease_grant(int64_t ttl_ms) {
  if (ttl_ms <= 0) return ErrorCode::INVALID_PARAMETERS;
  std::lock_guard<std::mutex> lock(mutex_);
  LeaseId id = next_lease_++;
  leases_[id] = Lease{ttl_ms, Clock::now() + std::chrono::milliseconds(ttl_ms), {}};
  return id;
}

ErrorCode MemCoordinator::lease_keepalive(LeaseId lease) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = leases_.find(lease);
  if (it == leases_.end()) return ErrorCode::COORD_LEASE_ERROR;
  it->second.deadline = Clock::now() + std::chrono::milliseconds(it->second.ttl_ms);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::lease_revoke(LeaseId lease) {
  std::unique_lock<std::mutex> lock(mutex_);
  auto it = leases_.find(lease);
  if (it == leases_.end()) return ErrorCode::COORD_LEASE_ERROR;
  auto keys = it->second.keys;
  leases_.erase(it);
  for (const auto& key : keys) {
    auto entry = data_.find(key);
    if (entry == data_.end() || entry->second.lease != lease) continue;
    del_locked(key, lock);
  }
  for (auto& [election, candidates] : elections_) {
    auto dead = std::find_if(candidates.begin(), candidates.end(),
                             [&](const Candidate& c) { return c.lease == lease; });
    if (dead != candidates.end()) {
      const bool was_leader = dead == candidates.begin();
      candidates.erase(dead);
      if (was_leader) promote_next_locked(election, lock);
    }
  }
  return ErrorCode::OK;
}

Result<WatchId> MemCoordinator::watch_prefix(const std::string& prefix, WatchCallback cb) {
  std::lock_guard<std::mutex> lock(mutex_);
  WatchId id = next_watch_++;
  watches_.push_back({id, prefix, std::move(cb)});
  return id;
}

ErrorCode MemCoordinator::unwatch(WatchId id) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = std::find_if(watches_.begin(), watches_.end(),
                         [id](const Watch& w) { return w.id == id; });
  if (it == watches_.end()) return ErrorCode::COORD_WATCH_ERROR;
  watches_.erase(it);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::register_service(const std::string& service_name, const std::string& id,
                                           const std::string& address, int64_t ttl_ms) {
  return put_with_ttl(services_prefix(service_name) + id, address, ttl_ms);
}

Result<std::vector<KeyValue>> MemCoordinator::discover_service(const std::string& service_name) {
  return get_with_prefix(services_prefix(service_name));
}

ErrorCode MemCoordinator::unregister_service(const std::string& service_name,
                                             const std::string& id) {
  return del(services_prefix(service_name) + id);
}

void MemCoordinator::promote_next_locked(const std::string& election,
                                         std::unique_lock<std::mutex>& lock) {
  auto it = elections_.find(election);
  if (it == elections_.end() || it->second.empty()) return;
  auto cb = it->second.front().cb;
  const std::string leader_id = it->second.front().id;
  LOG_INFO << "election '" << election << "': " << leader_id << " is now leader";
  if (cb) {
    lock.unlock();
    cb(true);
    lock.lock();
  }
}

ErrorCode MemCoordinator::campaign(const std::string& election, const std::string& candidate_id,
                                   int64_t lease_ttl_ms, std::function<void(bool)> cb) {
  auto lease = lease_grant(lease_ttl_ms);
  if (!lease.ok()) return lease.error();
  bool is_leader = false;
  {
    std::unique_lock<std::mutex> lock(mutex_);
    auto& candidates = elections_[election];
    if (std::any_of(candidates.begin(), candidates.end(),
                    [&](const Candidate& c) { return c.id == candidate_id; }))
      return ErrorCode::CLIENT_ALREADY_EXISTS;
    candidates.push_back({candidate_id, lease.value(), cb});
    is_leader = candidates.size() == 1;
  }
  if (cb) cb(is_leader);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::resign(const std::string& election, const std::string& candidate_id) {
  std::unique_lock<std::mutex> lock(mutex_);
  auto it = elections_.find(election);
  if (it == elections_.end()) return ErrorCode::LEADER_ELECTION_FAILED;
  auto& candidates = it->second;
  auto me = std::find_if(candidates.begin(), candidates.end(),
                         [&](const Candidate& c) { return c.id == candidate_id; });
  if (me == candidates.end()) return ErrorCode::LEADER_ELECTION_FAILED;
  const bool was_leader = me == candidates.begin();
  const LeaseId lease = me->lease;
  candidates.erase(me);
  leases_.erase(lease);
  if (was_leader) promote_next_locked(election, lock);
  return ErrorCode::OK;
}

ErrorCode MemCoordinator::campaign_keepalive(const std::string& election,
                                             const std::string& candidate_id) {
  LeaseId lease = 0;
  {
    std::lock_guard<std::mutex> lock(mutex_);
    auto it = elections_.find(election);
    if (it == elections_.end()) return ErrorCode::LEADER_ELECTION_FAILED;
    auto me = std::find_if(it->second.begin(), it->second.end(),
                           [&](const Candidate& c) { return c.id == candidate_id; });
    if (me == it->second.end()) return ErrorCode::LEADER_ELECTION_FAILED;
    lease = me->lease;
  }
  return lease_keepalive(lease);
}

Result<std::string> MemCoordinator::current_leader(const std::string& election) {
  std::lock_guard<std::mutex> lock(mutex_);
  auto it = elections_.find(election);
  if (it == elections_.end() || it->second.empty()) return ErrorCode::COORD_KEY_NOT_FOUND;
  return it->second.front().id;
}

}  // namespace btpu::coord
