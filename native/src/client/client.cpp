#include "btpu/client/client.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <random>

#include "btpu/common/crc32c.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/wire.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/trace.h"
#include "btpu/coord/remote_coordinator.h"
#include "btpu/ec/rs.h"
#include "btpu/rpc/rpc.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::client {

void ClientOptions::set_keystone_endpoints(const std::string& list) {
  keystone_address.clear();
  keystone_fallbacks.clear();
  size_t pos = 0;
  while (pos <= list.size()) {
    const size_t next = list.find(',', pos);
    const std::string part = list.substr(pos, next - pos);
    if (!part.empty()) {
      if (keystone_address.empty()) {
        keystone_address = part;
      } else {
        keystone_fallbacks.push_back(part);
      }
    }
    if (next == std::string::npos) break;
    pos = next + 1;
  }
}

namespace {
// Namespaces this client session's pooled slot keys on the keystone.
std::string random_slot_tag() {
  std::random_device rd;
  char buf[17];
  std::snprintf(buf, sizeof(buf), "%08x%08x", rd(), rd());
  return buf;
}

// Operator/env overrides for the robustness knobs (tests and deployments
// flip these without a code change).
void apply_robustness_env(ClientOptions& options) {
  options.op_deadline_ms = env_u32("BTPU_OP_DEADLINE_MS", options.op_deadline_ms);
  options.hedge_reads = env_bool("BTPU_HEDGE_READS", options.hedge_reads);
  options.inline_refusal_backoff_ms =
      env_u32("BTPU_INLINE_RETRY_MS", options.inline_refusal_backoff_ms);
}

// Sampled latency probe for the cached-get fast path: a ~2us local memcpy
// cannot absorb the full tracing scope (two clock reads alone are ~3% of
// it — the bench.py trace-overhead guard holds the line at 5%), so
// 1-in-8 hits measure and record with weight 8 into
// btpu_op_duration_us{op="get_cached"} + one flight op_end event. Uniform
// sampling is quantile-unbiased, and the weight keeps _count/_sum rates
// honest; the unmeasured 7/8 pay one tls increment and a branch. Cache
// hits make no wire calls, so there is nothing to trace-propagate here.
inline uint64_t cached_probe_start() {
  thread_local uint32_t tick = 0;
  if ((++tick & 7u) != 0 || !trace::enabled()) return 0;
  return trace::now_ns();
}

inline void cached_probe_finish(uint64_t t0) {
  if (t0 == 0) return;
  const uint64_t dur_us = (trace::now_ns() - t0) / 1000;
  hist::op("get_cached").record_us_weighted(dur_us, 8);
  flight::record_at(t0 + dur_us * 1000, flight::Ev::kOpEnd, dur_us, 0, 0);
}
}  // namespace

ObjectClient::ObjectClient(ClientOptions options)
    : options_(std::move(options)),
      verify_default_(options_.verify_reads),
      data_(transport::make_transport_client()),
      slot_tag_(random_slot_tag()),
      breakers_(options_.breaker) {
  apply_robustness_env(options_);
  {
    MutexLock lock(rpc_mutex_);
    rpc_ = std::make_shared<rpc::KeystoneRpcClient>(options_.keystone_address);
    rpc_->set_retry_policy(options_.retry);
  }
  setup_cache();
}

ObjectClient::ObjectClient(ClientOptions options, keystone::KeystoneService* embedded)
    : options_(std::move(options)),
      verify_default_(options_.verify_reads),
      embedded_(embedded),
      data_(transport::make_transport_client()),
      breakers_(options_.breaker) {
  apply_robustness_env(options_);
  setup_cache();
}

ObjectClient::~ObjectClient() {
  teardown_cache_watch();
  cancel_pooled_slots();
  // Loser hedge attempts still reference this client's transport; wait for
  // them to drain into their discard buffers before tearing anything down.
  MutexLock lock(hedge_mutex_);
  // ordering: acquire — pairs with the losers' acq_rel decrement: observing 0 means every loser's last touch of this client happened-before teardown.
  while (hedge_inflight_.load(std::memory_order_acquire) != 0) hedge_cv_.wait(lock);
}

ErrorCode ObjectClient::connect() {
  if (embedded_) return ErrorCode::OK;
  auto snap = rpc_snapshot();
  auto ec = snap->connect();
  // Initial connect participates in failover too: the configured primary
  // may already be a dead or standby keystone.
  const size_t endpoints = 1 + options_.keystone_fallbacks.size();
  for (size_t i = 0; i + 1 < endpoints && ec != ErrorCode::OK; ++i) {
    rotate_keystone(snap);
    snap = rpc_snapshot();
    ec = snap->connect();
  }
  return ec;
}

void ObjectClient::rotate_keystone(const std::shared_ptr<rpc::KeystoneRpcClient>& failed) {
  // The decision and the swap are ONE critical section: N threads failing
  // on the same dead keystone must produce one rotation, not N (each extra
  // rotation steps the shared index past the live endpoint and burns a
  // caller's only retry). A caller whose failed snapshot is no longer
  // installed simply adopts the sibling's rotation. The dial is deferred:
  // constructing KeystoneRpcClient is cheap, and call_raw connects lazily,
  // so the lock is never held across a (possibly seconds-long) connect.
  std::shared_ptr<rpc::KeystoneRpcClient> fresh;
  std::string address;
  {
    MutexLock lock(rpc_mutex_);
    if (failed && rpc_ != failed) return;  // a sibling already rotated past it
    const size_t endpoints = 1 + options_.keystone_fallbacks.size();
    keystone_index_ = (keystone_index_ + 1) % endpoints;
    address = keystone_index_ == 0 ? options_.keystone_address
                                   : options_.keystone_fallbacks[keystone_index_ - 1];
    fresh = std::make_shared<rpc::KeystoneRpcClient>(address);
    fresh->set_retry_policy(options_.retry);  // survives failover rotation
    rpc_ = fresh;
  }
  LOG_WARN << "keystone failover: switching to " << address;
  (void)fresh->connect();  // best-effort pre-dial; calls reconnect lazily anyway
}

Result<bool> ObjectClient::object_exists(const ObjectKey& key) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  if (embedded_) return embedded_->object_exists(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.object_exists(key); });
}

Result<std::vector<CopyPlacement>> ObjectClient::get_workers(const ObjectKey& key) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
#if defined(BTPU_POOLSAN)
  // PLANTED MUTANT — stale-descriptor class (the bug generation stamps
  // exist to convict): serve placements from a never-invalidated memo, the
  // way an over-eager placement cache once could across a remove/GC. The
  // first get memoizes; every later get reuses the stale descriptors, and
  // the data plane must answer STALE_EXTENT — never a neighbor object's
  // bytes. Pinned by Poolsan.MutantStaleRead.
  if (poolsan::mutant() == poolsan::Mutant::kStaleRead) {
    static Mutex memo_mutex;
    static std::unordered_map<ObjectKey, std::vector<CopyPlacement>> memo;
    {
      MutexLock lock(memo_mutex);
      auto it = memo.find(key);
      if (it != memo.end()) return it->second;
    }
    auto fresh = embedded_ ? embedded_->get_workers(key)
                           : rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) {
                               return r.get_workers(key);
                             });
    if (fresh.ok()) {
      MutexLock lock(memo_mutex);
      memo[key] = fresh.value();
    }
    return fresh;
  }
#endif
  if (embedded_) return embedded_->get_workers(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.get_workers(key); });
}

// ---- placement cache (ClientOptions::placement_cache_ms) -------------------

Result<std::vector<CopyPlacement>> ObjectClient::get_workers_cached(const ObjectKey& key,
                                                                    bool& from_cache) {
  from_cache = false;
  if (options_.placement_cache_ms > 0 && !embedded_) {
    const auto now = std::chrono::steady_clock::now();
    MutexLock lock(placement_cache_mutex_);
    auto it = placement_cache_.find(key);
    if (it != placement_cache_.end()) {
      if (now - it->second.fetched_at <=
          std::chrono::milliseconds(options_.placement_cache_ms)) {
        from_cache = true;
        return it->second.copies;
      }
      placement_cache_.erase(it);
    }
  }
  auto copies = get_workers(key);
  if (copies.ok()) cache_placements(key, copies.value());
  return copies;
}

void ObjectClient::cache_placements(const ObjectKey& key,
                                    const std::vector<CopyPlacement>& copies) {
  if (options_.placement_cache_ms == 0 || embedded_) return;
  // Staleness detection rides the content CRC; an unstamped copy (legacy
  // record) could serve stale bytes undetected, so it is never cached.
  for (const auto& copy : copies) {
    if (copy.content_crc == 0) return;
  }
  MutexLock lock(placement_cache_mutex_);
  // Bounded: entries expire by TTL anyway, so a rare full reset under churn
  // beats per-access LRU bookkeeping on the hot read path.
  if (placement_cache_.size() >= 4096) placement_cache_.clear();
  placement_cache_[key] = {copies, std::chrono::steady_clock::now()};
}

void ObjectClient::invalidate_placements(const ObjectKey& key) {
  // This client's own mutations drop the OBJECT cache entry too (a
  // re-created key must not serve the previous object's bytes from either
  // cache); cross-client mutations ride the watch/lease machinery.
  if (cache_) cache_->invalidate(key);
  if (options_.placement_cache_ms == 0 || embedded_) return;
  MutexLock lock(placement_cache_mutex_);
  placement_cache_.erase(key);
}

void ObjectClient::invalidate_all_placements() {
  if (cache_) cache_->invalidate_all();
  if (options_.placement_cache_ms == 0 || embedded_) return;
  MutexLock lock(placement_cache_mutex_);
  placement_cache_.clear();
}

// ---- client object cache (ClientOptions::cache_bytes) ----------------------

void ObjectClient::setup_cache() {
  if (options_.cache_bytes == 0) return;
  cache_ = std::make_shared<cache::ObjectCache>(options_.cache_bytes,
                                                options_.cache_max_object_bytes);
  // Embedded clients validate every hit against the in-process keystone's
  // version — strictly stronger than any invalidation stream, so no watch.
  if (embedded_ && !options_.cache_force_lease_mode) return;
  inval_coord_ = options_.cache_coordinator;
  if (!inval_coord_ && !options_.coordinator_endpoints.empty()) {
    auto rc = std::make_shared<coord::RemoteCoordinator>(options_.coordinator_endpoints);
    if (rc->connect() == ErrorCode::OK) {
      inval_coord_ = std::move(rc);
    } else {
      LOG_WARN << "object cache: coordinator " << options_.coordinator_endpoints
               << " unreachable; invalidations degrade to lease expiry";
    }
  }
  if (!inval_coord_) return;  // lease-expiry + revalidation coherence only
  const std::string prefix = coord::cache_inval_prefix(options_.cluster_id);
  // weak_ptr: a late watch event racing client destruction pins the cache
  // (or finds it gone) instead of dereferencing a dead client.
  std::weak_ptr<cache::ObjectCache> weak = cache_;
  auto watch =
      inval_coord_->watch_prefix(prefix, [prefix, weak](const coord::WatchEvent& ev) {
        // PUT events only: the topic's TTL'd values self-clean with a
        // kDelete ~30 s after each publish, which must not evict an entry
        // legitimately re-cached since the original invalidation.
        if (ev.type != coord::WatchEvent::Type::kPut) return;
        if (ev.key.size() <= prefix.size()) return;
        if (auto cache = weak.lock()) cache->invalidate(ev.key.substr(prefix.size()));
      });
  if (watch.ok()) {
    inval_watch_ = watch.value();
  } else {
    LOG_WARN << "object cache: invalidation watch failed ("
             << to_string(watch.error()) << "); degrading to lease expiry";
  }
}

void ObjectClient::teardown_cache_watch() {
  if (inval_coord_ && inval_watch_ >= 0) warn_if_error(inval_coord_->unwatch(inval_watch_), "cache-inval unwatch");
  inval_watch_ = -1;
  inval_coord_.reset();
}

void ObjectClient::configure_cache(uint64_t cache_bytes) {
  teardown_cache_watch();
  cache_.reset();
  options_.cache_bytes = cache_bytes;
  setup_cache();
}

void ObjectClient::sever_cache_watch_for_test() {
  teardown_cache_watch();
  // Push coherence is gone: entries must not outlive their lease.
  if (cache_) cache_->expire_all_leases();
}

cache::ObjectCache::Bytes ObjectClient::cache_acquire(const ObjectKey& key) {
  if (!cache_) return nullptr;
  using Outcome = cache::ObjectCache::Outcome;
  cache::ObjectCache::Hit hit;
  if (embedded_ && !options_.cache_force_lease_mode) {
    // Direct validation: linearizable with the in-process metadata.
    const auto [gen, epoch] = embedded_->object_cache_version(key);
    hit = cache_->lookup_validated(key, {gen, epoch});
    if (hit.outcome == Outcome::kHit && hit.lease_lapsed) {
      // Keep the keystone's LRU honest: validated hits never pass through
      // get_workers, so once per lease period run a real (in-process)
      // metadata read — it touches the object's last_access, without which
      // pressure eviction would judge the hottest cached objects coldest
      // and destroy them under their readers.
      auto copies = get_workers(key);
      const auto meta_at = std::chrono::steady_clock::now();
      if (copies.ok() && !copies.value().empty()) {
        const auto& c0 = copies.value().front();
        const cache::ObjectVersion current{c0.cache_gen, c0.cache_version};
        if (current.valid() && c0.cache_lease_ms > 0)
          cache_->renew(key, current,
                        meta_at + std::chrono::milliseconds(c0.cache_lease_ms));
      }
    }
  } else {
    hit = cache_->lookup(key);
    if (hit.outcome == Outcome::kExpired) {
      // Lease lapsed: ONE control RTT revalidates, then cache_revalidate
      // applies the verdict (renew-and-serve vs snapshot-guarded drop).
      auto copies = get_workers(key);
      const auto meta_at = std::chrono::steady_clock::now();  // lease anchor
      if (!cache_revalidate(key, hit, copies, meta_at)) return nullptr;
      hit.outcome = Outcome::kHit;
    }
  }
  return hit.outcome == Outcome::kHit ? hit.bytes : nullptr;
}

bool ObjectClient::cache_revalidate(const ObjectKey& key,
                                    const cache::ObjectCache::Hit& hit,
                                    const Result<std::vector<CopyPlacement>>& meta,
                                    std::chrono::steady_clock::time_point meta_at) {
  if (meta.ok() && !meta.value().empty()) {
    const auto& c0 = meta.value().front();
    const cache::ObjectVersion current{c0.cache_gen, c0.cache_version};
    if (current.valid() && c0.cache_lease_ms > 0) {
      // renew() keeps/renews the resident entry iff it matches `current` —
      // including one a concurrent reader refilled at `current` while we
      // revalidated, which must not be clobbered; a moved resident version
      // is dropped there (stale_reject). The snapshot is serveable only on
      // a full version + content-stamp match (the stamp is the belt over
      // braces across keystone incarnations).
      cache_->renew(key, current, meta_at + std::chrono::milliseconds(c0.cache_lease_ms));
      if (current == hit.version && c0.content_crc == hit.content_crc) {
        cache_->count_revalidated_hit();
        return true;
      }
      return false;
    }
  }
  // Object gone, metadata unreachable, or the server stopped granting:
  // drop OUR snapshot only (never a newer concurrent fill).
  cache_->invalidate_if_version(key, hit.version);
  return false;
}

bool ObjectClient::cache_serve(const ObjectKey& key, void* out, uint64_t out_cap,
                               uint64_t& got) {
  auto bytes = cache_acquire(key);
  if (!bytes || bytes->size() > out_cap) return false;
  std::memcpy(out, bytes->data(), bytes->size());
  got = bytes->size();
  cache::note_cached_serve(got);  // lane counts bytes actually delivered
  return true;
}

void ObjectClient::cache_fill(const ObjectKey& key, const CopyPlacement& copy,
                              const uint8_t* data, uint64_t size,
                              std::chrono::steady_clock::time_point granted_at) {
  if (!cache_ || size == 0 || size > options_.cache_max_object_bytes) return;
  const cache::ObjectVersion version{copy.cache_gen, copy.cache_version};
  // Only keystone-granted (version + lease), CRC-stamped reads are
  // cacheable — "a hit returns verified bytes" is a contract, not a mood.
  if (!version.valid() || copy.cache_lease_ms == 0 || copy.content_crc == 0) return;
  // The lease runs from the moment the grant was FETCHED, not from fill:
  // a slow transfer between the two must never stretch the staleness bound
  // past grant + lease.
  cache_->fill(key, version, copy.content_crc,
               std::make_shared<const std::vector<uint8_t>>(data, data + size),
               granted_at + std::chrono::milliseconds(copy.cache_lease_ms));
}

std::optional<uint64_t> ObjectClient::cached_object_size(const ObjectKey& key) {
  if (!cache_) return std::nullopt;
  auto hit = cache_->peek(key);
  if (!hit.bytes) return std::nullopt;
  if (embedded_ && !options_.cache_force_lease_mode) {
    const auto [gen, epoch] = embedded_->object_cache_version(key);
    if (!(cache::ObjectVersion{gen, epoch} == hit.version)) return std::nullopt;
  } else if (hit.outcome != cache::ObjectCache::Outcome::kHit) {
    return std::nullopt;  // lease lapsed: let the probe revalidate normally
  }
  return hit.bytes->size();
}

// Runs `attempt` against possibly-cached placements with ONE fresh-metadata
// retry when every cached placement failed — the single home of the cache
// discipline documented on ClientOptions::placement_cache_ms.
ErrorCode ObjectClient::read_with_cache(
    const ObjectKey& key, bool verify,
    const std::function<ErrorCode(const std::vector<CopyPlacement>&, bool)>& attempt) {
  bool from_cache = false;
  auto copies = verify ? get_workers_cached(key, from_cache) : get_workers(key);
  if (!copies.ok()) return copies.error();
  ErrorCode ec = attempt(copies.value(), from_cache);
  if (ec == ErrorCode::OK || !from_cache) return ec;
  // Cached placements failed (moved bytes, dead worker, size change):
  // drop the entry and retry once with fresh metadata.
  invalidate_placements(key);
  from_cache = false;
  copies = get_workers_cached(key, from_cache);
  if (!copies.ok()) return copies.error();
  return attempt(copies.value(), from_cache);
}

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size) {
  return put(key, data, size, options_.default_config);
}

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size,
                            const WorkerConfig& config) {
  trace::OpScope op_trace("put");  // relabeled once the serving tier is known
  TRACE_SPAN("client.put");
  // The end-to-end budget covers every tier probe, transfer, and retry
  // below; a RETRY_LATER shed re-runs the whole body after jittered backoff
  // (safe: a shed provably did not execute, and put_many rolls back failed
  // reservations before reporting).
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  return with_shed_retry([&]() -> ErrorCode {
    // Tiny objects ride the inline tier when the keystone grants it: ONE
    // control RTT stores the bytes in the object map, and the first verified
    // read needs no data-plane hop at all. nullopt = not applicable — fall
    // through to slots/placed.
    if (auto inl = put_via_inline(key, data, size, config)) {
      op_trace.relabel("put_inline");
      return *inl;
    }
    // Small objects ride the pooled-slot path when possible: write into a
    // pre-allocated slot, then ONE control RTT commits it as `key` (and
    // refills the pool in the same round trip). nullopt = not applicable
    // (disabled, oversized, EC, embedded, slot reclaimed) — fall through.
    if (auto pooled = put_via_slot(key, data, size, config)) {
      op_trace.relabel("put_slot");
      return *pooled;
    }
    // One-item batch: put_many pipelines the wire shards of EVERY copy in a
    // single pass (a replicated put costs ~one round trip, not one per copy),
    // coalesces device shards, and rolls back failed reservations — the exact
    // single-object semantics (put_start -> transfer -> complete/cancel,
    // reference blackbird_client.cpp:87-117) with none of the code repeated.
    return put_many({{key, data, size}}, config)[0];
  });
}

Result<std::vector<uint8_t>> ObjectClient::get(const ObjectKey& key,
                                               std::optional<bool> verify) {
  // Hot path: a coherent cached entry answers with one memcpy and zero
  // worker involvement (the bytes were verified at fill time). It gets the
  // SAMPLED light instrumentation (cached_probe_*): the full OpScope below
  // costs a few hundred ns, which the ~2us cached serve cannot absorb
  // inside the bench.py trace-overhead budget, while the wire-bound path
  // below hides it completely.
  const uint64_t cached_t0 = cached_probe_start();
  if (auto cached = cache_acquire(key)) {
    cache::note_cached_serve(cached->size());
    std::vector<uint8_t> out(cached->begin(), cached->end());
    cached_probe_finish(cached_t0);
    return out;
  }
  trace::OpScope op_trace("get");
  TRACE_SPAN("client.get");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  const bool v = verify.value_or(verify_reads());
  std::vector<uint8_t> buffer;
  const ErrorCode ec = with_shed_retry([&] { return read_with_cache(
      key, v, [&](const std::vector<CopyPlacement>& copies, bool stale_meta) -> ErrorCode {
        const auto meta_at = std::chrono::steady_clock::now();  // lease anchor
        uint64_t size = 0;
        if (!copies.empty()) size = copy_logical_size(copies.front());
        buffer.resize(size);
        if (try_split_read(copies, buffer.data(), size, v) == ErrorCode::OK) {
          if (v && !stale_meta) cache_fill(key, copies.front(), buffer.data(), size, meta_at);
          return ErrorCode::OK;
        }
        // Per-copy failover via the replica attempt engine: breaker-aware
        // candidate order, hedged when the first copy runs long. Corruption
        // stays the strongest reported signal (see attempt_copies).
        uint64_t got_size = 0;
        const CopyPlacement* winner = nullptr;
        const ErrorCode aec = attempt_copies(
            copies, v,
            [&](uint64_t copy_size) -> uint8_t* {
              buffer.resize(copy_size);
              return buffer.data();
            },
            got_size, &winner);
        if (aec != ErrorCode::OK) return aec;
        if (v && !stale_meta && winner)
          cache_fill(key, *winner, buffer.data(), got_size, meta_at);
        return ErrorCode::OK;
      }); });
  if (ec != ErrorCode::OK) return ec;
  return buffer;
}

Result<uint64_t> ObjectClient::get_into(const ObjectKey& key, void* buffer,
                                        uint64_t buffer_size, std::optional<bool> verify) {
  uint64_t got = 0;
  // Hot path: serve verified bytes straight out of the object cache (an
  // entry too large for `buffer` falls through; the normal path reports
  // BUFFER_OVERFLOW with fresh metadata). Sampled light instrumentation —
  // see cached_probe_start for the overhead-budget rationale.
  const uint64_t cached_t0 = cached_probe_start();
  if (cache_ && cache_serve(key, buffer, buffer_size, got)) {
    cached_probe_finish(cached_t0);
    return got;
  }
  trace::OpScope op_trace("get");
  TRACE_SPAN("client.get");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  const bool v = verify.value_or(verify_reads());
  const ErrorCode ec = with_shed_retry([&] { return read_with_cache(
      key, v, [&](const std::vector<CopyPlacement>& copies, bool stale_meta) -> ErrorCode {
        const auto meta_at = std::chrono::steady_clock::now();  // lease anchor
        uint64_t size = 0;
        if (!copies.empty()) size = copy_logical_size(copies.front());
        if (size <= buffer_size &&
            try_split_read(copies, static_cast<uint8_t*>(buffer), size, v) ==
                ErrorCode::OK) {
          got = size;
          if (v && !stale_meta)
            cache_fill(key, copies.front(), static_cast<const uint8_t*>(buffer), size,
                       meta_at);
          return ErrorCode::OK;
        }
        // Replica attempt engine (breakers + hedging); an oversized copy is
        // refused by the buffer callback and participates in the
        // cache-retry as BUFFER_OVERFLOW, exactly like the old loop.
        const CopyPlacement* winner = nullptr;
        const ErrorCode aec = attempt_copies(
            copies, v,
            [&](uint64_t copy_size) -> uint8_t* {
              return copy_size > buffer_size ? nullptr : static_cast<uint8_t*>(buffer);
            },
            got, &winner);
        if (aec != ErrorCode::OK) return aec;
        if (v && !stale_meta && winner)
          cache_fill(key, *winner, static_cast<const uint8_t*>(buffer), got, meta_at);
        return ErrorCode::OK;
      }); });
  if (ec != ErrorCode::OK) return ec;
  return got;
}

ErrorCode ObjectClient::fabric_offer(const RemoteDescriptor& remote, uint64_t addr,
                                     uint64_t rkey, uint64_t len, uint64_t transfer_id) {
  return data_->fabric_offer(remote, addr, rkey, len, transfer_id);
}

ErrorCode ObjectClient::fabric_pull(const RemoteDescriptor& remote, uint64_t addr,
                                    uint64_t rkey, uint64_t len, uint64_t transfer_id,
                                    const std::string& src_fabric) {
  return data_->fabric_pull(remote, addr, rkey, len, transfer_id, src_fabric);
}

Result<std::vector<CopyPlacement>> ObjectClient::put_start(const ObjectKey& key,
                                                           uint64_t size,
                                                           const WorkerConfig& config,
                                                           uint32_t content_crc) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_placements(key);  // same re-created-key rule as put()
  if (embedded_) return embedded_->put_start(key, size, config, content_crc);
  return rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
    return r.put_start(key, size, config, content_crc);
  });
}

ErrorCode ObjectClient::put_complete(const ObjectKey& key,
                                     const std::vector<CopyShardCrcs>& shard_crcs) {
  if (embedded_) return embedded_->put_complete(key, shard_crcs);
  return rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
    return r.put_complete(key, shard_crcs);
  });
}

ErrorCode ObjectClient::put_cancel(const ObjectKey& key) {
  if (embedded_) return embedded_->put_cancel(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.put_cancel(key); });
}

ErrorCode ObjectClient::remove(const ObjectKey& key) {
  trace::OpScope op_trace("remove");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_placements(key);  // a re-created key must not serve stale bytes
  if (embedded_) return embedded_->remove_object(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_object(key); });
}

Result<uint64_t> ObjectClient::remove_all() {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_all_placements();  // same re-created-key rule as remove()
  if (embedded_) return embedded_->remove_all_objects();
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_all_objects(); });
}

Result<uint64_t> ObjectClient::drain_worker(const NodeId& worker_id) {
  if (embedded_) return embedded_->drain_worker(worker_id);
  // A long-running mutation: NOT_LEADER rotates, lost replies do not retry.
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.drain_worker(worker_id); });
}

Result<std::vector<ObjectSummary>> ObjectClient::list_objects(const std::string& prefix,
                                                              uint64_t limit) {
  if (embedded_) return embedded_->list_objects(prefix, limit);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) {
    return r.list_objects(prefix, limit);
  });
}

Result<ClusterStats> ObjectClient::cluster_stats() {
  if (embedded_) return embedded_->get_cluster_stats();
  return rpc_failover(/*idempotent=*/true,
                      [&](rpc::KeystoneRpcClient& r) { return r.get_cluster_stats(); });
}

Result<ViewVersionId> ObjectClient::ping() {
  if (embedded_) return embedded_->get_view_version();
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.ping(); });
}

// One shard transfer; `buf` already points at the shard's slice of the
// object buffer (running-offset math lives in the copy-level loop).
// Location dispatch lives in transport::shard_io, shared with keystone's
// repair/demotion data movers.
ErrorCode ObjectClient::shard_io(const ShardPlacement& shard, uint8_t* buf, bool is_write) {
  return transport::shard_io(*data_, shard, 0, buf, shard.length, is_write);
}

// Wide replicated reads split the byte range into slices assigned
// round-robin across replicas, issued as ONE pipelined batch — aggregate
// read bandwidth is every replica's link, not one (the reference left this
// as a TODO, blackbird_client.cpp:283). Any failure reports back and the
// caller falls back to sequential per-copy reads, so a dead replica costs a
// retry, never the object.
ErrorCode ObjectClient::try_split_read(const std::vector<CopyPlacement>& copies,
                                       uint8_t* buffer, uint64_t size, bool verify) {
  constexpr uint64_t kSplitReadMin = 512 * 1024;  // below this, one copy wins
  if (copies.size() < 2 || size < kSplitReadMin || options_.io_parallelism < 2)
    return ErrorCode::NOT_IMPLEMENTED;
  for (const auto& copy : copies) {
    uint64_t copy_size = 0;
    for (const auto& shard : copy.shards) {
      if (!std::holds_alternative<MemoryLocation>(shard.location))
        return ErrorCode::NOT_IMPLEMENTED;  // device reads batch better whole
      copy_size += shard.length;
    }
    if (copy_size != size) return ErrorCode::NOT_IMPLEMENTED;  // divergent copies
  }
  const uint64_t n_slices =
      std::min<uint64_t>(options_.io_parallelism, size / (kSplitReadMin / 2));
  const uint64_t slice = (size + n_slices - 1) / n_slices;
  std::vector<transport::WireOp> ops;
  for (uint64_t j = 0; j < n_slices; ++j) {
    const uint64_t lo = j * slice;
    const uint64_t len = std::min(slice, size - lo);
    if (!transport::append_range_wire_ops(copies[j % copies.size()], lo, len, buffer + lo,
                                          ops))
      return ErrorCode::NOT_IMPLEMENTED;
  }
  const uint32_t expect = copies.front().content_crc;
  // Content-unstamped but shard-stamped (pre-v3 completion): bow out so the
  // per-copy path runs its shard-stamp fallback — a split read here would
  // silently skip verification.
  if (verify && expect == 0 &&
      copies.front().shard_crcs.size() == copies.front().shards.size())
    return ErrorCode::NOT_IMPLEMENTED;
  const bool check = verify && expect != 0;
  // Transport-computed CRCs: ops cover [0, size) contiguously in array
  // order (slices ascending, ranges within a slice ascending), so their
  // ordered combine IS the object CRC — no post-pass over the buffer.
  for (auto& op : ops) op.want_crc = check;
  if (auto ec = data_->read_batch(ops.data(), ops.size(), options_.io_parallelism);
      ec != ErrorCode::OK)
    return ec;
  if (check) {
    uint32_t combined = 0;
    for (size_t j = 0; j < ops.size(); ++j) {
      combined = j == 0 ? ops[j].crc : crc32c_combine(combined, ops[j].crc, ops[j].len);
    }
    if (combined != expect) {
      // Some slice came from a corrupt replica; the caller's per-copy
      // (verified) reads identify the healthy one.
      LOG_WARN << "content crc mismatch on split-replica read: retrying per copy";
      return ErrorCode::CHECKSUM_MISMATCH;
    }
  }
  return ErrorCode::OK;
}

// ---- erasure-coded copies --------------------------------------------------
//
// An EC copy holds k data shards (equal length L = ceil(size/k), last one
// zero-padded) + m Reed-Solomon parity shards (btpu/ec/rs.h). Writes encode
// and send all k+m in one pipelined batch; reads fetch the k data shards
// and only on failure fetch survivors + parity and reconstruct (systematic
// code: the healthy path never decodes).

ErrorCode ObjectClient::transfer_copy_ec(const CopyPlacement& copy, uint8_t* data,
                                         uint64_t size, bool is_write, bool verify) {
  const size_t k = copy.ec_data_shards;
  const size_t m = copy.ec_parity_shards;
  if (copy.shards.size() != k + m || size != copy.ec_object_size)
    return ErrorCode::INVALID_PARAMETERS;
  const uint64_t L = copy.shards.front().length;
  for (const auto& shard : copy.shards) {
    if (shard.length != L) return ErrorCode::INVALID_PARAMETERS;
  }
  // Data shard i holds object bytes [i*L, i*L+valid_of(i)); with small
  // objects (size < k*L - L) SEVERAL trailing shards are partly or wholly
  // padding, not just the last one.
  auto valid_of = [&](size_t i) -> uint64_t {
    const uint64_t start = i * L;
    return start >= size ? 0 : std::min<uint64_t>(L, size - start);
  };
  // Shards with padding read/write through a temp; full shards use the
  // user buffer directly.
  std::vector<std::vector<uint8_t>> temps(k);
  auto shard_buf = [&](size_t i) -> uint8_t* {
    if (valid_of(i) == L) return data + i * L;
    if (temps[i].empty()) temps[i].assign(L, 0);
    return temps[i].data();
  };

  if (is_write) {
    std::vector<const uint8_t*> data_ptrs(k);
    for (size_t i = 0; i < k; ++i) {
      uint8_t* buf = shard_buf(i);
      if (valid_of(i) < L && valid_of(i) > 0) std::memcpy(buf, data + i * L, valid_of(i));
      data_ptrs[i] = buf;
    }
    std::vector<std::vector<uint8_t>> parity(m, std::vector<uint8_t>(L));
    std::vector<uint8_t*> parity_ptrs(m);
    for (size_t j = 0; j < m; ++j) parity_ptrs[j] = parity[j].data();
    if (!ec::rs_encode(data_ptrs.data(), k, parity_ptrs.data(), m, L))
      return ErrorCode::INVALID_PARAMETERS;

    std::vector<transport::WireOp> ops(k + m);
    for (size_t i = 0; i < k + m; ++i) {
      uint8_t* buf = i < k ? const_cast<uint8_t*>(data_ptrs[i]) : parity[i - k].data();
      if (!transport::make_wire_op(copy.shards[i], 0, buf, L, ops[i]))
        return ErrorCode::NOT_IMPLEMENTED;
    }
    return data_->write_batch(ops.data(), ops.size(), options_.io_parallelism);
  }

  // Read path: fetch the k data shards (systematic code: no decode when
  // they all arrive). A shard with no wire address (e.g. one mid-repair or
  // mis-placed on a device tier) counts as MISSING — that is exactly the
  // failure parity exists to absorb, not a reason to abort the read.
  std::vector<transport::WireOp> ops(k);
  std::vector<bool> addressable(k + m, true);
  std::vector<bool> padding_only(k, false);
  for (size_t i = 0; i < k; ++i) {
    if (valid_of(i) == 0) {
      // Pure padding: content is all zeros by construction — shard_buf's
      // temp already is; no wire fetch, and it can serve reconstruction.
      padding_only[i] = true;
      (void)shard_buf(i);
      ops[i] = {};
      continue;
    }
    if (!transport::make_wire_op(copy.shards[i], 0, shard_buf(i), L, ops[i])) {
      addressable[i] = false;
      ops[i] = {};  // len 0: skipped by the batch
    }
  }
  (void)data_->read_batch(ops.data(), ops.size(), options_.io_parallelism);  // per-op status consumed below; CRC gate backstops
  // Shard i's current bytes (user buffer or padded temp).
  auto shard_bytes = [&](size_t i) -> const uint8_t* {
    return temps[i].empty() ? data + i * L : temps[i].data();
  };
  // Per-shard CRCs (when the writer stamped them) LOCALIZE corruption: a
  // shard whose bytes arrived but fail its own CRC is treated exactly like
  // a missing shard, so the one reconstruction path below absorbs any mix
  // of lost and bit-rotten shards up to m — multi-shard corruption included
  // (the object-level CRC alone can only detect that case, not repair it).
  const bool stamped = verify && copy.shard_crcs.size() == k + m;
  size_t condemned = 0;  // shards whose bytes arrived but failed their CRC
  auto shard_corrupt = [&](size_t i, const uint8_t* bytes) {
    if (!stamped) return false;
    if (crc32c(bytes, L) == copy.shard_crcs[i]) return false;
    const auto& s = copy.shards[i];
    LOG_WARN << "ec read: shard " << i << " corrupt (pool " << s.pool_id << ", worker "
             << s.worker_id << ")";
    ++condemned;
    return true;
  };
  std::vector<bool> have(k + m, false);
  size_t missing = 0;
  for (size_t i = 0; i < k; ++i) {
    have[i] = padding_only[i] ||
              (addressable[i] && ops[i].status == ErrorCode::OK &&
               !shard_corrupt(i, shard_bytes(i)));
    if (!have[i]) ++missing;
  }
  auto copy_out = [&](size_t i, const uint8_t* src) {
    if (valid_of(i) > 0 && valid_of(i) < L) std::memcpy(data + i * L, src, valid_of(i));
  };
  // Parity fetch (shared by the degraded path and the corruption hunt).
  std::vector<std::vector<uint8_t>> parity;
  auto fetch_parity = [&] {
    if (!parity.empty()) return;
    parity.assign(m, std::vector<uint8_t>(L));
    std::vector<transport::WireOp> pops(m);
    for (size_t j = 0; j < m; ++j) {
      if (!transport::make_wire_op(copy.shards[k + j], 0, parity[j].data(), L, pops[j])) {
        addressable[k + j] = false;
        pops[j] = {};
      }
    }
    (void)data_->read_batch(pops.data(), pops.size(), options_.io_parallelism);  // per-op status consumed below; CRC gate backstops
    for (size_t j = 0; j < m; ++j)
      have[k + j] = addressable[k + j] && pops[j].status == ErrorCode::OK &&
                    !shard_corrupt(k + j, parity[j].data());
  };
  // Verifies the object CRC treating per-shard sources; `override_i`/bytes
  // substitute one shard (the corruption hunt's candidate reconstruction).
  auto crc_with = [&](size_t override_i, const uint8_t* override_bytes) {
    uint32_t crc = 0;
    for (size_t i = 0; i < k; ++i) {
      const uint64_t valid = valid_of(i);
      if (valid == 0) break;
      const uint8_t* src = i == override_i ? override_bytes : shard_bytes(i);
      crc = crc32c(src, valid, crc);
    }
    return crc;
  };

  if (missing == 0) {
    if (!verify || copy.content_crc == 0 || crc_with(k + m, nullptr) == copy.content_crc) {
      for (size_t i = 0; i < k; ++i) {
        if (!temps[i].empty()) copy_out(i, temps[i].data());
      }
      return ErrorCode::OK;
    }
    // CRC mismatch with every data shard readable: one of them is silently
    // corrupt (bit rot). Hunt it — reconstruct each candidate from parity
    // in turn and keep the variant whose CRC matches.
    LOG_WARN << "ec read: content crc mismatch, hunting the corrupt shard";
    fetch_parity();
    std::vector<uint8_t> candidate(L);
    for (size_t i = 0; i < k; ++i) {
      if (valid_of(i) == 0) break;  // padding shards cannot corrupt the crc
      std::vector<const uint8_t*> present(k + m, nullptr);
      for (size_t x = 0; x < k; ++x) {
        if (x != i) present[x] = shard_bytes(x);
      }
      for (size_t j = 0; j < m; ++j) {
        if (have[k + j]) present[k + j] = parity[j].data();
      }
      std::vector<uint8_t*> out(k, nullptr);
      out[i] = candidate.data();
      if (!ec::rs_reconstruct(present.data(), k, m, L, out.data())) continue;
      if (crc_with(i, candidate.data()) == copy.content_crc) {
        LOG_WARN << "ec read: shard " << i << " was corrupt; reconstructed through parity";
        const uint64_t valid = valid_of(i);
        std::memcpy(data + i * L, candidate.data(), valid);
        for (size_t x = 0; x < k; ++x) {
          if (x != i && !temps[x].empty()) copy_out(x, temps[x].data());
        }
        return ErrorCode::OK;
      }
    }
    return ErrorCode::CHECKSUM_MISMATCH;  // multi-shard corruption: beyond m=?
  }
  // Beyond tolerance: when CRC condemnation contributed, report corruption
  // (scrubbers key off CHECKSUM_MISMATCH, not transport loss).
  if (missing > m) {
    return condemned > 0 ? ErrorCode::CHECKSUM_MISMATCH : ErrorCode::NO_COMPLETE_WORKER;
  }

  // Degraded read: fetch parity shards, reconstruct the missing data.
  LOG_WARN << "ec read: " << missing << " data shard(s) unreadable, reconstructing";
  fetch_parity();

  std::vector<std::vector<uint8_t>> rebuilt(k);
  std::vector<const uint8_t*> present(k + m, nullptr);
  std::vector<uint8_t*> out(k, nullptr);
  for (size_t i = 0; i < k; ++i) {
    if (have[i]) {
      present[i] = shard_bytes(i);
    } else {
      rebuilt[i].resize(L);
      out[i] = rebuilt[i].data();
    }
  }
  for (size_t j = 0; j < m; ++j) {
    if (have[k + j]) present[k + j] = parity[j].data();
  }
  if (!ec::rs_reconstruct(present.data(), k, m, L, out.data()))
    return condemned > 0 ? ErrorCode::CHECKSUM_MISMATCH : ErrorCode::NO_COMPLETE_WORKER;
  for (size_t i = 0; i < k; ++i) {
    if (have[i]) {
      if (!temps[i].empty()) copy_out(i, temps[i].data());
    } else if (valid_of(i) > 0) {
      std::memcpy(data + i * L, rebuilt[i].data(), valid_of(i));
    }
  }
  if (verify && copy.content_crc != 0) {
    uint32_t crc = 0;
    for (size_t i = 0; i < k && valid_of(i) > 0; ++i) {
      const uint8_t* src = have[i] ? shard_bytes(i) : rebuilt[i].data();
      crc = crc32c(src, valid_of(i), crc);
    }
    if (crc != copy.content_crc) {
      LOG_WARN << "ec read: crc mismatch after degraded reconstruction";
      return ErrorCode::CHECKSUM_MISMATCH;
    }
  }
  return ErrorCode::OK;
}

// Shared by the single-object and batched paths: device-location shards are
// coalesced into ONE provider scatter/gather call (per-op device latency is
// the enemy, hbm_provider.h v2), wire shards move as one pipelined batch.
ErrorCode ObjectClient::transfer_copy(const CopyPlacement& copy, uint8_t* data, uint64_t size,
                                      bool is_write, bool verify) {
  if (!copy.inline_data.empty()) {
    // Inline tier: the metadata reply already carried the bytes — a read is
    // a memcpy (plus the CRC gate), and a write is meaningless here (inline
    // objects are written whole through put_inline, never through
    // placements).
    if (is_write || size != copy.inline_data.size()) return ErrorCode::INVALID_PARAMETERS;
    if (verify && copy.content_crc != 0 &&
        crc32c(copy.inline_data.data(), copy.inline_data.size()) != copy.content_crc)
      return ErrorCode::CHECKSUM_MISMATCH;
    std::memcpy(data, copy.inline_data.data(), copy.inline_data.size());
    return ErrorCode::OK;
  }
  if (copy.ec_data_shards > 0) return transfer_copy_ec(copy, data, size, is_write, verify);
  // Running-offset layout: shard i covers [offsets[i], offsets[i]+len).
  std::vector<uint64_t> offsets(copy.shards.size());
  uint64_t off = 0;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    offsets[i] = off;
    off += copy.shards[i].length;
  }
  if (off != size) return ErrorCode::INVALID_PARAMETERS;
  std::vector<transport::ShardJob> device_jobs;
  std::vector<size_t> wire_idx;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    if (std::holds_alternative<DeviceLocation>(copy.shards[i].location)) {
      device_jobs.push_back({&copy.shards[i], 0, data + offsets[i], copy.shards[i].length});
    } else {
      wire_idx.push_back(i);
    }
  }
  if (!device_jobs.empty()) {
    if (auto ec = transport::shard_io_batch(*data_, device_jobs.data(), device_jobs.size(),
                                            is_write);
        ec != ErrorCode::OK)
      return ec;
    // Device writes may be asynchronous; a single-object put must be durable
    // in the tier before put_complete is sent (put_many batches this flush).
    if (is_write) {
      if (auto ec = storage::hbm_flush(); ec != ErrorCode::OK) return ec;
    }
  }
  // Whole-object stamp preferred; per-shard stamps arm verification when
  // the content stamp is missing (e.g. an object completed through a
  // pre-v3 keystone during a rolling upgrade drops the appended
  // content_crc field but still applies shard_crcs — integrity must not
  // silently lapse for those).
  const bool have_shard_stamps =
      copy.shard_crcs.size() == copy.shards.size() && !copy.shards.empty();
  const bool check = verify && !is_write && (copy.content_crc != 0 || have_shard_stamps);
  std::vector<transport::WireOp> ops;
  if (!wire_idx.empty()) {
    // Wire shards move as one pipelined batch: every request issued before
    // any response is awaited, so a striped object costs ~one round trip.
    ops.reserve(wire_idx.size());
    for (size_t i : wire_idx) {
      const auto& shard = copy.shards[i];
      transport::WireOp op;
      if (!transport::make_wire_op(shard, 0, data + offsets[i], shard.length, op))
        return ErrorCode::NOT_IMPLEMENTED;  // FileLocation: worker-served
      // Verified reads: the transport hashes the bytes WHILE they move
      // (per-segment under the socket drain, fused with staging copies), so
      // the integrity check below needs no second pass over wire shards.
      op.want_crc = check;
      ops.push_back(op);
    }
    if (is_write)
      return data_->write_batch(ops.data(), ops.size(), options_.io_parallelism);
    if (auto ec = data_->read_batch(ops.data(), ops.size(), options_.io_parallelism);
        ec != ErrorCode::OK)
      return ec;
  } else if (is_write) {
    return ErrorCode::OK;
  }
  // Verify AFTER every shard (device and wire alike) has landed: a
  // device-only copy bit-rots just as silently as a host one. Wire shard
  // CRCs come from the transport; device shards (provider-filled) are
  // hashed here; the object CRC is their ordered combine.
  if (check) {
    std::vector<uint32_t> shard_crc(copy.shards.size(), 0);
    for (size_t j = 0; j < wire_idx.size(); ++j) shard_crc[wire_idx[j]] = ops[j].crc;
    for (size_t i = 0; i < copy.shards.size(); ++i) {
      if (std::holds_alternative<DeviceLocation>(copy.shards[i].location))
        shard_crc[i] = crc32c(data + offsets[i], copy.shards[i].length);
    }
    bool ok;
    if (copy.content_crc != 0) {
      uint32_t combined = 0;
      for (size_t i = 0; i < copy.shards.size(); ++i)
        combined = i == 0 ? shard_crc[i]
                          : crc32c_combine(combined, shard_crc[i], copy.shards[i].length);
      ok = combined == copy.content_crc;
    } else {
      // Shard-stamp fallback: every shard must match its own stamp.
      ok = true;
      for (size_t i = 0; i < copy.shards.size(); ++i) ok &= shard_crc[i] == copy.shard_crcs[i];
    }
    if (!ok) {
      LOG_WARN << "content crc mismatch on copy " << copy.copy_index
               << " (bit rot or torn write): treating as copy loss";
      // Stamped shard CRCs localize the rot for the operator/scrubber.
      if (have_shard_stamps) {
        for (size_t i = 0; i < copy.shards.size(); ++i) {
          if (shard_crc[i] != copy.shard_crcs[i]) {
            const auto& s = copy.shards[i];
            LOG_WARN << "  corrupt shard " << i << " (pool " << s.pool_id << ", worker "
                     << s.worker_id << ")";
          }
        }
      }
      return ErrorCode::CHECKSUM_MISMATCH;
    }
  }
  return ErrorCode::OK;
}

ErrorCode ObjectClient::transfer_copy_put(const CopyPlacement& copy, const uint8_t* data,
                                          uint64_t size) {
  // Writes never verify-on-read; the flag is meaningless here.
  return transfer_copy(copy, const_cast<uint8_t*>(data), size, /*is_write=*/true,
                       /*verify=*/false);
}

ErrorCode ObjectClient::transfer_copy_get(const CopyPlacement& copy, uint8_t* data,
                                          uint64_t size, bool verify) {
  return transfer_copy(copy, data, size, /*is_write=*/false, verify);
}

// ---- replica attempt engine (breakers + hedged reads) -----------------------

namespace {
// Breaker/hedge identity of a copy: its first wire-addressable shard's
// transport endpoint. Inline and device-only copies have none ("") — they
// are served locally, so they are neither breaker-ordered nor hedged.
const std::string& copy_endpoint(const CopyPlacement& copy) {
  static const std::string kNone;
  if (!copy.inline_data.empty()) return kNone;
  for (const auto& shard : copy.shards) {
    if (!shard.remote.endpoint.empty() &&
        std::holds_alternative<MemoryLocation>(shard.location))
      return shard.remote.endpoint;
  }
  return kNone;
}

uint64_t us_since(std::chrono::steady_clock::time_point t0) {
  return static_cast<uint64_t>(std::chrono::duration_cast<std::chrono::microseconds>(
                                   std::chrono::steady_clock::now() - t0)
                                   .count());
}
}  // namespace

std::vector<size_t> ObjectClient::order_copies(const std::vector<CopyPlacement>& copies) {
  std::vector<size_t> order(copies.size());
  for (size_t i = 0; i < copies.size(); ++i) order[i] = i;
  if (copies.size() < 2) return order;
  // Stable partition: copies on OPEN endpoints sort last — deprioritized,
  // never dropped. When every replica's breaker is open the read proceeds
  // in the original order (a degraded read beats no read).
  std::stable_partition(order.begin(), order.end(), [&](size_t i) {
    const std::string& ep = copy_endpoint(copies[i]);
    if (ep.empty()) return true;
    if (!breakers_.for_endpoint(ep)->open_now()) return true;
    // ordering: relaxed — monotonic stat counter.
    robust_counters().breaker_skips.fetch_add(1, std::memory_order_relaxed);
    return false;
  });
  return order;
}

void ObjectClient::record_copy_outcome(const CopyPlacement& copy, ErrorCode ec,
                                       uint64_t us) {
  const std::string& ep = copy_endpoint(copy);
  if (ep.empty()) return;
  auto breaker = breakers_.for_endpoint(ep);
  if (ec == ErrorCode::OK) {
    breaker->record_success(us);
  } else if (ec != ErrorCode::DEADLINE_EXCEEDED) {
    // A spent budget indicts the caller's deadline, not this endpoint;
    // everything else (transport error, corruption, shed) is the replica
    // failing to serve and feeds the trip counter.
    breaker->record_failure();
  }
}

uint64_t ObjectClient::hedge_delay_us() const {
  if (!options_.hedge_reads) return 0;
  if (options_.hedge_delay_ms > 0) return static_cast<uint64_t>(options_.hedge_delay_ms) * 1000;
  // Adaptive trigger: the op's observed p95 — ~5% of reads hedge, which is
  // the Tail-at-Scale sweet spot (tail coverage at ~negligible extra load).
  return read_latency_.quantile_us(0.95, options_.hedge_min_samples);
}

// Every race pays one thread spawn + one size-byte private buffer UP FRONT,
// even for the ~95% of reads whose primary beats the trigger. That price is
// structural, not an oversight: transfers block, so first-wins (returning
// the moment EITHER replica finishes — the entire p99 win) requires the
// primary off the calling thread from t0, and the primary needs a private
// buffer because the caller may have returned with the hedge's bytes while
// the primary thread is still writing. Callers that cannot hedge (one
// endpoint, no trigger samples, hedging off) never enter here; a persistent
// hedge executor would amortize the spawn if this path ever shows up hot.
ErrorCode ObjectClient::hedged_race(const CopyPlacement& primary,
                                    const CopyPlacement& secondary, uint64_t size,
                                    bool verify, uint8_t* out,
                                    const CopyPlacement** winner) {
  struct Race {
    Mutex m;
    CondVarAny cv;
    bool primary_done BTPU_GUARDED_BY(m){false};
    ErrorCode primary_ec BTPU_GUARDED_BY(m){ErrorCode::OK};
    // The primary fills a PRIVATE buffer: first-wins must never race the
    // caller's buffer (the hedge writes `out` directly on this thread).
    std::vector<uint8_t> primary_buf;
  };
  auto race = std::make_shared<Race>();
  race->primary_buf.resize(size);
  const auto t0 = std::chrono::steady_clock::now();
  // The ambient deadline is thread-local: hand it to the primary's thread
  // explicitly so its wire ops still carry the caller's budget.
  const Deadline op_deadline = current_op_deadline();
  if (!copy_endpoint(primary).empty()) breakers_.for_endpoint(copy_endpoint(primary))->allow();
  // ordering: acq_rel — the increment must be visible before the spawned
  // thread can decrement (release), and the destructor's acquire load of 0
  // must see every loser's writes as retired.
  hedge_inflight_.fetch_add(1, std::memory_order_acq_rel);
  BTPU_SCHED_DECL_SPAWN();
  std::thread([this, race, copy = primary, size, verify, op_deadline, t0] {
    BTPU_SCHED_ADOPT_SPAWNED();
    OpDeadlineScope scope(op_deadline);
    const ErrorCode ec = transfer_copy_get(copy, race->primary_buf.data(), size, verify);
    record_copy_outcome(copy, ec, us_since(t0));
    {
      MutexLock lock(race->m);
      race->primary_ec = ec;
      race->primary_done = true;
    }
    race->cv.notify_all();
#if defined(BTPU_SCHED)
    if (sched::mutant_enabled("hedge_notify_after_unlock")) {
      // PLANTED MUTANT — the exact pre-PR-5 bug shape this block's comment
      // below exists to prevent: decrement under the mutex but notify AFTER
      // unlock. The destructor's drain loop may observe inflight == 0 in
      // the unlock/notify window and free the client, so the notify below
      // touches a destroyed hedge_cv_ (SchedMutants matrix detects this as
      // an ASan heap-use-after-free within the seed budget).
      {
        MutexLock lock(hedge_mutex_);
        // ordering: acq_rel — pairs with the destructor's acquire drain load.
        hedge_inflight_.fetch_sub(1, std::memory_order_acq_rel);
      }
      hedge_cv_.notify_all();
      return;
    }
#endif
    {
      // Notify UNDER the mutex: the destructor's drain loop frees the client
      // the instant it observes inflight == 0, so a notify after unlock would
      // touch a destroyed condition variable.
      MutexLock lock(hedge_mutex_);
      // ordering: acq_rel — pairs with the destructor's acquire drain load.
      hedge_inflight_.fetch_sub(1, std::memory_order_acq_rel);
      hedge_cv_.notify_all();
    }
  }).detach();

  const uint64_t delay_us = hedge_delay_us();
  bool hedged = false;
  {
    MutexLock lock(race->m);
    const auto trigger = t0 + std::chrono::microseconds(delay_us);
    while (!race->primary_done) {
      if (race->cv.wait_until(lock, trigger) == std::cv_status::timeout &&
          !race->primary_done)
        break;
    }
    if (race->primary_done) {
      if (race->primary_ec == ErrorCode::OK) {
        std::memcpy(out, race->primary_buf.data(), size);
        read_latency_.record_us(us_since(t0));
        if (winner) *winner = &primary;
        return ErrorCode::OK;
      }
      // Primary failed before the trigger: the second attempt below is
      // ordinary failover, not a hedge.
    } else {
      hedged = true;
      // ordering: relaxed — monotonic stat counter.
      robust_counters().hedges_fired.fetch_add(1, std::memory_order_relaxed);
      flight::record(flight::Ev::kHedgeFired);
    }
  }

  // The hedge (or failover) runs on the calling thread, straight into `out`.
  if (!copy_endpoint(secondary).empty())
    breakers_.for_endpoint(copy_endpoint(secondary))->allow();
  const auto s0 = std::chrono::steady_clock::now();
  const ErrorCode sec_ec = transfer_copy_get(secondary, out, size, verify);
  record_copy_outcome(secondary, sec_ec, us_since(s0));

  MutexLock lock(race->m);
  if (sec_ec == ErrorCode::OK) {
    if (hedged && !race->primary_done) {
      // ordering: relaxed — monotonic stat counter.
      robust_counters().hedge_wins.fetch_add(1, std::memory_order_relaxed);
      flight::record(flight::Ev::kHedgeWin);
    }
    read_latency_.record_us(us_since(t0));
    if (winner) *winner = &secondary;
    return ErrorCode::OK;  // bytes already in `out`; the primary drains into its loser buffer
  }
  // Hedge failed: the primary is the only hope left — wait it out (its own
  // wire ops carry the deadline, so a spent budget aborts it server-side).
  while (!race->primary_done) race->cv.wait(lock);
  if (race->primary_ec == ErrorCode::OK) {
    std::memcpy(out, race->primary_buf.data(), size);
    read_latency_.record_us(us_since(t0));
    if (winner) *winner = &primary;
    return ErrorCode::OK;
  }
  // Corruption is the strongest signal (scrubbers key off it).
  if (sec_ec == ErrorCode::CHECKSUM_MISMATCH || race->primary_ec == ErrorCode::CHECKSUM_MISMATCH)
    return ErrorCode::CHECKSUM_MISMATCH;
  return race->primary_ec;
}

ErrorCode ObjectClient::attempt_copies(const std::vector<CopyPlacement>& copies,
                                       bool verify,
                                       const std::function<uint8_t*(uint64_t)>& buffer_for,
                                       uint64_t& got_size, const CopyPlacement** winner) {
  if (winner) *winner = nullptr;
  const std::vector<size_t> order = order_copies(copies);
  ErrorCode last = ErrorCode::NO_COMPLETE_WORKER;
  bool tried_hedge = false;
  for (size_t oi = 0; oi < order.size(); ++oi) {
    // A spent budget fails the op here instead of starting another replica
    // transfer nobody is waiting for (transport-independent: TCP ops also
    // carry the budget on the wire, but LOCAL/SHM have no wire to carry it).
    if (oi > 0 && current_op_deadline().expired()) {
      // ordering: relaxed — monotonic stat counter.
      robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
      return ErrorCode::DEADLINE_EXCEEDED;
    }
    const CopyPlacement& copy = copies[order[oi]];
    const uint64_t copy_size = copy_logical_size(copy);
    uint8_t* dst = buffer_for(copy_size);
    if (!dst) {
      // This copy cannot be accepted (caller's buffer too small). Keep the
      // cache-retry semantics: a stale cached size must not mask a fit.
      if (last == ErrorCode::NO_COMPLETE_WORKER) last = ErrorCode::BUFFER_OVERFLOW;
      continue;
    }
    // Hedge opportunity: two wire-served same-size candidates on DIFFERENT
    // endpoints, hedging enabled, and a trigger delay is known (fixed knob
    // or enough observed samples for a p95).
    if (!tried_hedge && options_.hedge_reads && oi + 1 < order.size()) {
      const CopyPlacement& second = copies[order[oi + 1]];
      const std::string& ep1 = copy_endpoint(copy);
      const std::string& ep2 = copy_endpoint(second);
      if (!ep1.empty() && !ep2.empty() && ep1 != ep2 &&
          copy_logical_size(second) == copy_size && hedge_delay_us() > 0) {
        tried_hedge = true;
        const ErrorCode hec = hedged_race(copy, second, copy_size, verify, dst, winner);
        if (hec == ErrorCode::OK) {
          got_size = copy_size;
          return ErrorCode::OK;
        }
        if (last != ErrorCode::CHECKSUM_MISMATCH) last = hec;
        ++oi;  // both candidates consumed
        continue;
      }
    }
    const std::string& ep = copy_endpoint(copy);
    if (!ep.empty()) breakers_.for_endpoint(ep)->allow();
    const auto t0 = std::chrono::steady_clock::now();
    const ErrorCode tec = transfer_copy_get(copy, dst, copy_size, verify);
    const uint64_t us = us_since(t0);
    record_copy_outcome(copy, tec, us);
    if (tec == ErrorCode::OK) {
      read_latency_.record_us(us);
      got_size = copy_size;
      if (winner) *winner = &copy;
      return ErrorCode::OK;
    }
    if (last != ErrorCode::CHECKSUM_MISMATCH) last = tec;
    LOG_WARN << "get copy " << copy.copy_index << " failed (" << to_string(tec)
             << "), trying next replica";
  }
  return last;
}

Result<std::vector<ObjectClient::ShardFinding>> ObjectClient::scrub_object(
    const ObjectKey& key) {
  auto copies = get_workers(key);
  if (!copies.ok()) return copies.error();
  std::vector<ShardFinding> findings;
  // Stamped copies: every shard of every copy reads as ONE pipelined wire
  // batch (per-op status lands on its finding), so the audit costs ~one
  // round trip per object, not one per shard. Device-located shards can't
  // ride the wire batch; they go through shard_io below.
  std::vector<transport::WireOp> ops;
  std::vector<size_t> op_finding;
  std::vector<std::vector<uint8_t>> bufs;
  struct Deferred {  // device shards + expected CRC, checked after the batch
    size_t finding;
    const ShardPlacement* shard;
    uint32_t expect;
  };
  std::vector<Deferred> deferred;
  std::vector<uint32_t> expected;  // parallel to findings (stamped ones)
  std::vector<uint8_t> buf;
  for (const auto& copy : copies.value()) {
    if (copy.shard_crcs.size() == copy.shards.size() && !copy.shards.empty()) {
      // Writer-stamped shard CRCs: verify each shard in isolation so the
      // report names exactly which worker/pool holds rotten bytes.
      for (size_t i = 0; i < copy.shards.size(); ++i) {
        const auto& shard = copy.shards[i];
        findings.push_back({copy.copy_index, static_cast<uint32_t>(i), shard.pool_id,
                            shard.worker_id, ErrorCode::OK});
        expected.resize(findings.size(), 0);
        expected.back() = copy.shard_crcs[i];
        bufs.emplace_back(shard.length);
        transport::WireOp op;
        if (transport::make_wire_op(shard, 0, bufs.back().data(), shard.length, op)) {
          ops.push_back(op);
          op_finding.push_back(findings.size() - 1);
        } else {
          deferred.push_back({findings.size() - 1, &shard, copy.shard_crcs[i]});
        }
      }
      continue;
    }
    // Pre-shard-CRC copy: the object CRC can only judge the copy as a whole.
    const uint64_t size = copy_logical_size(copy);
    ShardFinding f{copy.copy_index, ShardFinding::kWholeCopy, {}, {}, ErrorCode::OK};
    try {
      buf.resize(size);
      f.status = transfer_copy_get(copy, buf.data(), size, /*verify=*/true);
    } catch (const std::bad_alloc&) {
      f.status = ErrorCode::OUT_OF_MEMORY;
    }
    findings.push_back(std::move(f));
    expected.resize(findings.size(), 0);
  }
  if (!ops.empty()) (void)data_->read_batch(ops.data(), ops.size(), options_.io_parallelism);  // per-op status consumed below
  for (size_t j = 0; j < ops.size(); ++j) {
    auto& f = findings[op_finding[j]];
    if (ops[j].status != ErrorCode::OK) {
      f.status = ops[j].status;
    } else if (crc32c(ops[j].buf, ops[j].len) != expected[op_finding[j]]) {
      f.status = ErrorCode::CHECKSUM_MISMATCH;
    }
  }
  for (const auto& d : deferred) {
    auto& f = findings[d.finding];
    buf.resize(d.shard->length);
    if (auto ec = transport::shard_io(*data_, *d.shard, 0, buf.data(), d.shard->length,
                                      /*is_write=*/false);
        ec != ErrorCode::OK) {
      f.status = ec;
    } else if (crc32c(buf.data(), d.shard->length) != d.expect) {
      f.status = ErrorCode::CHECKSUM_MISMATCH;
    }
  }
  return findings;
}

// ---- batched object I/O ----------------------------------------------------

namespace {

// Per-item shard jobs for a whole batch, partitioned by data path.
struct BatchJobs {
  std::vector<transport::ShardJob> device;   // all items' device shards
  std::vector<size_t> device_item;           // item index per device job
  std::vector<transport::ShardJob> wire;     // all items' wire shards
  std::vector<size_t> wire_item;
};

// Splits one copy of `size` bytes at `data` into jobs, appending to `jobs`.
// Returns INVALID_PARAMETERS when the shard lengths do not sum to size.
// `crcs_out` (when non-null) receives this copy's per-shard CRC32C stamps —
// computed here because the put path is the one place the shard boundaries
// and the bytes are both in hand.
ErrorCode append_copy_jobs(const CopyPlacement& copy, uint8_t* data, uint64_t size,
                           size_t item_index, BatchJobs& jobs,
                           CopyShardCrcs* crcs_out = nullptr) {
  if (crcs_out) {
    crcs_out->copy_index = copy.copy_index;
    crcs_out->crcs.clear();
    crcs_out->crcs.reserve(copy.shards.size());
  }
  uint64_t off = 0;
  for (const auto& shard : copy.shards) {
    if (off + shard.length > size) return ErrorCode::INVALID_PARAMETERS;
    transport::ShardJob job{&shard, 0, data + off, shard.length};
    if (std::holds_alternative<DeviceLocation>(shard.location)) {
      jobs.device.push_back(job);
      jobs.device_item.push_back(item_index);
    } else {
      jobs.wire.push_back(job);
      jobs.wire_item.push_back(item_index);
    }
    if (crcs_out) crcs_out->crcs.push_back(crc32c(data + off, shard.length));
    off += shard.length;
  }
  return off == size ? ErrorCode::OK : ErrorCode::INVALID_PARAMETERS;
}

// Coded-copy batch helpers. Arena owns padded-data and parity buffers until
// the wire batch executes (inner-vector buffers stay put when the arena
// grows). EC pools are wire-only by placement, so every job is a wire job.
ErrorCode append_ec_put_jobs(const CopyPlacement& copy, const uint8_t* data, uint64_t size,
                             size_t item_index, std::vector<std::vector<uint8_t>>& arena,
                             BatchJobs& jobs, CopyShardCrcs* crcs_out = nullptr) {
  const size_t k = copy.ec_data_shards, m = copy.ec_parity_shards;
  if (copy.shards.size() != k + m || size != copy.ec_object_size)
    return ErrorCode::INVALID_PARAMETERS;
  const uint64_t L = copy.shards.front().length;
  for (const auto& s : copy.shards) {
    if (s.length != L) return ErrorCode::INVALID_PARAMETERS;
  }
  std::vector<const uint8_t*> data_ptrs(k);
  for (size_t i = 0; i < k; ++i) {
    const uint64_t start = i * L;
    const uint64_t valid = start >= size ? 0 : std::min<uint64_t>(L, size - start);
    if (valid == L) {
      data_ptrs[i] = data + start;
    } else {
      arena.emplace_back(L, 0);
      if (valid > 0) std::memcpy(arena.back().data(), data + start, valid);
      data_ptrs[i] = arena.back().data();
    }
  }
  std::vector<uint8_t*> parity_ptrs(m);
  for (size_t j = 0; j < m; ++j) {
    arena.emplace_back(L);
    parity_ptrs[j] = arena.back().data();
  }
  if (!ec::rs_encode(data_ptrs.data(), k, parity_ptrs.data(), m, L))
    return ErrorCode::INVALID_PARAMETERS;
  if (crcs_out) {
    crcs_out->copy_index = copy.copy_index;
    crcs_out->crcs.clear();
    crcs_out->crcs.reserve(k + m);
  }
  for (size_t i = 0; i < k + m; ++i) {
    uint8_t* buf = i < k ? const_cast<uint8_t*>(data_ptrs[i]) : parity_ptrs[i - k];
    jobs.wire.push_back({&copy.shards[i], 0, buf, L});
    jobs.wire_item.push_back(item_index);
    // Shard CRCs cover the full L wire bytes (padding included) so readers
    // and scrubbers can verify a shard without knowing the object size.
    if (crcs_out) crcs_out->crcs.push_back(crc32c(buf, L));
  }
  return ErrorCode::OK;
}

// Post-batch copy of a padded shard's valid bytes into the user buffer.
struct EcReadFixup {
  size_t item;
  uint8_t* dst;
  const uint8_t* src;
  uint64_t n;
};

// Appends the k data-shard reads of one coded copy (the healthy fast path;
// a failed item falls back to the full reconstructing read).
void append_ec_get_jobs(const CopyPlacement& copy, uint8_t* buffer, uint64_t size,
                        size_t item_index, std::vector<std::vector<uint8_t>>& arena,
                        BatchJobs& jobs, std::vector<EcReadFixup>& fixups) {
  const size_t k = copy.ec_data_shards;
  const uint64_t L = copy.shards.front().length;
  for (size_t i = 0; i < k; ++i) {
    const uint64_t start = i * L;
    const uint64_t valid = start >= size ? 0 : std::min<uint64_t>(L, size - start);
    if (valid == 0) continue;  // pure padding: nothing to read
    uint8_t* buf;
    if (valid == L) {
      buf = buffer + start;
    } else {
      arena.emplace_back(L);
      buf = arena.back().data();
      fixups.push_back({item_index, buffer + start, buf, valid});
    }
    jobs.wire.push_back({&copy.shards[i], 0, buf, L});
    jobs.wire_item.push_back(item_index);
  }
}

// Range (offset, length) -> CRC32C map. Prefilled by the transport's fused
// write hashes; stamp_copy_crcs fills the gaps (device shards, failed ops).
using RangeCrcMap = std::map<std::pair<uint64_t, uint64_t>, uint32_t>;

// Per-copy shard CRC stamps for replicated/striped copies: replica copies
// cover the SAME bytes, so each distinct (offset, length) range is hashed
// once and reused. Wire shards arrive pre-hashed in `range_crc` (the
// transport fused the CRC into its copy/send of the bytes), so the typical
// put stamps every shard with ZERO standalone passes; only device shards
// and retried ranges fall back to hashing here.
std::vector<CopyShardCrcs> stamp_copy_crcs(const std::vector<CopyPlacement>& copies,
                                           const uint8_t* data, RangeCrcMap& range_crc) {
  std::vector<CopyShardCrcs> out;
  out.reserve(copies.size());
  for (const auto& copy : copies) {
    CopyShardCrcs crcs;
    crcs.copy_index = copy.copy_index;
    crcs.crcs.reserve(copy.shards.size());
    uint64_t off = 0;
    for (const auto& shard : copy.shards) {
      auto [it, fresh] = range_crc.try_emplace({off, shard.length}, 0);
      if (fresh) it->second = crc32c(data + off, shard.length);
      crcs.crcs.push_back(it->second);
      off += shard.length;
    }
    out.push_back(std::move(crcs));
  }
  return out;
}

// Whole-object CRC folded from one copy's shard stamps (shards tile the
// object contiguously in order — append_copy_jobs enforces exact cover).
// With fused wire hashes this makes the content stamp FREE: no pass over
// the bytes anywhere in the put path.
uint32_t fold_content_crc(const CopyShardCrcs& crcs, const CopyPlacement& copy) {
  uint32_t crc = 0;
  for (size_t i = 0; i < crcs.crcs.size(); ++i)
    crc = i == 0 ? crcs.crcs[0] : crc32c_combine(crc, crcs.crcs[i], copy.shards[i].length);
  return crc;
}

// Read-side mirror of stamp_copy_crcs: folds one copy's object CRC from the
// transport's fused read hashes, hashing only the gaps (device shards,
// skipped ops, the rare genuine-zero crc). The batched verified get then
// checks integrity with ~no second pass over wire bytes.
uint32_t fold_ranges_crc(const CopyPlacement& copy, const uint8_t* base, RangeCrcMap& ranges) {
  uint32_t crc = 0;
  uint64_t off = 0;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    const uint64_t len = copy.shards[i].length;
    auto [it, fresh] = ranges.try_emplace({off, len}, 0);
    if (fresh) it->second = crc32c(base + off, len);
    crc = i == 0 ? it->second : crc32c_combine(crc, it->second, len);
    off += len;
  }
  return crc;
}

// Collects one item's fused write hashes out of run_wire_jobs' output into
// the (object offset, length) -> crc form stamp_copy_crcs consumes. `item`
// filters a batch down to one object; 0-crc entries (skipped/failed ops, or
// the rare genuine zero) fall through to stamp_copy_crcs' own hashing.
void harvest_wire_ranges(const BatchJobs& jobs, const std::vector<uint32_t>& wire_crcs,
                         size_t item, const uint8_t* base, RangeCrcMap& ranges) {
  for (size_t j = 0; j < jobs.wire.size() && j < wire_crcs.size(); ++j) {
    if (jobs.wire_item[j] != item || wire_crcs[j] == 0) continue;
    ranges[{static_cast<uint64_t>(jobs.wire[j].buf - base), jobs.wire[j].len}] =
        wire_crcs[j];
  }
}

// Runs the wire jobs as ONE pipelined batch; per-op failures land on their
// item, jobs of items that already failed are skipped (their reservation is
// cancelled by the caller anyway). With `wire_crcs` (put path) ops ask the
// transport for a fused hash of the bytes they moved; (*wire_crcs)[j] gets
// job j's crc for ops that completed (entries stay 0 for skipped/failed
// jobs — stamp_copy_crcs treats a missing range as "hash it here").
// `crc_items` (parallel to the caller's items, may be null = all) limits
// the request to items whose hashes will actually be harvested — EC items
// stamp during encode, so hashing their padded/parity ranges is waste.
void run_wire_jobs(transport::TransportClient& client, const BatchJobs& jobs, bool is_write,
                   size_t max_concurrency, std::vector<ErrorCode>& item_errors,
                   std::vector<uint32_t>* wire_crcs = nullptr,
                   const std::vector<bool>* crc_items = nullptr) {
  if (jobs.wire.empty()) return;
  if (wire_crcs) wire_crcs->assign(jobs.wire.size(), 0);
  std::vector<transport::WireOp> ops;
  std::vector<size_t> op_item, op_job;
  ops.reserve(jobs.wire.size());
  for (size_t j = 0; j < jobs.wire.size(); ++j) {
    const size_t item = jobs.wire_item[j];
    if (item_errors[item] != ErrorCode::OK) continue;
    const auto& job = jobs.wire[j];
    transport::WireOp op;
    if (!transport::make_wire_op(*job.shard, job.in_off, job.buf, job.len, op)) {
      // FileLocation: worker-served, never a client target.
      item_errors[item] = ErrorCode::NOT_IMPLEMENTED;
      continue;
    }
    op.want_crc =
        wire_crcs != nullptr && (!crc_items || (item < crc_items->size() && (*crc_items)[item]));
    ops.push_back(op);
    op_item.push_back(item);
    op_job.push_back(j);
  }
  if (is_write) {
    (void)client.write_batch(ops.data(), ops.size(), max_concurrency);  // per-op status folded into item_errors below
  } else {
    (void)client.read_batch(ops.data(), ops.size(), max_concurrency);  // per-op status folded into item_errors below
  }
  for (size_t j = 0; j < ops.size(); ++j) {
    if (ops[j].status != ErrorCode::OK && item_errors[op_item[j]] == ErrorCode::OK)
      item_errors[op_item[j]] = ops[j].status;
    if (wire_crcs && ops[j].status == ErrorCode::OK) (*wire_crcs)[op_job[j]] = ops[j].crc;
  }
}

// Runs the device jobs as ONE provider batch; when the whole batch fails,
// retries per job so one poisoned item cannot sink the rest, recording
// errors into per-item slots.
void run_device_jobs(transport::TransportClient& client, const BatchJobs& jobs, bool is_write,
                     std::vector<ErrorCode>& item_errors) {
  if (jobs.device.empty()) return;
  if (transport::shard_io_batch(client, jobs.device.data(), jobs.device.size(), is_write) ==
      ErrorCode::OK)
    return;
  for (size_t j = 0; j < jobs.device.size(); ++j) {
    if (item_errors[jobs.device_item[j]] != ErrorCode::OK) continue;
    if (auto ec = transport::shard_io_batch(client, &jobs.device[j], 1, is_write);
        ec != ErrorCode::OK)
      item_errors[jobs.device_item[j]] = ec;
  }
}

}  // namespace

std::vector<Result<std::vector<CopyPlacement>>> ObjectClient::get_workers_many(
    const std::vector<ObjectKey>& keys) {
  if (embedded_) return embedded_->batch_get_workers(keys);
  auto r = rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& c) {
    return c.batch_get_workers(keys);
  });
  if (!r.ok())
    return std::vector<Result<std::vector<CopyPlacement>>>(keys.size(), r.error());
  return std::move(r.value());
}

std::vector<ErrorCode> ObjectClient::put_many(const std::vector<PutItem>& items) {
  return put_many(items, options_.default_config);
}

std::vector<ErrorCode> ObjectClient::put_many(const std::vector<PutItem>& items,
                                              const WorkerConfig& config) {
  trace::OpScope op_trace("put_many");  // inert when put() already opened one
  TRACE_SPAN("client.put_many");
  // Nested scopes tighten: when put() already opened the op deadline this
  // is a no-op, and a direct put_many call gets its own budget.
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  std::vector<ErrorCode> results(items.size(), ErrorCode::OK);
  if (items.empty()) return results;

  std::vector<BatchPutStartItem> starts;
  starts.reserve(items.size());
  for (const auto& item : items) {
    // A put of a removed-then-recreated key must not let this client's own
    // cached placement serve the PREVIOUS object's bytes afterwards.
    invalidate_placements(item.key);
    // content_crc rides in batch_put_complete instead (folded from the
    // transport's fused shard hashes) — hashing the bytes here would cost a
    // full standalone pass before the transfer even starts.
    starts.push_back({item.key, item.size, config, 0});
  }
  std::vector<Result<std::vector<CopyPlacement>>> placed;
  if (embedded_) {
    placed = embedded_->batch_put_start(starts);
  } else {
    auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
      // Deferred content stamps require a keystone that applies them at
      // put_complete. Against an older server, stamp at put_start like the
      // pre-fusion path — otherwise every object written during a rolling
      // upgrade would complete unstamped and verified reads would silently
      // skip the CRC gate. One ping learns the version (and a v1 server
      // that cannot answer it stays at 0 = conservative up-front hashing).
      if (c.server_proto_version() == 0) (void)c.ping();  // best-effort probe; 0 keeps conservative stamping
      if (c.server_proto_version() < rpc::kProtoContentCrcAtComplete) {
        for (size_t i = 0; i < starts.size(); ++i) {
          if (starts[i].content_crc == 0)
            starts[i].content_crc = crc32c(items[i].data, items[i].size);
        }
      }
      return c.batch_put_start(starts);
    });
    if (!r.ok()) return std::vector<ErrorCode>(items.size(), r.error());
    placed = std::move(r.value());
  }

  BatchJobs jobs;
  std::vector<std::vector<uint8_t>> ec_arena;
  std::vector<std::vector<CopyShardCrcs>> item_crcs(items.size());
  std::vector<bool> fuse_crc(items.size(), true);  // EC items stamp at encode
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok()) {
      results[i] = placed[i].error();
      continue;
    }
    auto* data = const_cast<uint8_t*>(static_cast<const uint8_t*>(items[i].data));
    if (!placed[i].value().empty() && placed[i].value().front().ec_data_shards > 0) {
      // Erasure-coded item: encode now, ship with the shared wire batch.
      fuse_crc[i] = false;
      CopyShardCrcs crcs;
      results[i] = append_ec_put_jobs(placed[i].value().front(), data, items[i].size, i,
                                      ec_arena, jobs, &crcs);
      if (results[i] == ErrorCode::OK) item_crcs[i].push_back(std::move(crcs));
      continue;
    }
    for (const auto& copy : placed[i].value()) {
      // Shard CRCs are computed AFTER the device dispatch below, riding
      // under the in-flight transfer instead of serializing before it.
      if (auto ec = append_copy_jobs(copy, data, items[i].size, i, jobs, nullptr);
          ec != ErrorCode::OK) {
        results[i] = ec;
        break;
      }
    }
  }

  std::vector<uint32_t> wire_crcs;
  {
    TRACE_SPAN("client.put.transfer");
    run_device_jobs(*data_, jobs, /*is_write=*/true, results);
    run_wire_jobs(*data_, jobs, /*is_write=*/true, options_.io_parallelism, results,
                  &wire_crcs, &fuse_crc);
  }
  // Replicated/striped shard CRC stamps: harvested from the transport's
  // FUSED write hashes (computed while the bytes moved), so the typical put
  // sweeps the source bytes zero extra times; device shards and retried
  // ranges are hashed in stamp_copy_crcs, overlapped with any still-
  // draining device DMA (the flush below is the only wait). EC items
  // computed theirs during encode (parity shards have no plain-data
  // source; their wire bufs live in the arena, so they are excluded from
  // the offset harvest).
  std::vector<uint32_t> item_content_crcs(items.size(), 0);
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok() || results[i] != ErrorCode::OK) continue;
    if (!placed[i].value().empty() && placed[i].value().front().ec_data_shards > 0) {
      // Coded object: shard stamps cover padded/parity wire bytes, so the
      // whole-object stamp still needs its own pass here.
      item_content_crcs[i] = crc32c(items[i].data, items[i].size);
      continue;
    }
    const auto* base = static_cast<const uint8_t*>(items[i].data);
    RangeCrcMap ranges;
    harvest_wire_ranges(jobs, wire_crcs, i, base, ranges);
    item_crcs[i] = stamp_copy_crcs(placed[i].value(), base, ranges);
    if (!item_crcs[i].empty() && !placed[i].value().empty())
      item_content_crcs[i] = fold_content_crc(item_crcs[i][0], placed[i].value()[0]);
  }
  // Device writes may be asynchronous; put_complete must not be sent until
  // the bytes are durably in the tier.
  if (!jobs.device.empty()) {
    if (auto ec = storage::hbm_flush(); ec != ErrorCode::OK) {
      for (size_t j = 0; j < jobs.device.size(); ++j) {
        if (results[jobs.device_item[j]] == ErrorCode::OK) results[jobs.device_item[j]] = ec;
      }
    }
  }

  std::vector<ObjectKey> completes, cancels;
  std::vector<std::vector<CopyShardCrcs>> complete_crcs;
  std::vector<uint32_t> complete_content_crcs;
  std::vector<size_t> complete_idx;
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok()) continue;  // never reserved
    if (results[i] == ErrorCode::OK) {
      completes.push_back(items[i].key);
      complete_crcs.push_back(std::move(item_crcs[i]));
      complete_content_crcs.push_back(item_content_crcs[i]);
      complete_idx.push_back(i);
    } else {
      LOG_WARN << "put " << items[i].key << " transfer failed ("
               << to_string(results[i]) << "), cancelling";
      cancels.push_back(items[i].key);
    }
  }
  if (!completes.empty()) {
    std::vector<ErrorCode> ecs;
    if (embedded_) {
      ecs = embedded_->batch_put_complete(completes, complete_crcs, complete_content_crcs);
    } else {
      auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
        return c.batch_put_complete(completes, complete_crcs, complete_content_crcs);
      });
      ecs = r.ok() ? std::move(r.value())
                   : std::vector<ErrorCode>(completes.size(), r.error());
    }
    for (size_t j = 0; j < complete_idx.size() && j < ecs.size(); ++j)
      results[complete_idx[j]] = ecs[j];
  }
  if (!cancels.empty()) {
    if (embedded_) {
      embedded_->batch_put_cancel(cancels);
    } else {
      (void)rpc_failover(/*idempotent=*/false,
                   [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(cancels); });  // best-effort cancel; slot TTL reclaims
    }
  }
  return results;
}

std::optional<ErrorCode> ObjectClient::put_via_inline(const ObjectKey& key, const void* data,
                                                      uint64_t size,
                                                      const WorkerConfig& config) {
  // Explicit placement intent (replicas, EC, a tier or node preference)
  // means the caller wants bytes ON THE DATA PLANE — e.g. 2 KiB of HBM-tier
  // metadata read device-locally — so only default-placement puts are
  // offered to the inline tier.
  if (options_.inline_max_bytes == 0 || size == 0 || size > options_.inline_max_bytes ||
      config.replication_factor > 1 || config.ec_parity_shards > 0 ||
      !config.preferred_classes.empty() || !config.preferred_node.empty() || key.empty() ||
      key.find('\x01') != ObjectKey::npos)
    return std::nullopt;
  const int64_t now_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                             std::chrono::steady_clock::now().time_since_epoch())
                             .count();
  // ordering: relaxed — advisory backoff gate: a stale read just means one extra (harmless) inline probe.
  if (now_ms < inline_retry_after_ms_.load(std::memory_order_relaxed)) return std::nullopt;

  invalidate_placements(key);  // same re-created-key rule as the normal path
  const uint32_t crc = crc32c(data, size);
  std::string bytes(static_cast<const char*>(data), size);
  ErrorCode ec;
  if (embedded_) {
    ec = embedded_->put_inline(key, config, crc, std::move(bytes));
  } else {
    // Mutation: NOT_LEADER rotates, lost replies do not retry (matching
    // put_complete's stance — a resend could misreport ALREADY_EXISTS).
    ec = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
      return r.put_inline(key, config, crc, bytes);
    });
  }
  if (ec == ErrorCode::NOT_IMPLEMENTED) {
    // Refused: disabled, the server's limit is smaller than ours, or the
    // budget is spent. Budget refusals clear as objects expire, so re-probe
    // after a while rather than pinning the fallback forever. Jittered
    // around the configured backoff (was a fixed 60 s) so a fleet of
    // clients does not re-probe a recovering keystone in lockstep.
    const RetryPolicy probe{options_.inline_refusal_backoff_ms,
                            options_.inline_refusal_backoff_ms, 1.0, 1};
    inline_retry_after_ms_.store(now_ms + static_cast<int64_t>(probe.backoff_ms(0)),
                                 // ordering: relaxed — advisory backoff gate (see the read above).
                                 std::memory_order_relaxed);
    return std::nullopt;
  }
  return ec;
}

std::optional<ErrorCode> ObjectClient::put_via_slot(const ObjectKey& key, const void* data,
                                                    uint64_t size,
                                                    const WorkerConfig& config) {
  if (embedded_ || options_.put_slots == 0 || size == 0 ||
      size > options_.put_slot_max_bytes || config.ec_parity_shards > 0 || key.empty() ||
      key.find('\x01') != ObjectKey::npos)
    return std::nullopt;
  // Slot classes are exact-(size, config): the commit renames placements
  // verbatim, so shard geometry must match the bytes exactly. Repeat puts
  // of one class — the fixed-block serving pattern — hit the pool.
  std::string class_key;
  {
    wire::Writer w;
    wire::encode(w, config);
    const auto cfg = w.take();
    class_key.assign(reinterpret_cast<const char*>(cfg.data()), cfg.size());
    class_key += '/' + std::to_string(size);
  }

  invalidate_placements(key);  // same re-created-key rule as the normal path
  PutSlot slot;
  auto slot_granted_at = std::chrono::steady_clock::now();
  std::vector<ObjectKey> expired;
  {
    MutexLock lock(slot_mutex_);
    if (slots_unsupported_) return std::nullopt;
    auto& pool = slot_pool_[class_key];
    // Age gate: a slot the keystone may have reclaimed (slot TTL) must
    // never see a data-plane write — its ranges could already belong to
    // another object. Expired entries are cancelled below, not used.
    const auto now = std::chrono::steady_clock::now();
    const auto max_age = std::chrono::milliseconds(options_.put_slot_max_age_ms);
    while (!pool.empty()) {
      PooledSlot entry = std::move(pool.back());
      pool.pop_back();
      if (now - entry.granted_at > max_age) {
        expired.push_back(std::move(entry.slot.slot_key));
        continue;
      }
      slot = std::move(entry.slot);
      slot_granted_at = entry.granted_at;
      break;
    }
  }
  if (!expired.empty()) {
    // Best-effort release of the stale reservations (the TTL reclaims them
    // regardless); outside the pool lock, one batch RPC.
    (void)rpc_failover(/*idempotent=*/false,
                 [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(expired); });  // best-effort cancel; slot TTL reclaims
  }
  if (slot.slot_key.empty()) {
    // First put of this class pays the same two RTTs as the normal path,
    // but the grant covers this put AND the pool for the next ones.
    auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
      return c.put_start_pooled(size, config, options_.put_slots + 1, slot_tag_);
    });
    if (!r.ok() || r.value().empty()) {
      if (r.error() == ErrorCode::NOT_IMPLEMENTED) {
        // Old server or slots disabled server-side: stop asking.
        MutexLock lock(slot_mutex_);
        slots_unsupported_ = true;
      }
      return std::nullopt;  // the normal path reports the real outcome
    }
    auto slots = std::move(r).value();
    slot = std::move(slots.back());
    slots.pop_back();
    if (!slots.empty()) {
      const auto now = std::chrono::steady_clock::now();
      MutexLock lock(slot_mutex_);
      auto& pool = slot_pool_[class_key];
      for (auto& s : slots) pool.push_back({std::move(s), now});
    }
  }

  // Transfer into the slot's placements — the same jobs machinery as
  // put_many, for one item.
  auto* bytes = const_cast<uint8_t*>(static_cast<const uint8_t*>(data));
  uint32_t content_crc = 0;
  BatchJobs jobs;
  std::vector<ErrorCode> item_errors(1, ErrorCode::OK);
  std::vector<CopyShardCrcs> crcs;
  for (const auto& copy : slot.copies) {
    if (auto ec = append_copy_jobs(copy, bytes, size, 0, jobs, nullptr);
        ec != ErrorCode::OK) {
      item_errors[0] = ec;
      break;
    }
  }
  if (item_errors[0] == ErrorCode::OK) {
    TRACE_SPAN("client.put.transfer");
    std::vector<uint32_t> wire_crcs;
    run_device_jobs(*data_, jobs, /*is_write=*/true, item_errors);
    run_wire_jobs(*data_, jobs, /*is_write=*/true, options_.io_parallelism, item_errors,
                  &wire_crcs);
    if (item_errors[0] == ErrorCode::OK) {
      // Shard stamps come from the transport's fused write hashes; the
      // content stamp folds out of them — zero standalone passes for the
      // single-shard small-put norm. (Skipped entirely on transfer failure:
      // the fallback branch below discards them.)
      RangeCrcMap ranges;
      harvest_wire_ranges(jobs, wire_crcs, 0, bytes, ranges);
      crcs = stamp_copy_crcs(slot.copies, bytes, ranges);
      if (!crcs.empty() && !slot.copies.empty())
        content_crc = fold_content_crc(crcs[0], slot.copies[0]);
      if (!jobs.device.empty()) item_errors[0] = storage::hbm_flush();
    }
  }
  if (item_errors[0] != ErrorCode::OK) {
    // The slot's worker may be the problem (crashed after the grant): drop
    // the slot and FALL BACK — the normal path re-reserves on currently
    // healthy workers, preserving the pre-slot availability story.
    LOG_WARN << "put " << key << " slot transfer failed (" << to_string(item_errors[0])
             << "), cancelling slot and falling back";
    (void)rpc_failover(/*idempotent=*/false,
                 [&](rpc::KeystoneRpcClient& c) { return c.put_cancel(slot.slot_key); });  // best-effort cancel; slot TTL reclaims
    return std::nullopt;
  }

  PutCommitSlotRequest req;
  req.slot_key = slot.slot_key;
  req.key = key;
  req.content_crc = content_crc;
  req.shard_crcs = std::move(crcs);
  req.data_size = size;
  req.config = config;
  req.client_tag = slot_tag_;
  {
    MutexLock lock(slot_mutex_);
    const size_t have = slot_pool_[class_key].size();
    req.refill_count =
        have < options_.put_slots ? static_cast<uint32_t>(options_.put_slots - have) : 0;
  }
  std::vector<PutSlot> refills;
  const ErrorCode ec = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
    return c.put_commit_slot(req, &refills);
  });
  if (ec == ErrorCode::OK) {
    std::vector<ObjectKey> overflow;
    {
      const auto now = std::chrono::steady_clock::now();
      MutexLock lock(slot_mutex_);
      auto& pool = slot_pool_[class_key];
      for (auto& s : refills) {
        // Overflow (a concurrent put of this class refilled first) is
        // cancelled, not dropped: each refill reserves real capacity.
        if (pool.size() >= options_.put_slots) {
          overflow.push_back(std::move(s.slot_key));
        } else {
          pool.push_back({std::move(s), now});
        }
      }
    }
    if (!overflow.empty()) {
      (void)rpc_failover(/*idempotent=*/false,
                   [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(overflow); });  // best-effort cancel; slot TTL reclaims
    }
    return ErrorCode::OK;
  }
  if (ec == ErrorCode::OBJECT_NOT_FOUND) {
    // Slot reclaimed (TTL) or minted by a deposed leader: transparent
    // fallback — the normal path re-reserves and re-writes.
    return std::nullopt;
  }
  // Duplicate key, fail-closed persist, etc.: the slot survives server-side
  // (commit rolled it back), so it can serve the next put of this class.
  {
    MutexLock lock(slot_mutex_);
    slot_pool_[class_key].push_back({std::move(slot), slot_granted_at});
  }
  return ec;
}

void ObjectClient::cancel_pooled_slots() {
  std::vector<ObjectKey> keys;
  {
    MutexLock lock(slot_mutex_);
    for (auto& [cls, pool] : slot_pool_) {
      for (auto& s : pool) keys.push_back(std::move(s.slot.slot_key));
    }
    slot_pool_.clear();
  }
  // Only when already connected: the destructor must not pay a connect
  // timeout for a dead keystone — the slot TTL reclaims either way.
  std::shared_ptr<rpc::KeystoneRpcClient> rpc;
  if (!embedded_) rpc = rpc_snapshot();
  if (keys.empty() || !rpc || !rpc->connected()) return;
  (void)rpc->batch_put_cancel(keys);  // best-effort cancel; slot TTL reclaims
}

std::vector<Result<uint64_t>> ObjectClient::get_many(const std::vector<GetItem>& items,
                                                     std::optional<bool> verify) {
  trace::OpScope op_trace("get_many");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  if (!cache_ || items.empty()) return get_many_uncached(items, verify);
  // Cache pass first: hits (e.g. a checkpoint's hot shards re-read by
  // load_sharded) are served locally; only the misses ride the batch.
  std::vector<Result<uint64_t>> results(items.size(), ErrorCode::NO_COMPLETE_WORKER);
  std::vector<GetItem> missing;
  std::vector<size_t> missing_idx;
  const bool direct = embedded_ && !options_.cache_force_lease_mode;
  using Outcome = cache::ObjectCache::Outcome;
  // Lease-mode entries whose lease lapsed: revalidated as ONE batched
  // metadata round below, never one control RTT per key (an idle-then-
  // reloaded checkpoint would otherwise serialize N round trips).
  struct ExpiredItem {
    size_t idx;
    cache::ObjectCache::Hit hit;
  };
  std::vector<ExpiredItem> expired;
  for (size_t i = 0; i < items.size(); ++i) {
    if (!items[i].buffer) {
      missing.push_back(items[i]);
      missing_idx.push_back(i);
      continue;
    }
    if (direct) {
      uint64_t got = 0;
      if (cache_serve(items[i].key, items[i].buffer, items[i].buffer_size, got)) {
        results[i] = got;
      } else {
        missing.push_back(items[i]);
        missing_idx.push_back(i);
      }
      continue;
    }
    auto hit = cache_->lookup(items[i].key);
    if (hit.outcome == Outcome::kHit && hit.bytes->size() <= items[i].buffer_size) {
      std::memcpy(items[i].buffer, hit.bytes->data(), hit.bytes->size());
      results[i] = hit.bytes->size();
      cache::note_cached_serve(hit.bytes->size());
    } else if (hit.outcome == Outcome::kExpired &&
               hit.bytes->size() <= items[i].buffer_size) {
      expired.push_back({i, std::move(hit)});
    } else {
      missing.push_back(items[i]);
      missing_idx.push_back(i);
    }
  }
  if (!expired.empty()) {
    std::vector<ObjectKey> keys;
    keys.reserve(expired.size());
    for (const auto& e : expired) keys.push_back(items[e.idx].key);
    auto metas = get_workers_many(keys);
    const auto meta_at = std::chrono::steady_clock::now();  // lease anchor
    for (size_t j = 0; j < expired.size(); ++j) {
      auto& e = expired[j];
      const Result<std::vector<CopyPlacement>> meta =
          j < metas.size() ? std::move(metas[j])
                           : Result<std::vector<CopyPlacement>>(ErrorCode::OBJECT_NOT_FOUND);
      if (cache_revalidate(items[e.idx].key, e.hit, meta, meta_at)) {
        std::memcpy(items[e.idx].buffer, e.hit.bytes->data(), e.hit.bytes->size());
        results[e.idx] = e.hit.bytes->size();
        cache::note_cached_serve(e.hit.bytes->size());
      } else {
        missing.push_back(items[e.idx]);
        missing_idx.push_back(e.idx);
      }
    }
  }
  if (missing.empty()) return results;
  auto sub = get_many_uncached(missing, verify);
  for (size_t j = 0; j < missing_idx.size() && j < sub.size(); ++j)
    results[missing_idx[j]] = sub[j];
  return results;
}

std::vector<Result<uint64_t>> ObjectClient::get_many_uncached(
    const std::vector<GetItem>& items, std::optional<bool> verify) {
  TRACE_SPAN("client.get_many");
  const bool v = verify.value_or(verify_reads());
  std::vector<Result<uint64_t>> results(items.size(), ErrorCode::NO_COMPLETE_WORKER);
  if (items.empty()) return results;

  std::vector<ObjectKey> keys;
  keys.reserve(items.size());
  for (const auto& item : items) keys.push_back(item.key);
  std::vector<Result<std::vector<CopyPlacement>>> placements;
  if (embedded_) {
    placements = embedded_->batch_get_workers(keys);
  } else {
    auto r = rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& c) {
      return c.batch_get_workers(keys);
    });
    if (!r.ok()) return std::vector<Result<uint64_t>>(items.size(), r.error());
    placements = std::move(r.value());
  }
  const auto meta_at = std::chrono::steady_clock::now();  // cache lease anchor

  // First pass: batched transfer of every item's first replica.
  BatchJobs jobs;
  std::vector<std::vector<uint8_t>> ec_arena;
  std::vector<EcReadFixup> ec_fixups;
  std::vector<ErrorCode> errors(items.size(), ErrorCode::OK);
  std::vector<uint64_t> sizes(items.size(), 0);
  // Items whose integrity gate can fold the transport's fused read hashes
  // instead of re-hashing the whole buffer: plain striped/replicated copies
  // with a content stamp. EC reads cover padded arena buffers (their ranges
  // don't map onto the object) and inline items carry no wire ops.
  std::vector<bool> fuse_crc(items.size(), false);
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placements[i].ok()) {
      errors[i] = placements[i].error();
      continue;
    }
    if (placements[i].value().empty()) {
      errors[i] = ErrorCode::NO_COMPLETE_WORKER;
      continue;
    }
    const auto& copy = placements[i].value().front();
    const uint64_t copy_size = copy_logical_size(copy);
    sizes[i] = copy_size;
    if (copy_size > items[i].buffer_size) {
      errors[i] = ErrorCode::BUFFER_OVERFLOW;
      continue;
    }
    if (!copy.inline_data.empty()) {
      // Inline item: the metadata reply already carried the bytes (the CRC
      // gate below judges them like any other first-pass read).
      std::memcpy(items[i].buffer, copy.inline_data.data(), copy.inline_data.size());
      continue;
    }
    if (copy.ec_data_shards > 0) {
      // Erasure-coded item: data-shard reads ride the shared batch; a
      // failed item retries below through the reconstructing path.
      append_ec_get_jobs(copy, static_cast<uint8_t*>(items[i].buffer), copy_size, i,
                         ec_arena, jobs, ec_fixups);
      continue;
    }
    if (auto ec = append_copy_jobs(copy, static_cast<uint8_t*>(items[i].buffer), copy_size, i,
                                   jobs);
        ec != ErrorCode::OK)
      errors[i] = ec;
    else
      fuse_crc[i] = v && copy.content_crc != 0;
  }
  run_device_jobs(*data_, jobs, /*is_write=*/false, errors);
  std::vector<uint32_t> wire_crcs;
  run_wire_jobs(*data_, jobs, /*is_write=*/false, options_.io_parallelism, errors,
                v ? &wire_crcs : nullptr, v ? &fuse_crc : nullptr);
  for (const auto& fix : ec_fixups) {
    if (errors[fix.item] == ErrorCode::OK) std::memcpy(fix.dst, fix.src, fix.n);
  }
  // Integrity gate: a clean-looking first-pass read with a CRC mismatch is
  // demoted to a failure so the per-item retry below heals it (replica
  // failover, or the coded path's corruption hunt). Wire shards were hashed
  // WHILE they moved (fuse_crc items): their fold replaces the old whole-
  // buffer post-pass, which cost ~11% of verified get throughput at 1 MiB.
  // One pass over the batch's jobs distributes the fused hashes to their
  // items (a per-item harvest would rescan the whole job list K times).
  std::vector<RangeCrcMap> item_ranges(v ? items.size() : 0);
  if (v) {
    for (size_t j = 0; j < jobs.wire.size() && j < wire_crcs.size(); ++j) {
      const size_t item = jobs.wire_item[j];
      if (wire_crcs[j] == 0 || !fuse_crc[item]) continue;
      const auto* base = static_cast<const uint8_t*>(items[item].buffer);
      item_ranges[item][{static_cast<uint64_t>(jobs.wire[j].buf - base),
                         jobs.wire[j].len}] = wire_crcs[j];
    }
  }
  for (size_t i = 0; i < items.size(); ++i) {
    if (errors[i] != ErrorCode::OK || !placements[i].ok() || placements[i].value().empty())
      continue;
    const auto& copy = placements[i].value().front();
    const uint32_t expect = copy.content_crc;
    if (!v || expect == 0) continue;
    const uint32_t got =
        fuse_crc[i] ? fold_ranges_crc(copy, static_cast<const uint8_t*>(items[i].buffer),
                                      item_ranges[i])
                    : crc32c(items[i].buffer, sizes[i]);
    if (got != expect) {
      LOG_WARN << "get_many: content crc mismatch on " << items[i].key << "; retrying";
      errors[i] = ErrorCode::CHECKSUM_MISMATCH;
    }
  }

  for (size_t i = 0; i < items.size(); ++i) {
    if (!placements[i].ok() || placements[i].value().empty() ||
        errors[i] == ErrorCode::BUFFER_OVERFLOW) {
      results[i] = errors[i];
      continue;
    }
    if (errors[i] == ErrorCode::OK) {
      results[i] = sizes[i];
      if (v)
        cache_fill(items[i].key, placements[i].value().front(),
                   static_cast<const uint8_t*>(items[i].buffer), sizes[i], meta_at);
      continue;
    }
    // Replica failover, one item at a time (first copy already failed).
    ErrorCode last = errors[i];
    bool done = false;
    const auto& copies = placements[i].value();
    if (copies.front().ec_data_shards > 0) {
      // Coded object: the retry IS the degraded read (fetch survivors +
      // parity, reconstruct).
      if (transfer_copy_ec(copies.front(), static_cast<uint8_t*>(items[i].buffer), sizes[i],
                           /*is_write=*/false, v) == ErrorCode::OK) {
        results[i] = sizes[i];
        if (v)
          cache_fill(items[i].key, copies.front(),
                     static_cast<const uint8_t*>(items[i].buffer), sizes[i], meta_at);
      } else {
        results[i] = last;
      }
      continue;
    }
    for (size_t c = 1; c < copies.size() && !done; ++c) {
      const uint64_t copy_size = copy_logical_size(copies[c]);
      if (copy_size > items[i].buffer_size) {
        last = ErrorCode::BUFFER_OVERFLOW;
        continue;
      }
      if (auto ec = transfer_copy_get(copies[c], static_cast<uint8_t*>(items[i].buffer),
                                      copy_size, v);
          ec == ErrorCode::OK) {
        results[i] = copy_size;
        if (v)
          cache_fill(items[i].key, copies[c],
                     static_cast<const uint8_t*>(items[i].buffer), copy_size, meta_at);
        done = true;
      } else {
        last = ec;
      }
    }
    if (!done) results[i] = last;
  }
  return results;
}

}  // namespace btpu::client
