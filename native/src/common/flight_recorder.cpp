#include "btpu/common/flight_recorder.h"

#include <csignal>
#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <vector>

#include "btpu/common/env.h"
#include "btpu/common/sched.h"
#include "btpu/common/trace.h"

namespace btpu::flight {

const char* ev_name(Ev ev) noexcept {
  switch (ev) {
    case Ev::kOpStart: return "op_start";
    case Ev::kOpEnd: return "op_end";
    case Ev::kRpcStart: return "rpc_start";
    case Ev::kRpcEnd: return "rpc_end";
    case Ev::kRetry: return "retry";
    case Ev::kRetryBudgetOut: return "retry_budget_out";
    case Ev::kHedgeFired: return "hedge_fired";
    case Ev::kHedgeWin: return "hedge_win";
    case Ev::kShed: return "shed";
    case Ev::kDeadlineExceeded: return "deadline_exceeded";
    case Ev::kBreakerTrip: return "breaker_trip";
    case Ev::kCacheHit: return "cache_hit";
    case Ev::kCacheMiss: return "cache_miss";
    case Ev::kWalAppend: return "wal_append";
    case Ev::kWalSync: return "wal_sync";
    case Ev::kUringSubmit: return "uring_submit";
    case Ev::kUringComplete: return "uring_complete";
    case Ev::kDataOp: return "data_op";
    case Ev::kSlowOp: return "slow_op";
    case Ev::kSampled: return "sampled";
    case Ev::kPoolsanConviction: return "poolsan_conviction";
  }
  return "unknown";
}

// One event slot: seqlock-lite, all-atomic (see header + CORRECTNESS §9).
struct alignas(64) Slot {
  std::atomic<uint64_t> seq{0};
  std::atomic<uint64_t> t_ns{0};
  std::atomic<uint64_t> trace_id{0};
  std::atomic<uint64_t> a0{0};
  std::atomic<uint64_t> a1{0};
  std::atomic<uint64_t> ev_tid{0};  // ev in high 8 bits, tid low 32
};

struct Recorder::Stripe {
  std::atomic<uint64_t> head{0};
  std::unique_ptr<Slot[]> slots;
};

namespace {

uint32_t flight_tid() noexcept {
  // One syscall per thread; the recorder must not depend on trace.cpp's
  // internals, so it keeps its own cached tid.
  thread_local const uint32_t tid = static_cast<uint32_t>(::syscall(SYS_gettid));
  return tid;
}

size_t round_pow2(size_t v, size_t floor_pow2) {
  size_t p = floor_pow2;
  while (p < v) p <<= 1;
  return p;
}

}  // namespace

Recorder::Recorder(size_t events_per_stripe, size_t stripes)
    : nstripes_(std::max<size_t>(stripes, 1)),
      per_stripe_(round_pow2(std::max<size_t>(events_per_stripe, 64), 64)) {
  stripes_ = std::make_unique<Stripe[]>(nstripes_);
  for (size_t i = 0; i < nstripes_; ++i)
    stripes_[i].slots = std::make_unique<Slot[]>(per_stripe_);
}

Recorder::~Recorder() = default;

void Recorder::record(Ev ev, uint64_t a0, uint64_t a1, uint64_t trace_id,
                      uint64_t t_ns) noexcept {
  // Round-robin stripe per thread (StripeCounter idiom): stable for the
  // thread's lifetime, spreads writers without a hash.
  static std::atomic<unsigned> next{0};
  // ordering: relaxed — round-robin stripe assignment; any interleaving of the counter is a valid spreading.
  thread_local const unsigned sidx = next.fetch_add(1, std::memory_order_relaxed);
  Stripe& s = stripes_[sidx % nstripes_];
  // ordering: relaxed claim — the index only partitions slots between
  // writers; publication order is carried by each slot's seq, not the head.
  const uint64_t i = s.head.fetch_add(1, std::memory_order_relaxed);
  Slot& slot = s.slots[i & (per_stripe_ - 1)];
  // The BTPU_ATOMIC_YIELD points mark the seqlock-lite protocol edges the
  // DFS model check enumerates (SchedDfs.FlightRecorderSeqlock): claim /
  // invalidate / payload / publish.
  BTPU_ATOMIC_YIELD();
  // ordering: release on seq=0 — the in-flight mark must not sink below a
  // racing dumper's acquire re-read, or a torn payload could validate.
  slot.seq.store(0, std::memory_order_release);  // in flight
  BTPU_ATOMIC_YIELD();
  // ordering: relaxed payload stores — each field is its own atomic (no
  // torn reads); cross-field consistency is proven by the seq protocol, so
  // only the seq stores need ordering.
  slot.t_ns.store(t_ns, std::memory_order_relaxed);
  slot.trace_id.store(trace_id, std::memory_order_relaxed);
  BTPU_ATOMIC_YIELD();
  slot.a0.store(a0, std::memory_order_relaxed);
  slot.a1.store(a1, std::memory_order_relaxed);
  slot.ev_tid.store((static_cast<uint64_t>(ev) << 56) | flight_tid(),
                    // ordering: relaxed payload (cont.) — the seq bracket proves set-consistency.
                    std::memory_order_relaxed);
  BTPU_ATOMIC_YIELD();
  // ordering: release publish — orders every payload store above before the
  // new seq; a dumper that acquire-loads this seq sees the whole payload.
  slot.seq.store(i + 1, std::memory_order_release);
}

namespace {

struct Snapped {
  uint64_t t_ns, trace_id, a0, a1;
  uint32_t tid;
  Ev ev;
};

// Snapshot one slot; false when in flight / overwritten mid-read.
bool snap_slot(const Slot& slot, uint64_t want_seq, Snapped& out) noexcept {
  // ordering: acquire validate — pairs with the writer's release publish so
  // a matching seq proves the payload reads below see that generation.
  if (slot.seq.load(std::memory_order_acquire) != want_seq) return false;
  BTPU_ATOMIC_YIELD();
  // ordering: relaxed payload loads — single-field atomicity suffices; the
  // bracketing seq loads decide whether the SET is consistent.
  out.t_ns = slot.t_ns.load(std::memory_order_relaxed);
  out.trace_id = slot.trace_id.load(std::memory_order_relaxed);
  BTPU_ATOMIC_YIELD();
  out.a0 = slot.a0.load(std::memory_order_relaxed);
  out.a1 = slot.a1.load(std::memory_order_relaxed);
  const uint64_t et = slot.ev_tid.load(std::memory_order_relaxed);
  out.tid = static_cast<uint32_t>(et & 0xffffffffu);
  out.ev = static_cast<Ev>(et >> 56);
  BTPU_ATOMIC_YIELD();
  // ordering: acquire re-validate — any concurrent overwrite passed through
  // seq=0 (release), so an unchanged nonzero seq rules out a mixed payload.
  return slot.seq.load(std::memory_order_acquire) == want_seq;
}

int format_event(char* buf, size_t cap, const Snapped& e) noexcept {
  return std::snprintf(buf, cap,
                       "{\"t_us\":%.3f,\"ev\":\"%s\",\"a0\":%llu,\"a1\":%llu,"
                       "\"trace\":\"%016llx\",\"tid\":%u}\n",
                       static_cast<double>(e.t_ns) / 1000.0, ev_name(e.ev),
                       static_cast<unsigned long long>(e.a0),
                       static_cast<unsigned long long>(e.a1),
                       static_cast<unsigned long long>(e.trace_id), e.tid);
}

}  // namespace

std::string Recorder::dump_json(size_t max_events) const {
  std::vector<Snapped> events;
  events.reserve(256);
  for (size_t si = 0; si < nstripes_; ++si) {
    const Stripe& s = stripes_[si];
    // ordering: acquire — bounds the scan at a head whose slots' seq stores are visible.
    const uint64_t head = s.head.load(std::memory_order_acquire);
    const uint64_t first = head > per_stripe_ ? head - per_stripe_ : 0;
    for (uint64_t i = first; i < head; ++i) {
      Snapped e{};
      if (snap_slot(s.slots[i & (per_stripe_ - 1)], i + 1, e)) events.push_back(e);
    }
  }
  std::sort(events.begin(), events.end(),
            [](const Snapped& a, const Snapped& b) { return a.t_ns < b.t_ns; });
  if (max_events > 0 && events.size() > max_events)
    events.erase(events.begin(), events.end() - static_cast<ptrdiff_t>(max_events));
  std::string out;
  out.reserve(events.size() * 96);
  char line[256];
  for (const Snapped& e : events) {
    const int n = format_event(line, sizeof(line), e);
    if (n > 0) out.append(line, std::min<size_t>(static_cast<size_t>(n), sizeof(line) - 1));
  }
  return out;
}

void Recorder::dump_to_fd(int fd) const noexcept {
  // No allocation, no locks: snprintf into a stack buffer + write(2). Runs
  // from the fatal-signal handler; a torn or overwritten slot is skipped,
  // ordering across stripes is NOT reconstructed (sorting needs memory).
  static const char hdr[] = "---- flight recorder (unsorted, per stripe) ----\n";
  (void)!::write(fd, hdr, sizeof(hdr) - 1);
  char line[256];
  for (size_t si = 0; si < nstripes_; ++si) {
    const Stripe& s = stripes_[si];
    // ordering: acquire — bounds the scan at a head whose slots' seq stores are visible.
    const uint64_t head = s.head.load(std::memory_order_acquire);
    const uint64_t first = head > per_stripe_ ? head - per_stripe_ : 0;
    for (uint64_t i = first; i < head; ++i) {
      Snapped e{};
      if (!snap_slot(s.slots[i & (per_stripe_ - 1)], i + 1, e)) continue;
      const int n = format_event(line, sizeof(line), e);
      if (n > 0) (void)!::write(fd, line, std::min<size_t>(static_cast<size_t>(n), sizeof(line) - 1));
    }
  }
  static const char tail[] = "---- end flight recorder ----\n";
  (void)!::write(fd, tail, sizeof(tail) - 1);
}

uint64_t Recorder::recorded() const noexcept {
  uint64_t sum = 0;
  for (size_t i = 0; i < nstripes_; ++i)
    // ordering: relaxed — diagnostic fold of monotonic heads.
    sum += stripes_[i].head.load(std::memory_order_relaxed);
  return sum;
}

size_t Recorder::capacity() const noexcept { return nstripes_ * per_stripe_; }

Recorder& recorder() {
  static Recorder* r = [] {
    constexpr size_t kStripes = 16;
    size_t total = env_u64("BTPU_FLIGHT_EVENTS", 65536);
    total = std::max<size_t>(total, 1024);
    return new Recorder(total / kStripes, kStripes);  // leaked: dumped at fatal
  }();
  return *r;
}

void record(Ev ev, uint64_t a0, uint64_t a1) noexcept {
  if (!trace::enabled()) return;
  record_at(trace::now_ns(), ev, a0, a1, trace::current().trace_id);
}

void record_at(uint64_t t_ns, Ev ev, uint64_t a0, uint64_t a1,
               uint64_t trace_id) noexcept {
  if (!trace::enabled()) return;
  recorder().record(ev, a0, a1, trace_id, t_ns);
}

// ---- fatal dump ------------------------------------------------------------

namespace {

struct sigaction g_prev[3];
const int g_signals[3] = {SIGSEGV, SIGBUS, SIGABRT};

void fatal_handler(int sig, siginfo_t* info, void* uctx) {
  static const char msg[] = "fatal signal; dumping flight recorder to stderr\n";
  (void)!::write(2, msg, sizeof(msg) - 1);
  recorder().dump_to_fd(2);
  // Restore the previous disposition and re-raise so the default (or the
  // prior handler's) crash semantics are preserved.
  for (int i = 0; i < 3; ++i) {
    if (g_signals[i] == sig) {
      ::sigaction(sig, &g_prev[i], nullptr);
      break;
    }
  }
  ::raise(sig);
  (void)info;
  (void)uctx;
}

}  // namespace

void install_fatal_dump() {
  static bool installed = [] {
    if (!env_bool("BTPU_FLIGHT_FATAL_DUMP", true)) return false;
    // Construct the recorder NOW: the handler must never be the first
    // caller (operator new + a magic-static guard inside a SIGSEGV —
    // possibly under a held heap lock — deadlocks instead of dumping).
    (void)recorder();
    struct sigaction sa{};
    sa.sa_sigaction = fatal_handler;
    sa.sa_flags = SA_SIGINFO | SA_RESETHAND;
    sigemptyset(&sa.sa_mask);
    for (int i = 0; i < 3; ++i) ::sigaction(g_signals[i], &sa, &g_prev[i]);
    return true;
  }();
  (void)installed;
}

}  // namespace btpu::flight
