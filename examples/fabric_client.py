"""Client-driven device fabric: move device-tier bytes with YOUR runtime.

The reference's defining data-path property is that clients move bytes
themselves — one-sided RMA into worker memory, no per-op worker
involvement (/root/reference/src/client/blackbird_client.cpp:276-343).
On the device tier the TPU-native equivalent is the transfer fabric
(jax.experimental.transfer; the chip fabric on real TPUs): a process that
owns a JAX runtime commands the worker to OFFER a shard range and pulls
it itself, or offers its own array and has the worker PULL it straight
into device memory. The worker's staged host lane never carries a byte.

This example runs fully self-contained on CPU devices: it launches a
real separate worker process owning a (virtual) device, then does a
fabric put + get from THIS process.

Run:  JAX_PLATFORMS=cpu python examples/fabric_client.py
"""

import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from blackbird_tpu import Client, FabricClient, FabricUnavailable  # noqa: E402
from blackbird_tpu.procluster import ProcessCluster  # noqa: E402


def main() -> None:
    with ProcessCluster(workers=1, devices_per_worker=1, pool_mb=64) as pc:
        pc.wait_ready(timeout=300)
        client = Client(f"127.0.0.1:{pc.keystone_port}")
        fc = FabricClient(client)

        # Put: this runtime offers each shard, the worker pulls it into its
        # device region. Works for any dtype — bytes are bitcast on device.
        weights = np.linspace(0.0, 1.0, 262_144, dtype=np.float32)  # 1 MiB
        try:
            fc.put("demo/weights", weights, max_workers=1,
                   preferred_class="hbm_tpu")
        except FabricUnavailable as exc:
            # A stack whose PJRT plugin can't move transfer-fabric bytes
            # (TransferLink's end-to-end probe failed): every data path
            # still works over the staged lane — demonstrate that instead.
            print(f"fabric unavailable on this stack: {exc}")
            client.put("demo/weights", weights.tobytes(),
                       preferred_class=None)
            assert client.get("demo/weights") == weights.tobytes()
            print("staged lane served the same bytes; nothing else to demo")
            return
        print(f"fabric put: {weights.nbytes} bytes "
              f"({fc.fabric_puts} puts rode the fabric)")

        # Get: the worker offers, THIS runtime pulls — the result is a
        # uint8 device array in this process, never staged through a host
        # socket payload.
        arr = fc.get("demo/weights")
        back = np.asarray(arr).view(np.float32)
        assert np.array_equal(back, weights)
        print(f"fabric get: {arr.nbytes} bytes on {arr.device} "
              f"({fc.fabric_gets} gets rode the fabric)")

        # Host-tier objects have no fabric endpoint; get_bytes falls back
        # to the staged lane transparently.
        client.put("demo/host", b"plain host bytes" * 512)
        try:
            fc.get("demo/host")
        except FabricUnavailable as exc:
            print(f"host-tier object correctly refused: {exc}")
        assert fc.get_bytes("demo/host") == b"plain host bytes" * 512
        print("staged fallback ok")


if __name__ == "__main__":
    main()
