// Keystone: the control plane. Object metadata, put lifecycle, placement via
// the allocator, TTL GC, watermark eviction, worker/pool registries mirrored
// from the coordination service, failure detection, and repair.
//
// Parity target: reference include/blackbird/keystone/keystone_service.h:84-322
// and src/keystone/keystone_service.cpp. Behaviors preserved: the 14-method
// object API incl. batches, allocate-on-put_start / free-on-cancel/remove/GC,
// TTL GC thread, health thread with high-watermark eviction honoring
// soft-pin, view-version counter, heartbeat-DELETE-driven dead-worker
// cleanup, boot-time registry replay. Changes from the reference:
//   * re-replication repair: objects referencing a dead worker are rebuilt
//     from surviving replicas through the data-plane transport (the reference
//     leaves placements dangling, keystone_service.cpp:956-1004 + SURVEY §3.5);
//   * tier-aware eviction: watermark pressure is evaluated per storage class
//     so a hot HBM tier evicts without the global average hiding it
//     (reference eviction is global-average based, :530-584);
//   * cleanup_stale_workers is implemented (reference stub :527-528);
//   * HA: keystone campaigns for leadership when enable_ha is set (reference
//     flag exists but election was stubbed).
#pragma once

#include <atomic>
#include <condition_variable>
#include <memory>
#include <unordered_set>
#include <thread>

#include "btpu/alloc/keystone_adapter.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/coord/coordinator.h"
#include "btpu/transport/transport.h"

namespace btpu::keystone {

struct WorkerInfo {
  NodeId worker_id;
  std::string address;  // "host:port" of the worker's transport listener
  TopoCoord topo;
  int64_t registered_at_ms{0};
  int64_t last_heartbeat_ms{0};

  bool is_stale(int64_t now_ms, int64_t ttl_ms) const {
    return last_heartbeat_ms > 0 && now_ms - last_heartbeat_ms > ttl_ms;
  }
};

enum class ObjectState : uint8_t { kPending = 0, kComplete = 1 };

// Registry advertisement codecs (coordinator store values; also used by the
// worker service when advertising itself).
std::string encode_worker_info(const WorkerInfo& info);
BTPU_NODISCARD bool decode_worker_info(const std::string& bytes, WorkerInfo& out);
std::string encode_pool_record(const MemoryPool& pool);
BTPU_NODISCARD bool decode_pool_record(const std::string& bytes, MemoryPool& out);

// Hostile-input probe for the WAL/persist object-record decoder (all
// historical layouts + the envelope dispatch): decodes `bytes` and discards
// the result. Exists so the fuzz harnesses and the corpus-replay regression
// test can drive the exact decoder a keystone restart runs, without
// constructing a KeystoneService. Returns decode_object_record's verdict.
BTPU_NODISCARD bool probe_object_record(const std::string& bytes);

// Process-global sum of every in-process keystone's persist_retry_backlog()
// (capi/lane_counters surface — remote deployments read the per-service
// /metrics gauge instead). Services subtract their remainder on shutdown.
uint64_t persist_retry_backlog_process_total();

// Relaxed-atomic steady_clock stamp: get_workers touches last_access on
// every read, and making that touch atomic is what lets reads hold the
// object shard SHARED (a reader-parallel hot path) instead of exclusively.
// Copyable so ObjectInfo keeps value semantics (snapshot/restore paths);
// store() is const because an LRU touch is logically non-mutating state.
class AtomicAccessStamp {
 public:
  using TimePoint = std::chrono::steady_clock::time_point;
  AtomicAccessStamp() = default;
  // ordering: relaxed throughout — the stamp is a single 64-bit freshness
  // hint folded by eviction scans; readers need any non-torn value, never
  // an ordering edge with other state (copies are shard-lock-guarded).
  // SchedDfs.AtomicAccessStamp enumerates store/load interleavings and pins
  // value-set membership + per-reader coherence.
  AtomicAccessStamp(const AtomicAccessStamp& other)
      : rep_(other.rep_.load(std::memory_order_relaxed)) {}
  AtomicAccessStamp& operator=(const AtomicAccessStamp& other) {
    // ordering: relaxed — see class comment above.
    rep_.store(other.rep_.load(std::memory_order_relaxed), std::memory_order_relaxed);
    return *this;
  }
  AtomicAccessStamp& operator=(TimePoint tp) {
    store(tp);
    return *this;
  }
  TimePoint load() const {
    BTPU_ATOMIC_YIELD();
    // ordering: relaxed — see class comment above.
    return TimePoint(TimePoint::duration(rep_.load(std::memory_order_relaxed)));
  }
  void store(TimePoint tp) const {
    BTPU_ATOMIC_YIELD();
    // ordering: relaxed — see class comment above.
    rep_.store(tp.time_since_epoch().count(), std::memory_order_relaxed);
  }

 private:
  mutable std::atomic<TimePoint::duration::rep> rep_{0};
};

struct ObjectInfo {
  uint64_t size{0};
  uint64_t ttl_ms{0};
  bool soft_pin{false};
  ObjectState state{ObjectState::kPending};
  WorkerConfig config;  // original placement policy (needed for repair)
  std::chrono::steady_clock::time_point created_at;
  AtomicAccessStamp last_access;
  std::vector<CopyPlacement> copies;
  // Monotonic placement revision (process-local, from a keystone-wide
  // counter; bumped on every copies mutation and fresh on every create).
  // Lock-free movers (demotion, repair) snapshot it and swap placements in
  // only if it is unchanged — unlike comparing the placements themselves,
  // an epoch cannot suffer ABA when a remove+re-put reuses the same ranges.
  uint64_t epoch{0};
  // Anonymous pooled put slot (put_start_pooled): pending with no writer
  // attached yet; reclaimed on the shorter slot_ttl_sec deadline. Never
  // persisted (pending objects are not persisted at all).
  bool slot{false};

  bool expired(std::chrono::steady_clock::time_point now) const {
    return ttl_ms > 0 && now >= created_at + std::chrono::milliseconds(ttl_ms);
  }
};

struct KeystoneCounters {
  std::atomic<uint64_t> put_starts{0};
  std::atomic<uint64_t> put_completes{0};
  std::atomic<uint64_t> put_cancels{0};
  std::atomic<uint64_t> slots_granted{0};
  std::atomic<uint64_t> slot_commits{0};
  std::atomic<uint64_t> inline_puts{0};  // puts absorbed by the inline tier
  // Cross-process device moves that rode the fabric instead of the host lane.
  std::atomic<uint64_t> fabric_moves{0};
  // Objects spared from the loss path because their bytes sit on a dead
  // worker's PERSISTENT pools (mmap/io_uring backing files survive the
  // process), and objects whose placements were re-validated and refreshed
  // when such a pool re-registered.
  std::atomic<uint64_t> objects_offline{0};
  std::atomic<uint64_t> objects_adopted{0};
  std::atomic<uint64_t> gets{0};
  std::atomic<uint64_t> removes{0};
  std::atomic<uint64_t> gc_collected{0};
  std::atomic<uint64_t> pending_reclaimed{0};  // abandoned mid-put reservations
  std::atomic<uint64_t> evicted{0};
  std::atomic<uint64_t> objects_demoted{0};
  std::atomic<uint64_t> workers_lost{0};
  std::atomic<uint64_t> objects_repaired{0};
  std::atomic<uint64_t> objects_lost{0};
  std::atomic<uint64_t> shards_drained{0};
  std::atomic<uint64_t> scrub_checked{0};   // objects verified by background scrub
  std::atomic<uint64_t> scrub_corrupt{0};   // corrupt shards found
  std::atomic<uint64_t> scrub_healed{0};    // corrupt shards restored
};

class KeystoneService {
 public:
  // coordinator may be null: pure in-process mode (reference runs etcd-less
  // too, keystone_service.cpp:42-44); registries are then fed by
  // register_worker/register_memory_pool directly.
  KeystoneService(KeystoneConfig config, std::shared_ptr<coord::Coordinator> coordinator);
  ~KeystoneService();

  ErrorCode initialize();
  ErrorCode start();
  void stop();

  // ---- object API (RPC surface, reference keystone_service.h:84-322) ----
  Result<bool> object_exists(const ObjectKey& key);
  Result<std::vector<CopyPlacement>> get_workers(const ObjectKey& key);
  // content_crc: CRC32C of the bytes the client is about to write (0 =
  // unknown); stamped into every returned CopyPlacement so readers verify.
  Result<std::vector<CopyPlacement>> put_start(const ObjectKey& key, uint64_t size,
                                               const WorkerConfig& config,
                                               uint32_t content_crc = 0);
  // shard_crcs: per-copy per-shard CRC32C stamps the writing client computed
  // against the placement put_start returned (empty = not stamped); entries
  // that don't match a copy's index/shard count are ignored. content_crc:
  // whole-object stamp computed under the transfer (0 = keep put_start's) —
  // carried here so clients can hash while the bytes move.
  ErrorCode put_complete(const ObjectKey& key, const std::vector<CopyShardCrcs>& shard_crcs = {},
                         uint32_t content_crc = 0);
  ErrorCode put_cancel(const ObjectKey& key);
  // Pooled small-put slots (see PutSlot in types.h): grants up to `count`
  // anonymous PENDING allocations of one (size, config) class; commit
  // renames a slot to its final key and completes it in one call — the
  // 1-RTT control path for small objects. A reclaimed/unknown slot commits
  // as OBJECT_NOT_FOUND and the client falls back to put_start/complete.
  Result<std::vector<PutSlot>> put_start_pooled(uint64_t size, const WorkerConfig& config,
                                                uint32_t count, const std::string& client_tag);
  ErrorCode put_commit_slot(const ObjectKey& slot_key, const ObjectKey& key,
                            uint32_t content_crc,
                            const std::vector<CopyShardCrcs>& shard_crcs);
  // Inline tier (KeystoneConfig::inline_max_bytes): stores a small object's
  // bytes directly in the object map as a shardless copy — the durable
  // record carries them, get_workers returns them. One control RTT per put,
  // zero data-plane hops per get. NOT_IMPLEMENTED = refuse (disabled /
  // oversized / budget spent); the client falls back to the placed path.
  ErrorCode put_inline(const ObjectKey& key, const WorkerConfig& config,
                       uint32_t content_crc, std::string data);
  uint64_t inline_bytes_resident() const noexcept { return inline_bytes_.load(); }
  ErrorCode remove_object(const ObjectKey& key);
  Result<uint64_t> remove_all_objects();

  std::vector<Result<bool>> batch_object_exists(const std::vector<ObjectKey>& keys);
  std::vector<Result<std::vector<CopyPlacement>>> batch_get_workers(
      const std::vector<ObjectKey>& keys);
  std::vector<Result<std::vector<CopyPlacement>>> batch_put_start(
      const std::vector<BatchPutStartItem>& items);
  std::vector<ErrorCode> batch_put_complete(
      const std::vector<ObjectKey>& keys,
      const std::vector<std::vector<CopyShardCrcs>>& shard_crcs = {},
      const std::vector<uint32_t>& content_crcs = {});
  std::vector<ErrorCode> batch_put_cancel(const std::vector<ObjectKey>& keys);

  // Prefix listing ("" = everything), lexicographically ordered, COMPLETE
  // objects only (pending puts are invisible, like object placement reads).
  // limit 0 = unlimited. A read: standbys serve it too.
  Result<std::vector<ObjectSummary>> list_objects(const std::string& prefix,
                                                  uint64_t limit = 0) const;

  // Pool-registry listing for placement-plane topology discovery: every
  // registered pool with its TopoCoord, capacity, and transport descriptor,
  // ordered by pool id (deterministic). A read: standbys serve it too.
  Result<std::vector<MemoryPool>> list_pools() const;

  // One background-scrub pass (the health loop drives this on
  // scrub_interval_sec; tools/tests may call it directly): verifies up to
  // config_.scrub_objects_per_pass complete objects' stamped shards against
  // their CRC32C and heals what it can — replicated shards byte-identically
  // from a healthy sibling copy, coded shards via parity reconstruction.
  // Returns the number of corrupt shards found.
  size_t run_scrub_once();
  // Queue one object for verification ahead of the next pass's ring walk
  // (on top of the pass budget). Movers use this for fabric-moved bytes,
  // which carry their stamps without the staged lane's streaming CRC gate.
  void queue_scrub_target(const ObjectKey& key);

  // ---- client object-cache coherence (btpu/cache/object_cache.h) ----
  // Current cache version of `key` for IN-PROCESS (embedded) clients:
  // {incarnation generation, epoch}, or {0, 0} when the object is absent or
  // still pending. A shared-lock map read — cheap enough to validate every
  // cache hit against, which is what makes embedded hits linearizable with
  // the metadata (no staleness window at all).
  std::pair<uint64_t, uint64_t> object_cache_version(const ObjectKey& key) const;
  uint64_t cache_generation() const noexcept { return cache_gen_; }

  // Durability-lag backlog: keys whose durable object record could not be
  // written at mutation time (coordinator outage / fence) and are being
  // re-persisted by the health loop. Nonzero means acked state and durable
  // state have diverged — exported as btpu_persist_retry_backlog on
  // /metrics, capi, and Client.lane_counters() (docs/OPERATIONS.md alert).
  size_t persist_retry_backlog() const;

  Result<ClusterStats> get_cluster_stats() const;
  // Allocator view with per-storage-class breakdowns (metrics exports the
  // same numbers tier-aware eviction keys off).
  alloc::AllocatorStats allocator_stats() const { return adapter_.get_stats(); }
  ViewVersionId get_view_version() const noexcept { return view_version_.load(); }

  // ---- registry (coordinator watches call these; embedded mode calls them
  // directly) ----
  ErrorCode register_worker(const WorkerInfo& worker);
  ErrorCode register_memory_pool(const MemoryPool& pool);
  ErrorCode remove_worker(const NodeId& worker_id);
  // Gracefully evacuates a LIVE worker (TPU-VM preemption notice): new
  // placements skip it immediately, every copy with shards on it is rebuilt
  // on the remaining workers — streamed from the still-alive source, so
  // replication_factor=1 objects survive where a crash would lose them —
  // and the worker is retired only once NOTHING references it (in-flight
  // puts are waited out and re-scanned). Returns SHARDS migrated (bytes on
  // surviving workers are never re-streamed);
  // WORKER_DRAIN_INCOMPLETE leaves the worker registered and still excluded
  // from new placements so the drain can be retried after fixing capacity
  // or transport. Neither the reference nor its etcd layer has an
  // equivalent.
  Result<uint64_t> drain_worker(const NodeId& worker_id);

  // Snapshot views
  std::vector<WorkerInfo> workers() const;
  alloc::PoolMap memory_pools() const;
  const KeystoneConfig& config() const noexcept { return config_; }
  const KeystoneCounters& counters() const noexcept { return counters_; }
  bool is_leader() const noexcept { return is_leader_.load(); }
  // Resolved object-map shard count (config/$BTPU_KEYSTONE_SHARDS/auto —
  // see KeystoneConfig::metadata_shards). Fixed for the service lifetime.
  size_t metadata_shard_count() const noexcept { return shard_count_; }

  // Exposed for tests/ops: run one GC / health sweep synchronously.
  void run_gc_once();
  void run_health_check_once();

  // Test-only: swaps the repair/demotion data mover so fault-injection
  // tests can fail a repair stream mid-copy. Inject before the failure
  // event fires; not thread-safe against in-flight repairs.
  void inject_data_client_for_test(std::unique_ptr<transport::TransportClient> client) {
    data_client_ = std::move(client);
  }

 private:
  void gc_loop();
  void health_loop();
  void keepalive_loop();
  void bump_view() noexcept { view_version_.fetch_add(1); }
  std::string election_name() const { return "btpu-keystone-leader/" + config_.cluster_id; }
  int64_t now_wall_ms() const;

  // Fan out a cache invalidation for `key` over the coordinator watch lane
  // ("cacheinval" topic): version = the new epoch, 0 = object gone. Fired on
  // DELETION and BYTE-MOVE events (remove/GC/evict/demote/repair/drain) —
  // never on the put path: a fresh put's key has no live cached entries
  // (its prior removal already published), so puts stay zero-overhead.
  // Best-effort: clients that miss an event (severed watch) are bounded by
  // their lease TTL + version revalidation. TTL'd value; fine to call with
  // or without an object-shard mutex held (watch callbacks never re-enter
  // the keystone).
  void publish_cache_invalidation(const ObjectKey& key, uint64_t version);

  ErrorCode setup_coordinator_integration();
  void load_existing_state();
  void load_persisted_objects();
  // Durable object metadata (persist_objects): COMPLETE objects are written
  // to the coordinator and replayed (with allocator range adoption) on boot.
  // Durable object-record writes. Under HA these are FENCED with the
  // leader epoch minted at this keystone's promotion: a deposed leader
  // (SIGSTOP/GC-pause window) gets FENCED back, steps down, and the
  // mutation provably never reached durable state. Returns the write's
  // outcome so commit points (put_complete) can fail closed.
  ErrorCode normalize_put_config(WorkerConfig& effective) const;
  ErrorCode persist_object(const ObjectKey& key, const ObjectInfo& info);
  ErrorCode unpersist_object(const ObjectKey& key);
  // For mutation sites that cannot fail closed (the splice already landed in
  // memory): queue the key so the health loop re-persists it from current
  // memory until the durable record catches up.
  void mark_persist_dirty(const ObjectKey& key);
  void retry_dirty_persists();
  // Drops every deferred-persist entry (demotion / shutdown), keeping the
  // process-global backlog gauge in step. Idempotent.
  void drain_persist_retry();
  // Routes a leader-owned coordinator write through the fence (plain write
  // when HA is off). FENCED triggers fence_stepdown().
  ErrorCode coord_put_record(const std::string& key, const std::string& value);
  ErrorCode coord_del_record(const std::string& key);
  // A FENCED write proves this node was deposed: stop claiming leadership
  // immediately and let the keepalive thread resign + re-campaign.
  void fence_stepdown();
  // Installs/replaces the local view of one persisted object record (map
  // entry + allocator ranges). Standbys mirror the leader's writes through
  // this; boot replay and promotion reconcile reuse it. kGarbage = the
  // record is undecodable (safe to purge from the coordinator); kFailed = a
  // transient local condition (no live pools yet, range conflict) — the
  // durable record must be kept so a retry can succeed.
  enum class ApplyResult { kApplied, kGarbage, kFailed };
  ApplyResult apply_object_record(const ObjectKey& key, const std::string& bytes,
                                  const alloc::PoolMap& pools);
  // Removes the local view of one object (map entry + allocator ranges)
  // without touching coordinator state — the mirror of the leader's delete.
  void drop_object_locally(const ObjectKey& key);
  // Registers this keystone as an election candidate; re-invoked (back of
  // the queue) when a promotion has to be refused.
  ErrorCode start_campaign();
  // Leadership transition: standby -> leader re-reads every persisted record
  // so writes that raced the promotion are not lost, and drops local entries
  // whose records are gone. Returns false when the coordinator cannot be
  // read even after retries — the caller must refuse leadership.
  bool on_promoted();
  // Leader -> standby: drop never-persisted pending objects staged by our
  // own put_starts so their ranges don't linger and fight the mirror.
  void on_demoted();
  void on_heartbeat_event(const coord::WatchEvent& ev);
  void on_worker_event(const coord::WatchEvent& ev);
  void on_pool_event(const coord::WatchEvent& ev);
  void on_object_event(const coord::WatchEvent& ev);
  void cleanup_dead_worker(const NodeId& worker_id);
  // Pools eligible for NEW placements: draining workers' pools excluded.
  alloc::PoolMap allocatable_pools_snapshot() const;
  // One live shard's bytes into a staged placement (device fast path incl.).
  // `pools`: caller-hoisted pool snapshot (drain calls this per shard).
  // `used_unchecked` (optional) reports a fabric or chip-to-chip move —
  // those skip the staged lane's CRC gate, so the caller queues the object
  // for scrub revalidation. `host_crc` (optional) returns the CRC32C of the
  // bytes as streamed when the HOST lane carried them (untouched otherwise):
  // the caller holds the shard's stamp and can detect a rotten source.
  ErrorCode stream_shard(const ShardPlacement& src, const CopyPlacement& dst,
                         const alloc::PoolMap& pools, bool* used_unchecked = nullptr,
                         uint32_t* host_crc = nullptr);
  // A persistent-tier pool re-registered after its worker restarted:
  // re-carve the spared objects' ranges, rewrite their placements onto the
  // new base/rkey, and re-validate stamped shards by CRC. Runs BEFORE the
  // pool becomes allocatable so fresh allocations cannot race the carve.
  void readopt_offline_pool(const MemoryPool& pool);
  // Health-loop leg: CRC-revalidates re-adopted stamped shards (queued by
  // readopt_offline_pool — the watch thread must not stream pool bytes).
  void run_readopt_checks();
  // Reconstructs the dead shards of one erasure-coded copy from any k
  // survivors (segmented) onto fresh placements and splices them in.
  bool repair_ec_object(const ObjectKey& key, uint64_t epoch, const CopyPlacement& copy,
                        const std::vector<size_t>& dead_idx,
                        const alloc::PoolMap& target_pools);
  void cleanup_stale_workers();
  void scrub_loop();

  // Repair: rebuild placements that referenced a dead worker from surviving
  // replicas over the data plane. Returns number of objects repaired.
  size_t repair_objects_for_dead_worker(const NodeId& worker_id);

  // Demotion: move an object's bytes out of the pressured tier `from` into
  // the nearest lower tier with capacity (ladder order per tier_rank, capped
  // at HDD — CUSTOM/unspecified pools are never an eviction backstop), over
  // the data plane. The transfer runs WITHOUT any shard mutex held: the new
  // placement is staged under a temporary allocator key while the old ranges
  // stay live, then swapped in under the lock only if the object did not
  // change in the meantime (wire-encoded placement fingerprint).
  // kFailed -> caller falls back to delete-eviction; kSkipped -> object was
  // removed/changed concurrently, caller leaves it alone.
  enum class DemoteOutcome { kDemoted, kFailed, kSkipped };
  DemoteOutcome demote_object(const ObjectKey& key, StorageClass from);

  // Eviction: evict least-recently-accessed, non-soft-pinned complete
  // objects until the (per-tier when configured) utilization drops below
  // high_watermark * (1 - eviction_ratio).
  void evict_for_pressure();
  double tier_utilization(std::optional<StorageClass> cls) const;

  // One lock-striped shard of the object map. The map field is guarded by
  // the SHARD's own mutex; clang's analysis resolves `s.map` against
  // `s.mutex` through the local reference, so every access point is still
  // machine-checked (take the reference ONCE per scope — two aliases to the
  // same shard defeat the textual matching).
  struct ObjectShard {
    mutable SharedMutex mutex;
    std::unordered_map<ObjectKey, ObjectInfo> map BTPU_GUARDED_BY(mutex);
  };

  // Stable key -> shard mapping (FNV-1a, process-independent): persisted
  // records re-hash identically on every boot, and remote clients cannot
  // observe the shard layout at all.
  size_t shard_index(const ObjectKey& key) const noexcept {
    return static_cast<size_t>(fnv1a64(key) % shard_count_);
  }
  ObjectShard& shard_for(const ObjectKey& key) const { return shards_[shard_index(key)]; }

  ErrorCode free_object_locked(ObjectShard& shard, const ObjectKey& key, ObjectInfo& info)
      BTPU_REQUIRES(shard.mutex);

  KeystoneConfig config_;
  std::shared_ptr<coord::Coordinator> coordinator_;
  alloc::KeystoneAllocatorAdapter adapter_;
  std::unique_ptr<transport::TransportClient> data_client_;  // for repair

  // Keystone lock order (outermost first; see docs/CORRECTNESS.md):
  //   drain_mutex_ -> shards_[i].mutex -> {registry_mutex_,
  //                                        readopt_checks_mutex_,
  //                                        persist_retry_mutex_,
  //                                        allocator internals}
  // Shard discipline: AT MOST ONE shard mutex is ever held at a time.
  // Single-key ops lock exactly their key's shard; multi-key walks (GC,
  // eviction scan, listing, scrub, drain/repair passes, remove_all) visit
  // shards strictly in ascending index order, releasing each before the
  // next. Cross-shard moves (put_commit_slot's slot -> final key) transfer
  // OWNERSHIP instead of nesting: the entry is extracted under the source
  // shard's lock, then inserted under the destination's — no thread can
  // double-claim the extracted entry, and no two shard locks ever nest.
  // clang's analysis cannot encode ordering edges over a dynamic mutex
  // array, so the per-shard position in the hierarchy is enforced by this
  // convention (the static edges below still pin drain -> registry/readopt);
  // registry_mutex_ and a shard mutex are normally taken in SEPARATE scopes
  // (snapshot the registry, release, then splice objects); where they nest
  // (repair consults offline_pools_ while splicing placements) the SHARD
  // comes FIRST.
  size_t shard_count_{1};
  std::unique_ptr<ObjectShard[]> shards_;

  mutable SharedMutex registry_mutex_ BTPU_ACQUIRED_AFTER(drain_mutex_);
  std::unordered_map<NodeId, WorkerInfo> workers_ BTPU_GUARDED_BY(registry_mutex_);
  alloc::PoolMap pools_ BTPU_GUARDED_BY(registry_mutex_);

  std::atomic<ViewVersionId> view_version_{0};
  std::atomic<uint64_t> next_epoch_{1};  // feeds ObjectInfo::epoch
  // Cache-coherence incarnation nonce (random 64-bit, minted per keystone
  // construction): epochs are process-local and re-minted on restart/
  // failover, so clients compare (gen, epoch) pairs — a fresh incarnation's
  // epochs can never validate bytes cached from a previous one. Paired with
  // the cached content CRC at revalidation, a cross-incarnation false match
  // is out of the failure model.
  uint64_t cache_gen_{0};
  // Set when a promotion had to be refused (reconcile failed): the keepalive
  // thread resigns and re-campaigns. Deferred because leader callbacks run
  // on the coordinator's event thread, where issuing coordinator RPCs would
  // self-deadlock (the response is delivered by that same thread).
  std::atomic<bool> needs_recampaign_{false};
  // Wakes the keepalive thread immediately for the FIRST attempt; retries
  // after a failure pace at the normal refresh interval so a down
  // coordinator cannot busy-spin the loop.
  std::atomic<bool> recampaign_asap_{false};
  std::atomic<uint32_t> promotion_refusals_{0};  // streak; reset on success
  // Set by fence_stepdown(): on_demoted() must run (drop this node's own
  // never-persisted pending objects), but the fenced op's caller holds
  // an object-shard mutex, so the cleanup is deferred to the keepalive
  // thread.
  std::atomic<bool> pending_demote_cleanup_{false};
  std::atomic<bool> running_{false};
  std::atomic<bool> is_leader_{false};
  std::atomic<uint64_t> leader_epoch_{0};  // fencing token from promotion
  std::thread gc_thread_, health_thread_, keepalive_thread_, scrub_thread_;
  CondVarAny stop_cv_;
  Mutex stop_mutex_;

  std::vector<coord::WatchId> watch_ids_;
  KeystoneCounters counters_;
  std::unordered_set<NodeId> draining_ BTPU_GUARDED_BY(registry_mutex_);
  // Dead workers whose repair pass could not finish (coordinator outage or
  // deposition mid-pass): the health loop re-runs repair for them — the
  // death event itself fires only once per worker.
  Mutex repair_retry_mutex_;
  std::unordered_set<NodeId> repair_retry_ BTPU_GUARDED_BY(repair_retry_mutex_);
  // Objects whose in-memory state advanced but whose durable-record write
  // failed (coordinator outage, fence race): repair/demotion/drain splices
  // are irreversible in memory, so "fail closed" is not available to them —
  // instead the health loop re-persists these keys from current memory
  // until the record catches up (retry_dirty_persists).
  mutable Mutex persist_retry_mutex_;
  std::unordered_set<ObjectKey> persist_retry_ BTPU_GUARDED_BY(persist_retry_mutex_);
  // Background scrub ring position (scrub thread only).
  ObjectKey scrub_cursor_;
  std::atomic<uint64_t> slot_seq_{0};  // unique suffix for pooled slot keys
  // Resident inline-tier bytes (budget: KeystoneConfig::inline_total_bytes).
  // Credited by put_inline, debited wherever an inline object leaves the
  // map (free_object_locked, record replace/drop on the mirror path).
  std::atomic<uint64_t> inline_bytes_{0};
  // Live pooled slots (granted, not yet committed/cancelled/reclaimed):
  // keeps get_cluster_stats O(1) when excluding them from total_objects.
  std::atomic<int64_t> slot_objects_{0};
  Mutex drain_mutex_;                    // serializes drain_worker per service
  std::string service_id_;
  // Persistent-tier pools of dead workers, as last advertised (old base +
  // rkey), awaiting re-adoption when the restarted worker re-registers them
  // (guarded by registry_mutex_). Consumed by readopt_offline_pool.
  std::unordered_map<MemoryPoolId, MemoryPool> offline_pools_ BTPU_GUARDED_BY(registry_mutex_);
  // Re-adopted stamped shards pending CRC revalidation (run_readopt_checks).
  // Keyed by the shard's placement + stamped CRC, not the object epoch:
  // epochs move for unrelated reasons (a second pool adopting the same
  // object bumps it), and a stale check must neither be dropped for that
  // nor condemn a shard that a later repair/re-put has since replaced.
  struct ReadoptCheck {
    ObjectKey key;
    ShardPlacement shard;
    uint32_t expect;
    // Adoption sequence of the pool when this check was queued. A later
    // re-adoption of the same pool supersedes outstanding checks (its own
    // fresh checks govern): without this, a check whose lock-free CRC read
    // raced a pool bounce could condemn bytes the second adoption restored.
    uint64_t seq{0};
  };
  Mutex readopt_checks_mutex_ BTPU_ACQUIRED_AFTER(drain_mutex_);
  std::vector<ReadoptCheck> readopt_checks_ BTPU_GUARDED_BY(readopt_checks_mutex_);
  // Latest adoption sequence per pool. Adoptions stamp their seq BEFORE
  // rewriting any placement; checkers read it under readopt_checks_mutex_
  // while holding their key's shard lock — see readopt_offline_pool for
  // the ordering argument.
  std::unordered_map<MemoryPoolId, uint64_t> readopt_seq_ BTPU_GUARDED_BY(readopt_checks_mutex_);
  std::atomic<uint64_t> readopt_seq_counter_{0};
  // Objects whose bytes moved over the device fabric without the staged
  // lane's streaming CRC gate (stamps are carried, bytes unchecked). The
  // scrub verifies them on its next pass, ahead of the ring walk, healing
  // through the normal sibling/parity machinery.
  Mutex scrub_targets_mutex_;
  std::unordered_set<ObjectKey> scrub_targets_ BTPU_GUARDED_BY(scrub_targets_mutex_);
};

}  // namespace btpu::keystone
