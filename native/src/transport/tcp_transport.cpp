// TCP transport: the dev-fallback + DCN inter-slice data plane.
//
// Wire format (fixed headers, no generic framing — this is the hot path):
//   request:  u8 op (1=read, 2=write), u64 addr, u64 rkey, u64 len
//             [+ len payload bytes for write]
//   response: u32 status                        (write)
//             u32 status [+ len payload bytes]  (read, len from request)
// The worker side services requests against registered regions with bounds +
// rkey validation; the client side keeps a per-endpoint connection pool so a
// transfer costs zero connection setups in steady state (the reference paid
// one UCX endpoint creation per transfer, blackbird_client.cpp:162-188).
#include <atomic>
#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>

#include <fcntl.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>

#include <condition_variable>
#include <deque>
#include <functional>
#include <optional>

#include "btpu/common/admission.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/log.h"
#include "btpu/common/stripe_counter.h"
#include "btpu/common/trace.h"
#include "btpu/net/net.h"
#include "btpu/transport/data_wire.h"
#include "btpu/transport/transport.h"

#include "../net/uring_engine.h"

namespace btpu::transport {

// Packed headers + checked decoders live in data_wire.h so the fuzz gate
// drives the exact parser this file runs.
using namespace datawire;

namespace {

// Server-side stream lane (shared with the uring engine through
// DataPlaneCounters): reads answered straight off registered pool pages.
StripeCounter g_pool_direct_ops;
StripeCounter g_pool_direct_bytes;
// SEND_ZC completion classification (engine only; REPORT_USAGE notifs):
// sent = the kernel transmitted from the pool pages, copied = it fell back
// to a private copy (loopback always does; on a real NIC a sustained
// copied stream means the "zero-copy" path is paying for pinning AND the
// copy it was meant to avoid).
StripeCounter g_zerocopy_sent;
StripeCounter g_zerocopy_copied;

// Data-plane admission options (read once per server instance at start):
// bounded concurrent data ops AND in-flight payload bytes, so neither a
// flood of small ops nor a few giant transfers can queue unboundedly.
AdmissionGate::Options data_gate_options() {
  AdmissionGate::Options opts;
  opts.max_inflight = static_cast<uint32_t>(env_u64("BTPU_DATA_MAX_INFLIGHT_OPS", 64));
  opts.max_queue = static_cast<uint32_t>(env_u64("BTPU_DATA_MAX_QUEUE", 128));
  opts.max_inflight_bytes = env_u64("BTPU_DATA_MAX_INFLIGHT_BYTES", 256ull << 20);
  opts.backoff_hint_ms = static_cast<uint32_t>(env_u64("BTPU_DATA_SHED_HINT_MS", 25));
  return opts;
}

// Opcodes and the packed DataRequestHeader/StagedFrame now live in
// btpu/transport/data_wire.h (shared with the fuzz harnesses); this file
// pulls them in via `using namespace datawire` above. The staged lane
// (kOpHello + kOpReadStaged/kOpWriteStaged over a client-created shm
// segment) and the device-fabric commands (kOpFabricOffer/kOpFabricPull)
// behave as documented there: a virtual region's callbacks move bytes
// DIRECTLY between the backing store and the shared segment — for an HBM
// pool in a standalone worker that is device<->shm with no socket copy and
// no worker-side scratch (VERDICT r2 item 2; ref contract: one-sided data
// plane, blackbird_client.cpp:276-343). A server that cannot open the
// segment (different host, old build) refuses or drops the connection and
// the client falls back to streaming, remembered per endpoint.
//
// `Region` + the shared registry now live in ../net/uring_engine.h: the
// same table serves whichever engine the server runs — the io_uring event
// loop (default where the kernel allows it) or this file's thread-per-
// connection fallback. Both speak the identical wire bytes.

class TcpTransportServer : public TransportServer {
 public:
  ~TcpTransportServer() override { stop(); }

  TransportKind kind() const noexcept override { return TransportKind::TCP; }

  ErrorCode start(const std::string& host, uint16_t port) override {
    uint16_t bound = 0;
    gate_ = std::make_unique<AdmissionGate>(data_gate_options());
    auto listener = net::tcp_listen(host, port, &bound);
    if (!listener.ok()) return listener.error();
    listener_ = std::move(listener).value();
    // Accepted data-plane sockets inherit the listener's buffer sizes, and
    // the receive window scale is negotiated at accept time — so size the
    // listener, not the accepted fds (tcp(7)).
    net::set_bulk_buffers(listener_.fd());
    host_ = (host.empty() || host == "0.0.0.0") ? "127.0.0.1" : host;
    port_ = bound;
    running_ = true;
    // Engine selection at start time: the io_uring event loop where the
    // kernel allows it (thousands of connections per core, pool-direct
    // sends), thread-per-connection otherwise. BTPU_FORCE_NO_URING=1
    // forces the fallback (tests exercise it; ops can pin it on a box
    // where io_uring misbehaves).
    // Clamps: a typo'd env value must not spawn a thread/ring storm (same
    // policy as BTPU_WIRE_POOL_THREADS).
    UringDataPlane::Options uopts;
    uopts.loops = std::min(env_u32("BTPU_URING_LOOPS", 0), 64u);  // 0 = auto (min(hw, 4))
    uopts.sq_entries = std::clamp(env_u32("BTPU_URING_SQ_ENTRIES", 512), 16u, 32768u);
    // Exec pool sizes for BLOCKING callbacks, which are sleep/IO-bound,
    // not CPU-bound: the bound that matters is the admission gate's op
    // concurrency, not cores — 2 threads under a 64-op gate would queue
    // admitted callback-tier reads 32 deep where the thread server ran
    // them all concurrently. Threads are lazy, so the cap is free until a
    // workload actually fans callbacks out.
    const uint32_t hw = std::max(1u, std::thread::hardware_concurrency());
    uopts.exec_threads =
        std::min(env_u32("BTPU_URING_EXEC_THREADS", std::max(8u, hw)), 64u);
    uopts.counters = {&g_pool_direct_ops, &g_pool_direct_bytes, &g_zerocopy_sent,
                      &g_zerocopy_copied};
    engine_ = UringDataPlane::create(listener_, &regions_, gate_.get(), uopts);
    if (!engine_) {
      accept_thread_ = std::thread([this] { accept_loop(); });
    }
    LOG_INFO << "tcp transport listening on " << host_ << ":" << port_
             << (engine_ ? " (io_uring engine)" : " (thread-per-connection)");
    return ErrorCode::OK;
  }

  void stop() override {
    if (!running_.exchange(false)) return;
    if (engine_) {
      engine_->stop();  // cancels in-flight ops, closes conns + listener
      engine_.reset();
      return;
    }
    if (accept_thread_.joinable()) accept_thread_.join();  // poll wakes <=200ms
    listener_.close();
    std::vector<ConnSlot> slots;
    {
      MutexLock lock(conns_mutex_);
      slots.swap(conns_);
      for (auto& s : slots) s.sock->shutdown();
    }
    for (auto& s : slots)
      if (s.thread.joinable()) s.thread.join();
  }

  Result<RemoteDescriptor> register_region(void* base, uint64_t len,
                                           const std::string& tag) override {
    if (!base || len == 0) return ErrorCode::INVALID_PARAMETERS;
    if (!running_) return ErrorCode::INVALID_STATE;
    MutexLock lock(regions_.mutex);
    uint64_t rkey = rng_() | 1;
    while (regions_.map.contains(rkey)) rkey = rng_() | 1;
    const uint64_t remote_base = reinterpret_cast<uint64_t>(base);
    Region region;
    region.base = static_cast<uint8_t*>(base);
    region.len = len;
    region.remote_base = remote_base;
    region.tag = tag;  // poolsan shadow lookup key (pool id)
    regions_.map[rkey] = std::move(region);
    RemoteDescriptor d;
    d.transport = TransportKind::TCP;
    d.endpoint = host_ + ":" + std::to_string(port_);
    d.remote_base = remote_base;
    d.rkey_hex = rkey_to_hex(rkey);
    d.data_wire_version = kTcpDataWireVersion;
    LOG_DEBUG << "registered tcp region " << tag << " rkey=" << d.rkey_hex << " len=" << len;
    return d;
  }

  Result<RemoteDescriptor> register_virtual_region(uint64_t len, const std::string& tag,
                                                   RegionReadFn read_fn,
                                                   RegionWriteFn write_fn) override {
    if (len == 0 || !read_fn || !write_fn) return ErrorCode::INVALID_PARAMETERS;
    if (!running_) return ErrorCode::INVALID_STATE;
    MutexLock lock(regions_.mutex);
    uint64_t rkey = rng_() | 1;
    while (regions_.map.contains(rkey)) rkey = rng_() | 1;
    Region region;
    region.len = len;
    region.read_fn = std::move(read_fn);
    region.write_fn = std::move(write_fn);
    region.tag = tag;
    regions_.map[rkey] = std::move(region);
    RemoteDescriptor d;
    d.transport = TransportKind::TCP;
    d.endpoint = host_ + ":" + std::to_string(port_);
    d.remote_base = 0;
    d.rkey_hex = rkey_to_hex(rkey);
    d.data_wire_version = kTcpDataWireVersion;
    LOG_DEBUG << "registered tcp virtual region " << tag << " rkey=" << d.rkey_hex;
    return d;
  }

  ErrorCode unregister_region(const RemoteDescriptor& desc) override {
    uint64_t rkey = 0;
    try {
      rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    } catch (...) {
      return ErrorCode::INVALID_PARAMETERS;
    }
    MutexLock lock(regions_.mutex);
    return regions_.map.erase(rkey) ? ErrorCode::OK : ErrorCode::MEMORY_POOL_NOT_FOUND;
  }

  ErrorCode attach_fabric(const RemoteDescriptor& desc, RegionOfferFn offer_fn,
                          RegionPullFn pull_fn) override {
    uint64_t rkey = 0;
    try {
      rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    } catch (...) {
      return ErrorCode::INVALID_PARAMETERS;
    }
    MutexLock lock(regions_.mutex);
    auto it = regions_.map.find(rkey);
    if (it == regions_.map.end()) return ErrorCode::MEMORY_POOL_NOT_FOUND;
    it->second.offer_fn = std::move(offer_fn);
    it->second.pull_fn = std::move(pull_fn);
    return ErrorCode::OK;
  }

  ErrorCode attach_direct_io(const RemoteDescriptor& desc, int fd, bool odirect) override {
    if (fd < 0) return ErrorCode::INVALID_PARAMETERS;
    uint64_t rkey = 0;
    try {
      rkey = std::stoull(desc.rkey_hex, nullptr, 16);
    } catch (...) {
      return ErrorCode::INVALID_PARAMETERS;
    }
    MutexLock lock(regions_.mutex);
    auto it = regions_.map.find(rkey);
    if (it == regions_.map.end()) return ErrorCode::MEMORY_POOL_NOT_FOUND;
    if (it->second.base) return ErrorCode::INVALID_PARAMETERS;  // flat: already direct
    it->second.direct_fd = fd;
    it->second.direct_odirect = odirect;
    return ErrorCode::OK;
  }

  size_t debug_connection_count() const override {
    if (engine_) return engine_->connection_count();
    MutexLock lock(conns_mutex_);
    size_t live = 0;
    for (const auto& s : conns_)
      // ordering: acquire — pairs with the serve thread's release store; a true flag means the thread's serving writes are done and it is joinable.
      if (!s.done->load(std::memory_order_acquire)) ++live;
    return live;
  }

 private:
  struct ConnSlot {
    std::thread thread;
    std::shared_ptr<net::Socket> sock;
    // Set by the serving thread as its last act: the accept loop joins and
    // erases finished slots, so a long-lived worker no longer accumulates
    // dead thread handles until stop().
    std::shared_ptr<std::atomic<bool>> done;
  };

  void accept_loop() {
    while (running_) {
      auto sock = net::tcp_accept(listener_, 200);
      reap_finished();
      if (!sock.ok()) continue;
      auto conn = std::make_shared<net::Socket>(std::move(sock).value());
      auto done = std::make_shared<std::atomic<bool>>(false);
      MutexLock lock(conns_mutex_);
      ConnSlot slot;
      slot.sock = conn;
      slot.done = done;
      slot.thread = std::thread([this, conn, done] {
        serve(conn);
        // ordering: release — publishes every serving-side write before the reaper's acquire read can observe done.
        done->store(true, std::memory_order_release);
      });
      conns_.push_back(std::move(slot));
    }
  }

  // Joins and erases every finished serving thread. Runs on the accept
  // loop (each accept + each 200ms accept timeout), so the live-slot count
  // tracks live CONNECTIONS instead of growing monotonically.
  void reap_finished() {
    std::vector<ConnSlot> finished;
    {
      MutexLock lock(conns_mutex_);
      for (size_t i = 0; i < conns_.size();) {
        // ordering: acquire — see live_connections(): done pairs release/acquire with the serve thread.
        if (conns_[i].done->load(std::memory_order_acquire)) {
          finished.push_back(std::move(conns_[i]));
          conns_[i] = std::move(conns_.back());
          conns_.pop_back();
        } else {
          ++i;
        }
      }
    }
    // Join OUTSIDE the lock: `done` flips just before thread exit, so the
    // join may still wait a few instructions.
    for (auto& s : finished)
      if (s.thread.joinable()) s.thread.join();
  }

  void serve(std::shared_ptr<net::Socket> sock) {
    const int fd = sock->fd();
    net::SocketShutdownGuard shutdown_guard{*sock};
    DataRequestHeader hdr{};
    std::vector<uint8_t> scratch;
    // Per-connection staging segment (client-created, mapped at hello).
    uint8_t* stg_base = nullptr;
    uint64_t stg_len = 0;
    struct StagingGuard {
      uint8_t*& base;
      uint64_t& len;
      ~StagingGuard() {
        if (base) ::munmap(base, len);
      }
    } staging_guard{stg_base, stg_len};
    // Overload/deadline rejection codes share the status channel; the
    // counters make sheds visible on the robustness scoreboard.
    // Rejection flight events carry the REQUEST's trace id explicitly
    // (record_at): serving threads never install an ambient context, and a
    // shed op whose trace cannot see WHY it failed defeats the stitching
    // (the uring engine's shed()/expire() stamp the same way).
    auto rejection = [&hdr](const AdmissionTicket& ticket) -> uint32_t {
      if (ticket.verdict() == AdmissionGate::Verdict::kShed) {
        // ordering: relaxed — monotonic stat counters (this lambda and the two below).
        robust_counters().shed.fetch_add(1, std::memory_order_relaxed);
        flight::record_at(trace::now_ns(), flight::Ev::kShed, /*a0=data plane*/ 2, 0,
                          hdr.trace_id);
        return static_cast<uint32_t>(ErrorCode::RETRY_LATER);
      }
      robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
      flight::record_at(trace::now_ns(), flight::Ev::kDeadlineExceeded, /*a0=server*/ 1,
                        0, hdr.trace_id);
      return static_cast<uint32_t>(ErrorCode::DEADLINE_EXCEEDED);
    };
    auto expired_status = [&hdr]() -> uint32_t {
      // ordering: relaxed — monotonic stat counter.
      robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
      flight::record_at(trace::now_ns(), flight::Ev::kDeadlineExceeded, /*a0=server*/ 1,
                        0, hdr.trace_id);
      return static_cast<uint32_t>(ErrorCode::DEADLINE_EXCEEDED);
    };
    // Per-request observability: histogram sample always, span record when
    // the request carries a trace id (see data_wire.h field notes). RAII so
    // every continue/break path in the op dispatch below closes the op.
    struct ServedOp {
      const DataRequestHeader& hdr;
      uint64_t t0{0};
      explicit ServedOp(const DataRequestHeader& h) : hdr(h) {}
      void open() { t0 = trace::now_ns(); }
      void close() {
        if (t0 == 0) return;
        const uint64_t t1 = trace::now_ns();
        hist::data_op(data_op_hist_name(hdr.op)).record_us((t1 - t0) / 1000);
        if (hdr.trace_id != 0) {
          trace::record_remote_span(data_op_span_name(hdr.op), hdr.trace_id, hdr.span_id,
                                    t0, t1);
          flight::record_at(t1, flight::Ev::kDataOp, hdr.op, (t1 - t0) / 1000,
                            hdr.trace_id);
        }
        t0 = 0;
      }
    } served{hdr};
    while (running_) {
      // Close the PREVIOUS op before blocking on the next header: the
      // measured window is decode -> response written, never read idle.
      served.close();
      uint8_t raw_hdr[sizeof(DataRequestHeader)];
      if (net::read_exact(fd, raw_hdr, sizeof(raw_hdr)) != ErrorCode::OK) break;
      // Checked parse (data_wire.h): unknown op or a length past its
      // ceiling is a protocol violation, and with no frame boundaries the
      // only safe answer is dropping the connection — continuing would
      // interpret attacker-positioned payload bytes as the next header.
      if (!decode_request_header(raw_hdr, sizeof(raw_hdr), hdr)) break;
      served.open();
      // Relative budget -> absolute deadline anchored at receipt (0 = none).
      const Deadline op_deadline = Deadline::from_wire(hdr.deadline_ms);
      if (hdr.op == kOpHello) {
        // decode_request_header pinned len to [1, kMaxHelloNameBytes].
        char name[256] = {};
        if (net::read_exact(fd, name, hdr.len) != ErrorCode::OK) break;
        // Shared with the uring engine (uring_engine.h): both engines must
        // map — and refuse — segments identically.
        const uint32_t status =
            static_cast<uint32_t>(map_staging_segment(name, stg_base, stg_len));
        if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
        continue;
      }
      if (hdr.op == kOpReadStaged || hdr.op == kOpWriteStaged) {
        uint64_t shm_off = 0;
        if (net::read_exact(fd, &shm_off, sizeof(shm_off)) != ErrorCode::OK) break;
        uint8_t* target = nullptr;
        Region virt;
        uint64_t offset = 0;
        const ErrorCode resolved = regions_.resolve(
            hdr.addr, hdr.rkey, hdr.len, hdr.extent_gen,
            hdr.op == kOpWriteStaged ? poolspan::Access::kWrite : poolspan::Access::kRead,
            hdr.trace_id, target, virt, offset);
        const bool valid = resolved == ErrorCode::OK;
        uint32_t status = static_cast<uint32_t>(ErrorCode::OK);
        // Admission + deadline gate PER CHUNK: staged sub-ops arrive as a
        // pipeline of chunk headers, so a budget that expires mid-transfer
        // aborts the remaining chunks ("during service") instead of
        // finishing a copy whose reader has given up.
        std::optional<AdmissionTicket> ticket;
        // hdr is #pragma pack(1): copy len out before emplace forwards it
        // by reference (a reference to the packed member is misaligned UB).
        const uint64_t chunk_len = hdr.len;
        if (valid) {
          ticket.emplace(*gate_, op_deadline, chunk_len);
          if (!ticket->admitted()) {
            status = rejection(*ticket);
          } else if (op_deadline.expired()) {
            status = expired_status();
          }
        }
        if (!valid || !staging_bounds_ok(stg_base, stg_len, shm_off, hdr.len)) {
          // A poolsan conviction (STALE_EXTENT) outranks the generic access
          // error — the client must learn its descriptor is stale, not
          // merely out of bounds.
          status = static_cast<uint32_t>(valid ? ErrorCode::MEMORY_ACCESS_ERROR : resolved);
        } else if (status != static_cast<uint32_t>(ErrorCode::OK)) {
          // rejected above: acknowledge without touching the region
        } else if (hdr.op == kOpWriteStaged) {
          if (target) {
            std::memcpy(target, stg_base + shm_off, hdr.len);
          } else {
            // Virtual region: backing store reads straight from the shared
            // segment (HBM provider: shm -> device, no scratch).
            status = static_cast<uint32_t>(virt.write_fn(offset, stg_base + shm_off, hdr.len));
          }
        } else {
          if (target) {
            std::memcpy(stg_base + shm_off, target, hdr.len);
          } else {
            // Virtual region: backing store writes straight into the shared
            // segment (HBM provider: device -> shm, no scratch).
            status = static_cast<uint32_t>(virt.read_fn(offset, stg_base + shm_off, hdr.len));
          }
        }
        if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
        continue;
      }
      if (hdr.op == kOpFabricOffer || hdr.op == kOpFabricPull) {
        uint64_t transfer_id = 0;
        if (net::read_exact(fd, &transfer_id, sizeof(transfer_id)) != ErrorCode::OK) break;
        std::string fabric_addr;
        if (hdr.op == kOpFabricPull) {
          uint16_t alen = 0;
          if (net::read_exact(fd, &alen, sizeof(alen)) != ErrorCode::OK) break;
          if (!valid_fabric_addr_len(alen)) break;  // protocol violation
          fabric_addr.resize(alen);
          if (net::read_exact(fd, fabric_addr.data(), alen) != ErrorCode::OK) break;
        }
        uint8_t* target = nullptr;
        Region virt;
        uint64_t offset = 0;
        uint32_t status = static_cast<uint32_t>(ErrorCode::NOT_IMPLEMENTED);
        const ErrorCode fab_resolved =
            regions_.resolve(hdr.addr, hdr.rkey, hdr.len, hdr.extent_gen,
                             poolspan::Access::kRead, hdr.trace_id, target, virt, offset);
        if (fab_resolved != ErrorCode::OK || target) {
          // A poolsan conviction rides through verbatim (STALE_EXTENT —
          // the caller must refetch placements); a flat-region fabric op
          // stays the generic access error.
          status = static_cast<uint32_t>(
              fab_resolved != ErrorCode::OK ? fab_resolved : ErrorCode::MEMORY_ACCESS_ERROR);
        } else if (hdr.op == kOpFabricOffer && virt.offer_fn) {
          status = static_cast<uint32_t>(virt.offer_fn(offset, hdr.len, transfer_id));
        } else if (hdr.op == kOpFabricPull && virt.pull_fn) {
          // Blocks this connection thread until the bytes are in device
          // memory — the caller's status read doubles as the completion.
          status = static_cast<uint32_t>(virt.pull_fn(fabric_addr, transfer_id, offset,
                                                      hdr.len));
        }
        if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
        continue;
      }
      uint8_t* target = nullptr;
      Region virt;
      uint64_t offset = 0;
      const ErrorCode resolved = regions_.resolve(
          hdr.addr, hdr.rkey, hdr.len, hdr.extent_gen,
          hdr.op == kOpWrite ? poolspan::Access::kWrite : poolspan::Access::kRead,
          hdr.trace_id, target, virt, offset);
      const bool valid = resolved == ErrorCode::OK;

      if (hdr.op == kOpWrite) {
        uint32_t status = static_cast<uint32_t>(ErrorCode::OK);
        std::optional<AdmissionTicket> ticket;
        const uint64_t op_len = hdr.len;  // packed member: no reference binds
        if (valid) {
          ticket.emplace(*gate_, op_deadline, op_len);
          if (!ticket->admitted()) status = rejection(*ticket);
        }
        if (!valid || status != static_cast<uint32_t>(ErrorCode::OK)) {
          // Must still drain the payload to keep the stream aligned —
          // shed/expired/convicted writes drain to a sink, never into the
          // region (a STALE_EXTENT resolve answers that exact code).
          if (!valid) status = static_cast<uint32_t>(resolved);
          std::vector<uint8_t> sink(64 * 1024);
          uint64_t left = hdr.len;
          while (left > 0) {
            const uint64_t chunk = std::min<uint64_t>(left, sink.size());
            if (net::read_exact(fd, sink.data(), chunk) != ErrorCode::OK) return;
            left -= chunk;
          }
        } else if (target) {
          // Bytes land directly in the registered region: zero copy.
          if (net::read_exact(fd, target, hdr.len) != ErrorCode::OK) return;
          // Mid-service expiry (a slow sender dribbled past the budget):
          // the bytes landed — one-sided writes are unacknowledged until
          // this status, so the client treats them as not-written and the
          // range stays unreferenced until a successful put completes.
          if (op_deadline.expired()) status = expired_status();
        } else {
          scratch.resize(hdr.len);
          if (net::read_exact(fd, scratch.data(), hdr.len) != ErrorCode::OK) return;
          if (op_deadline.expired()) {
            // Budget spent during the drain: refuse the (possibly
            // expensive) backing-store apply — that is the doomed work.
            status = expired_status();
          } else {
            status = static_cast<uint32_t>(virt.write_fn(offset, scratch.data(), hdr.len));
          }
        }
        if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
      } else if (hdr.op == kOpRead) {
        if (!valid) {
          const uint32_t status = static_cast<uint32_t>(resolved);
          if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
          continue;
        }
        AdmissionTicket ticket(*gate_, op_deadline, hdr.len);
        if (!ticket.admitted() || op_deadline.expired()) {
          const uint32_t status =
              !ticket.admitted() ? rejection(ticket) : expired_status();
          if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
          continue;
        }
        if (!target) {
          scratch.resize(hdr.len);
          const auto ec = virt.read_fn(offset, scratch.data(), hdr.len);
          const uint32_t status = static_cast<uint32_t>(ec);
          if (ec != ErrorCode::OK) {
            if (net::write_all(fd, &status, sizeof(status)) != ErrorCode::OK) return;
            continue;
          }
          if (net::write_iov2(fd, &status, sizeof(status), scratch.data(), hdr.len) !=
              ErrorCode::OK)
            return;
          continue;
        }
        // Header + region bytes in one gather write: zero copy out. Same
        // pool-direct lane the uring engine serves (completion-only count).
        const uint32_t status = static_cast<uint32_t>(ErrorCode::OK);
        if (net::write_iov2(fd, &status, sizeof(status), target, hdr.len) != ErrorCode::OK)
          return;
        g_pool_direct_ops.add();
        g_pool_direct_bytes.add(hdr.len);
      } else {
        break;  // protocol violation
      }
    }
    served.close();  // the loop's final op (exit via break)
  }

  std::string host_;
  uint16_t port_{0};
  net::Socket listener_;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;

  mutable Mutex conns_mutex_;
  std::vector<ConnSlot> conns_ BTPU_GUARDED_BY(conns_mutex_);

  // Shared with the uring engine (uring_engine.h): one registry, one
  // resolve, whichever engine is serving.
  RegionTable regions_;
  std::mt19937_64 rng_{0x7463707265670aull};
  // Data-plane admission (one gate per server; both engines share it).
  // Created at start() so env-configured tests see their knobs.
  std::unique_ptr<AdmissionGate> gate_;
  // Event-loop engine (null = thread-per-connection fallback active).
  std::unique_ptr<UringDataPlane> engine_;
};

}  // namespace

// ---- client-side connection pool ------------------------------------------

namespace {

constexpr uint64_t kStagingBytes = 4ull << 20;  // == kChunkBytesMax: every sub-op fits

StripeCounter g_staged_ops;
StripeCounter g_staged_bytes;
StripeCounter g_stream_ops;
StripeCounter g_stream_bytes;

bool staged_lane_enabled() {
  // Read per call (it only runs when a NEW connection probes the lane):
  // tests and operators can flip BTPU_STAGED_DATA without a restart.
  return env_bool("BTPU_STAGED_DATA", true);
}

}  // namespace

uint64_t tcp_staged_op_count() noexcept { return g_staged_ops.total(); }
uint64_t tcp_staged_byte_count() noexcept { return g_staged_bytes.total(); }
uint64_t tcp_stream_op_count() noexcept { return g_stream_ops.total(); }
uint64_t tcp_stream_byte_count() noexcept { return g_stream_bytes.total(); }
uint64_t tcp_pool_direct_op_count() noexcept { return g_pool_direct_ops.total(); }
uint64_t tcp_pool_direct_byte_count() noexcept { return g_pool_direct_bytes.total(); }
uint64_t tcp_zerocopy_sent_count() noexcept { return g_zerocopy_sent.total(); }
uint64_t tcp_zerocopy_copied_count() noexcept { return g_zerocopy_copied.total(); }

// A pooled data-plane connection, optionally with a negotiated same-host
// staging segment (see the opcode block comment).
struct PooledConn {
  net::Socket sock;
  uint8_t* stg_base{nullptr};
  uint64_t stg_len{0};

  PooledConn() = default;
  explicit PooledConn(net::Socket s) : sock(std::move(s)) {}
  PooledConn(PooledConn&& other) noexcept
      : sock(std::move(other.sock)), stg_base(other.stg_base), stg_len(other.stg_len) {
    other.stg_base = nullptr;
    other.stg_len = 0;
  }
  PooledConn& operator=(PooledConn&& other) noexcept {
    if (this != &other) {
      drop_staging();
      sock = std::move(other.sock);
      stg_base = other.stg_base;
      stg_len = other.stg_len;
      other.stg_base = nullptr;
      other.stg_len = 0;
    }
    return *this;
  }
  ~PooledConn() { drop_staging(); }

  void drop_staging() {
    if (stg_base) {
      ::munmap(stg_base, stg_len);
      stg_base = nullptr;
      stg_len = 0;
    }
  }
};

// One pooled connection per concurrent transfer per endpoint; connections are
// created on demand and returned after use. At creation the pool probes the
// staged lane once per endpoint (hello handshake); cross-host endpoints
// refuse or drop the probe connection and are remembered as stream-only.
//
// Sharded by endpoint hash: N client threads (or the shard-parallel batch
// engine's workers) hitting DIFFERENT endpoints never share a lock, and
// same-endpoint acquire/release critical sections are a few pointer moves —
// the 4-process/4-thread retention rows convoyed on the old single mutex.
class TcpEndpointPool {
 public:
  static TcpEndpointPool& instance() {
    static TcpEndpointPool pool;
    return pool;
  }

  Result<PooledConn> acquire(const std::string& endpoint) {
    Shard& shard = shard_for(endpoint);
    int staged_hint;
    {
      MutexLock lock(shard.mutex);
      auto& free_list = shard.pools[endpoint];
      if (!free_list.empty()) {
        PooledConn c = std::move(free_list.back());
        free_list.pop_back();
        return c;
      }
      auto it = shard.staged_support.find(endpoint);
      staged_hint = it == shard.staged_support.end() ? 0 : it->second;
    }
    auto hp = net::parse_host_port(endpoint);
    if (!hp) return ErrorCode::INVALID_ADDRESS;
    auto sock = net::tcp_connect(hp->host, hp->port, 5000, /*bulk_buffers=*/true);
    if (!sock.ok()) return sock.error();
    PooledConn conn(std::move(sock).value());
    if (staged_hint >= 0 && staged_lane_enabled()) {
      const int verdict = try_staging_handshake(conn);
      if (verdict < 0 && !conn.sock.valid()) {
        // An old server drops the connection on an unknown opcode; redial
        // plain for this attempt — the endpoint is remembered stream-only.
        auto redial = net::tcp_connect(hp->host, hp->port, 5000, true);
        if (!redial.ok()) return redial.error();
        conn = PooledConn(std::move(redial).value());
      }
      if (verdict != 0) {
        // 0 = client-local shm setup failed (/dev/shm full, EMFILE):
        // transient, so the next connection re-probes. Only a server
        // answer (yes / refused / dropped) is worth remembering.
        MutexLock lock(shard.mutex);
        shard.staged_support[endpoint] = verdict;
      }
    }
    return conn;
  }

  void release(const std::string& endpoint, PooledConn conn) {
    Shard& shard = shard_for(endpoint);
    MutexLock lock(shard.mutex);
    auto& free_list = shard.pools[endpoint];
    if (free_list.size() < kMaxPooledPerEndpoint) free_list.push_back(std::move(conn));
    // else: dtor closes socket + unmaps staging
  }

  void drop_endpoint(const std::string& endpoint) {
    Shard& shard = shard_for(endpoint);
    MutexLock lock(shard.mutex);
    shard.pools.erase(endpoint);
  }

 private:
  struct Shard {
    Mutex mutex;
    std::unordered_map<std::string, std::vector<PooledConn>> pools BTPU_GUARDED_BY(mutex);
    // 1 yes, -1 no.
    std::unordered_map<std::string, int> staged_support BTPU_GUARDED_BY(mutex);
  };

  Shard& shard_for(const std::string& endpoint) {
    return shards_[std::hash<std::string>{}(endpoint) & (kShards - 1)];
  }

  // Returns 1 staged (conn now carries a mapped segment), -1 stream-only
  // (server refused or dropped — sticky), 0 client-local shm failure
  // (transient — not recorded). On -1 the connection may be dead (old
  // server) — caller checks validity.
  static int try_staging_handshake(PooledConn& conn) {
    static std::atomic<uint64_t> counter{0};
    const std::string name = "/btpu_stg_" + std::to_string(::getpid()) + "_" +
                             std::to_string(counter.fetch_add(1));
    int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return 0;
    void* base = MAP_FAILED;
    if (::ftruncate(fd, static_cast<off_t>(kStagingBytes)) == 0) {
      base = ::mmap(nullptr, kStagingBytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    }
    ::close(fd);
    if (base == MAP_FAILED) {
      ::shm_unlink(name.c_str());
      return 0;
    }
    DataRequestHeader hdr{kOpHello, 0, 0, name.size(), 0, 0, 0, 0};
    uint32_t status = ~0u;
    const bool ok =
        net::write_iov2(conn.sock.fd(), &hdr, sizeof(hdr), name.data(), name.size()) ==
            ErrorCode::OK &&
        net::read_exact(conn.sock.fd(), &status, sizeof(status)) == ErrorCode::OK;
    // The server holds its own mapping now (or refused); the name can go
    // either way — mappings keep the segment alive, crashes leak nothing.
    ::shm_unlink(name.c_str());
    if (!ok) {
      ::munmap(base, kStagingBytes);
      conn.sock.close();  // stream desynced (old server): force redial
      return -1;
    }
    if (static_cast<ErrorCode>(status) != ErrorCode::OK) {
      ::munmap(base, kStagingBytes);
      return -1;  // server reachable but cannot map: different host
    }
    conn.stg_base = static_cast<uint8_t*>(base);
    conn.stg_len = kStagingBytes;
    return 1;
  }

  static constexpr size_t kMaxPooledPerEndpoint = 16;
  static constexpr size_t kShards = 8;  // power of two (mask in shard_for)
  Shard shards_[kShards];
};

// ---- shared wire worker pool ----------------------------------------------
//
// A small process-wide pool for data-path parallelism: shard-parallel
// striped transfers (each worker drives its own sub-ops on its own pooled
// connections) and parallel memory-lane copies. Threads are lazy, JOINABLE
// (a detached pool made shutdown unfenceable: workers could touch freed
// globals at process exit under asan/tsan), and park on a condvar between
// jobs; the destructor raises stop_ and joins every worker. On a
// single-core machine the pool is empty and run() degrades to the caller's
// inline loop. The caller always participates — even against a stopped or
// empty pool a job completes inline — so a saturated (or drained) pool
// delays work but can never deadlock it.
class WireWorkers {
 public:
  static WireWorkers& instance() {
    static WireWorkers pool;  // destructor joins the workers at exit
    return pool;
  }

  ~WireWorkers() {
    {
      MutexLock lock(mutex_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  size_t capacity() const noexcept { return nthreads_; }

  // Runs fn(0..n-1) across the pool + the calling thread; returns when every
  // call has completed (the completion barrier of a shard-parallel fetch).
  void run(size_t n, const std::function<void(size_t)>& fn) {
    if (n == 0) return;
    if (nthreads_ == 0 || n == 1) {
      for (size_t i = 0; i < n; ++i) fn(i);
      return;
    }
    auto job = std::make_shared<Job>();
    job->fn = &fn;
    job->n = n;
    {
      MutexLock lock(mutex_);
      jobs_.push_back(job);
    }
    cv_.notify_all();
    help(*job);
    MutexLock lock(job->done_mutex);
    job->done_cv.wait(lock, [&] { return job->done.load() >= job->n; });
    MutexLock qlock(mutex_);
    std::erase(jobs_, job);
  }

 private:
  struct Job {
    const std::function<void(size_t)>* fn{nullptr};
    size_t n{0};
    std::atomic<size_t> next{0};
    std::atomic<size_t> done{0};
    Mutex done_mutex;
    CondVarAny done_cv;
  };

 public:
  // Resolution is shared with the NON-instantiating metrics accessor
  // (wire_pool_threads_resolved): a /metrics scrape on a process that
  // never touches the data path must not spawn the pool as a side effect.
  static size_t resolved_size() {
    // Default: leave one core for the caller, cap at 6 (measured knee for
    // shard-parallel drains). BTPU_WIRE_POOL_THREADS overrides — 0 is an
    // explicit "inline only" (single-core semantics everywhere); values
    // are clamped to 64 so a typo can't spawn a thread storm. Latched on
    // FIRST call so the exported wire_pool_threads scoreboard value always
    // matches the thread count the pool actually runs — a re-read would
    // let a post-spawn setenv make the metric lie about the pool.
    static const size_t resolved = [] {
      const unsigned hw = std::thread::hardware_concurrency();
      const unsigned fallback = hw > 1 ? std::min(hw - 1, 6u) : 0;
      return std::min<size_t>(env_u32("BTPU_WIRE_POOL_THREADS", fallback), 64);
    }();
    return resolved;
  }

 private:
  WireWorkers() {
    nthreads_ = resolved_size();
    threads_.reserve(nthreads_);
    for (size_t i = 0; i < nthreads_; ++i) {
      threads_.emplace_back([this] { worker_loop(); });
    }
  }

  static void help(Job& job) {
    for (;;) {
      const size_t i = job.next.fetch_add(1);
      if (i >= job.n) return;
      // Containment, not handling: fn owns its error reporting (the batch
      // call sites catch inside fn and mark their ops failed). An escaped
      // exception here would std::terminate a pool worker, or strand the
      // job with dangling captures if it escaped the calling thread's
      // help() — either way `done` must still advance.
      try {
        (*job.fn)(i);
      } catch (...) {
      }
      if (job.done.fetch_add(1) + 1 == job.n) {
        MutexLock lock(job.done_mutex);
        job.done_cv.notify_all();
      }
    }
  }

  void worker_loop() {
    for (;;) {
      std::shared_ptr<Job> job;
      {
        MutexLock lock(mutex_);
        // Explicit loop: a predicate lambda is analyzed as an unannotated
        // function and would flag the guarded jobs_ read.
        while (jobs_.empty() && !stop_) cv_.wait(lock);
        // Drain-before-exit: a job enqueued concurrently with the
        // destructor still completes (its owner is blocked in run() until
        // `done` reaches n), THEN the worker honors stop_.
        if (jobs_.empty()) return;
        job = jobs_.front();
        if (job->next.load() >= job->n) {
          // Exhausted but not yet erased by its owner: skip past it so a
          // straggling worker cannot spin on a drained job.
          jobs_.pop_front();
          continue;
        }
      }
      help(*job);
    }
  }

  size_t nthreads_{0};
  Mutex mutex_;
  CondVarAny cv_;
  std::deque<std::shared_ptr<Job>> jobs_ BTPU_GUARDED_BY(mutex_);
  bool stop_ BTPU_GUARDED_BY(mutex_){false};
  std::vector<std::thread> threads_;  // written once in the ctor, joined in the dtor
};

// ---- pipelined batch engine ------------------------------------------------
//
// Every request in a batch is issued before any response is awaited, one
// pooled connection per in-flight sub-op. The server side processes the
// requests concurrently (thread per connection) while the client drains
// whichever response polls ready first (a slow endpoint in a mixed batch
// cannot head-of-line-block buffered responses), so a batch costs ~one
// round trip of latency and zero fan-out threads; ops wider than the
// batch-adaptive chunk size are split so one huge transfer also pipelines.
// One-sided reads and writes are idempotent, so a sub-op whose connection
// dies mid-flight (worker restarted, stale pooled socket) is simply re-run
// once on a fresh connection.
//
// Two further levels of overlap inside a batch:
//   * Intra-connection chunk pipeline (staged lane): a staged sub-op no
//     longer moves as stage-whole -> status -> drain-whole. It is sliced
//     into pipe chunks at distinct segment offsets; the client streams the
//     chunk requests and the server answers them in order, so while the
//     client copies+hashes chunk N out of the segment the server is already
//     copying chunk N+1 in — the two memcpy passes of the staged lane run
//     concurrently instead of back to back, and the CRC rides the one
//     client-side pass (seed-chained, no combine, no post-pass).
//   * Shard-parallel drains: a batch with several ops (a striped get's
//     shards, split-replica slices) is partitioned BY OP across the wire
//     worker pool, each slice driving its own sub-ops on its own pooled
//     connections, with a completion barrier before the CRC fold. The
//     client-side copy out of the segments was previously serialized on the
//     calling thread even though the worker side served shards in parallel.

namespace {

// Sub-op sizing: ops split into chunks so the batch fills the in-flight
// window — a single 1 MiB staged op becomes two 512 KiB sub-ops whose
// worker-side copies overlap the client-side drains (two connections, two
// segments), while an already-wide batch keeps 4 MiB chunks (finer splits
// only add header/status round trips — measured ~15% off at 16 MiB).
constexpr uint64_t kChunkBytesMax = 4ull << 20;   // fits the 4 MiB segments
constexpr uint64_t kChunkBytesMin = 512ull << 10; // below this, RTTs dominate
constexpr size_t kMaxInflight = 12;           // < kMaxPooledPerEndpoint
// Batches smaller than this stay on the calling thread: handing a few
// hundred KiB to the worker pool costs more in wakeups than the parallel
// memcpy returns.
constexpr uint64_t kShardParallelMin = 512ull << 10;

uint64_t pick_chunk_bytes(uint64_t total_batch_bytes) {
  static const uint64_t forced = [] {
    return env_u64("BTPU_CHUNK_BYTES", 0);  // perf experiments only
  }();
  if (forced) return forced;
  // Target ~4 concurrent sub-ops: enough that worker-side staging overlaps
  // client-side drains, few enough that wide batches (already >= 4 ops)
  // keep whole 4 MiB chunks — interleaved A/B at 16 MiB read ~15% slower
  // when its 4 ops were split finer.
  const uint64_t want = total_batch_bytes / 4;
  return std::clamp(want, kChunkBytesMin, kChunkBytesMax);
}

// Intra-connection pipeline slice for staged sub-ops (see the block comment
// above). 256 KiB keeps both sides inside L2 while giving the server a
// useful head start; BTPU_PIPE_CHUNK overrides for perf experiments.
constexpr uint64_t kPipeChunkMin = 64ull << 10;  // bounds the frame array too

uint64_t pipe_chunk_bytes() {
  static const uint64_t v = [] {
    const uint64_t forced = env_u64("BTPU_PIPE_CHUNK", 0);
    return forced ? std::clamp(forced, kPipeChunkMin, kStagingBytes) : 256ull << 10;
  }();
  return v;
}

struct SubOp {
  WireOp* op;
  uint64_t addr;   // absolute remote address of this chunk
  uint8_t* buf;    // client-side slice
  uint64_t len;
  uint64_t off;    // offset within the op (orders the crc combine)
  uint32_t crc;    // this chunk's crc32c (op->want_crc only)
};

bool use_staged(const PooledConn& c, const SubOp& sub) {
  return c.stg_base != nullptr && sub.len <= c.stg_len;
}

// Remaining budget for this sub-op's next request header (0 = none).
uint32_t sub_budget_ms(const SubOp& sub) {
  const Deadline& d = sub.op->deadline;
  return d.is_infinite() ? 0 : d.wire_budget_ms();
}

ErrorCode issue_sub(const PooledConn& c, SubOp& sub, uint8_t opcode) {
  if (use_staged(c, sub)) {
    const uint64_t pipe = pipe_chunk_bytes();
    if (opcode == kOpWrite) {
      // Pipelined staging: copy+hash one chunk into the segment, send its
      // header, move to the next — the server's segment->target copy of
      // chunk N runs while chunk N+1 is being staged. The staging copy is
      // the only client-side read of the bytes, so want_crc writes get
      // their shard stamp for free (seed-chained across chunks).
      Crc32cStream crc;
      for (uint64_t off = 0; off < sub.len; off += pipe) {
        const uint64_t n = std::min(pipe, sub.len - off);
        if (sub.op->want_crc) {
          crc.update_copy(c.stg_base + off, sub.buf + off, n);
        } else {
          std::memcpy(c.stg_base + off, sub.buf + off, n);
        }
        StagedFrame framed{{kOpWriteStaged, sub.addr + off, sub.op->rkey, n,
                            sub_budget_ms(sub), sub.op->trace_id, sub.op->span_id,
                            sub.op->extent_gen},
                           off};
        if (auto ec = net::write_all(c.sock.fd(), &framed, sizeof(framed));
            ec != ErrorCode::OK)
          return ec;
      }
      if (sub.op->want_crc) sub.crc = crc.value();
      return ErrorCode::OK;
    }
    // Staged read: every chunk request goes out in one send; the server
    // fills chunk N's segment slice and acks it while the client is still
    // draining chunk N-1 (the drain happens in collect_sub, in order).
    StagedFrame frames[kStagingBytes / kPipeChunkMin];
    size_t nframes = 0;
    for (uint64_t off = 0; off < sub.len; off += pipe) {
      const uint64_t n = std::min(pipe, sub.len - off);
      frames[nframes++] = {{kOpReadStaged, sub.addr + off, sub.op->rkey, n,
                            sub_budget_ms(sub), sub.op->trace_id, sub.op->span_id,
                            sub.op->extent_gen},
                          off};
    }
    return net::write_all(c.sock.fd(), frames, nframes * sizeof(StagedFrame));
  }
  DataRequestHeader hdr{opcode,           sub.addr,         sub.op->rkey,
                        sub.len,          sub_budget_ms(sub), sub.op->trace_id,
                        sub.op->span_id,  sub.op->extent_gen};
  if (opcode == kOpWrite) {
    const ErrorCode ec = net::write_iov2(c.sock.fd(), &hdr, sizeof(hdr), sub.buf, sub.len);
    // No copy to fuse into on the plain socket lane: hash after the send so
    // the pass overlaps sibling chunks already moving through the kernel.
    if (ec == ErrorCode::OK && sub.op->want_crc) sub.crc = crc32c(sub.buf, sub.len);
    return ec;
  }
  return net::write_all(c.sock.fd(), &hdr, sizeof(hdr));
}

// Reads one response. `healthy` reports whether the stream is still aligned
// (server-reported errors keep the connection reusable; socket errors don't).
ErrorCode collect_sub(const PooledConn& c, SubOp& sub, uint8_t opcode, bool& healthy) {
  healthy = false;
  if (use_staged(c, sub)) {
    // Per-chunk statuses, in issue order. Every status is drained even past
    // the first error so the stream stays aligned for the next op.
    const uint64_t pipe = pipe_chunk_bytes();
    ErrorCode first = ErrorCode::OK;
    Crc32cStream crc;
    const bool want_crc = sub.op->want_crc;
    for (uint64_t off = 0; off < sub.len; off += pipe) {
      const uint64_t n = std::min(pipe, sub.len - off);
      uint32_t status = 0;
      if (auto ec = net::read_exact(c.sock.fd(), &status, sizeof(status));
          ec != ErrorCode::OK)
        return ec;
      if (static_cast<ErrorCode>(status) != ErrorCode::OK) {
        if (first == ErrorCode::OK) first = static_cast<ErrorCode>(status);
        continue;
      }
      if (opcode == kOpRead) {
        // Fused copy+crc: the drain out of the staging segment is the only
        // read of the bytes either way; meanwhile the server is already
        // copying the NEXT chunk into its slice of the segment.
        if (want_crc) {
          crc.update_copy(sub.buf + off, c.stg_base + off, n);
        } else {
          std::memcpy(sub.buf + off, c.stg_base + off, n);
        }
      }
    }
    if (opcode == kOpRead && want_crc) sub.crc = crc.value();
    healthy = true;
    // Lane accounting on COMPLETION only: a failed or retried sub-op must
    // not inflate the copies-per-byte scoreboard (the pvm counters follow
    // the same rule).
    if (first == ErrorCode::OK) {
      g_staged_ops.add();
      g_staged_bytes.add(sub.len);
    }
    return first;
  }
  uint32_t status = 0;
  if (auto ec = net::read_exact(c.sock.fd(), &status, sizeof(status)); ec != ErrorCode::OK)
    return ec;
  if (static_cast<ErrorCode>(status) != ErrorCode::OK) {
    healthy = true;  // error responses carry no payload
    return static_cast<ErrorCode>(status);
  }
  if (opcode == kOpRead) {
    const bool want_crc = sub.op->want_crc;
    if (!want_crc) {
      if (auto ec = net::read_exact(c.sock.fd(), sub.buf, sub.len); ec != ErrorCode::OK)
        return ec;
    } else {
      // Segmented drain: hash each segment after it lands while TCP keeps
      // delivering the next one into the socket buffer — the CRC rides
      // under the wire instead of costing a post-pass.
      constexpr uint64_t kSeg = 256 * 1024;
      Crc32cStream crc;
      for (uint64_t pos = 0; pos < sub.len; pos += kSeg) {
        const uint64_t n = std::min(kSeg, sub.len - pos);
        if (auto ec = net::read_exact(c.sock.fd(), sub.buf + pos, n); ec != ErrorCode::OK)
          return ec;
        crc.update(sub.buf + pos, n);
      }
      sub.crc = crc.value();
    }
  }
  healthy = true;
  g_stream_ops.add();  // completion-only accounting, like the staged branch
  g_stream_bytes.add(sub.len);
  return ErrorCode::OK;
}

bool is_socket_failure(ErrorCode ec) {
  return ec == ErrorCode::NETWORK_ERROR || ec == ErrorCode::CLIENT_DISCONNECTED ||
         ec == ErrorCode::CONNECTION_FAILED;
}

// State shared across the batch's engine slices. `dead` memoizes endpoints
// whose connect failed once in this batch: every later sub-op to them fails
// immediately instead of re-paying the connect timeout serially (a preempted
// worker must not stall the whole pipeline N x 5s — the caller falls back to
// another replica). Ops are partitioned whole onto slices, so op->status
// stays single-writer; only `dead` and `first` cross threads.
struct BatchShared {
  Mutex mutex;
  std::unordered_map<std::string, ErrorCode> dead BTPU_GUARDED_BY(mutex);
  ErrorCode first BTPU_GUARDED_BY(mutex){ErrorCode::OK};

  bool known_dead(const std::string& endpoint, ErrorCode& ec) {
    MutexLock lock(mutex);
    auto it = dead.find(endpoint);
    if (it == dead.end()) return false;
    ec = it->second;
    return true;
  }
  void mark_dead(const std::string& endpoint, ErrorCode ec) {
    MutexLock lock(mutex);
    dead.emplace(endpoint, ec);
  }
  void fail(WireOp* op, ErrorCode ec) {
    if (op->status == ErrorCode::OK) op->status = ec;
    MutexLock lock(mutex);
    if (first == ErrorCode::OK) first = ec;
  }
};

// Synchronous single-shot on a fresh connection (retry path).
ErrorCode run_sub_fresh(SubOp& sub, uint8_t opcode, BatchShared& shared) {
  auto& pool = TcpEndpointPool::instance();
  const std::string& endpoint = sub.op->remote->endpoint;
  if (ErrorCode dead_ec; shared.known_dead(endpoint, dead_ec)) return dead_ec;
  pool.drop_endpoint(endpoint);  // the whole pool is suspect once one died
  auto acquired = pool.acquire(endpoint);
  if (!acquired.ok()) {
    shared.mark_dead(endpoint, acquired.error());
    return acquired.error();
  }
  PooledConn c = std::move(acquired).value();
  if (auto ec = issue_sub(c, sub, opcode); ec != ErrorCode::OK) return ec;
  bool healthy = false;
  const ErrorCode ec = collect_sub(c, sub, opcode, healthy);
  if (healthy) pool.release(endpoint, std::move(c));
  return ec;
}

// One engine slice: issues/collects the sub-ops named by `order` with its
// own in-flight window and pooled connections. Runs standalone for a serial
// batch, or as one lane of the shard-parallel fan-out.
void run_subs(std::vector<SubOp>& subs, const std::vector<size_t>& order, uint8_t opcode,
              size_t inflight_cap, BatchShared& shared) {
  auto& pool = TcpEndpointPool::instance();
  struct Flight {
    size_t sub;
    PooledConn conn;
  };
  std::vector<Flight> inflight;
  size_t next = 0;
  while (next < order.size() || !inflight.empty()) {
    if (next < order.size() && inflight.size() < inflight_cap) {
      SubOp& sub = subs[order[next]];
      if (sub.op->status != ErrorCode::OK) {  // sibling chunk already failed
        ++next;
        continue;
      }
      if (sub.op->deadline.expired()) {
        // Budget spent before this sub-op even left: fail locally instead
        // of shipping doomed work to the worker.
        // ordering: relaxed — monotonic stat counter.
        robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
        shared.fail(sub.op, ErrorCode::DEADLINE_EXCEEDED);
        ++next;
        continue;
      }
      if (ErrorCode dead_ec; shared.known_dead(sub.op->remote->endpoint, dead_ec)) {
        shared.fail(sub.op, dead_ec);
        ++next;
        continue;
      }
      auto acquired = pool.acquire(sub.op->remote->endpoint);
      if (!acquired.ok()) {
        shared.mark_dead(sub.op->remote->endpoint, acquired.error());
        shared.fail(sub.op, acquired.error());
        ++next;
        continue;
      }
      PooledConn c = std::move(acquired).value();
      if (auto ec = issue_sub(c, sub, opcode); ec != ErrorCode::OK) {
        // Stale pooled connection dies at send time: one fresh retry.
        if (auto rec = is_socket_failure(ec) ? run_sub_fresh(sub, opcode, shared) : ec;
            rec != ErrorCode::OK)
          shared.fail(sub.op, rec);
        ++next;
        continue;
      }
      inflight.push_back({order[next], std::move(c)});
      ++next;
      continue;
    }
    // Collect whichever response is ready first — a slow endpoint in a
    // mixed batch must not head-of-line-block responses already buffered
    // on other sockets.
    size_t pick = 0;
    if (inflight.size() > 1) {
      std::vector<pollfd> fds(inflight.size());
      for (size_t i = 0; i < inflight.size(); ++i)
        fds[i] = {inflight[i].conn.sock.fd(), POLLIN, 0};
      int rc;
      do {
        rc = ::poll(fds.data(), static_cast<nfds_t>(fds.size()), -1);
      } while (rc < 0 && errno == EINTR);
      if (rc > 0) {
        for (size_t i = 0; i < fds.size(); ++i) {
          if (fds[i].revents != 0) {  // ready, error, or invalid: collect it
            pick = i;
            break;
          }
        }
      }
    }
    Flight flight = std::move(inflight[pick]);
    inflight.erase(inflight.begin() + static_cast<ptrdiff_t>(pick));
    SubOp& sub = subs[flight.sub];
    bool healthy = false;
    ErrorCode ec = collect_sub(flight.conn, sub, opcode, healthy);
    if (healthy) {
      pool.release(sub.op->remote->endpoint, std::move(flight.conn));
    } else if (is_socket_failure(ec)) {
      // Stale pooled connection dies at response time (or the worker
      // restarted mid-op): the op is idempotent, re-run it once.
      ec = run_sub_fresh(sub, opcode, shared);
    }
    if (ec != ErrorCode::OK) shared.fail(sub.op, ec);
  }
}

}  // namespace

void wire_parallel_for(size_t n, const std::function<void(size_t)>& fn) {
  WireWorkers::instance().run(n, fn);
}

size_t wire_parallel_capacity() noexcept { return WireWorkers::instance().capacity(); }

size_t wire_pool_threads_resolved() noexcept { return WireWorkers::resolved_size(); }

ErrorCode tcp_batch(WireOp* ops, size_t n, bool is_write, size_t max_concurrency) {
  const uint8_t opcode = is_write ? kOpWrite : kOpRead;
  const size_t inflight_cap =
      max_concurrency ? std::min(max_concurrency, kMaxInflight) : kMaxInflight;
  uint64_t total_bytes = 0;
  for (size_t i = 0; i < n; ++i) total_bytes += ops[i].len;
  const uint64_t chunk_bytes = pick_chunk_bytes(total_bytes);
  std::vector<SubOp> subs;
  subs.reserve(n);
  ErrorCode refused = ErrorCode::OK;
  // Sub-ops of one op stay contiguous (the CRC fold below relies on offset
  // order) and `groups` records each op's [begin, end) span so the parallel
  // path can partition whole ops onto slices.
  std::vector<std::pair<size_t, size_t>> groups;
  groups.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    ops[i].status = ErrorCode::OK;
    ops[i].crc = 0;
    // Framing-dialect guard: a peer advertising a DIFFERENT raw-header
    // version would desync the byte stream on the first request (the packed
    // headers carry no length prefix) — refuse before any byte goes out.
    // 0 = pre-versioned metadata (legacy peer or WAL-restored placement):
    // served under the documented ship-together contract.
    const uint32_t peer_v = ops[i].remote ? ops[i].remote->data_wire_version : 0;
    if (peer_v != 0 && peer_v != kTcpDataWireVersion) {
      ops[i].status = ErrorCode::REMOTE_ENDPOINT_ERROR;
      refused = ErrorCode::REMOTE_ENDPOINT_ERROR;
      continue;
    }
    const size_t begin = subs.size();
    for (uint64_t off = 0; off < ops[i].len; off += chunk_bytes) {
      const uint64_t len = std::min(chunk_bytes, ops[i].len - off);
      subs.push_back({&ops[i], ops[i].addr + off, ops[i].buf + off, len, off, 0});
    }
    if (subs.size() > begin) groups.emplace_back(begin, subs.size());
  }

  BatchShared shared;
  size_t nslices = 1;
  if (groups.size() > 1 && inflight_cap > 1 && total_bytes >= kShardParallelMin)
    nslices = std::min({groups.size(), wire_parallel_capacity() + 1, inflight_cap});
  if (nslices <= 1) {
    std::vector<size_t> order(subs.size());
    for (size_t i = 0; i < subs.size(); ++i) order[i] = i;
    run_subs(subs, order, opcode, inflight_cap, shared);
  } else {
    // Shard-parallel: ops round-robin onto slices (shards of a striped get
    // are near-equal, so this balances bytes), each slice drains its own
    // connections concurrently; WireWorkers::run is the completion barrier.
    std::vector<std::vector<size_t>> slices(nslices);
    for (size_t g = 0; g < groups.size(); ++g) {
      auto& slice = slices[g % nslices];
      for (size_t s = groups[g].first; s < groups[g].second; ++s) slice.push_back(s);
    }
    const size_t slice_cap = std::max<size_t>(2, inflight_cap / nslices);
    wire_parallel_for(nslices, [&](size_t s) {
      try {
        run_subs(subs, slices[s], opcode, slice_cap, shared);
      } catch (...) {
        // Allocation failure mid-slice (inflight/pollfd growth): fail the
        // slice's ops — conservative for sub-ops that already landed, but
        // one-sided ops are idempotent and the caller retries/fails over.
        // Silently dropping them would report success for unmoved bytes.
        for (size_t idx : slices[s]) shared.fail(subs[idx].op, ErrorCode::INTERNAL_ERROR);
      }
    });
  }
  // Per-op CRC from the per-chunk CRCs (reads hash while draining, writes
  // while staging/sending). Chunks completed in any order, but each op's
  // subs sit contiguously in offset order here, so one forward fold (cached
  // combine operators — chunk lengths repeat) per op reassembles its crc.
  for (const SubOp& sub : subs) {
    WireOp* op = sub.op;
    if (!op->want_crc || op->status != ErrorCode::OK) continue;
    op->crc = sub.off == 0 ? sub.crc : crc32c_combine(op->crc, sub.crc, sub.len);
  }
  {
    MutexLock lock(shared.mutex);
    if (shared.first != ErrorCode::OK) return shared.first;
  }
  return refused;
}

namespace {
// Shared shape of the two fabric commands: header + trailer, one status.
ErrorCode tcp_fabric_command(const std::string& endpoint, uint8_t opcode, uint64_t addr,
                             uint64_t rkey, uint64_t len, const void* trailer,
                             size_t trailer_len) {
  auto& pool = TcpEndpointPool::instance();
  auto acquired = pool.acquire(endpoint);
  if (!acquired.ok()) return acquired.error();
  PooledConn c = std::move(acquired).value();
  const Deadline ambient = current_op_deadline();
  const auto tctx = trace::current();
  DataRequestHeader hdr{opcode, addr, rkey, len,
                        ambient.is_infinite() ? 0 : ambient.wire_budget_ms(),
                        tctx.trace_id, tctx.span_id, /*extent_gen=*/0};
  uint32_t status = 0;
  // Deadline on the status read: a wedged provider on the far side must not
  // hang the caller's drain/repair thread forever — time out, drop the
  // connection (stream state unknown), and let the caller fall back to the
  // host lane. Generous bound: the pull moves up to a 32 MiB segment.
  constexpr int kFabricTimeoutMs = 60'000;
  struct timeval tv{kFabricTimeoutMs / 1000, 0};
  ::setsockopt(c.sock.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  const bool ok =
      net::write_iov2(c.sock.fd(), &hdr, sizeof(hdr), trailer, trailer_len) ==
          ErrorCode::OK &&
      net::read_exact(c.sock.fd(), &status, sizeof(status)) == ErrorCode::OK;
  if (!ok) return ErrorCode::NETWORK_ERROR;  // dead/timed-out conn: not repooled
  struct timeval off{0, 0};
  ::setsockopt(c.sock.fd(), SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
  pool.release(endpoint, std::move(c));
  return static_cast<ErrorCode>(status);
}
}  // namespace

ErrorCode tcp_fabric_offer(const std::string& endpoint, uint64_t addr, uint64_t rkey,
                           uint64_t len, uint64_t transfer_id) {
  return tcp_fabric_command(endpoint, kOpFabricOffer, addr, rkey, len, &transfer_id,
                            sizeof(transfer_id));
}

ErrorCode tcp_fabric_pull(const std::string& endpoint, uint64_t addr, uint64_t rkey,
                          uint64_t len, uint64_t transfer_id,
                          const std::string& src_fabric_addr) {
  if (src_fabric_addr.empty() || src_fabric_addr.size() > 255)
    return ErrorCode::INVALID_PARAMETERS;
  std::vector<uint8_t> trailer(sizeof(uint64_t) + sizeof(uint16_t) + src_fabric_addr.size());
  std::memcpy(trailer.data(), &transfer_id, sizeof(transfer_id));
  const uint16_t alen = static_cast<uint16_t>(src_fabric_addr.size());
  std::memcpy(trailer.data() + sizeof(uint64_t), &alen, sizeof(alen));
  std::memcpy(trailer.data() + sizeof(uint64_t) + sizeof(uint16_t), src_fabric_addr.data(),
              src_fabric_addr.size());
  return tcp_fabric_command(endpoint, kOpFabricPull, addr, rkey, len, trailer.data(),
                            trailer.size());
}

ErrorCode tcp_read(const std::string& endpoint, uint64_t addr, uint64_t rkey, void* dst,
                   uint64_t len, uint64_t extent_gen) {
  RemoteDescriptor remote;
  remote.transport = TransportKind::TCP;
  remote.endpoint = endpoint;
  WireOp op{&remote, addr, rkey, static_cast<uint8_t*>(dst), len};
  op.deadline = current_op_deadline();
  const auto rctx = trace::current();
  op.trace_id = rctx.trace_id;
  op.span_id = rctx.span_id;
  op.extent_gen = extent_gen;
  return tcp_batch(&op, 1, /*is_write=*/false, 0);
}

ErrorCode tcp_write(const std::string& endpoint, uint64_t addr, uint64_t rkey, const void* src,
                    uint64_t len, uint64_t extent_gen) {
  RemoteDescriptor remote;
  remote.transport = TransportKind::TCP;
  remote.endpoint = endpoint;
  WireOp op{&remote, addr, rkey, const_cast<uint8_t*>(static_cast<const uint8_t*>(src)), len};
  op.deadline = current_op_deadline();
  const auto wctx = trace::current();
  op.trace_id = wctx.trace_id;
  op.span_id = wctx.span_id;
  op.extent_gen = extent_gen;
  return tcp_batch(&op, 1, /*is_write=*/true, 0);
}

std::unique_ptr<TransportServer> make_tcp_transport_server() {
  return std::make_unique<TcpTransportServer>();
}

}  // namespace btpu::transport
