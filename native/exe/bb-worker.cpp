// bb-worker: data-plane daemon (role of reference examples/worker_example.cpp,
// planned as a production binary in src/executables/CMakeLists.txt).
#include <csignal>
#include <cstdio>
#include <cstring>
#include <memory>
#include <thread>

#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/rpc/http_metrics.h"
#include "btpu/worker/worker.h"

namespace {
volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  btpu::trace::set_process_name("bb-worker");
  btpu::flight::install_fatal_dump();
  std::string config_path;
  std::string coord_override;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) config_path = argv[++i];
    else if (!std::strcmp(argv[i], "--coord") && i + 1 < argc) coord_override = argv[++i];
    else if (!std::strcmp(argv[i], "--help")) {
      std::printf("usage: bb-worker --config worker.yaml [--coord host:port]\n");
      return 0;
    }
  }
  if (config_path.empty()) {
    std::fprintf(stderr, "bb-worker: --config is required\n");
    return 1;
  }

  auto service = btpu::worker::WorkerService::create_from_yaml(config_path, coord_override);
  if (!service.ok()) {
    std::fprintf(stderr, "bb-worker: startup failed (%s)\n",
                 std::string(btpu::to_string(service.error())).c_str());
    return 1;
  }
  auto worker_ptr = std::move(service).value();
  auto& worker = *worker_ptr;
  const auto& config = worker.config();
  std::printf("bb-worker %s up with %zu pools\n", config.worker_id.c_str(),
              config.pools.size());
  // Observability HTTP server (BTPU_OBS_PORT; 0 = ephemeral): process-wide
  // /metrics (histograms, lane counters) + /debug/flight + /debug/trace —
  // bb-trace collects the worker hop of a distributed trace from here.
  std::unique_ptr<btpu::rpc::MetricsHttpServer> obs;
  if (btpu::env_str("BTPU_OBS_PORT")) {
    obs = std::make_unique<btpu::rpc::MetricsHttpServer>(
        nullptr, "0.0.0.0", static_cast<uint16_t>(btpu::env_u32("BTPU_OBS_PORT", 0)));
    if (obs->start() == btpu::ErrorCode::OK) {
      std::printf("bb-worker obs http on :%u\n", obs->port());
    } else {
      std::fprintf(stderr, "bb-worker: obs http failed to listen (continuing)\n");
      obs.reset();
    }
  }
  std::fflush(stdout);

  std::signal(SIGINT, handle_signal);
  std::signal(SIGTERM, handle_signal);
  while (!g_stop) std::this_thread::sleep_for(std::chrono::milliseconds(200));
  worker.stop();
  return 0;
}
