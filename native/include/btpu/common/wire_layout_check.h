// Compile-time wire-layout lint.
//
// The wire format (btpu/common/wire.h) is append-only: fields are encoded in
// a fixed order with fixed widths, and cross-version compatibility (rolling
// upgrades, durable coordinator records, PR-2's CopyPlacement cache stamps)
// depends on nobody reordering fields, changing a scalar's width, or
// widening an enum. Nothing enforced that rule until now; this header turns
// the load-bearing widths into static_asserts, and the macros below freeze
// the handful of RAW structs that cross a socket via memcpy (packed request
// headers). The field-by-field encodings are frozen at runtime by the wire
// golden table (native/tests/test_wire_layout.cpp + wire_golden.txt,
// regenerate with `make wire-golden`).
//
// Included from native/src/common/types.cpp so every build of libbtpu.so
// evaluates the asserts — a width change fails the build, not a code review.
#pragma once

#include <cstddef>
#include <type_traits>

#include "btpu/common/error.h"
#include "btpu/common/types.h"
#include "btpu/coord/coord_proto.h"
#include "btpu/coord/wal_format.h"
#include "btpu/rpc/rpc.h"

// A type whose bytes go on the wire raw (Writer::put / packed header
// memcpy): must be trivially copyable AND padding-free, or the "layout" is
// whatever the compiler invented this week.
#define BTPU_WIRE_RAW_TYPE(T)                                                   \
  static_assert(std::is_trivially_copyable_v<T>,                                \
                "wire layout: " #T " must be trivially copyable");              \
  static_assert(std::has_unique_object_representations_v<T>,                    \
                "wire layout: " #T " has padding or non-unique representation")

// Freeze a raw struct's size / a field's offset. The numbers are the wire
// contract: changing one breaks decode on every peer that still runs the
// old build. New fields go AFTER the last frozen offset (append-only).
#define BTPU_WIRE_FROZEN_SIZEOF(T, n)                                           \
  static_assert(sizeof(T) == (n),                                               \
                "wire layout: sizeof(" #T ") changed — append-only rule broken")
#define BTPU_WIRE_FROZEN_OFFSET(T, member, n)                                   \
  static_assert(offsetof(T, member) == (n),                                     \
                "wire layout: offsetof(" #T ", " #member                        \
                ") moved — fields may only be appended")

namespace btpu::wire_layout {

// ---- scalar/enum widths every encoder relies on ---------------------------
// Result<T>'s error arm, every *Response's error_code.
static_assert(sizeof(ErrorCode) == 4, "wire: ErrorCode is u32 on the wire");
static_assert(std::is_same_v<std::underlying_type_t<ErrorCode>, uint32_t>);
// Pool/placement records (durable in the coordinator).
static_assert(sizeof(StorageClass) == 4, "wire: StorageClass is u32");
static_assert(sizeof(TransportKind) == 4, "wire: TransportKind is u32");
// RPC + coordinator opcodes ride one frame byte.
static_assert(sizeof(rpc::Method) == 1, "wire: rpc opcode is u8");
static_assert(sizeof(coord::Op) == 1, "wire: coordinator opcode is u8");
// Frame header: u8 opcode + u32 length (net::send_frame/recv_frame).
static_assert(sizeof(uint32_t) == 4 && sizeof(uint8_t) == 1);
// Scalars embedded in encoded structs.
static_assert(sizeof(ViewVersionId) == 8 && sizeof(LeaseId) == 8 && sizeof(Version) == 8);
static_assert(sizeof(double) == 8, "wire: ClusterStats.avg_utilization is f64");
// TopoCoord members are encoded as i32 each.
static_assert(sizeof(decltype(TopoCoord{}.slice_id)) == 4);

// Raw-encoded scalar/enum types must be padding-free by construction; the
// composite structs are NOT raw (they encode field-by-field), so nothing
// here asserts sizeof(CopyPlacement) — that would freeze an ABI no peer
// ever sees. The encoded form is frozen by the golden table instead.
BTPU_WIRE_RAW_TYPE(ErrorCode);
BTPU_WIRE_RAW_TYPE(StorageClass);
BTPU_WIRE_RAW_TYPE(TransportKind);
BTPU_WIRE_RAW_TYPE(coord::Op);
BTPU_WIRE_RAW_TYPE(rpc::Method);

// Coordinator WAL v2 on-disk framing (wal_format.h): raw memcpy'd headers
// that outlive binaries — frozen like the packed TCP headers. The record
// byte stream itself is pinned by the golden table (wal/* rows).
BTPU_WIRE_RAW_TYPE(coord::wal::FileHeader);
BTPU_WIRE_FROZEN_SIZEOF(coord::wal::FileHeader, 8);
BTPU_WIRE_FROZEN_OFFSET(coord::wal::FileHeader, magic, 0);
BTPU_WIRE_FROZEN_OFFSET(coord::wal::FileHeader, version, 4);
BTPU_WIRE_RAW_TYPE(coord::wal::RecordHeader);
BTPU_WIRE_FROZEN_SIZEOF(coord::wal::RecordHeader, 8);
BTPU_WIRE_FROZEN_OFFSET(coord::wal::RecordHeader, len, 0);
BTPU_WIRE_FROZEN_OFFSET(coord::wal::RecordHeader, chain_crc, 4);

}  // namespace btpu::wire_layout
