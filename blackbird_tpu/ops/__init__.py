from blackbird_tpu.ops.checksum import checksum_u32  # noqa: F401
