"""Runtime complement of the static FFI-boundary check (capi_check.py).

The static gate proves the headers, the golden manifest, and the Python
manifest agree as TEXT; these tests prove the LIVE library agrees too:
every ErrorCode mirror value round-trips through btpu_error_name, every
required symbol actually bound, and the checker itself still convicts
planted drift (docs/CORRECTNESS.md §11).
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

from blackbird_tpu import native
from blackbird_tpu._capi import OPTIONAL, SIGNATURES, ErrorCode

REPO = Path(__file__).resolve().parent.parent
CAPI_CHECK = REPO / "scripts" / "capi_check.py"


def test_error_names_round_trip() -> None:
    """Every Python ErrorCode mirror value must resolve to ITS OWN name in
    the native to_string table: a renumbered or renamed mirror entry makes
    Python report the wrong error for the rest of time, silently."""
    for code in ErrorCode:
        assert native.error_name(int(code)) == code.name, (
            f"ErrorCode.{code.name} = {int(code)} names "
            f"{native.error_name(int(code))!r} natively — mirror drift"
        )


def test_unknown_code_does_not_crash() -> None:
    assert native.error_name(987654) == "UNKNOWN_ERROR"


def test_every_required_symbol_bound() -> None:
    """_load() must have bound every non-OPTIONAL manifest symbol with its
    manifest types — a silent fallback-to-zero path must not exist."""
    for name in SIGNATURES:
        if name in OPTIONAL:
            # OPTIONAL symbols answer have() honestly either way.
            assert isinstance(native.have(name), bool)
            continue
        assert native.have(name), f"required symbol {name} not bound"
        fn = getattr(native.lib, name)
        assert fn.argtypes is not None, f"{name} bound without argtypes"


def test_have_rejects_unknown_symbols() -> None:
    """have() is a manifest query, not a symbol probe: asking about a name
    outside the manifest is a programming error."""
    import pytest

    with pytest.raises(KeyError):
        native.have("btpu_totally_made_up")


def test_capi_check_clean_on_tree() -> None:
    """The static checker agrees with the tree as committed."""
    proc = subprocess.run(
        [sys.executable, str(CAPI_CHECK)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"capi_check found drift:\n{proc.stdout}\n{proc.stderr}"
    )


def test_capi_check_convicts_planted_drift() -> None:
    """The checker can CONVICT, not just agree: the planted-drift self-test
    mutates one signature width and one enum value in a temp header copy
    and must flag both."""
    proc = subprocess.run(
        [sys.executable, str(CAPI_CHECK), "--self-test"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"self-test failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "both planted drifts convicted" in proc.stdout


def test_lane_counters_all_ints() -> None:
    """lane_counters() reads every counter through a typed required binding
    (no hasattr-silent-zero path); sanity-check the shapes."""
    from blackbird_tpu.client import Client

    counters = Client.lane_counters()
    assert counters, "no counters?"
    for key, value in counters.items():
        assert isinstance(value, int) and value >= 0, (key, value)
    # Spot-check that the robustness family is present (these were the
    # symbols the old code read WITHOUT argtypes/restype — a u64 truncation
    # hazard).
    for key in ("deadline_exceeded", "retries", "hedges_fired",
                "breaker_trips", "persist_retry_backlog"):
        assert key in counters, key
