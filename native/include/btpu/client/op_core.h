// Completion-based client op core (ISSUE 16 / ROADMAP item 3): each client
// op is a state machine advanced by completions, not a parked thread. A
// small pool of persistent lanes drains a completion queue of ops; a
// multi-stage op re-enqueues itself between stages (Step::kYield), so one
// submitter thread keeps thousands of ops in flight while the lanes
// interleave them. The sync SDK surface is untouched — sync ops still run
// inline on the caller's thread through the same decomposed stage
// functions — and hedged reads ride the core as second in-flight
// submissions instead of hedged_race's former spawn-per-race thread.
//
// Ownership / lock model (docs/CORRECTNESS.md "client op core"):
//   * A state machine is advanced by EXACTLY ONE thread at a time: the lane
//     that dequeued it (or, under the schedule explorer, the per-op adopted
//     thread). Re-enqueue happens after the stage returns, so no two lanes
//     ever run the same op concurrently.
//   * Op completion publishes under Op::m (done flag + status), and waiters
//     block on Op::cv — the btpu::Mutex/CondVarAny pair, so the schedule
//     explorer preempts at every queue/complete edge.
//   * The queue itself is guarded by OpCore::m_; the queue_depth/inflight
//     gauges are relaxed atomics (stat folds, not synchronization).
//   * Shutdown drains: remaining queued ops RUN to completion (they may
//     reference client state that outlives the core in the destructor
//     order), then lanes join. Nothing is dropped on the floor.
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <thread>
#include <vector>

#include "btpu/common/deadline.h"
#include "btpu/common/error.h"
#include "btpu/common/thread_annotations.h"

namespace btpu::client {

// Process-global client-core scoreboard (capi btpu_client_inflight_ops and
// friends; the /metrics gauges and Client.lane_counters() read the same
// struct). inflight/queue_depth are gauges; the rest are monotonic.
struct ClientCoreCounters {
  std::atomic<uint64_t> submitted{0};
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> cancelled{0};
  // Ops submitted and not yet completed (queued ops count: a completion
  // core's in-flight set is everything the submitter no longer holds).
  std::atomic<uint64_t> inflight{0};
  std::atomic<uint64_t> peak_inflight{0};
  // Ops parked in completion queues right now (across every live core).
  std::atomic<uint64_t> queue_depth{0};
  // Optimistic-read lane (client_cache.cpp): reads served straight from
  // cached placements with zero keystone turns / revalidation round trips
  // taken after a cached attempt failed (STALE_EXTENT, CRC, lease expiry).
  std::atomic<uint64_t> optimistic_hits{0};
  std::atomic<uint64_t> optimistic_revalidates{0};
};
ClientCoreCounters& client_core_counters() noexcept;

class OpCore {
 public:
  // What a stage returns: kDone completes the op (waiters wake); kYield
  // re-enqueues it at the queue tail — the stage function is called again
  // when a lane next dequeues it (the closure owns its stage cursor).
  enum class Step : uint8_t { kDone, kYield };

  struct Op {
    std::function<Step()> step;
    Deadline deadline;  // checked before every stage; expiry completes the op
    std::atomic<bool> cancel{false};
    mutable Mutex m;
    CondVarAny cv;
    bool done BTPU_GUARDED_BY(m){false};
    ErrorCode status BTPU_GUARDED_BY(m){ErrorCode::OK};
  };

  // Completion handle (the "future" half): shared with the core, so a
  // dropped handle never dangles an in-flight op.
  class Handle {
   public:
    Handle() = default;
    bool valid() const noexcept { return op_ != nullptr; }
    bool done() const;
    // Blocks until completion; false on deadline expiry (op keeps running).
    bool wait(const Deadline& deadline = Deadline::infinite()) const;
    // Best-effort: stages not yet started are skipped and the op completes
    // CANCELLED; a stage already running finishes first.
    void cancel() const;
    // The op's completion status (OK / CANCELLED / DEADLINE_EXCEEDED).
    // Meaningful only after done().
    ErrorCode status() const;

   private:
    friend class OpCore;
    explicit Handle(std::shared_ptr<Op> op) : op_(std::move(op)) {}
    std::shared_ptr<Op> op_;
  };

  // lanes == 0 resolves $BTPU_CLIENT_LANES, default min(4, max(1, hw)).
  explicit OpCore(uint32_t lanes = 0);
  ~OpCore();  // drains the queue (ops run to completion), then joins lanes

  // Submits a state machine. Under the schedule explorer (sched::armed())
  // the op runs on a dedicated adopted thread instead of a lane — the
  // explorer owns every interleaving decision, exactly like the former
  // spawn-per-race shape the Sched fixtures pin.
  Handle submit(std::function<Step()> step, Deadline deadline = Deadline::infinite());

  // Fire-and-forget single-stage op for latency rescues (hedge primaries):
  // taken ONLY when a lane is idle and the queue is shallow — a hedge
  // parked behind a deep queue would rescue nothing — and never under the
  // schedule explorer. Returns false when the caller should fall back to
  // its own spawn.
  bool try_run_detached(std::function<void()> fn);

  uint32_t lanes() const noexcept { return lanes_; }
  // Ops queued in THIS core right now (the process gauge sums all cores).
  uint64_t queue_depth() const;

 private:
  void lane_main();
  void start_lanes_locked() BTPU_REQUIRES(m_);
  void advance(const std::shared_ptr<Op>& op);
  static void finish(const std::shared_ptr<Op>& op, ErrorCode status);

  const uint32_t lanes_;
  mutable Mutex m_;
  CondVarAny cv_;
  std::deque<std::shared_ptr<Op>> queue_ BTPU_GUARDED_BY(m_);
  bool stopping_ BTPU_GUARDED_BY(m_){false};
  bool started_ BTPU_GUARDED_BY(m_){false};
  uint32_t idle_lanes_ BTPU_GUARDED_BY(m_){0};
  std::vector<std::thread> threads_ BTPU_GUARDED_BY(m_);
  // Sched-armed per-op threads in flight (joined at shutdown via drain).
  std::atomic<uint32_t> spawned_{0};
  Mutex spawn_mutex_;
  CondVarAny spawn_cv_;
};

}  // namespace btpu::client
