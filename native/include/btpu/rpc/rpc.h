// Keystone RPC protocol: opcodes map 1:1 to KeystoneService methods.
//
// Versioning stance: the wire protocol IS cross-version stable within the
// v2 opcode epoch. Every composite struct is size-prefixed and every
// message decodes tail-tolerantly (wire.h), so the append-only evolution
// rule — new fields only at the end, types never change — lets older and
// newer peers interoperate in both directions during a rolling upgrade;
// test_rpc.cpp's compatibility tests frame newer- and older-peer messages
// by hand and prove it. kPing carries each side's kProtocolVersion so
// operators can audit a mixed fleet. The v1 epoch (opcodes 1-17, unprefixed
// structs) predates this guarantee; v2 opcodes live at +64 so a cross-epoch
// call fails loudly with an unknown-opcode error instead of a mis-decode.
//
// Parity target: reference include/blackbird/rpc/rpc_service.h:28-274 — 14
// rpc_* handlers over YLT coro_rpc (rpc_service.cpp:360-385; struct_pack had
// no version tolerance — this is our own bar, not the reference's). Framing
// is the shared net.h frame: [u32 len][u8 opcode][wire-encoded struct];
// responses reuse the request opcode.
#pragma once

#include <cstdint>

namespace btpu::rpc {

// Wire-protocol version advertised in the kPing handshake. Bump when the
// append-only rule is insufficient to describe a change (should be rare).
inline constexpr uint32_t kProtocolVersion = 3;

// First version whose put_complete APPLIES the appended content_crc field.
// A newer client talking to an older keystone must keep stamping the
// whole-object CRC at put_start (the old path) — deferring it would decode
// cleanly but silently leave every object unstamped, disabling the
// verified-read gate for bytes written during a rolling upgrade.
inline constexpr uint32_t kProtoContentCrcAtComplete = 3;

enum class Method : uint8_t {
  kObjectExists = 65,
  kGetWorkers = 66,
  kPutStart = 67,
  kPutComplete = 68,
  kPutCancel = 69,
  kRemoveObject = 70,
  kRemoveAllObjects = 71,
  kGetClusterStats = 72,
  kGetViewVersion = 73,
  kBatchObjectExists = 74,
  kBatchGetWorkers = 75,
  kBatchPutStart = 76,
  kBatchPutComplete = 77,
  kBatchPutCancel = 78,
  kPing = 79,
  kDrainWorker = 80,
  kListObjects = 81,
  kPutStartPooled = 82,
  kPutCommitSlot = 83,
  kPutInline = 84,
};

}  // namespace btpu::rpc
