// HBM_TPU tier: device memory behind the provider C ABI (hbm_provider.h).
// Replaces the reference's broken RAM_GPU tier (worker_service.cpp:196) with
// the BASELINE.json north-star arrangement: a TPU-HBM allocator exposing the
// same region/offset contract as every other tier.
#include <atomic>
#include <cstdlib>
#include <vector>
#include <cstring>
#include <mutex>
#include <unordered_map>

#include "backend_base.h"
#include "btpu/common/log.h"
#include "btpu/common/pool_span.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::storage {

// ---- built-in emulated provider (host memory) -----------------------------

namespace {

struct EmulatedState {
  Mutex mutex;
  std::unordered_map<uint64_t, std::pair<uint8_t*, uint64_t>> regions BTPU_GUARDED_BY(mutex);
  uint64_t next_id BTPU_GUARDED_BY(mutex){1};

  static EmulatedState& instance() {
    static EmulatedState s;
    return s;
  }
};

int emu_alloc(void*, const char*, uint64_t size, uint64_t* out_id) {
  auto* mem = static_cast<uint8_t*>(std::malloc(size));
  if (!mem) return 1;
  auto& st = EmulatedState::instance();
  MutexLock lock(st.mutex);
  *out_id = st.next_id++;
  st.regions[*out_id] = {mem, size};
  return 0;
}

int emu_free(void*, uint64_t region_id) {
  auto& st = EmulatedState::instance();
  MutexLock lock(st.mutex);
  auto it = st.regions.find(region_id);
  if (it == st.regions.end()) return 1;
  std::free(it->second.first);
  st.regions.erase(it);
  return 0;
}

int emu_write(void*, uint64_t region_id, uint64_t offset, const void* src, uint64_t len) {
  auto& st = EmulatedState::instance();
  MutexLock lock(st.mutex);
  auto it = st.regions.find(region_id);
  if (it == st.regions.end() || len > it->second.second || offset > it->second.second - len)
    return 1;
  std::memcpy(it->second.first + offset, src, len);
  return 0;
}

int emu_read(void*, uint64_t region_id, uint64_t offset, void* dst, uint64_t len) {
  auto& st = EmulatedState::instance();
  MutexLock lock(st.mutex);
  auto it = st.regions.find(region_id);
  if (it == st.regions.end() || len > it->second.second || offset > it->second.second - len)
    return 1;
  std::memcpy(dst, it->second.first + offset, len);
  return 0;
}

uint64_t emu_available(void*, const char*) { return 0; }

int emu_write_batch(void* ctx, const BtpuHbmIoVec* vecs, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) {
    if (emu_write(ctx, vecs[i].region_id, vecs[i].offset, vecs[i].buf, vecs[i].len) != 0)
      return 1;
  }
  return 0;
}

int emu_read_batch(void* ctx, const BtpuHbmIoVec* vecs, uint64_t n) {
  for (uint64_t i = 0; i < n; ++i) {
    if (emu_read(ctx, vecs[i].region_id, vecs[i].offset, vecs[i].buf, vecs[i].len) != 0)
      return 1;
  }
  return 0;
}

int emu_flush(void*) { return 0; }  // memcpy writes are synchronous

int emu_copy(void*, uint64_t src_region, uint64_t src_off, uint64_t dst_region,
             uint64_t dst_off, uint64_t len) {
  auto& st = EmulatedState::instance();
  MutexLock lock(st.mutex);
  auto src = st.regions.find(src_region);
  auto dst = st.regions.find(dst_region);
  if (src == st.regions.end() || dst == st.regions.end()) return 1;
  if (len > src->second.second || src_off > src->second.second - len) return 1;
  if (len > dst->second.second || dst_off > dst->second.second - len) return 1;
  std::memmove(dst->second.first + dst_off, src->second.first + src_off, len);
  return 0;
}

const BtpuHbmProviderV3 kEmulatedProvider = {
    nullptr,  emu_alloc,       emu_free,       emu_write, emu_read,
    emu_available, emu_write_batch, emu_read_batch, emu_flush, emu_copy,
};

Mutex g_provider_mutex;
BtpuHbmProviderV3 g_provider BTPU_GUARDED_BY(g_provider_mutex) = kEmulatedProvider;
bool g_provider_emulated BTPU_GUARDED_BY(g_provider_mutex) = true;
// v4 fabric entries; all-null for v3 registrations and the emulation.
struct FabricEntries {
  int (*address)(void*, char*, uint64_t){nullptr};
  int (*offer)(void*, uint64_t, uint64_t, uint64_t, uint64_t){nullptr};
  int (*pull)(void*, const char*, uint64_t, uint64_t, uint64_t, uint64_t){nullptr};
};
FabricEntries g_fabric;
// v5 host-view entry; null for older registrations and the emulation.
void* (*g_host_view_base)(void*, uint64_t) = nullptr;
// Bumped on every (un)registration: backends cache the host-view pointer
// and revalidate it with one relaxed load per op, so a provider swap can
// never leave them copying through a pointer into freed Python memory.
std::atomic<uint64_t> g_provider_gen{1};

}  // namespace

const BtpuHbmProviderV3& hbm_provider() {
  MutexLock lock(g_provider_mutex);
  return g_provider;
}

bool hbm_provider_is_emulated() {
  MutexLock lock(g_provider_mutex);
  return g_provider_emulated;
}

ErrorCode hbm_batch_io(const BtpuHbmIoVec* vecs, uint64_t n, bool is_write) {
  if (n == 0) return ErrorCode::OK;
  const auto& provider = hbm_provider();
  auto* batch_fn = is_write ? provider.write_batch : provider.read_batch;
  if (batch_fn != nullptr) {
    return batch_fn(provider.ctx, vecs, n) == 0 ? ErrorCode::OK
                                                : ErrorCode::MEMORY_ACCESS_ERROR;
  }
  for (uint64_t i = 0; i < n; ++i) {
    const int rc = is_write
                       ? provider.write(provider.ctx, vecs[i].region_id, vecs[i].offset,
                                        vecs[i].buf, vecs[i].len)
                       : provider.read(provider.ctx, vecs[i].region_id, vecs[i].offset,
                                       vecs[i].buf, vecs[i].len);
    if (rc != 0) return ErrorCode::MEMORY_ACCESS_ERROR;
  }
  return ErrorCode::OK;
}

ErrorCode hbm_flush() {
  const auto& provider = hbm_provider();
  if (provider.flush == nullptr) return ErrorCode::OK;
  return provider.flush(provider.ctx) == 0 ? ErrorCode::OK : ErrorCode::MEMORY_ACCESS_ERROR;
}

ErrorCode hbm_copy(uint64_t src_region, uint64_t src_offset, uint64_t dst_region,
                   uint64_t dst_offset, uint64_t len) {
  if (len == 0) return ErrorCode::OK;
  const auto& provider = hbm_provider();
  if (provider.copy != nullptr &&
      provider.copy(provider.ctx, src_region, src_offset, dst_region, dst_offset, len) == 0)
    return ErrorCode::OK;
  // Fallback: bounded staging through host memory (the provider either has
  // no device-to-device path or could not express this copy).
  constexpr uint64_t kChunk = 16ull << 20;
  std::vector<uint8_t> buf(static_cast<size_t>(std::min(len, kChunk)));
  for (uint64_t off = 0; off < len; off += kChunk) {
    const uint64_t n = std::min(kChunk, len - off);
    if (provider.read(provider.ctx, src_region, src_offset + off, buf.data(), n) != 0)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    if (provider.write(provider.ctx, dst_region, dst_offset + off, buf.data(), n) != 0)
      return ErrorCode::MEMORY_ACCESS_ERROR;
  }
  return hbm_flush();
}

// ---- HbmBackend -----------------------------------------------------------

class HbmBackend : public OffsetBackendBase {
 public:
  explicit HbmBackend(BackendConfig config) : OffsetBackendBase(std::move(config)) {}
  ~HbmBackend() override { shutdown(); }

  ErrorCode initialize() override {
    const auto& provider = hbm_provider();
    if (provider.alloc_region(provider.ctx, config_.device_id.c_str(), config_.capacity,
                              &region_id_) != 0) {
      LOG_ERROR << "hbm provider failed to allocate " << config_.capacity << " bytes on "
                << config_.device_id;
      return ErrorCode::OUT_OF_MEMORY;
    }
    active_ = true;
    view_gen_.store(hbm_provider_generation());
    host_view_.store(static_cast<uint8_t*>(hbm_host_view_base(region_id_)));
    LOG_INFO << "hbm region " << region_id_ << " on " << config_.device_id << " ("
             << config_.capacity << " bytes, "
             << (hbm_provider_is_emulated()
                     ? "emulated"
                     : (host_view_.load() ? "device, host-view" : "device"))
             << ")";
    return init_allocator();
  }

  void shutdown() override {
    if (active_) {
      const auto& provider = hbm_provider();
      provider.free_region(provider.ctx, region_id_);
      active_ = false;
    }
  }

  void* base_address() const override { return nullptr; }  // no host mapping
  uint64_t device_region_id() const override { return region_id_; }
  const std::string& device_id() const override { return config_.device_id; }

  // Host-view fast path (provider v5): CPU-addressable device memory moves
  // by native memcpy — no provider dispatch in the data path, so the
  // per-op ctypes/Python tax on the cross-process staged device lane
  // vanishes. On real TPUs the view is null and every byte goes through
  // the provider as before. The cached pointer revalidates against the
  // registration generation with one relaxed load: a provider swap mid-
  // flight must never leave us copying through freed Python memory.
  uint8_t* host_view() const {
    const uint64_t gen = hbm_provider_generation();
    // ordering: acquire/release generation check — pairs with the registrars' acq_rel bump so a stale cached view pointer is revalidated before any byte is copied through it (a swapped provider must never leave us in freed Python memory).
    if (gen != view_gen_.load(std::memory_order_acquire)) {
      host_view_.store(static_cast<uint8_t*>(hbm_host_view_base(region_id_)),
                       std::memory_order_release);
      view_gen_.store(gen, std::memory_order_release);
    }
    return host_view_.load(std::memory_order_acquire);
  }

  // PVM-lane advertisement (backend.h): the view is the region buffer
  // itself (never donated in host-view mode), stable until the region is
  // freed — a provider SWAP invalidates it, which the worker host never
  // does mid-life; clients behind a swap are caught by the verified-read
  // CRC gate like any stale one-sided read.
  void* host_view_base() const override { return active_ ? host_view() : nullptr; }

  ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) override {
    if (!active_) return ErrorCode::INVALID_STATE;
    if (len > config_.capacity || offset > config_.capacity - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    if (uint8_t* view = host_view()) {
      auto span = poolspan::resolve(view, config_.capacity, offset, len, 0,
                                    poolspan::Access::kWrite, config_.pool_id.c_str());
      if (!span.ok()) return span.error();
      std::memcpy(span.value().data(), src, len);
      return ErrorCode::OK;
    }
    const auto& provider = hbm_provider();
    return provider.write(provider.ctx, region_id_, offset, src, len) == 0
               ? ErrorCode::OK
               : ErrorCode::MEMORY_ACCESS_ERROR;
  }

  ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) override {
    if (!active_) return ErrorCode::INVALID_STATE;
    if (len > config_.capacity || offset > config_.capacity - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    if (uint8_t* view = host_view()) {
      auto span = poolspan::resolve(view, config_.capacity, offset, len, 0,
                                    poolspan::Access::kRead, config_.pool_id.c_str());
      if (!span.ok()) return span.error();
      std::memcpy(dst, span.value().data(), len);
      return ErrorCode::OK;
    }
    const auto& provider = hbm_provider();
    return provider.read(provider.ctx, region_id_, offset, dst, len) == 0
               ? ErrorCode::OK
               : ErrorCode::MEMORY_ACCESS_ERROR;
  }

  std::string fabric_address() const override { return hbm_fabric_address(); }
  ErrorCode fabric_offer(uint64_t offset, uint64_t len, uint64_t transfer_id) override {
    if (!active_) return ErrorCode::INVALID_STATE;
    return hbm_fabric_offer(region_id_, offset, len, transfer_id);
  }
  ErrorCode fabric_pull(const std::string& remote_addr, uint64_t transfer_id,
                        uint64_t offset, uint64_t len) override {
    if (!active_) return ErrorCode::INVALID_STATE;
    return hbm_fabric_pull(remote_addr, transfer_id, region_id_, offset, len);
  }

 private:
  uint64_t region_id_{0};
  bool active_{false};
  // Cached CPU-addressable view of the region (provider v5), or null;
  // revalidated against the registration generation (see host_view()).
  mutable std::atomic<uint8_t*> host_view_{nullptr};
  mutable std::atomic<uint64_t> view_gen_{0};
};

std::unique_ptr<StorageBackend> make_hbm_backend(const BackendConfig& config) {
  return std::make_unique<HbmBackend>(config);
}

// ordering: acquire — pairs with the registrar bumps; callers revalidate cached pointers against it.
uint64_t hbm_provider_generation() { return g_provider_gen.load(std::memory_order_acquire); }

void* hbm_host_view_base(uint64_t region_id) {
  void* (*fn)(void*, uint64_t);
  void* ctx;
  {
    MutexLock lock(g_provider_mutex);
    fn = g_host_view_base;
    ctx = g_provider.ctx;
  }
  return fn ? fn(ctx, region_id) : nullptr;
}

std::string hbm_fabric_address() {
  FabricEntries fabric;
  void* ctx;
  {
    MutexLock lock(g_provider_mutex);
    fabric = g_fabric;
    ctx = g_provider.ctx;
  }
  if (!fabric.address) return {};
  char buf[256] = {};
  if (fabric.address(ctx, buf, sizeof(buf)) != 0) return {};
  buf[sizeof(buf) - 1] = '\0';
  return buf;
}

ErrorCode hbm_fabric_offer(uint64_t region_id, uint64_t offset, uint64_t len,
                           uint64_t transfer_id) {
  FabricEntries fabric;
  void* ctx;
  {
    MutexLock lock(g_provider_mutex);
    fabric = g_fabric;
    ctx = g_provider.ctx;
  }
  if (!fabric.offer) return ErrorCode::NOT_IMPLEMENTED;
  return fabric.offer(ctx, region_id, offset, len, transfer_id) == 0
             ? ErrorCode::OK
             : ErrorCode::MEMORY_ACCESS_ERROR;
}

ErrorCode hbm_fabric_pull(const std::string& remote_addr, uint64_t transfer_id,
                          uint64_t region_id, uint64_t offset, uint64_t len) {
  FabricEntries fabric;
  void* ctx;
  {
    MutexLock lock(g_provider_mutex);
    fabric = g_fabric;
    ctx = g_provider.ctx;
  }
  if (!fabric.pull) return ErrorCode::NOT_IMPLEMENTED;
  return fabric.pull(ctx, remote_addr.c_str(), transfer_id, region_id, offset, len) == 0
             ? ErrorCode::OK
             : ErrorCode::MEMORY_ACCESS_ERROR;
}

}  // namespace btpu::storage

extern "C" void btpu_register_hbm_provider_v3(const BtpuHbmProviderV3* provider) {
  btpu::MutexLock lock(btpu::storage::g_provider_mutex);
  // ordering: acq_rel — the bump publishes the swap (old viewers revalidate) and orders it after the provider fields written under g_provider_mutex.
  btpu::storage::g_provider_gen.fetch_add(1, std::memory_order_acq_rel);
  btpu::storage::g_fabric = {};  // v3 has no fabric entries
  btpu::storage::g_host_view_base = nullptr;
  if (provider) {
    btpu::storage::g_provider = *provider;
    btpu::storage::g_provider_emulated = false;
  } else {
    btpu::storage::g_provider = btpu::storage::kEmulatedProvider;
    btpu::storage::g_provider_emulated = true;
  }
}

extern "C" void btpu_register_hbm_provider_v4(const BtpuHbmProviderV4* provider) {
  btpu::MutexLock lock(btpu::storage::g_provider_mutex);
  // ordering: acq_rel — see the v3 registrar.
  btpu::storage::g_provider_gen.fetch_add(1, std::memory_order_acq_rel);
  btpu::storage::g_host_view_base = nullptr;
  if (provider) {
    btpu::storage::g_provider = provider->base;
    btpu::storage::g_fabric = {provider->fabric_address, provider->fabric_offer,
                               provider->fabric_pull};
    btpu::storage::g_provider_emulated = false;
  } else {
    btpu::storage::g_provider = btpu::storage::kEmulatedProvider;
    btpu::storage::g_fabric = {};
    btpu::storage::g_provider_emulated = true;
  }
}

extern "C" void btpu_register_hbm_provider_v5(const BtpuHbmProviderV5* provider) {
  btpu_register_hbm_provider_v4(provider ? &provider->base : nullptr);
  btpu::MutexLock lock(btpu::storage::g_provider_mutex);
  // ordering: acq_rel — see the v3 registrar.
  btpu::storage::g_provider_gen.fetch_add(1, std::memory_order_acq_rel);
  btpu::storage::g_host_view_base = provider ? provider->host_view_base : nullptr;
}
