// End-to-end tests: client -> keystone -> allocator -> transport -> worker
// backends, in every wiring (embedded/local, shm, full TCP with RPC), plus
// failure/failover flows. This is the hermetic put->write->complete->
// get->verify slice SURVEY §7 defines as the minimum e2e artifact.
#include <unistd.h>

#include <cstring>
#include <map>
#include <random>
#include <set>
#include <filesystem>
#include <fstream>
#include <thread>

#include "btest.h"
#include "btpu/client/embedded.h"
#include "btpu/common/crc32c.h"
#include "btpu/rpc/rpc_server.h"

using namespace btpu;
using namespace btpu::client;

namespace {

std::vector<uint8_t> pattern(uint64_t size, uint8_t seed = 1) {
  std::vector<uint8_t> data(size);
  for (uint64_t i = 0; i < size; ++i) data[i] = static_cast<uint8_t>(i * 131 + seed);
  return data;
}

bool eventually(const std::function<bool()>& pred, int timeout_ms = 3000) {
  auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    if (pred()) return true;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  return pred();
}

}  // namespace

BTEST(EndToEnd, PutGetStripedAcrossWorkers) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 4;
  auto data = pattern(1 << 20);
  BT_ASSERT(client->put("e2e/striped", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("e2e/striped");
  BT_ASSERT_OK(placements);
  BT_EXPECT_EQ(placements.value()[0].shards.size(), 4u);  // striped wide

  auto back = client->get("e2e/striped");
  BT_ASSERT_OK(back);
  BT_ASSERT(back.value().size() == data.size());
  BT_EXPECT(std::memcmp(back.value().data(), data.data(), data.size()) == 0);

  // Non-page-aligned odd size too.
  auto odd = pattern(123457, 9);
  BT_ASSERT(client->put("e2e/odd", odd.data(), odd.size(), cfg) == ErrorCode::OK);
  auto odd_back = client->get("e2e/odd");
  BT_ASSERT_OK(odd_back);
  BT_EXPECT(odd_back.value() == odd);

  BT_EXPECT(client->remove("e2e/striped") == ErrorCode::OK);
  BT_EXPECT(client->get("e2e/striped").error() == ErrorCode::OBJECT_NOT_FOUND);
}

BTEST(EndToEnd, ReplicatedPutWritesAllCopies) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 2;
  auto data = pattern(256 * 1024, 3);
  BT_ASSERT(client->put("e2e/replicated", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("e2e/replicated");
  BT_ASSERT_OK(placements);
  BT_ASSERT(placements.value().size() == 2);

  // Verify every copy independently through the data plane.
  auto data_client = transport::make_transport_client();
  for (const auto& copy : placements.value()) {
    std::vector<uint8_t> buf(data.size());
    uint64_t off = 0;
    for (const auto& shard : copy.shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(data_client->read(shard.remote, mem.remote_addr, mem.rkey, buf.data() + off,
                                  shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT(buf == data);
  }
}

BTEST(EndToEnd, WorkerDeathRepairThenGet) {
  auto options = EmbeddedClusterOptions::simple(3, 4 << 20);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(128 * 1024, 7);
  BT_ASSERT(client->put("e2e/survivor", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto before = client->get_workers("e2e/survivor");
  BT_ASSERT_OK(before);
  const NodeId victim = before.value()[0].shards[0].worker_id;
  size_t victim_idx = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    // worker ids are worker-<i>
    if ("worker-" + std::to_string(i) == victim) victim_idx = i;
  }
  cluster.kill_worker(victim_idx);

  // Repair re-replicates onto the remaining workers.
  BT_EXPECT(eventually(
      [&] { return cluster.keystone().counters().objects_repaired.load() == 1; }));
  auto after = client->get_workers("e2e/survivor");
  BT_ASSERT_OK(after);
  BT_EXPECT_EQ(after.value().size(), 2u);
  for (const auto& copy : after.value()) {
    for (const auto& shard : copy.shards) BT_EXPECT_NE(shard.worker_id, victim);
  }
  auto back = client->get("e2e/survivor");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, GetFailsOverToSurvivingReplicaWithoutRepair) {
  auto options = EmbeddedClusterOptions::simple(2, 4 << 20);
  options.keystone.enable_repair = false;
  options.use_coordinator = false;  // direct feed: death only via remove_worker
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(64 * 1024, 5);
  BT_ASSERT(client->put("e2e/failover", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("e2e/failover");
  BT_ASSERT_OK(placements);
  // Stop the worker behind copy 0's transport (regions unregister), leaving
  // placements stale — get() must fail over to copy 1.
  const NodeId victim = placements.value()[0].shards[0].worker_id;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) {
      // Stop only the transport by killing the worker but keeping keystone
      // metadata (repair disabled; remove_worker not called).
      cluster.kill_worker(i);
    }
  }
  // NOTE: kill_worker with no coordinator calls remove_worker, which prunes
  // dead placements even with repair off — so copies shrink to the survivor.
  auto back = client->get("e2e/failover");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, MultiSlicePlacementPrefersIciAndSpillsToDcn) {
  // Acceptance-ladder item 5's placement story on the CPU harness: two
  // "slices" of TCP workers (TCP = the DCN path). Slice-affine puts stay on
  // the preferred slice while it has room, spill across only when it is
  // full, and repair after a preemption re-replicates across slices.
  EmbeddedClusterOptions options;
  options.keystone.gc_interval_sec = 60;
  options.keystone.health_check_interval_sec = 3600;
  for (int i = 0; i < 4; ++i) {
    worker::WorkerServiceConfig w;
    w.worker_id = "slice" + std::to_string(i / 2) + "-w" + std::to_string(i % 2);
    w.transport = TransportKind::TCP;
    w.listen_host = "127.0.0.1";
    w.topo = {/*slice_id=*/i / 2, /*host_id=*/i % 2, -1};
    w.heartbeat_interval_ms = 100;
    w.heartbeat_ttl_ms = 60000;
    w.pools = {{"pool-" + w.worker_id, StorageClass::RAM_CPU, 1 << 20, "", ""}};
    options.workers.push_back(w);
  }
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  cfg.min_shard_size = 4096;
  cfg.preferred_slice = 0;

  // Fits in slice 0: every shard must ride ICI (stay on slice 0).
  auto small = pattern(512 * 1024, 31);
  BT_ASSERT(client->put("dcn/ici", small.data(), small.size(), cfg) == ErrorCode::OK);
  auto placed = client->get_workers("dcn/ici");
  BT_ASSERT_OK(placed);
  for (const auto& shard : placed.value()[0].shards) {
    BT_EXPECT_EQ(shard.worker_id.substr(0, 6), "slice0");
  }

  // Too big for what's left of slice 0 (2 MiB total): spills across DCN.
  auto big = pattern((2 << 20) + (512 << 10), 32);
  cfg.max_workers_per_copy = 4;
  BT_ASSERT(client->put("dcn/spill", big.data(), big.size(), cfg) == ErrorCode::OK);
  auto spilled = client->get_workers("dcn/spill");
  BT_ASSERT_OK(spilled);
  bool crossed = false;
  for (const auto& shard : spilled.value()[0].shards) {
    if (shard.worker_id.substr(0, 6) == "slice1") crossed = true;
  }
  BT_EXPECT(crossed);
  auto big_back = client->get("dcn/spill");
  BT_ASSERT_OK(big_back);
  BT_EXPECT(big_back.value() == big);

  // Preemption on slice 0: replicated object must be repaired onto workers
  // that are still alive, and remain readable.
  BT_EXPECT(client->remove("dcn/spill") == ErrorCode::OK);
  WorkerConfig rep = cfg;
  rep.replication_factor = 2;
  rep.max_workers_per_copy = 1;
  auto prec = pattern(256 * 1024, 33);
  BT_ASSERT(client->put("dcn/replicated", prec.data(), prec.size(), rep) == ErrorCode::OK);
  auto before = client->get_workers("dcn/replicated");
  BT_ASSERT_OK(before);
  const NodeId victim = before.value()[0].shards[0].worker_id;
  size_t victim_index = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if (cluster.worker(i).config().worker_id == victim) victim_index = i;
  }
  cluster.kill_worker(victim_index);
  BT_ASSERT(eventually([&] {
    auto copies = client->get_workers("dcn/replicated");
    if (!copies.ok() || copies.value().size() != 2) return false;
    for (const auto& copy : copies.value()) {
      for (const auto& shard : copy.shards) {
        if (shard.worker_id == victim) return false;
      }
    }
    return true;
  }));
  auto prec_back = client->get("dcn/replicated");
  BT_ASSERT_OK(prec_back);
  BT_EXPECT(prec_back.value() == prec);
}

BTEST(EndToEnd, ShmTransportSameHostRoundtrip) {
  auto options = EmbeddedClusterOptions::simple(2, 4 << 20);
  for (auto& w : options.workers) w.transport = TransportKind::SHM;
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  auto data = pattern(512 * 1024, 11);
  BT_ASSERT(client->put("e2e/shm", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto placements = client->get_workers("e2e/shm");
  BT_ASSERT_OK(placements);
  BT_EXPECT(placements.value()[0].shards[0].remote.transport == TransportKind::SHM);
  auto back = client->get("e2e/shm");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, FullTcpWireModeWithRpc) {
  // Everything over real sockets: TCP data plane + RPC control plane.
  auto options = EmbeddedClusterOptions::simple(2, 4 << 20);
  for (auto& w : options.workers) {
    w.transport = TransportKind::TCP;
    w.listen_host = "127.0.0.1";
  }
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);

  rpc::KeystoneRpcServer rpc_server(cluster.keystone(), "127.0.0.1", 0);
  BT_ASSERT(rpc_server.start() == ErrorCode::OK);

  ClientOptions copts;
  copts.keystone_address = rpc_server.endpoint();
  ObjectClient remote_client(copts);  // real RPC client, not embedded
  BT_ASSERT(remote_client.connect() == ErrorCode::OK);

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  auto data = pattern(1 << 20, 13);
  BT_ASSERT(remote_client.put("e2e/tcp", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto back = remote_client.get("e2e/tcp");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
  BT_EXPECT_EQ(remote_client.cluster_stats().value().total_objects, 1ull);
}

BTEST(EndToEnd, PooledSlotsServeRepeatSmallPutsAndFallBackWhenReclaimed) {
  // Remote small puts ride the slot pool: after the first put of a
  // (size, config) class, every put is write + ONE commit RPC. The fallback
  // contract: when the keystone reclaims a client's slots (TTL, here forced
  // via remove_all + restartish flush), puts keep succeeding through the
  // normal two-RTT path.
  auto options = EmbeddedClusterOptions::simple(2, 16 << 20);
  for (auto& w : options.workers) {
    w.transport = TransportKind::TCP;
    w.listen_host = "127.0.0.1";
  }
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  rpc::KeystoneRpcServer rpc_server(cluster.keystone(), "127.0.0.1", 0);
  BT_ASSERT(rpc_server.start() == ErrorCode::OK);

  ClientOptions copts;
  copts.keystone_address = rpc_server.endpoint();
  copts.put_slots = 3;
  ObjectClient remote_client(copts);
  BT_ASSERT(remote_client.connect() == ErrorCode::OK);

  WorkerConfig cfg;
  cfg.replication_factor = 2;  // replicated slots work too
  cfg.max_workers_per_copy = 1;
  const auto& counters = cluster.keystone().counters();
  for (int i = 0; i < 8; ++i) {
    auto data = pattern(64 * 1024, static_cast<uint8_t>(i + 1));
    const std::string key = "slots/obj" + std::to_string(i);
    BT_ASSERT(remote_client.put(key, data.data(), data.size(), cfg) == ErrorCode::OK);
    auto back = remote_client.get(key);
    BT_ASSERT_OK(back);
    BT_EXPECT(back.value() == data);
  }
  // All but the first (pool-priming) put committed through a slot.
  BT_EXPECT(counters.slot_commits.load() >= 7ull);
  // Duplicate key via the slot path reports cleanly and the slot survives.
  auto dup = pattern(64 * 1024, 9);
  BT_EXPECT(remote_client.put("slots/obj0", dup.data(), dup.size(), cfg) ==
            ErrorCode::OBJECT_ALREADY_EXISTS);

  // Forced reclaim of every pooled slot server-side (remove_all wipes slot
  // objects too): the client's next slot commit misses and falls back.
  BT_ASSERT_OK(remote_client.remove_all());
  const uint64_t commits_before = counters.slot_commits.load();
  auto data = pattern(64 * 1024, 42);
  BT_ASSERT(remote_client.put("slots/after", data.data(), data.size(), cfg) ==
            ErrorCode::OK);
  auto back = remote_client.get("slots/after");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
  BT_EXPECT_EQ(counters.slot_commits.load(), commits_before);  // fallback path
}

BTEST(EndToEnd, PlacementCacheServesReadsAndHealsStalePlacements) {
  // Small-object reads are metadata-RPC-bound; verified reads may reuse
  // cached placements (ClientOptions::placement_cache_ms). Two properties:
  // (1) a cache hit needs NO control plane — reads keep working with the
  // keystone RPC server stopped; (2) a stale cached placement (bytes moved
  // by drain, old worker dead) fails, invalidates, refetches, and the read
  // succeeds — the client never returns an error for an object that is
  // alive and well somewhere else.
  auto options = EmbeddedClusterOptions::simple(2, 8 << 20);
  for (auto& w : options.workers) {
    w.transport = TransportKind::TCP;
    w.listen_host = "127.0.0.1";
  }
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  rpc::KeystoneRpcServer rpc_server(cluster.keystone(), "127.0.0.1", 0);
  BT_ASSERT(rpc_server.start() == ErrorCode::OK);

  ClientOptions copts;
  copts.keystone_address = rpc_server.endpoint();
  copts.placement_cache_ms = 60'000;  // hits must come from the cache, not luck
  ObjectClient remote_client(copts);
  BT_ASSERT(remote_client.connect() == ErrorCode::OK);

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(256 * 1024, 29);
  BT_ASSERT(remote_client.put("cache/obj", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto first = remote_client.get("cache/obj");  // fetches + caches placements
  BT_ASSERT_OK(first);
  BT_EXPECT(first.value() == data);

  // (1) Control plane down: the cached placement alone serves the read.
  rpc_server.stop();
  auto cached = remote_client.get("cache/obj");
  BT_ASSERT_OK(cached);
  BT_EXPECT(cached.value() == data);

  // (2) Restart the control plane on the SAME port, move the bytes (drain
  // streams them to the other worker), and kill the old home. The cached
  // placement now points at a dead endpoint: the read fails against it,
  // invalidates, refetches fresh metadata, and lands on the drained-to
  // worker — the client never errors for an object alive elsewhere.
  const uint16_t rpc_port = rpc_server.port();
  rpc::KeystoneRpcServer rpc_server2(cluster.keystone(), "127.0.0.1", rpc_port);
  BT_ASSERT(rpc_server2.start() == ErrorCode::OK);
  const auto placed = cluster.keystone().get_workers("cache/obj");
  BT_ASSERT_OK(placed);
  const NodeId home = placed.value().front().shards.front().worker_id;
  BT_ASSERT_OK(cluster.keystone().drain_worker(home));
  size_t home_idx = options.workers.size();
  for (size_t i = 0; i < options.workers.size(); ++i) {
    if (cluster.worker(i).config().worker_id == home) home_idx = i;
  }
  BT_ASSERT(home_idx < options.workers.size());
  cluster.kill_worker(home_idx);

  auto moved = remote_client.get("cache/obj");  // stale cache -> heal -> read
  BT_ASSERT_OK(moved);
  BT_EXPECT(moved.value() == data);
}

BTEST(EndToEnd, TierPressureDemotesHbmObjectsToDiskThroughRealBackends) {
  // Acceptance-ladder item 4 end-to-end: a real worker's HBM tier (emulated
  // provider, virtual-region data path) crosses the watermark and the LRU
  // object is demoted onto the NVMe backend — still readable, bytes intact.
  auto dir = std::filesystem::temp_directory_path() /
             ("btpu_demote_" + std::to_string(::getpid()));
  std::filesystem::create_directories(dir);

  EmbeddedClusterOptions options;
  options.keystone.gc_interval_sec = 60;
  options.keystone.health_check_interval_sec = 3600;  // driven manually
  options.keystone.high_watermark = 0.5;
  options.keystone.eviction_ratio = 0.2;
  worker::WorkerServiceConfig w;
  w.worker_id = "demote-worker";
  w.transport = TransportKind::LOCAL;
  w.heartbeat_interval_ms = 100;
  w.heartbeat_ttl_ms = 60000;
  w.pools = {
      {"hbm-pool", StorageClass::HBM_TPU, 8 << 20, "", "tpu:0"},
      {"nvme-pool", StorageClass::NVME, 32 << 20, (dir / "nvme.dat").string(), ""},
  };
  options.workers.push_back(w);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  cfg.preferred_classes = {StorageClass::HBM_TPU};
  cfg.min_shard_size = 1024;

  // Three 2 MiB objects: 6/8 MiB of HBM = 75% > 50% watermark.
  std::vector<std::vector<uint8_t>> payloads;
  for (int i = 0; i < 3; ++i) {
    payloads.push_back(pattern(2 << 20, 40 + i));
    const std::string key = "demote/" + std::to_string(i);
    BT_ASSERT(client->put(key, payloads[i].data(), payloads[i].size(), cfg) == ErrorCode::OK);
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  (void)client->get_workers("demote/1");  // touch: demote/0 is the LRU victim
  (void)client->get_workers("demote/2");

  cluster.keystone().run_health_check_once();
  BT_EXPECT(cluster.keystone().counters().objects_demoted.load() >= 1ull);
  BT_EXPECT_EQ(cluster.keystone().counters().evicted.load(), 0ull);

  // Every object is still present and byte-identical; the victim now lives
  // on the NVMe tier.
  for (int i = 0; i < 3; ++i) {
    const std::string key = "demote/" + std::to_string(i);
    auto back = client->get(key);
    BT_ASSERT_OK(back);
    BT_ASSERT(back.value().size() == payloads[i].size());
    BT_EXPECT(std::memcmp(back.value().data(), payloads[i].data(), payloads[i].size()) == 0);
  }
  auto moved = client->get_workers("demote/0");
  BT_ASSERT_OK(moved);
  BT_EXPECT(moved.value()[0].shards[0].storage_class == StorageClass::NVME);

  std::filesystem::remove_all(dir);
}

BTEST(EndToEnd, TieredPoolsHbmPreferredWithDiskSpill) {
  auto dir = std::filesystem::temp_directory_path() /
             ("btpu_e2e_" + std::to_string(::getpid()));
  std::filesystem::create_directories(dir);

  EmbeddedClusterOptions options;
  options.keystone.gc_interval_sec = 1;
  options.keystone.health_check_interval_sec = 1;
  worker::WorkerServiceConfig w;
  w.worker_id = "tiered-worker";
  w.transport = TransportKind::LOCAL;
  w.heartbeat_interval_ms = 100;
  w.heartbeat_ttl_ms = 500;
  w.pools = {
      {"hbm-pool", StorageClass::HBM_TPU, 64 * 1024, "", "tpu:0"},
      {"nvme-pool", StorageClass::NVME, 4 << 20, (dir / "nvme.dat").string(), ""},
  };
  options.workers.push_back(w);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  cfg.preferred_classes = {StorageClass::HBM_TPU};
  cfg.min_shard_size = 1024;

  // Small object lands in HBM.
  auto small = pattern(16 * 1024, 21);
  BT_ASSERT(client->put("tier/hot", small.data(), small.size(), cfg) == ErrorCode::OK);
  auto hot = client->get_workers("tier/hot");
  BT_ASSERT_OK(hot);
  BT_EXPECT(hot.value()[0].shards[0].storage_class == StorageClass::HBM_TPU);

  // Big object spills to NVMe (HBM pool too small), served via the virtual
  // region data path.
  auto big = pattern(1 << 20, 22);
  BT_ASSERT(client->put("tier/cold", big.data(), big.size(), cfg) == ErrorCode::OK);
  auto cold = client->get_workers("tier/cold");
  BT_ASSERT_OK(cold);
  BT_EXPECT(cold.value()[0].shards[0].storage_class == StorageClass::NVME);

  auto hot_back = client->get("tier/hot");
  auto cold_back = client->get("tier/cold");
  BT_ASSERT_OK(hot_back);
  BT_ASSERT_OK(cold_back);
  BT_EXPECT(hot_back.value() == small);
  BT_EXPECT(cold_back.value() == big);

  cluster.stop();
  std::filesystem::remove_all(dir);
}

BTEST(EndToEnd, WorkerConfigFromYaml) {
  auto path = std::filesystem::temp_directory_path() /
              ("btpu_worker_" + std::to_string(::getpid()) + ".yaml");
  {
    std::ofstream f(path);
    f << R"(worker_id: yaml-worker
cluster_id: test_cluster
transport: tcp
listen_host: 127.0.0.1
slice_id: 2
host_id: 5
heartbeat:
  interval_ms: 1000
  ttl_ms: 4000
pools:
  - id: dram
    storage_class: ram_cpu
    capacity: 64MB
  - id: scratch
    storage_class: nvme
    capacity: 1GB
    path: /tmp/btpu-scratch/backing.dat
  - id: hot
    storage_class: hbm_tpu
    capacity: 32MB
    device_id: tpu:0
)";
  }
  auto cfg = worker::WorkerServiceConfig::from_yaml(path.string());
  BT_EXPECT_EQ(cfg.worker_id, "yaml-worker");
  BT_EXPECT(cfg.transport == TransportKind::TCP);
  BT_EXPECT_EQ(cfg.topo.slice_id, 2);
  BT_EXPECT_EQ(cfg.topo.host_id, 5);
  BT_EXPECT_EQ(cfg.heartbeat_interval_ms, 1000);
  BT_ASSERT(cfg.pools.size() == 3);
  BT_EXPECT_EQ(cfg.pools[0].capacity, 64ull << 20);
  BT_EXPECT(cfg.pools[1].storage_class == StorageClass::NVME);
  BT_EXPECT_EQ(cfg.pools[2].device_id, "tpu:0");
  std::filesystem::remove(path);

  // Invalid: disk pool without path throws.
  auto bad = std::filesystem::temp_directory_path() /
             ("btpu_worker_bad_" + std::to_string(::getpid()) + ".yaml");
  {
    std::ofstream f(bad);
    f << "worker_id: x\npools:\n  - id: d\n    storage_class: nvme\n    capacity: 1MB\n";
  }
  bool threw = false;
  try {
    (void)worker::WorkerServiceConfig::from_yaml(bad.string());
  } catch (const std::runtime_error&) {
    threw = true;
  }
  BT_EXPECT(threw);
  std::filesystem::remove(bad);
}

BTEST(EndToEnd, PinnedCxlPoolUnderShmTransport) {
  // A CXL pool with a backing path keeps its CxlBackend (persistence, NUMA)
  // even when the primary transport is shm; registration falls back to a
  // callback-backed virtual region instead of failing worker init.
  auto dir = std::filesystem::temp_directory_path() /
             ("btpu_e2e_cxl_" + std::to_string(::getpid()));
  std::filesystem::create_directories(dir);

  EmbeddedClusterOptions options;
  worker::WorkerServiceConfig w;
  w.worker_id = "cxl-worker";
  w.transport = TransportKind::SHM;
  w.heartbeat_interval_ms = 100;
  w.heartbeat_ttl_ms = 500;
  w.pools = {
      {"cxl-pool", StorageClass::CXL_MEMORY, 4 << 20, (dir / "pmem.dat").string(), ""},
  };
  options.workers.push_back(w);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  cfg.preferred_classes = {StorageClass::CXL_MEMORY};
  auto data = pattern(256 * 1024, 21);
  BT_ASSERT(client->put("e2e/cxl", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto back = client->get("e2e/cxl");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  std::filesystem::remove_all(dir);
}

BTEST(EndToEnd, PutManyGetManyBatchedRam) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  constexpr size_t kN = 12;
  std::vector<std::vector<uint8_t>> payloads;
  std::vector<ObjectClient::PutItem> puts;
  for (size_t i = 0; i < kN; ++i) {
    payloads.push_back(pattern(100 * 1024 + i * 7, static_cast<uint8_t>(i)));
    puts.push_back({"batch/ram" + std::to_string(i), payloads[i].data(), payloads[i].size()});
  }
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  auto put_ecs = client->put_many(puts, cfg);
  BT_ASSERT(put_ecs.size() == kN);
  for (auto ec : put_ecs) BT_EXPECT(ec == ErrorCode::OK);

  // Duplicate keys are rejected per item without sinking the batch.
  auto dup_ecs = client->put_many({puts[0]}, cfg);
  BT_EXPECT(dup_ecs[0] == ErrorCode::OBJECT_ALREADY_EXISTS);

  std::vector<std::vector<uint8_t>> bufs(kN);
  std::vector<ObjectClient::GetItem> gets;
  for (size_t i = 0; i < kN; ++i) {
    bufs[i].resize(payloads[i].size());
    gets.push_back({puts[i].key, bufs[i].data(), bufs[i].size()});
  }
  auto got = client->get_many(gets);
  BT_ASSERT(got.size() == kN);
  for (size_t i = 0; i < kN; ++i) {
    BT_ASSERT_OK(got[i]);
    BT_EXPECT_EQ(got[i].value(), payloads[i].size());
    BT_EXPECT(bufs[i] == payloads[i]);
  }

  // Missing keys report per item; present keys still succeed.
  std::vector<uint8_t> small(16);
  auto mixed = client->get_many({{"batch/ram0", bufs[0].data(), bufs[0].size()},
                                 {"batch/definitely-missing", bufs[1].data(), bufs[1].size()},
                                 {"batch/ram1", small.data(), small.size()}});
  BT_ASSERT(mixed.size() == 3);
  BT_EXPECT(mixed[0].ok());
  BT_EXPECT(mixed[1].error() == ErrorCode::OBJECT_NOT_FOUND);
  BT_EXPECT(mixed[2].error() == ErrorCode::BUFFER_OVERFLOW);
}

BTEST(EndToEnd, PutManyGetManyDeviceTier) {
  // HBM pools (emulated provider): the batch must travel the provider's
  // scatter/gather path, one coalesced call for all shards.
  EmbeddedCluster cluster(
      EmbeddedClusterOptions::simple(2, 16 << 20, StorageClass::HBM_TPU));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  constexpr size_t kN = 8;
  std::vector<std::vector<uint8_t>> payloads;
  std::vector<ObjectClient::PutItem> puts;
  for (size_t i = 0; i < kN; ++i) {
    payloads.push_back(pattern(1 << 20, static_cast<uint8_t>(40 + i)));
    puts.push_back({"batch/hbm" + std::to_string(i), payloads[i].data(), payloads[i].size()});
  }
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  cfg.preferred_classes = {StorageClass::HBM_TPU};
  auto put_ecs = client->put_many(puts, cfg);
  for (auto ec : put_ecs) BT_ASSERT(ec == ErrorCode::OK);

  // Placements must actually be device locations (not silently spilled).
  auto placements = client->get_workers("batch/hbm0");
  BT_ASSERT_OK(placements);
  BT_ASSERT(std::holds_alternative<DeviceLocation>(
      placements.value().front().shards.front().location));

  std::vector<std::vector<uint8_t>> bufs(kN);
  std::vector<ObjectClient::GetItem> gets;
  for (size_t i = 0; i < kN; ++i) {
    bufs[i].resize(payloads[i].size());
    gets.push_back({puts[i].key, bufs[i].data(), bufs[i].size()});
  }
  auto got = client->get_many(gets);
  for (size_t i = 0; i < kN; ++i) {
    BT_ASSERT_OK(got[i]);
    BT_EXPECT(bufs[i] == payloads[i]);
  }
}

// ---- fault injection (VERDICT r1 task 6: the reference has none) ---------

BTEST(FaultInjection, PutMidStripeFailureRollsBackAllocatorState) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();
  auto stats_before = client->cluster_stats();
  BT_ASSERT_OK(stats_before);
  const uint64_t used_before = stats_before.value().used_capacity;

  // Fail the 3rd shard write of a 4-shard striped put.
  transport::FaultSpec spec;
  spec.fail_nth_write = 3;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 4;
  auto data = pattern(1 << 20, 21);
  BT_EXPECT(client->put("fault/putfail", data.data(), data.size(), cfg) ==
            ErrorCode::NETWORK_ERROR);

  // put_cancel must have rolled everything back: no metadata, no leaked
  // ranges (used bytes return to the pre-put level), key reusable.
  auto exists = client->object_exists("fault/putfail");
  BT_ASSERT_OK(exists);
  BT_EXPECT(!exists.value());
  auto stats = client->cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().used_capacity, used_before);

  // The injected fault fires exactly once; the retry lands clean.
  BT_ASSERT(client->put("fault/putfail", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto back = client->get("fault/putfail");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(FaultInjection, GetReadFailureFailsOverToSecondReplica) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(3, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(256 * 1024, 33);
  BT_ASSERT(client->put("fault/getfail", data.data(), data.size(), cfg) == ErrorCode::OK);

  transport::FaultSpec spec;
  spec.fail_nth_read = 1;  // first copy's read dies; client must fail over
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));
  auto back = client->get("fault/getfail");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(FaultInjection, RepairStreamFailureKeepsObjectDegradedButReadable) {
  auto options = EmbeddedClusterOptions::simple(3, 4 << 20);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(128 * 1024, 55);
  BT_ASSERT(client->put("fault/repair", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Every repair read the keystone issues fails (fail op 1, and far beyond
  // any retry budget via a huge spec on a second injection is unnecessary:
  // one failed stream aborts this repair pass for the object).
  transport::FaultSpec spec;
  spec.fail_nth_read = 1;
  cluster.keystone().inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  auto before = client->get_workers("fault/repair");
  BT_ASSERT_OK(before);
  const NodeId victim = before.value()[0].shards[0].worker_id;
  size_t victim_idx = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) victim_idx = i;
  }
  cluster.kill_worker(victim_idx);

  // The dead placement is pruned promptly even though re-replication failed.
  BT_EXPECT(eventually([&] {
    auto placements = client->get_workers("fault/repair");
    if (!placements.ok()) return false;
    for (const auto& copy : placements.value())
      for (const auto& shard : copy.shards)
        if (shard.worker_id == victim) return false;
    return true;
  }));

  // Degraded (one copy) but never deleted, and still readable.
  auto placements = client->get_workers("fault/repair");
  BT_ASSERT_OK(placements);
  BT_EXPECT_EQ(placements.value().size(), 1u);
  BT_EXPECT_EQ(cluster.keystone().counters().objects_repaired.load(), 0u);
  auto back = client->get("fault/repair");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

// ---- ICI transport (VERDICT r1 task 3) -----------------------------------

BTEST(EndToEnd, IciMeshPutGetRepairAndDemotionPaths) {
  // 4 device-resident pools, one per (emulated) chip, under the ICI
  // transport: placements must be DeviceLocation with ICI descriptors, the
  // client put/get path must round-trip, and worker death must repair the
  // object chip-to-chip via the provider copy path (no wire transport is
  // even configured for these pools).
  auto options = EmbeddedClusterOptions::simple(4, 8 << 20, StorageClass::HBM_TPU);
  options.transport = TransportKind::ICI;
  for (auto& w : options.workers) w.transport = TransportKind::ICI;
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 2;
  auto data = pattern(3 << 20, 77);
  BT_ASSERT(client->put("ici/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("ici/obj");
  BT_ASSERT_OK(placements);
  BT_ASSERT(placements.value().size() == 2);
  for (const auto& copy : placements.value()) {
    for (const auto& shard : copy.shards) {
      BT_EXPECT(std::holds_alternative<DeviceLocation>(shard.location));
      BT_EXPECT(shard.remote.transport == TransportKind::ICI);
    }
  }

  auto back = client->get("ici/obj");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Kill the worker hosting the first copy's first shard: repair must
  // re-replicate device-to-device onto surviving chips.
  const NodeId victim = placements.value()[0].shards[0].worker_id;
  size_t victim_idx = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) victim_idx = i;
  }
  cluster.kill_worker(victim_idx);
  BT_EXPECT(eventually(
      [&] { return cluster.keystone().counters().objects_repaired.load() == 1; }));

  auto after = client->get_workers("ici/obj");
  BT_ASSERT_OK(after);
  BT_EXPECT_EQ(after.value().size(), 2u);
  for (const auto& copy : after.value()) {
    for (const auto& shard : copy.shards) {
      BT_EXPECT_NE(shard.worker_id, victim);
      BT_EXPECT(std::holds_alternative<DeviceLocation>(shard.location));
    }
  }
  auto repaired = client->get("ici/obj");
  BT_ASSERT_OK(repaired);
  BT_EXPECT(repaired.value() == data);
}

BTEST(EndToEnd, SplitReplicaGetReadsBothCopiesAndFallsBack) {
  // A wide replicated object: the read splits its byte range across both
  // replicas in parallel (reference TODO blackbird_client.cpp:283); any
  // slice failure falls back to whole-copy reads, costing a retry, never
  // the object.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 16 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 2;
  auto data = pattern(4 << 20, 91);
  BT_ASSERT(client->put("split/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto back = client->get("split/obj");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Odd (non-divisible) size exercises the tail-slice math.
  auto odd = pattern((2 << 20) + 12345, 17);
  BT_ASSERT(client->put("split/odd", odd.data(), odd.size(), cfg) == ErrorCode::OK);
  auto odd_back = client->get("split/odd");
  BT_ASSERT_OK(odd_back);
  BT_EXPECT(odd_back.value() == odd);

  // Persistently kill ONE replica's endpoint (a dead worker): the split
  // path fails on its slices, and the fallback must produce the full object
  // from the surviving copy — not retry the dead one forever.
  auto placements = client->get_workers("split/obj");
  BT_ASSERT_OK(placements);
  transport::FaultSpec spec;
  spec.fail_endpoint = placements.value()[0].shards[0].remote.endpoint;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));
  auto after = client->get("split/obj");
  BT_ASSERT_OK(after);
  BT_EXPECT(after.value() == data);
}

BTEST(EndToEnd, DrainWorkerMigratesEverythingIncludingRf1) {
  // Graceful evacuation (TPU preemption notice): unlike crash repair, drain
  // streams from the still-alive worker, so replication_factor=1 objects
  // survive. After the drain the worker is retired and no placement
  // references it; new puts avoid a draining worker from the first moment.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(3, 16 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig rf1;
  rf1.replication_factor = 1;
  rf1.max_workers_per_copy = 3;  // striped across all workers incl. victim
  auto a = pattern(1 << 20, 11);
  BT_ASSERT(client->put("drain/rf1", a.data(), a.size(), rf1) == ErrorCode::OK);

  WorkerConfig rf2;
  rf2.replication_factor = 2;
  rf2.max_workers_per_copy = 1;
  auto b = pattern(512 * 1024, 22);
  BT_ASSERT(client->put("drain/rf2", b.data(), b.size(), rf2) == ErrorCode::OK);

  auto moved = client->drain_worker("worker-0");
  BT_ASSERT_OK(moved);
  BT_EXPECT(moved.value() >= 1);  // at least the striped rf1 copy moved

  // Worker is gone from the registry and from every placement.
  auto stats = client->cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().total_workers, 2u);
  for (const char* key : {"drain/rf1", "drain/rf2"}) {
    auto placements = client->get_workers(key);
    BT_ASSERT_OK(placements);
    for (const auto& copy : placements.value())
      for (const auto& shard : copy.shards) BT_EXPECT_NE(shard.worker_id, "worker-0");
  }

  // Bytes intact — including the rf=1 object a crash would have lost.
  auto back_a = client->get("drain/rf1");
  BT_ASSERT_OK(back_a);
  BT_EXPECT(back_a.value() == a);
  auto back_b = client->get("drain/rf2");
  BT_ASSERT_OK(back_b);
  BT_EXPECT(back_b.value() == b);

  // New puts land on the survivors.
  auto c = pattern(64 * 1024, 33);
  BT_ASSERT(client->put("drain/after", c.data(), c.size(), rf1) == ErrorCode::OK);
  auto after = client->get_workers("drain/after");
  BT_ASSERT_OK(after);
  for (const auto& copy : after.value())
    for (const auto& shard : copy.shards) BT_EXPECT_NE(shard.worker_id, "worker-0");
}

BTEST(EndToEnd, DrainOnIciMeshMovesDeviceBytesChipToChip) {
  // Device-tier drain: the copies move through the provider's
  // device-to-device entry (ICI), never staging through host memory.
  auto options = EmbeddedClusterOptions::simple(3, 8 << 20, StorageClass::HBM_TPU);
  options.transport = TransportKind::ICI;
  for (auto& w : options.workers) w.transport = TransportKind::ICI;
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(2 << 20, 44);
  BT_ASSERT(client->put("drain/ici", data.data(), data.size(), cfg) == ErrorCode::OK);
  const NodeId victim = [&] {
    auto p = client->get_workers("drain/ici");
    return p.ok() ? p.value()[0].shards[0].worker_id : NodeId{};
  }();
  BT_ASSERT(!victim.empty());

  auto moved = client->drain_worker(victim);
  BT_ASSERT_OK(moved);
  BT_EXPECT_EQ(moved.value(), 1u);
  auto back = client->get("drain/ici");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, ChurnLeavesNoLeakedRangesOrFragmentation) {
  // Heavy put/remove churn with mixed sizes and policies must return the
  // allocator to a clean state: used bytes back to zero, and the largest
  // possible object still placeable afterwards (no fragmentation creep,
  // no orphaned ranges — the availability bug class repair/demotion/drain
  // bookkeeping could introduce).
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(4, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  std::mt19937 rng(7);
  std::vector<std::string> live;
  for (int iter = 0; iter < 400; ++iter) {
    if (live.empty() || rng() % 3 != 0) {
      const uint64_t size = 1024 + rng() % (512 * 1024);
      WorkerConfig cfg;
      cfg.replication_factor = 1 + rng() % 2;
      cfg.max_workers_per_copy = 1 + rng() % 4;
      auto data = pattern(size, static_cast<uint8_t>(iter));
      const std::string key = "churn/" + std::to_string(iter);
      auto ec = client->put(key, data.data(), size, cfg);
      if (ec == ErrorCode::OK) live.push_back(key);
      else BT_ASSERT(ec == ErrorCode::INSUFFICIENT_SPACE);  // pool full is fine
    } else {
      const size_t pick = rng() % live.size();
      // Watermark eviction may legitimately beat the remove to an unpinned
      // LRU object when churn holds utilization near the threshold (seen
      // under TSan's slowdown, where the health loop runs mid-churn).
      const auto ec = client->remove(live[pick]);
      BT_ASSERT(ec == ErrorCode::OK || ec == ErrorCode::OBJECT_NOT_FOUND);
      live.erase(live.begin() + static_cast<ptrdiff_t>(pick));
    }
  }
  for (const auto& key : live) {
    const auto ec = client->remove(key);
    BT_ASSERT(ec == ErrorCode::OK || ec == ErrorCode::OBJECT_NOT_FOUND);
  }

  auto stats = client->cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().used_capacity, 0u);

  // The whole cluster must still be one allocatable space: a max-striped
  // object spanning ~all remaining capacity places cleanly.
  WorkerConfig wide;
  wide.replication_factor = 1;
  wide.max_workers_per_copy = 4;
  auto big = pattern(24 << 20, 99);  // 24 MiB of the 32 MiB total
  BT_ASSERT(client->put("churn/final", big.data(), big.size(), wide) == ErrorCode::OK);
  auto back = client->get("churn/final");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == big);
}

// ---- erasure coding (no reference counterpart: it only replicates) --------

BTEST(ErasureCoding, PutGetRoundtripAndGeometry) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(6, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  cfg.replication_factor = 3;  // ignored under EC: one coded copy
  auto data = pattern(1 << 20, 17);
  BT_ASSERT(client->put("ec/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("ec/obj");
  BT_ASSERT_OK(placements);
  BT_ASSERT(placements.value().size() == 1);  // ONE coded copy, not replicas
  const auto& copy = placements.value()[0];
  BT_EXPECT_EQ(copy.ec_data_shards, 4u);
  BT_EXPECT_EQ(copy.ec_parity_shards, 2u);
  BT_EXPECT_EQ(copy.ec_object_size, data.size());
  BT_ASSERT(copy.shards.size() == 6);
  const uint64_t L = copy.shards[0].length;
  BT_EXPECT_EQ(L, (data.size() + 3) / 4);
  std::set<std::string> workers;
  for (const auto& s : copy.shards) {
    BT_EXPECT_EQ(s.length, L);  // equal shards (parity needs equal lengths)
    workers.insert(s.worker_id);
  }
  BT_EXPECT_EQ(workers.size(), 6u);  // anti-affine: one shard per worker

  auto back = client->get("ec/obj");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Odd (non-divisible) size exercises the padded last shard.
  auto odd = pattern(123457, 3);
  BT_ASSERT(client->put("ec/odd", odd.data(), odd.size(), cfg) == ErrorCode::OK);
  auto odd_back = client->get("ec/odd");
  BT_ASSERT_OK(odd_back);
  BT_EXPECT(odd_back.value() == odd);

  // Tiny objects: size < (k-1)*L means SEVERAL trailing shards are pure
  // padding (L = ceil(5/4) = 2, shards 2..3 hold no data at all).
  for (uint64_t tiny_size : {1ull, 5ull, 7ull}) {
    const std::string tkey = "ec/tiny" + std::to_string(tiny_size);
    auto tiny = pattern(tiny_size, 11);
    BT_ASSERT(client->put(tkey, tiny.data(), tiny.size(), cfg) == ErrorCode::OK);
    auto tiny_back = client->get(tkey);
    BT_ASSERT_OK(tiny_back);
    BT_EXPECT(tiny_back.value() == tiny);
  }

  // Batched APIs route coded items correctly too.
  std::vector<ObjectClient::GetItem> gets;
  std::vector<uint8_t> buf_a(data.size()), buf_b(odd.size());
  gets.push_back({"ec/obj", buf_a.data(), buf_a.size()});
  gets.push_back({"ec/odd", buf_b.data(), buf_b.size()});
  auto many = client->get_many(gets);
  BT_ASSERT(many[0].ok() && many[1].ok());
  BT_EXPECT_EQ(many[0].value(), data.size());
  BT_EXPECT(std::memcmp(buf_a.data(), data.data(), data.size()) == 0);
  BT_EXPECT(std::memcmp(buf_b.data(), odd.data(), odd.size()) == 0);
}

BTEST(ErasureCoding, DegradedReadReconstructsThroughParity) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(6, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(1 << 20, 29);
  BT_ASSERT(client->put("ec/degraded", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Fail the first two data-shard reads: the client must fetch parity and
  // reconstruct (m=2 tolerates exactly this).
  transport::FaultSpec spec;
  spec.fail_nth_read = 1;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));
  auto back = client->get("ec/degraded");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // The batched path degrades the same way: a failed item falls back to
  // the reconstructing read.
  transport::FaultSpec bspec;
  bspec.fail_nth_read = 2;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), bspec));
  std::vector<uint8_t> bbuf(data.size());
  std::vector<ObjectClient::GetItem> bitems{{"ec/degraded", bbuf.data(), bbuf.size()}};
  auto bres = client->get_many(bitems);
  BT_ASSERT(bres[0].ok());
  BT_EXPECT(std::memcmp(bbuf.data(), data.data(), data.size()) == 0);

  // Beyond tolerance: every read fails -> NO_COMPLETE_WORKER, not garbage.
  transport::FaultSpec all;
  all.fail_endpoint = "";  // count-based: fail reads 1..8 (data + parity)
  all.fail_nth_read = 1;
  auto inner = transport::make_faulty_transport_client(
      transport::make_transport_client(), all);
  for (uint32_t n = 2; n <= 8; ++n) {
    transport::FaultSpec extra;
    extra.fail_nth_read = 1;
    inner = transport::make_faulty_transport_client(std::move(inner), extra);
  }
  client->inject_data_client_for_test(std::move(inner));
  auto dead = client->get("ec/degraded");
  BT_ASSERT(!dead.ok());
}

BTEST(ErasureCoding, RepairReconstructsLostShardsOntoFreshWorkers) {
  // 7 workers, ec=(4,2): kill one shard's worker; repair must REBUILD that
  // shard from survivors onto the spare worker (not just leave the object
  // degraded), restoring full m-loss tolerance.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(7, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(700 * 1024, 55);
  BT_ASSERT(client->put("ec/heal", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto before = client->get_workers("ec/heal");
  BT_ASSERT_OK(before);
  const auto victim = before.value()[0].shards[2].worker_id;  // a data shard
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) cluster.kill_worker(i);
  }

  BT_EXPECT(eventually(
      [&] { return cluster.keystone().counters().objects_repaired.load() >= 1; }));
  auto after = client->get_workers("ec/heal");
  BT_ASSERT_OK(after);
  const auto& copy = after.value()[0];
  BT_ASSERT(copy.shards.size() == 6);  // geometry intact
  BT_EXPECT_EQ(copy.ec_data_shards, 4u);
  for (const auto& s : copy.shards) {
    BT_EXPECT(s.worker_id != victim);  // the lost shard moved to a live worker
  }
  // Repair restamped the rebuilt shard's CRC: the copy is still fully
  // stamped and every stamp verifies (scrub_object reads each shard).
  BT_ASSERT(copy.shard_crcs.size() == copy.shards.size());
  auto scrubbed = client->scrub_object("ec/heal");
  BT_ASSERT_OK(scrubbed);
  for (const auto& f : scrubbed.value()) BT_EXPECT(f.status == ErrorCode::OK);
  // Anti-affinity preserved: still one shard per worker.
  std::set<std::string> workers;
  for (const auto& s : copy.shards) workers.insert(s.worker_id);
  BT_EXPECT_EQ(workers.size(), 6u);

  auto back = client->get("ec/heal");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Healed means FULL tolerance is back: two more deaths still read.
  auto p2 = client->get_workers("ec/heal");
  BT_ASSERT_OK(p2);
  for (size_t si : {size_t{0}, size_t{5}}) {
    const auto w = p2.value()[0].shards[si].worker_id;
    for (size_t i = 0; i < cluster.worker_count(); ++i) {
      if ("worker-" + std::to_string(i) == w) cluster.kill_worker(i);
    }
  }
  BT_EXPECT(eventually([&] {
    auto b2 = client->get("ec/heal");
    return b2.ok() && b2.value() == data;
  }, 8000));
}

BTEST(ErasureCoding, WorkerDeathLeavesObjectDegradedButReadable) {
  auto options = EmbeddedClusterOptions::simple(6, 4 << 20);
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(768 * 1024, 41);
  BT_ASSERT(client->put("ec/survive", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Kill the worker holding data shard 0. The coded copy must NOT be
  // dropped (the replication repairer would have deleted a 1-copy object);
  // reads reconstruct through parity.
  auto placements = client->get_workers("ec/survive");
  BT_ASSERT_OK(placements);
  const auto victim = placements.value()[0].shards[0].worker_id;
  size_t victim_idx = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) victim_idx = i;  // ids are worker-<i>
  }
  cluster.kill_worker(victim_idx);

  BT_EXPECT(eventually([&] {
    auto p = client->get_workers("ec/survive");
    return p.ok() && !p.value().empty();
  }));
  auto exists = client->object_exists("ec/survive");
  BT_ASSERT_OK(exists);
  BT_EXPECT(exists.value());  // degraded, NOT deleted

  auto back = client->get("ec/survive");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // A second death within tolerance still reads; the third loss kills it.
  auto p2 = client->get_workers("ec/survive");
  BT_ASSERT_OK(p2);
  const auto victim2 = p2.value()[0].shards[1].worker_id;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim2) cluster.kill_worker(i);
  }
  BT_EXPECT(eventually([&] {
    auto back2 = client->get("ec/survive");
    return back2.ok() && back2.value() == data;
  }));
}

// ---- end-to-end integrity (CRC32C; no reference counterpart) --------------

BTEST(ErasureCoding, RepairScreensRottenBasisAndHealsItInPlace) {
  // A live-but-rotten shard must never serve as a reconstruction basis
  // (the rebuild would be garbage restamped as valid); repair promotes it
  // to a repair target and heals BOTH the dead and the rotten shard.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(8, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(640 * 1024, 83);
  BT_ASSERT(client->put("ec/rot", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto before = client->get_workers("ec/rot");
  BT_ASSERT_OK(before);
  const auto& copy = before.value()[0];
  BT_ASSERT(copy.shard_crcs.size() == 6);

  // Rot data shard 1 silently (it would land in the naive basis {0,1,3,4}
  // once shard 2 dies), then kill shard 2's worker.
  {
    const auto& shard = copy.shards[1];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(4096, 0x77);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 256, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  }
  const auto victim = copy.shards[2].worker_id;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) cluster.kill_worker(i);
  }

  BT_EXPECT(eventually(
      [&] { return cluster.keystone().counters().objects_repaired.load() >= 1; }, 10000));

  // Healed: the object reads byte-correct and every shard passes its stamp
  // (the rotten shard was rebuilt too, not just the dead one).
  auto back = client->get("ec/rot");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
  auto scrubbed = client->scrub_object("ec/rot");
  BT_ASSERT_OK(scrubbed);
  for (const auto& f : scrubbed.value()) BT_EXPECT(f.status == ErrorCode::OK);
  auto after = client->get_workers("ec/rot");
  BT_ASSERT_OK(after);
  BT_EXPECT(after.value()[0].shards[1].worker_id != copy.shards[1].worker_id);
}

BTEST(Integrity, BackgroundScrubHealsCorruptReplicatedShard) {
  // Server-side scrub: a bit-rotted shard is found by its CRC stamp and
  // restored byte-identically from the sibling copy — no client read ever
  // has to hit the rot (the floor that makes verify=false honest).
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(512 * 1024, 83);
  BT_ASSERT(client->put("scrub/rep", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto& ks = cluster.keystone();
  BT_EXPECT_EQ(ks.run_scrub_once(), 0u);  // pristine pass
  BT_EXPECT_EQ(ks.counters().scrub_checked.load(), 1u);

  auto placements = client->get_workers("scrub/rep");
  BT_ASSERT_OK(placements);
  const auto& shard = placements.value()[0].shards[0];
  const auto& mem = std::get<MemoryLocation>(shard.location);
  std::vector<uint8_t> garbage(8192, 0x5a);
  auto raw = transport::make_transport_client();
  BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 1000, mem.rkey, garbage.data(),
                       garbage.size()) == ErrorCode::OK);

  BT_EXPECT_EQ(ks.run_scrub_once(), 1u);  // found...
  BT_EXPECT_EQ(ks.counters().scrub_corrupt.load(), 1u);
  BT_EXPECT_EQ(ks.counters().scrub_healed.load(), 1u);
  BT_EXPECT_EQ(ks.run_scrub_once(), 0u);  // ...and genuinely healed
  // Raw (unverified) read of the healed copy returns intact bytes.
  auto back = client->get("scrub/rep", /*verify=*/false);
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(InlineTier, SmallPutsRideTheMetadataPlane) {
  // A tiny put is absorbed by the keystone's inline tier (one control RTT,
  // bytes in the object map) and a verified get never touches the data
  // plane — the metadata reply carries the bytes.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(1, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  // Inline applies to default-placement puts only (rf<=1, no tier/node
  // preference, no EC): an explicit replica or tier request is a data-plane
  // contract the client must not silently downgrade.
  ClientOptions copts;
  copts.default_config.replication_factor = 1;
  auto client = cluster.make_client(copts);

  auto data = pattern(1024, 41);
  BT_ASSERT(client->put("inl/small", data.data(), data.size()) == ErrorCode::OK);
  BT_EXPECT_EQ(cluster.keystone().counters().inline_puts.load(), 1u);
  BT_EXPECT_EQ(cluster.keystone().inline_bytes_resident(), data.size());

  auto placements = client->get_workers("inl/small");
  BT_ASSERT_OK(placements);
  BT_ASSERT(placements.value().size() == 1);
  BT_EXPECT(placements.value()[0].shards.empty());  // no data-plane bytes

  auto back = client->get("inl/small", /*verify=*/true);
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Client-side audit judges the inline copy through its content CRC.
  auto findings = client->scrub_object("inl/small");
  BT_ASSERT_OK(findings);
  for (const auto& f : findings.value()) BT_EXPECT(f.status == ErrorCode::OK);

  // An oversized put falls through to the placed path transparently.
  auto big = pattern(64 * 1024, 42);
  BT_ASSERT(client->put("inl/big", big.data(), big.size()) == ErrorCode::OK);
  BT_EXPECT_EQ(cluster.keystone().counters().inline_puts.load(), 1u);  // unchanged
  auto big_placed = client->get_workers("inl/big");
  BT_ASSERT_OK(big_placed);
  BT_EXPECT(!big_placed.value()[0].shards.empty());
  auto big_back = client->get("inl/big");
  BT_ASSERT_OK(big_back);
  BT_EXPECT(big_back.value() == big);

  BT_EXPECT(client->remove("inl/small") == ErrorCode::OK);
  BT_EXPECT_EQ(cluster.keystone().inline_bytes_resident(), 0u);
  BT_EXPECT(!client->object_exists("inl/small").value());
}

BTEST(InlineTier, GetManyAndBatchedMetadataSeeInlineObjects) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(1, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.default_config.replication_factor = 1;
  auto client = cluster.make_client(copts);
  auto a = pattern(512, 3), b = pattern(2048, 5);
  BT_ASSERT(client->put("inl/a", a.data(), a.size()) == ErrorCode::OK);
  BT_ASSERT(client->put("inl/b", b.data(), b.size()) == ErrorCode::OK);
  std::vector<uint8_t> ba(a.size()), bb(b.size());
  auto many = client->get_many({{"inl/a", ba.data(), ba.size()},
                                {"inl/b", bb.data(), bb.size()}});
  BT_ASSERT(many.size() == 2);
  BT_ASSERT_OK(many[0]);
  BT_ASSERT_OK(many[1]);
  BT_EXPECT(ba == a);
  BT_EXPECT(bb == b);
  auto listed = client->list_objects("inl/");
  BT_ASSERT_OK(listed);
  BT_EXPECT_EQ(listed.value().size(), 2u);
}

BTEST(Integrity, QueuedScrubTargetVerifiedAheadOfRing) {
  // Movers queue fabric-moved objects for revalidation: a queued target is
  // scrubbed on the NEXT pass, ahead of the ring walk and on top of its
  // budget — rot propagated over the device fabric (whose moves carry CRC
  // stamps without the staged lane's streaming check) cannot hide behind a
  // long ring.
  auto opts = EmbeddedClusterOptions::simple(2, 16 << 20);
  opts.keystone.scrub_objects_per_pass = 1;  // ring crawls one object a pass
  // A scrub thread must exist for targets to queue (the guard refuses to
  // grow a queue nothing drains); the hour-long interval keeps it parked
  // while the test drives passes by hand.
  opts.keystone.scrub_interval_sec = 3600;
  EmbeddedCluster cluster(std::move(opts));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(256 * 1024, 11);
  for (char c : {'a', 'b', 'c', 'd', 'e', 'f'}) {
    BT_ASSERT(client->put(std::string("ring/") + c, data.data(), data.size(), cfg) ==
              ErrorCode::OK);
  }

  // Rot the LAST ring key — a budget-1 ring pass starting from scratch
  // would reach it five passes from now.
  auto placements = client->get_workers("ring/f");
  BT_ASSERT_OK(placements);
  const auto& shard = placements.value()[0].shards[0];
  const auto& mem = std::get<MemoryLocation>(shard.location);
  std::vector<uint8_t> garbage(4096, 0x21);
  auto raw = transport::make_transport_client();
  BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 512, mem.rkey, garbage.data(),
                       garbage.size()) == ErrorCode::OK);

  auto& ks = cluster.keystone();
  ks.queue_scrub_target("ring/f");
  BT_EXPECT_EQ(ks.run_scrub_once(), 1u);  // found out of ring order...
  BT_EXPECT_EQ(ks.counters().scrub_healed.load(), 1u);
  // ...and healed: both copies now serve intact bytes even unverified.
  auto back = client->get("ring/f", /*verify=*/false);
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(Integrity, BackgroundScrubReconstructsCorruptCodedShard) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(3, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 2;
  cfg.ec_parity_shards = 1;
  auto data = pattern(384 * 1024, 97);
  BT_ASSERT(client->put("scrub/ec", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("scrub/ec");
  BT_ASSERT_OK(placements);
  const auto& shard = placements.value()[0].shards[1];
  const auto& mem = std::get<MemoryLocation>(shard.location);
  std::vector<uint8_t> garbage(4096, 0x33);
  auto raw = transport::make_transport_client();
  BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 64, mem.rkey, garbage.data(),
                       garbage.size()) == ErrorCode::OK);

  auto& ks = cluster.keystone();
  BT_EXPECT_EQ(ks.run_scrub_once(), 1u);  // found + parity-reconstructed
  BT_EXPECT_EQ(ks.counters().scrub_healed.load(), 1u);
  BT_EXPECT_EQ(ks.run_scrub_once(), 0u);
  auto back = client->get("scrub/ec");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(Integrity, Crc32cKnownVector) {
  // RFC 3720 test vector: crc32c("123456789") = 0xE3069283.
  BT_EXPECT_EQ(crc32c("123456789", 9), 0xE3069283u);
  // Chained == one-shot.
  BT_EXPECT_EQ(crc32c("6789", 4, crc32c("12345", 5)), 0xE3069283u);
  BT_EXPECT_EQ(crc32c("", 0), 0u);
}

BTEST(Integrity, CorruptReplicaSelfHealsFromTheOther) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(256 * 1024, 61);
  BT_ASSERT(client->put("crc/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Flip bytes inside copy 0's region through a raw transport client —
  // exactly what bit rot or a torn write would leave behind.
  auto placements = client->get_workers("crc/obj");
  BT_ASSERT_OK(placements);
  BT_EXPECT(placements.value()[0].content_crc != 0u);
  auto corrupt = [&](const CopyPlacement& copy) {
    const auto& shard = copy.shards[0];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(4096, 0x5a);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 1000, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  };
  corrupt(placements.value()[0]);

  // get() must detect the mismatch on copy 0 and heal from copy 1.
  auto back = client->get("crc/obj");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Batched path heals the same way.
  std::vector<uint8_t> buf(data.size());
  std::vector<ObjectClient::GetItem> items{{"crc/obj", buf.data(), buf.size()}};
  auto many = client->get_many(items);
  BT_ASSERT(many[0].ok());
  BT_EXPECT(std::memcmp(buf.data(), data.data(), data.size()) == 0);

  // Both copies corrupt: detection, not garbage.
  corrupt(placements.value()[1]);
  auto dead = client->get("crc/obj");
  BT_ASSERT(!dead.ok());
  BT_EXPECT(dead.error() == ErrorCode::CHECKSUM_MISMATCH);
}

BTEST(Integrity, CorruptEcShardHuntedAndReconstructed) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(6, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(512 * 1024, 67);
  BT_ASSERT(client->put("crc/ec", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("crc/ec");
  BT_ASSERT_OK(placements);
  const auto& copy = placements.value()[0];
  auto corrupt_shard = [&](size_t idx) {
    const auto& shard = copy.shards[idx];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(2048, 0xa5);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 512, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  };
  // Silently corrupt data shard 2: the healthy read sees every shard OK but
  // the CRCs disagree — shard 2 must be identified and reconstructed.
  corrupt_shard(2);
  auto back = client->get("crc/ec");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // TWO corrupt shards (0 and 2 — the store still holds 2's rot; reads heal
  // transiently, not in place): per-shard CRCs localize both and parity
  // m=2 reconstructs both. An object-level CRC alone could only detect this.
  corrupt_shard(0);
  auto two = client->get("crc/ec");
  BT_ASSERT_OK(two);
  BT_EXPECT(two.value() == data);

  // A corrupt PARITY shard on top (3 corrupt of 6, beyond the m=2
  // tolerance): parity 5 is condemned by its own CRC, leaving only 3
  // readable rows < k. Detection (CHECKSUM_MISMATCH), never silent garbage.
  corrupt_shard(5);
  auto dead = client->get("crc/ec");
  BT_ASSERT(!dead.ok());
  BT_EXPECT(dead.error() == ErrorCode::CHECKSUM_MISMATCH);
}

BTEST(Integrity, ScrubObjectNamesCorruptWorkerAndPool) {
  // The scrub localization surface (bb-client scrub): per-shard CRCs turn
  // "this object is corrupt" into "THIS shard on THIS worker/pool is".
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(6, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(512 * 1024, 71);
  BT_ASSERT(client->put("scrub/ec", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("scrub/ec");
  BT_ASSERT_OK(placements);
  const auto& copy = placements.value()[0];
  BT_ASSERT(copy.shard_crcs.size() == copy.shards.size());  // writer stamped

  // A healthy object scrubs clean.
  auto clean = client->scrub_object("scrub/ec");
  BT_ASSERT_OK(clean);
  BT_ASSERT(clean.value().size() == copy.shards.size());
  for (const auto& f : clean.value()) BT_EXPECT(f.status == ErrorCode::OK);

  // Corrupt data shard 1 and parity shard 4; scrub must name exactly those,
  // with the pool/worker the placement points at.
  auto corrupt_shard = [&](size_t idx) {
    const auto& shard = copy.shards[idx];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(1024, 0x3c);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 64, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  };
  corrupt_shard(1);
  corrupt_shard(4);

  auto findings = client->scrub_object("scrub/ec");
  BT_ASSERT_OK(findings);
  size_t flagged = 0;
  for (const auto& f : findings.value()) {
    if (f.status == ErrorCode::OK) continue;
    ++flagged;
    BT_EXPECT(f.status == ErrorCode::CHECKSUM_MISMATCH);
    BT_ASSERT(f.shard_index == 1 || f.shard_index == 4);
    BT_EXPECT_EQ(f.pool_id, copy.shards[f.shard_index].pool_id);
    BT_EXPECT_EQ(f.worker_id, copy.shards[f.shard_index].worker_id);
  }
  BT_EXPECT_EQ(flagged, size_t{2});

  // And the object still READS correctly: 2 corruptions within rs(4,2)
  // tolerance reconstruct transparently.
  auto back = client->get("scrub/ec");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(Integrity, NoVerifyReadSkipsCrcAndItsProtections) {
  // verify=false is the documented raw mode: reads return whatever the
  // bytes are — no CHECKSUM_MISMATCH, no corrupt-replica failover. Both the
  // per-call override and the client-level default behave identically.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(1, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(128 * 1024, 91);
  BT_ASSERT(client->put("raw/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("raw/obj");
  BT_ASSERT_OK(placements);
  {
    const auto& shard = placements.value()[0].shards[0];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(512, 0x42);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 100, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  }

  // Default (verified): single replica, corrupt -> CHECKSUM_MISMATCH.
  auto verified = client->get("raw/obj");
  BT_ASSERT(!verified.ok());
  BT_EXPECT(verified.error() == ErrorCode::CHECKSUM_MISMATCH);

  // Per-call override: bytes come back (corrupt, by request).
  auto raw_read = client->get("raw/obj", /*verify=*/false);
  BT_ASSERT_OK(raw_read);
  BT_EXPECT(raw_read.value().size() == data.size());
  BT_EXPECT(raw_read.value() != data);  // it IS the rotten bytes

  // Client-level default off: same result through get_into and get_many.
  client->set_verify_reads(false);
  std::vector<uint8_t> buf(data.size());
  auto into = client->get_into("raw/obj", buf.data(), buf.size());
  BT_ASSERT_OK(into);
  std::vector<ObjectClient::GetItem> items{{"raw/obj", buf.data(), buf.size()}};
  auto many = client->get_many(items);
  BT_ASSERT(many[0].ok());
  // And the per-call override wins over the client default, both ways.
  client->set_verify_reads(true);
  BT_ASSERT_OK(client->get_into("raw/obj", buf.data(), buf.size(), /*verify=*/false));
}

BTEST(Integrity, RepairRefusesToPropagateCorruptSource) {
  // r=2 object; corrupt copy 0, then kill copy 1's worker. Repair's only
  // source is the corrupt copy — it must refuse (CHECKSUM_MISMATCH on the
  // stream) rather than mint a "repaired" copy from rotten bytes.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(3, 4 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(128 * 1024, 71);
  BT_ASSERT(client->put("crc/repair", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("crc/repair");
  BT_ASSERT_OK(placements);
  {
    const auto& shard = placements.value()[0].shards[0];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(1024, 0x3c);
    auto raw = transport::make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 64, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  }
  const auto victim = placements.value()[1].shards[0].worker_id;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if ("worker-" + std::to_string(i) == victim) cluster.kill_worker(i);
  }

  // Repair runs, finds its only source corrupt, and refuses.
  BT_EXPECT(eventually([&] {
    auto p = client->get_workers("crc/repair");
    return p.ok() && p.value().size() == 1;  // dead copy pruned, no top-up
  }));
  std::this_thread::sleep_for(std::chrono::milliseconds(200));  // let repair finish
  BT_EXPECT_EQ(cluster.keystone().counters().objects_repaired.load(), 0u);

  // The surviving copy is corrupt: reads DETECT it, never return garbage.
  auto back = client->get("crc/repair");
  BT_ASSERT(!back.ok());
  BT_EXPECT(back.error() == ErrorCode::CHECKSUM_MISMATCH);
}

BTEST(ErasureCoding, TierPressureDemotesCodedObjectsShardVerbatim) {
  // Coded objects demote too (they used to fall back to delete-eviction):
  // every shard — parity included — moves verbatim into the lower tier
  // with the geometry and copy CRC intact, and reads keep verifying.
  EmbeddedClusterOptions options;
  options.keystone.gc_interval_sec = 60;
  options.keystone.health_check_interval_sec = 3600;  // driven manually
  options.keystone.high_watermark = 0.5;
  options.keystone.eviction_ratio = 0.2;
  for (int i = 0; i < 3; ++i) {
    worker::WorkerServiceConfig w;
    w.worker_id = "ecd-" + std::to_string(i);
    w.transport = TransportKind::LOCAL;
    w.heartbeat_interval_ms = 100;
    w.heartbeat_ttl_ms = 60000;
    w.pools = {
        {"ram-" + std::to_string(i), StorageClass::RAM_CPU, 2 << 20, "", ""},
        {"cxl-" + std::to_string(i), StorageClass::CXL_MEMORY, 8 << 20, "", ""},
    };
    options.workers.push_back(w);
  }
  EmbeddedCluster cluster(options);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 2;
  cfg.ec_parity_shards = 1;
  cfg.preferred_classes = {StorageClass::RAM_CPU};
  auto data = pattern(2 << 20, 83);  // shards of 1 MiB: 3 MiB on 6 MiB of RAM
  BT_ASSERT(client->put("ecd/obj", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto second = pattern(1 << 20, 84);  // push RAM past the 50% watermark
  BT_ASSERT(client->put("ecd/filler", second.data(), second.size(), cfg) == ErrorCode::OK);

  cluster.keystone().run_health_check_once();
  BT_EXPECT(eventually([&] {
    return cluster.keystone().counters().objects_demoted.load() >= 1;
  }));

  // The demoted coded object: same (k, m), every shard in the lower tier,
  // CRC preserved, bytes identical (reads verify).
  bool found_demoted = false;
  for (const char* key : {"ecd/obj", "ecd/filler"}) {
    auto p = client->get_workers(key);
    BT_ASSERT_OK(p);
    const auto& copy = p.value()[0];
    BT_EXPECT_EQ(copy.ec_data_shards, 2u);
    BT_EXPECT(copy.content_crc != 0u);
    bool all_lower = !copy.shards.empty();
    for (const auto& s : copy.shards) all_lower &= s.storage_class == StorageClass::CXL_MEMORY;
    if (all_lower) found_demoted = true;
    auto back = client->get(key);
    BT_ASSERT_OK(back);
    BT_EXPECT(back.value() == (std::string(key) == "ecd/obj" ? data : second));
  }
  BT_EXPECT(found_demoted);
}

BTEST(EndToEnd, DurableClusterRestartServesAckedInlineObjects) {
  // The embedded half of the crash-durability story (tier-1 pytest mirrors
  // it from Python): acked inline puts round-trip a FULL cluster restart on
  // the same persist dir bit-exact, acked removes stay removed, and the
  // accounting comes back consistent. RAM-placed bytes die with the process
  // by design — this is exactly why the chaos/crash harnesses drive the
  // inline tier.
  char tmpl[] = "/tmp/btpu-e2e-durable-XXXXXX";
  const std::string dir = mkdtemp(tmpl);
  auto options = EmbeddedClusterOptions::simple(2, 8 << 20);
  options.durability.dir = dir;
  options.durability.group_commit_us = 200;

  std::map<std::string, std::vector<uint8_t>> acked;
  {
    EmbeddedCluster cluster(options);
    BT_ASSERT(cluster.start() == ErrorCode::OK);
    auto client = cluster.make_client();
    WorkerConfig wc;
    wc.replication_factor = 1;  // inline tier refuses multi-replica intent
    wc.ttl_ms = 0;
    std::mt19937_64 rng(7);
    for (int i = 0; i < 24; ++i) {
      const std::string key = "durable/" + std::to_string(i);
      std::vector<uint8_t> data(64 + rng() % 1500);
      for (auto& b : data) b = static_cast<uint8_t>(rng());
      BT_ASSERT(client->put(key, data.data(), data.size(), wc) == ErrorCode::OK);
      acked[key] = std::move(data);
    }
    for (int i = 0; i < 24; i += 4) {  // acked removes must stay removed
      const std::string key = "durable/" + std::to_string(i);
      BT_ASSERT(client->remove(key) == ErrorCode::OK);
      acked.erase(key);
    }
    cluster.stop();
  }
  {
    EmbeddedCluster revived(options);
    BT_ASSERT(revived.start() == ErrorCode::OK);
    auto client = revived.make_client();
    for (const auto& [key, data] : acked) {
      auto got = client->get(key, /*verify=*/true);
      BT_ASSERT_OK(got);
      BT_EXPECT(got.value() == data);
    }
    for (int i = 0; i < 24; i += 4) {
      BT_EXPECT(client->get("durable/" + std::to_string(i)).error() ==
                ErrorCode::OBJECT_NOT_FOUND);
    }
    auto stats = revived.keystone().get_cluster_stats();
    BT_ASSERT_OK(stats);
    BT_EXPECT_EQ(stats.value().total_objects, acked.size());
    BT_EXPECT_EQ(revived.keystone().persist_retry_backlog(), size_t{0});
    revived.stop();
  }
  std::filesystem::remove_all(dir);
}
