// bb-soak: bounded randomized chaos soak (VERDICT r4 item 8).
//
// Concurrent put/get/remove writers against an embedded cluster while a
// chaos thread kills and revives workers, runs scrub passes, and drains —
// the single-fault e2e tests' scenarios composed at random, under time
// pressure. Exit 0 requires the end-state invariants:
//   * every object the writers successfully put (and did not remove) reads
//     back byte-correct — with replication 2 and at most one worker down
//     at a time, nothing may be lost (objects_lost == 0);
//   * keystone accounting is consistent: total_objects matches the
//     writers' live-set size.
// Intended to run under TSan (build-tsan/bb-soak): the clean run is the
// data-race check the single-shot tests cannot give.
//
// --kill9 swaps the in-process worker chaos for PROCESS-death chaos: a
// single-threaded parent forks a child cluster (keystone + coordinator +
// workers in one process, durable coordinator dir), SIGKILLs it mid-traffic
// at random moments, restarts a fresh child on the SAME dir, and repeats;
// the final cycle runs the recovery invariant checker (chaos_common.h —
// zero acked-object loss, no fabricated state, clean accounting). This is
// the kill -9 half of ROADMAP item 5's "no lost acked objects under chaos".
#include <fcntl.h>
#include <poll.h>
#include <sys/resource.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>

#include "btpu/client/embedded.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/net/net.h"
#include "fanin_pump.h"
#include "chaos_common.h"
#include "tsan_clockwait_shim.h"
#include "tsan_rma_suppression.h"

using namespace btpu;
using Clock = std::chrono::steady_clock;

namespace {

// Deterministic per-key payload: verification needs no stored bytes.
std::vector<uint8_t> pattern_for(const std::string& key, uint64_t size) {
  std::vector<uint8_t> data(size);
  uint64_t h = fnv1a64(key);
  for (uint64_t i = 0; i < size; ++i) {
    h = h * 6364136223846793005ull + 1442695040888963407ull;
    data[i] = static_cast<uint8_t>(h >> 56);
  }
  return data;
}

struct LiveSet {
  btpu::Mutex mutex;
  std::unordered_map<std::string, uint64_t> sizes BTPU_GUARDED_BY(mutex);  // key -> size
  uint64_t bytes BTPU_GUARDED_BY(mutex){0};

  void add(const std::string& key, uint64_t size) {
    btpu::MutexLock lock(mutex);
    sizes[key] = size;
    bytes += size;
  }
  uint64_t total_bytes() {
    btpu::MutexLock lock(mutex);
    return bytes;
  }
  bool take_random(std::mt19937_64& rng, std::string& key, uint64_t& size, bool erase) {
    btpu::MutexLock lock(mutex);
    if (sizes.empty()) return false;
    auto it = sizes.begin();
    std::advance(it, std::uniform_int_distribution<size_t>(0, sizes.size() - 1)(rng));
    key = it->first;
    size = it->second;
    if (erase) {
      bytes -= it->second;
      sizes.erase(it);
    }
    return true;
  }
  size_t count() {
    btpu::MutexLock lock(mutex);
    return sizes.size();
  }
  std::vector<std::pair<std::string, uint64_t>> snapshot() {
    btpu::MutexLock lock(mutex);
    return {sizes.begin(), sizes.end()};
  }
};

}  // namespace

// ---- kill -9 chaos (process-death durability soak) -------------------------
//
// Parent stays single-threaded (fork-safe under tsan); each cycle's child
// runs the whole cluster over the shared durable dir and dies by SIGKILL at
// a random moment mid-traffic. The final child replays the oracle and runs
// the recovery invariant checker.
namespace {

client::EmbeddedClusterOptions kill9_options(const std::string& dir) {
  auto options = client::EmbeddedClusterOptions::simple(2, 32ull << 20);
  options.durability.dir = dir;
  options.durability.compact_every = 64;  // several compactions per cycle
  return options;
}

[[noreturn]] void kill9_traffic_child(const std::string& dir, uint64_t cycle, uint64_t seed) {
  client::EmbeddedCluster cluster(kill9_options(dir));
  if (cluster.start() != ErrorCode::OK) {
    std::fprintf(stderr, "soak: kill9 child cluster start failed (cycle %llu)\n",
                 (unsigned long long)cycle);
    ::_exit(3);
  }
  // Effectively unbounded: the parent's SIGKILL ends this child.
  chaos::run_traffic(cluster, dir, cycle, /*threads=*/2, /*ops_per_thread=*/1'000'000,
                     /*max_seconds=*/3600, seed + cycle);
  cluster.stop();
  ::_exit(0);
}

[[noreturn]] void kill9_verify_child(const std::string& dir) {
  client::EmbeddedCluster cluster(kill9_options(dir));
  if (cluster.start() != ErrorCode::OK) {
    std::fprintf(stderr, "soak: RECOVERY REFUSED after kill -9 chaos\n");
    ::_exit(2);
  }
  const bool ok = chaos::check_recovery(cluster, dir);
  cluster.stop();
  ::_exit(ok ? 0 : 1);
}

int run_kill9(int seconds, uint64_t seed, std::string dir) {
  if (dir.empty()) dir = "/tmp/bb-soak-kill9." + std::to_string(::getpid());
  std::error_code fs_ec;
  std::filesystem::remove_all(dir, fs_ec);
  std::filesystem::create_directories(dir, fs_ec);
  std::printf("soak: kill9 mode, durable dir %s\n", dir.c_str());

  std::mt19937_64 rng(seed);
  const auto deadline = Clock::now() + std::chrono::seconds(seconds);
  uint64_t cycle = 0;
  int kills = 0;
  while (Clock::now() < deadline) {
    ++cycle;
    const pid_t pid = ::fork();
    if (pid == 0) kill9_traffic_child(dir, cycle, seed);
    if (pid < 0) {
      std::fprintf(stderr, "soak: fork failed (errno %d)\n", errno);
      return 1;
    }
    // Let traffic flow (long enough to span compactions and group-commit
    // windows), then kill -9 mid-flight.
    std::this_thread::sleep_for(std::chrono::milliseconds(400 + rng() % 1600));
    ::kill(pid, SIGKILL);
    int status = 0;
    ::waitpid(pid, &status, 0);
    if (WIFSIGNALED(status) && WTERMSIG(status) == SIGKILL) {
      ++kills;
    } else if (!WIFEXITED(status) || WEXITSTATUS(status) != 0) {
      // Exited 0 = finished its op budget before the kill (fine); anything
      // else means the cluster could not even run on the recovered dir.
      std::fprintf(stderr, "soak: kill9 child died wrong (status %d)\n", status);
      return 1;
    }
  }
  const pid_t vpid = ::fork();
  if (vpid == 0) kill9_verify_child(dir);
  int status = 0;
  ::waitpid(vpid, &status, 0);
  const bool verified = WIFEXITED(status) && WEXITSTATUS(status) == 0;
  std::printf("soak: kill9 %llu cycles, %d SIGKILLs, recovery check %s\n",
              (unsigned long long)cycle, kills, verified ? "OK" : "FAILED");
  if (!verified || kills == 0) {
    std::fprintf(stderr, "soak FAILED\n");
    return 1;
  }
  std::filesystem::remove_all(dir, fs_ec);
  std::printf("soak OK\n");
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  int seconds = 60;
  uint64_t seed = 42;
  bool slow_worker = false;
  bool kill9 = false;
  size_t fanin = 0;
  std::string kill9_dir;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--seconds") && i + 1 < argc) seconds = std::stoi(argv[++i]);
    else if (!std::strcmp(argv[i], "--seed") && i + 1 < argc) seed = std::stoull(argv[++i]);
    else if (!std::strcmp(argv[i], "--slow-worker")) slow_worker = true;
    else if (!std::strcmp(argv[i], "--kill9")) kill9 = true;
    else if (!std::strcmp(argv[i], "--fanin") && i + 1 < argc)
      fanin = static_cast<size_t>(std::stoull(argv[++i]));
    else if (!std::strcmp(argv[i], "--dir") && i + 1 < argc) kill9_dir = argv[++i];
    else if (!std::strcmp(argv[i], "--help")) {
      std::printf("usage: bb-soak [--seconds N] [--seed S] [--slow-worker]\n"
                  "               [--kill9 [--dir D]] [--fanin N]\n"
                  "  --kill9  process-death chaos: SIGKILL + restart the cluster\n"
                  "           process on a durable dir mid-traffic; end-state runs\n"
                  "           the recovery invariant checker (no lost acked objects)\n"
                  "  --fanin  N concurrent raw data-plane connections held against\n"
                  "           worker 0 (TCP wire mode) WHILE the kill/revive chaos\n"
                  "           runs; the fleet dies with each kill and rebuilds\n"
                  "           against the revived worker's fresh endpoint\n");
      return 0;
    }
  }
  // kill9 forks its children BEFORE any thread exists in this process (the
  // embedded cluster would start threads), so it must run first.
  if (kill9) return run_kill9(seconds, seed, kill9_dir);

  auto options = client::EmbeddedClusterOptions::simple(4, 64ull << 20);
  options.keystone.scrub_interval_sec = 3600;  // driven by the chaos thread
  options.keystone.scrub_objects_per_pass = 8;
  if (fanin > 0) {
    // Fan-in needs a REAL socket data plane to pile connections onto, the
    // admission gate opened to one-op-per-connection width (no overwrite
    // if the operator pinned their own), and the fd budget for N sockets
    // on top of the cluster's own.
    for (auto& w : options.workers) {
      w.transport = TransportKind::TCP;
      w.listen_host = "127.0.0.1";
    }
    ::setenv("BTPU_DATA_MAX_INFLIGHT_OPS", "16384", 0);
    ::setenv("BTPU_DATA_MAX_QUEUE", "16384", 0);
    ::setenv("BTPU_DATA_MAX_INFLIGHT_BYTES", "8589934592", 0);
    rlimit lim{};
    if (::getrlimit(RLIMIT_NOFILE, &lim) == 0 && lim.rlim_cur < lim.rlim_max) {
      lim.rlim_cur = lim.rlim_max;
      (void)::setrlimit(RLIMIT_NOFILE, &lim);
    }
  }
  client::EmbeddedCluster cluster(std::move(options));
  if (cluster.start() != ErrorCode::OK) {
    std::fprintf(stderr, "soak: cluster start failed\n");
    return 1;
  }

  // --slow-worker chaos mode: instead of killing workers, worker 0's
  // endpoint gets RANDOM LATENCY SPIKES (the tail-at-scale failure mode —
  // a node that is alive but 50x slow). Writer clients read through a
  // latency-injecting transport whose per-op delay follows this dial, so
  // the chaos thread can spike and clear it mid-run without swapping
  // transports under I/O; hedged reads + replica failover must keep every
  // invariant (byte-correct live set, zero losses) intact regardless.
  auto slow_dial = std::make_shared<std::atomic<uint32_t>>(0);
  std::string slow_endpoint;
  if (slow_worker) {
    auto pools = cluster.worker(0).pools();
    if (pools.empty() || pools.front().remote.endpoint.empty()) {
      std::fprintf(stderr, "soak: --slow-worker found no endpoint to slow\n");
      return 1;
    }
    slow_endpoint = pools.front().remote.endpoint;
    std::printf("soak: slow-worker mode, spiking %s\n", slow_endpoint.c_str());
  }

  const auto deadline = Clock::now() + std::chrono::seconds(seconds);
  std::atomic<bool> stop{false};
  std::atomic<bool> failed{false};
  // Serializes worker OBJECT lifecycle (chaos kill/revive swap the
  // unique_ptr) against the fan-in driver's raw endpoint resolution
  // (worker_alive + pools() dereference that object). Held only across
  // the pointer-touching calls, never across the chaos sleeps. Clients go
  // through the keystone and need no such gate — this is the price of the
  // driver reading the worker object directly instead of the control
  // plane.
  Mutex worker_gate;
  std::atomic<uint64_t> puts{0}, gets{0}, removes{0}, verify_fails{0}, put_fails{0};
  LiveSet live;

  auto fail = [&](const char* what, const std::string& detail) {
    std::fprintf(stderr, "soak FAILURE: %s (%s)\n", what, detail.c_str());
    failed.store(true);
    stop.store(true);
  };

  // Writers: puts use replication 2 so ONE dead worker can never lose
  // bytes; sizes cross the inline (<=4KiB) and placed regimes. Slot churn:
  // rf=1 would engage slots only for remote clients, so the slot machinery
  // is exercised separately by the e2e suite — this soak drives the
  // embedded surface (direct keystone calls, the TSan-interesting one).
  std::vector<std::thread> writers;
  for (int w = 0; w < 2; ++w) {
    writers.emplace_back([&, w] {
      client::ClientOptions copts;
      // Slow-worker mode reads hedge aggressively: a spiked replica must
      // not gate a read that replication already paid to duplicate.
      if (slow_worker) copts.hedge_delay_ms = 20;
      auto client = cluster.make_client(copts);
      if (slow_worker) {
        transport::FaultSpec spec;
        spec.latency_endpoint = slow_endpoint;
        spec.latency_override_ms = slow_dial;
        client->inject_data_client_for_test(transport::make_faulty_transport_client(
            transport::make_transport_client(), spec));
      }
      std::mt19937_64 rng(seed * 977 + static_cast<uint64_t>(w));
      WorkerConfig wc;
      wc.replication_factor = 2;
      wc.max_workers_per_copy = 1;
      uint64_t counter = 0;
      const uint64_t size_choices[] = {1 << 10, 4 << 10, 64 << 10, 256 << 10, 1 << 20};
      // Writer pressure stays well under the eviction watermark: the soak's
      // strict invariant is "nothing ever disappears", which watermark
      // eviction (a legal, tested behavior) would void. 4 workers x 64 MiB
      // x ~85% watermark / 2 replicas => cap the logical live set at
      // 64 MiB so even one worker down leaves comfortable headroom.
      constexpr uint64_t kLiveCap = 64ull << 20;
      while (!stop.load() && Clock::now() < deadline) {
        int op = static_cast<int>(rng() % 10);
        if (op < 5 && live.total_bytes() > kLiveCap) op = 9;  // shed instead
        if (op < 5) {  // put
          const uint64_t size = size_choices[rng() % 5];
          const std::string key =
              "soak/" + std::to_string(w) + "/" + std::to_string(counter++);
          auto data = pattern_for(key, size);
          auto ec = client->put(key, data.data(), size, wc);
          if (ec == ErrorCode::OK) {
            live.add(key, size);
            puts.fetch_add(1);
          } else {
            // Transient refusals (mid-kill capacity squeeze, leadership
            // churn) are legal; systemic failure shows as zero progress.
            put_fails.fetch_add(1);
          }
        } else if (op < 9) {  // verified get
          std::string key;
          uint64_t size = 0;
          if (!live.take_random(rng, key, size, /*erase=*/false)) continue;
          auto got = client->get(key, /*verify=*/true);
          if (got.ok()) {
            if (got.value() != pattern_for(key, size)) {
              fail("byte mismatch on live object", key);
              return;
            }
            gets.fetch_add(1);
          } else if (got.error() != ErrorCode::OBJECT_NOT_FOUND) {
            // Reads may fail transiently mid-kill (dead replica, repair in
            // flight) — that is the point of replica failover, so a failed
            // read of a LIVE key is only fatal at the end-state check.
            // NOT_FOUND means a concurrent remove won the race: fine.
          }
        } else {  // remove
          std::string key;
          uint64_t size = 0;
          if (!live.take_random(rng, key, size, /*erase=*/true)) continue;
          if (client->remove(key) == ErrorCode::OK) removes.fetch_add(1);
        }
      }
    });
  }

  // Chaos: at most one worker down at any moment (replication 2 tolerates
  // exactly that); every cycle also drives a scrub pass. Occasionally a
  // live worker is DRAINED (graceful evacuation) and then revived as a
  // fresh worker under the same id.
  std::thread chaos([&] {
    std::mt19937_64 rng(seed);
    auto client = cluster.make_client();
    if (slow_worker) {
      // Latency-spike chaos: spike worker 0's endpoint to 25-250ms per op
      // (vs ~us-scale healthy local ops — well past 50x median), hold the
      // spike for a while, clear it, repeat; a scrub pass rides along
      // sometimes. No kills in this mode: the point is SLOWNESS, with
      // every worker nominally alive the whole time.
      while (!stop.load() && Clock::now() < deadline) {
        std::this_thread::sleep_for(std::chrono::milliseconds(300 + rng() % 700));
        if (stop.load() || Clock::now() >= deadline) break;
        slow_dial->store(static_cast<uint32_t>(25 + rng() % 226));
        std::this_thread::sleep_for(std::chrono::milliseconds(500 + rng() % 1500));
        slow_dial->store(0);
        if (rng() % 4 == 0) cluster.keystone().run_scrub_once();
      }
      return;
    }
    while (!stop.load() && Clock::now() < deadline) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1500 + rng() % 2000));
      if (stop.load() || Clock::now() >= deadline) break;
      const size_t victim = rng() % cluster.worker_count();
      const int action = static_cast<int>(rng() % 3);
      auto gated_alive = [&](size_t i) {
        MutexLock lock(worker_gate);
        return cluster.worker_alive(i);
      };
      auto gated_kill = [&](size_t i) {
        MutexLock lock(worker_gate);
        cluster.kill_worker(i);
      };
      auto gated_revive = [&](size_t i) {
        MutexLock lock(worker_gate);
        return cluster.revive_worker(i);
      };
      if (action == 0 && gated_alive(victim)) {
        gated_kill(victim);
        // Give failure detection + repair a beat, then bring it back.
        std::this_thread::sleep_for(std::chrono::milliseconds(2500));
        if (gated_revive(victim) != ErrorCode::OK) {
          fail("revive failed", "worker " + std::to_string(victim));
          return;
        }
      } else if (action == 1 && gated_alive(victim)) {
        // Graceful drain, then return the capacity as a fresh worker.
        (void)client->drain_worker("worker-" + std::to_string(victim));
        gated_kill(victim);  // drop the retired instance
        std::this_thread::sleep_for(std::chrono::milliseconds(500));
        if (gated_revive(victim) != ErrorCode::OK) {
          fail("revive after drain failed", "worker " + std::to_string(victim));
          return;
        }
      } else {
        cluster.keystone().run_scrub_once();
      }
    }
  });

  // --fanin N: one driver thread holds N concurrent raw data-plane
  // connections against worker 0 (the engine multiplexes them on its event
  // loops; the thread fallback pays a thread each — both must survive the
  // chaos). Every kill of worker 0 collapses the whole fleet at once —
  // a mass-EOF wave through the serving engine — and the revived worker
  // comes back on a FRESH endpoint the driver re-resolves, so the engine's
  // accept path also sees N-connection reconnect storms. Reads are raw
  // kOpRead ops against the pool region: bounds-valid, content-agnostic
  // (the writers own byte correctness; this thread owns fan-in pressure).
  std::atomic<uint64_t> fanin_ops{0};
  std::atomic<size_t> fanin_peak{0};
  std::atomic<uint64_t> fanin_waves{0};
  std::thread fanin_thread;
  if (fanin > 0) {
    fanin_thread = std::thread([&] {
      constexpr uint64_t kOpLen = 512;
      while (!stop.load() && Clock::now() < deadline) {
        // Snapshot the endpoint under the gate: the chaos thread swaps the
        // worker object under kill/revive, and the descriptor must be
        // COPIED out before the lock drops (the sockets below then live or
        // die on their own — a mid-pump kill just EOFs the fleet).
        RemoteDescriptor remote;
        uint64_t pool_size = 0;
        {
          MutexLock lock(worker_gate);
          if (cluster.worker_alive(0)) {
            auto pools = cluster.worker(0).pools();
            if (!pools.empty()) {
              remote = pools.front().remote;
              pool_size = pools.front().size;
            }
          }
        }
        if (remote.endpoint.empty() || pool_size <= kOpLen) {
          std::this_thread::sleep_for(std::chrono::milliseconds(50));
          continue;
        }
        auto hp = net::parse_host_port(remote.endpoint);
        if (!hp) {
          std::this_thread::sleep_for(std::chrono::milliseconds(50));
          continue;
        }
        const uint64_t rkey = std::stoull(remote.rkey_hex, nullptr, 16);
        auto cs = exe::fanin_connect(hp->host, hp->port, fanin,
                                     [&] { return stop.load(); });
        if (cs.empty()) {
          std::this_thread::sleep_for(std::chrono::milliseconds(100));
          continue;
        }
        if (cs.size() > fanin_peak.load()) fanin_peak.store(cs.size());
        fanin_waves.fetch_add(1);
        // Pump until the kill wave takes the fleet (majority dead — the
        // chaos working as intended) or time is up; then loop around and
        // rebuild against the revived worker's fresh endpoint.
        const size_t fleet = cs.size();
        const auto st = exe::fanin_pump(
            cs, remote.remote_base, rkey, pool_size, kOpLen,
            [&](const exe::FaninStats& s) {
              return stop.load() || Clock::now() >= deadline || s.dead > fleet / 2;
            });
        fanin_ops.fetch_add(st.completed);
      }
    });
  }

  for (auto& t : writers) t.join();
  stop.store(true);
  chaos.join();
  if (fanin_thread.joinable()) fanin_thread.join();

  // Settle: every worker alive, give repair/health a few beats to converge.
  // A revive failure here is a FAILED soak, not a shrug: the end-state
  // invariants assume full strength, and a cluster that cannot be restored
  // is exactly the regression this harness exists to catch.
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if (cluster.worker_alive(i)) continue;
    if (auto ec = cluster.revive_worker(i); ec != ErrorCode::OK) {
      fail("end-state revive failed",
           "worker " + std::to_string(i) + ": " + std::string(to_string(ec)));
    }
  }
  std::this_thread::sleep_for(std::chrono::seconds(3));

  // End-state invariants.
  auto client = cluster.make_client();
  uint64_t unreadable = 0;
  for (const auto& [key, size] : live.snapshot()) {
    auto got = client->get(key, /*verify=*/true);
    if (!got.ok()) {
      ++unreadable;
      std::fprintf(stderr, "soak: %s unreadable at end state: %s\n", key.c_str(),
                   std::string(to_string(got.error())).c_str());
      continue;
    }
    if (got.value() != pattern_for(key, size)) {
      ++verify_fails;
      std::fprintf(stderr, "soak: %s corrupt at end state\n", key.c_str());
    }
  }
  const auto& kc = cluster.keystone().counters();
  auto stats = cluster.keystone().get_cluster_stats();
  const uint64_t total_objects = stats.ok() ? stats.value().total_objects : 0;
  const uint64_t lost = kc.objects_lost.load();
  const bool accounting_ok = total_objects == live.count();

  std::printf(
      "soak: %llu puts (%llu refused), %llu verified gets, %llu removes, "
      "%llu repaired, %llu scrub-healed, %llu drained shards | end state: "
      "%zu live objects, %llu unreadable, %llu corrupt, %llu lost, "
      "keystone says %llu objects\n",
      (unsigned long long)puts.load(), (unsigned long long)put_fails.load(),
      (unsigned long long)gets.load(), (unsigned long long)removes.load(),
      (unsigned long long)kc.objects_repaired.load(),
      (unsigned long long)kc.scrub_healed.load(),
      (unsigned long long)kc.shards_drained.load(), live.count(),
      (unsigned long long)unreadable, (unsigned long long)verify_fails.load(),
      (unsigned long long)lost, (unsigned long long)total_objects);

  if (fanin > 0) {
    std::printf("soak fanin: target %zu conns, peak %zu, %llu ops over %llu waves\n",
                fanin, fanin_peak.load(), (unsigned long long)fanin_ops.load(),
                (unsigned long long)fanin_waves.load());
    // The fleet must actually have stood up (90% slack for mid-kill
    // connect windows and fd squeeze) and completed ops — a soak where the
    // fan-in never materialized proves nothing about the engine.
    if (fanin_peak.load() < fanin - fanin / 10 || fanin_ops.load() == 0) {
      std::fprintf(stderr, "soak FAILED: fan-in fleet never reached target\n");
      return 1;
    }
  }
  if (failed.load() || unreadable || verify_fails.load() || lost || !accounting_ok) {
    std::fprintf(stderr, "soak FAILED\n");
    return 1;
  }
  if (puts.load() == 0 || gets.load() == 0) {
    std::fprintf(stderr, "soak made no progress\n");
    return 1;
  }
  std::printf("soak OK\n");
  return 0;
}
