"""ICI data plane on a virtual 8-device mesh: striped put/get, collectives,
ring replication, checksum agreement."""

import jax
import numpy as np
import pytest

from blackbird_tpu.ops import checksum_u32
from blackbird_tpu.ops.checksum import checksum_bytes
from blackbird_tpu.parallel import ShardedPool, make_mesh
from typing import Any, Generator


@pytest.fixture(scope="module")
def mesh() -> Any:
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


def test_striped_put_get_roundtrip(mesh: Any) -> None:
    pool = ShardedPool(mesh, pool_elems_per_worker=4096)
    rng = np.random.default_rng(0)
    obj = rng.integers(0, 2**32, size=10_000, dtype=np.uint32)
    pool.put("obj", obj)
    back = pool.get("obj", n_elems=obj.size)
    np.testing.assert_array_equal(obj, back)

    # A second object lands at a different offset and both survive.
    obj2 = rng.integers(0, 2**32, size=3_333, dtype=np.uint32)
    pool.put("obj2", obj2)
    np.testing.assert_array_equal(pool.get("obj2", n_elems=obj2.size), obj2)
    np.testing.assert_array_equal(pool.get("obj", n_elems=obj.size), obj)


def test_checksum_agreement_via_psum(mesh: Any) -> None:
    pool = ShardedPool(mesh, pool_elems_per_worker=2048)
    obj = np.arange(8_000, dtype=np.uint32)
    pool.put("sum", obj)
    expected = int(np.sum(obj, dtype=np.uint64) % (1 << 32))
    assert pool.checksum("sum") == expected


def test_ring_replication_recovers_any_single_loss(mesh: Any) -> None:
    pool = ShardedPool(mesh, pool_elems_per_worker=2048)
    obj = np.arange(4_096, dtype=np.uint32)
    pool.put("r", obj)
    replica = pool.ring_replicate("r")

    # The replica's gather is a rotation of the original shards: worker i now
    # holds shard (i+1) mod n, so together both extents cover every shard
    # twice across distinct devices.
    orig = pool.get("r")
    rot = pool.get(replica)
    shard = orig.size // 8
    orig_shards = orig.reshape(8, shard)
    rot_shards = rot.reshape(8, shard)
    np.testing.assert_array_equal(np.roll(orig_shards, -1, axis=0), rot_shards)


def test_pool_capacity_enforced(mesh: Any) -> None:
    pool = ShardedPool(mesh, pool_elems_per_worker=128)
    pool.put("a", np.zeros(8 * 128, dtype=np.uint32))
    with pytest.raises(MemoryError):
        pool.put("b", np.zeros(8, dtype=np.uint32))
    with pytest.raises(KeyError):
        pool.put("a", np.zeros(8, dtype=np.uint32))


def test_checksum_kernel_matches_host() -> None:
    data = np.random.default_rng(5).integers(0, 2**32, size=5_000, dtype=np.uint32)
    host = int(np.sum(data, dtype=np.uint64) % (1 << 32))
    assert int(checksum_u32(jax.numpy.asarray(data))) == host
    # pallas path (interpret mode on cpu)
    assert int(checksum_u32(jax.numpy.asarray(data), use_pallas=True, interpret=True)) == host
    # byte-level helper agrees
    assert checksum_bytes(data.tobytes()) == host


def test_sharded_put_get_jit_compiles_once(mesh: Any) -> None:
    # Same shapes -> no retrace (guards against accidental dynamic shapes).
    pool = ShardedPool(mesh, pool_elems_per_worker=1024)
    obj = np.ones(1024, dtype=np.uint32)
    pool.put("x", obj)
    before = pool.get("x", n_elems=obj.size)
    obj2 = np.full(1024, 7, dtype=np.uint32)
    pool.put("y", obj2)  # same shard shape: cache hit
    np.testing.assert_array_equal(pool.get("y", n_elems=obj2.size), obj2)
    np.testing.assert_array_equal(before, np.ones(1024, dtype=np.uint32))


# ---- keystone mode: one namespace with the native store (VERDICT r1 #3) ----


@pytest.fixture()
def ici_cluster() -> Generator[Any, None, None]:
    from blackbird_tpu import EmbeddedCluster, StorageClass
    from blackbird_tpu.hbm import JaxHbmProvider
    from blackbird_tpu.native import TransportKind

    provider = JaxHbmProvider(page_bytes=64 * 1024).register()
    try:
        with EmbeddedCluster(workers=8, pool_bytes=8 << 20,
                             storage_class=StorageClass.HBM_TPU,
                             transport=TransportKind.ICI) as cluster:
            yield cluster, provider
    finally:
        JaxHbmProvider.unregister()


def test_keystone_mode_shares_namespace_with_native_client(mesh: Any, ici_cluster: Any) -> None:
    cluster, _provider = ici_cluster
    pool = ShardedPool(mesh, pool_elems_per_worker=1 << 20, cluster=cluster)
    obj = np.random.default_rng(1).integers(0, 2**32, size=200_000, dtype=np.uint32)
    pool.put("shared/obj", obj)

    # The native client sees the same object: same key, same bytes.
    native_client = cluster.client()
    assert native_client.exists("shared/obj")
    assert native_client.get("shared/obj") == obj.tobytes()

    # And keystone counts it in cluster stats (metadata, not a shadow world).
    stats = native_client.stats()
    assert stats["objects"] == 1
    assert stats["used"] >= obj.nbytes

    # The reverse direction holds too: native puts are pool-readable.
    native_client.put("shared/rev", np.arange(64, dtype=np.uint32).view(np.uint8))
    np.testing.assert_array_equal(
        pool.get("shared/rev"), np.arange(64, dtype=np.uint32))

    pool.remove("shared/obj")
    assert not native_client.exists("shared/obj")


def test_keystone_mode_replicated_object_survives_worker_death(mesh: Any, ici_cluster: Any) -> None:
    import time

    cluster, provider = ici_cluster
    pool = ShardedPool(mesh, pool_elems_per_worker=1 << 20, cluster=cluster,
                       replicas=2)
    obj = np.random.default_rng(2).integers(0, 2**32, size=100_000, dtype=np.uint32)
    pool.put("ha/obj", obj)

    cluster.kill_worker(0)
    deadline = time.monotonic() + 10
    while (cluster.counters()["workers_lost"] < 1 and time.monotonic() < deadline):
        time.sleep(0.02)
    # Whether or not worker 0 held a shard, the object must stay readable —
    # keystone pruned/repaired placements, the pool just reads the key.
    np.testing.assert_array_equal(pool.get("ha/obj"), obj)
    expected = int(np.sum(obj, dtype=np.uint64) % (1 << 32))
    assert pool.checksum("ha/obj") == expected


def test_keystone_mode_rejects_mismatched_mesh(ici_cluster: Any) -> None:
    cluster, _provider = ici_cluster
    with pytest.raises(ValueError, match="one device pool per row"):
        ShardedPool(make_mesh(4), pool_elems_per_worker=1024, cluster=cluster)
