#!/usr/bin/env bash
# Brings up a full localhost cluster: coordination service -> keystone ->
# worker -> smoke test. (Role parity: reference scripts/start_cluster.sh,
# which launched etcd + keystone_example + worker_example + a smoke client.)
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
BUILD="$REPO_ROOT/build"
RUN_DIR="${BTPU_RUN_DIR:-/tmp/btpu-cluster}"
COORD_PORT="${BTPU_COORD_PORT:-9290}"
KEYSTONE_PORT="${BTPU_KEYSTONE_PORT:-9090}"

mkdir -p "$RUN_DIR"

if [[ ! -x "$BUILD/bb-coord" ]]; then
  echo "building native binaries..."
  cmake -B "$BUILD" -G Ninja >/dev/null
  ninja -C "$BUILD" >/dev/null
fi

cleanup() {
  echo "stopping cluster..."
  [[ -n "${WORKER_PID:-}" ]] && kill "$WORKER_PID" 2>/dev/null || true
  [[ -n "${KEYSTONE2_PID:-}" ]] && kill "$KEYSTONE2_PID" 2>/dev/null || true
  [[ -n "${KEYSTONE_PID:-}" ]] && kill "$KEYSTONE_PID" 2>/dev/null || true
  [[ -n "${COORD2_PID:-}" ]] && kill "$COORD2_PID" 2>/dev/null || true
  [[ -n "${COORD_PID:-}" ]] && kill "$COORD_PID" 2>/dev/null || true
}
trap cleanup EXIT INT TERM

# BTPU_HA=1 runs active/standby pairs of BOTH control services: a mirroring
# standby bb-coord (promotes on primary loss) and a standby keystone;
# clients and services get both endpoints of each.
HA="${BTPU_HA:-0}"
COORD2_PORT="${BTPU_COORD2_PORT:-9294}"

# Fresh durable state per bring-up (reference parity: start_cluster.sh gave
# etcd a fresh datadir) — a leftover WAL would resurrect the previous run's
# objects and registry into this "clean" cluster.
rm -rf "$RUN_DIR/coord-data"

echo "starting bb-coord on :$COORD_PORT"
"$BUILD/bb-coord" --host 127.0.0.1 --port "$COORD_PORT" \
  --data-dir "$RUN_DIR/coord-data" >"$RUN_DIR/coord.log" 2>&1 &
COORD_PID=$!
sleep 0.3

COORD_ENDPOINTS="127.0.0.1:$COORD_PORT"
if [[ "$HA" == "1" ]]; then
  echo "starting standby bb-coord on :$COORD2_PORT (following :$COORD_PORT)"
  "$BUILD/bb-coord" --host 127.0.0.1 --port "$COORD2_PORT" \
    --follow "127.0.0.1:$COORD_PORT" >"$RUN_DIR/coord2.log" 2>&1 &
  COORD2_PID=$!
  COORD_ENDPOINTS="$COORD_ENDPOINTS,127.0.0.1:$COORD2_PORT"
  sleep 0.3
fi
KEYSTONE2_PORT="${BTPU_KEYSTONE2_PORT:-9092}"
HA_FLAGS=()
[[ "$HA" == "1" ]] && HA_FLAGS=(--ha)

echo "starting bb-keystone on :$KEYSTONE_PORT"
"$BUILD/bb-keystone" --config "$REPO_ROOT/configs/keystone.yaml" \
  --coord "$COORD_ENDPOINTS" --listen "127.0.0.1:$KEYSTONE_PORT" \
  --service-id ks-primary ${HA_FLAGS[@]+"${HA_FLAGS[@]}"} \
  >"$RUN_DIR/keystone.log" 2>&1 &
KEYSTONE_PID=$!
sleep 0.5

CLIENT_ENDPOINTS="127.0.0.1:$KEYSTONE_PORT"
if [[ "$HA" == "1" ]]; then
  echo "starting standby bb-keystone on :$KEYSTONE2_PORT"
  "$BUILD/bb-keystone" --config "$REPO_ROOT/configs/keystone.yaml" \
    --coord "$COORD_ENDPOINTS" --listen "127.0.0.1:$KEYSTONE2_PORT" \
    --metrics-port 9093 --service-id ks-standby --ha \
    >"$RUN_DIR/keystone2.log" 2>&1 &
  KEYSTONE2_PID=$!
  CLIENT_ENDPOINTS="$CLIENT_ENDPOINTS,127.0.0.1:$KEYSTONE2_PORT"
  sleep 0.5
fi

echo "starting bb-worker"
"$BUILD/bb-worker" --config "$REPO_ROOT/configs/worker.yaml" \
  --coord "$COORD_ENDPOINTS" >"$RUN_DIR/worker.log" 2>&1 &
WORKER_PID=$!
sleep 0.7

echo "smoke test: put/get/verify through bb-client"
"$BUILD/bb-client" --keystone "$CLIENT_ENDPOINTS" put smoke/obj --size 1048576
"$BUILD/bb-client" --keystone "$CLIENT_ENDPOINTS" get smoke/obj --out "$RUN_DIR/smoke.bin"
"$BUILD/bb-client" --keystone "$CLIENT_ENDPOINTS" stats
"$BUILD/bb-client" --keystone "$CLIENT_ENDPOINTS" remove smoke/obj
echo "metrics scrape:"
curl -sf "http://127.0.0.1:9091/metrics" | head -5 || true

echo
echo "cluster up. PIDs: coord=$COORD_PID${COORD2_PID:+ coord-standby=$COORD2_PID} keystone=$KEYSTONE_PID${KEYSTONE2_PID:+ standby=$KEYSTONE2_PID} worker=$WORKER_PID"
echo "logs in $RUN_DIR. Ctrl-C to stop."
if [[ "${BTPU_CLUSTER_ONESHOT:-0}" == "1" ]]; then
  exit 0
fi
wait
