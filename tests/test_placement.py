"""Mesh-aware placement plane: pool-registry topology discovery, host-
affinity routing through keystone placement, and the typed
put_array/get_array surface with its host-locality scoreboard."""

from types import SimpleNamespace
from typing import Any, Generator

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from blackbird_tpu import EmbeddedCluster
from blackbird_tpu.parallel import make_mesh
from blackbird_tpu.placement import (PodPlacement, device_coord, get_array,
                                     put_array, remove_array)


@pytest.fixture()
def store() -> Generator[Any, None, None]:
    with EmbeddedCluster(workers=4, pool_bytes=32 << 20) as cluster:
        yield cluster.client()


def test_pools_lists_topology_and_live_occupancy(store: Any) -> None:
    pools = store.pools()
    assert len(pools) == 4
    assert [p["pool"] for p in pools] == sorted(p["pool"] for p in pools)
    for p in pools:
        assert p["worker"]
        assert p["capacity"] == 32 << 20
        assert p["slice"] == 0
        assert p["used"] == 0
    # Each embedded worker models one pod host.
    assert sorted(p["host"] for p in pools) == [0, 1, 2, 3]
    # `used` is the LIVE allocator view, not the static registry record:
    # a put must show up, its removal must free it again.
    store.put("plc/occ", b"\xab" * 8192)
    assert sum(p["used"] for p in store.pools()) >= 8192
    store.remove("plc/occ")
    assert sum(p["used"] for p in store.pools()) == 0


def test_put_array_roundtrip_reshard_and_remove(store: Any) -> None:
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    # Shards must clear the 4 KiB inline tier (an inline object lives in
    # keystone metadata, placing no bytes on any worker to score).
    arr = jax.device_put(
        np.arange(8 * 64 * 32, dtype=np.float32).reshape(8 * 64, 32), sharding)
    placement = PodPlacement(store)
    put_array(store, "plc/arr", arr, placement=placement)
    # Every byte this process placed was scored (one host: all host-local).
    assert placement.host_local_bytes == arr.nbytes
    assert placement.cross_host_bytes == 0

    same = get_array(store, "plc/arr", sharding=sharding, placement=placement)
    np.testing.assert_array_equal(np.asarray(same), np.asarray(arr))
    other = get_array(store, "plc/arr",
                      sharding=NamedSharding(make_mesh(4), P(None, "workers")))
    np.testing.assert_array_equal(np.asarray(other), np.asarray(arr))
    np.testing.assert_array_equal(get_array(store, "plc/arr"), np.asarray(arr))

    remove_array(store, "plc/arr")
    assert store.list("plc/arr") == []


def test_put_validates_host_affinity_arguments(store: Any) -> None:
    with pytest.raises(ValueError, match="requires preferred_slice"):
        store.put("plc/bad", b"x", preferred_host=0)
    with pytest.raises(ValueError, match="incompatible with ec"):
        store.put("plc/bad", b"x", ec=(2, 1), preferred_slice=0,
                  preferred_host=0)


def test_host_affinity_routes_to_host_local_worker() -> None:
    """End-to-end keystone placement: two workers on the same slice but
    different pod hosts; a put hinted at (slice 0, host h) must land on
    host h's worker — the shard-local placement lane — and the placement
    plane must discover exactly that topology from the pool registry."""
    from blackbird_tpu.procluster import ProcessCluster

    with ProcessCluster(workers=2, devices_per_worker=0, pool_mb=0,
                        dram_pool_mb=16) as cluster:
        client = cluster.wait_ready()
        placement = PodPlacement(client)
        assert placement.worker_coord == {"mc-0": (0, 0), "mc-1": (0, 1)}
        assert placement.hosts == {(0, 0), (0, 1)}

        for host in (0, 1):
            fake_device = SimpleNamespace(slice_index=0, process_index=host)
            hint = placement.hint_for(fake_device)
            assert hint == {"preferred_slice": 0, "preferred_host": host}
            key = f"plc/host{host}"
            client.put(key, b"\x5a" * 65536, **hint)  # > inline threshold
            workers = {s["worker"] for copy in client.placements(key)
                       for s in copy["shards"]}
            assert workers == {f"mc-{host}"}, workers
            # Scoreboard agrees: against the intended coordinate the bytes
            # are host-local, against the other host they are cross-host.
            placement.record(key, (0, host))
            placement.record(key, (0, 1 - host))
        assert placement.host_local_bytes == 2 * 65536
        assert placement.cross_host_bytes == 2 * 65536
        assert placement.counters()["host_local_shards"] == 2

        # A coordinate the registry has never seen degrades to a slice-only
        # hint (or none): never a blind preferred_host the allocator would
        # ignore anyway.
        assert placement.hint_for(
            SimpleNamespace(slice_index=0, process_index=7)
        ) == {"preferred_slice": 0}
        assert placement.hint_for(
            SimpleNamespace(slice_index=3, process_index=0)) == {}
        assert device_coord(SimpleNamespace(slice_index=None,
                                            process_index=None)) == (0, 0)
