"""Distributed, resumable checkpoint/restore for sharded JAX arrays.

Each device shard of a `jax.Array` is saved as its own object (saves
parallelize over the striped native data path and, multi-host, every host
writes only the shards it owns), under a MANIFEST-COMMITTED-LAST layout:

    <prefix>/attempt/<save_id>    claim marker, written FIRST (atomic: the
                                  store's put_start rejects existing keys,
                                  so concurrent savers get disjoint ids)
    <prefix>/data/<save_id>/<box> one object per distinct shard box
    <prefix>/manifest/<save_id>   global shape + dtype + shard keys,
                                  written LAST by exactly one process

A checkpoint exists if and only if its manifest does. Readers resolve the
HIGHEST committed manifest, so concurrent savers serialize by id: the last
committed manifest wins atomically, and a crashed or in-flight save — any
number of data shards without a manifest — is invisible to
`list_checkpoints`/`load_sharded` (the same committed-reads-only contract
the store applies to PENDING objects).

Resumability: a restarted save claims a FRESH id, but reuses committed
shard objects from the newest unfinished attempt with the same layout when
the bytes still match — proven by comparing the store's recorded content
crc32c (placements) against the local shard bytes via the native crc — and
references those keys directly in the new manifest. Only fully-written,
bit-verified shards are skipped; everything else is rewritten.

Placement: shard writes carry (slice, host) affinity hints from the
mesh-aware placement plane (`blackbird_tpu.placement.PodPlacement`), so
each shard's bytes land on the shard's own host's worker — zero cross-host
data movement when the save sharding matches the pod layout.

Restore is sharding-polymorphic: `load_sharded` rebuilds the array under
ANY target sharding — same mesh, fewer/more devices, or a different layout
— via `jax.make_array_from_callback`: each target device slice reads only
the stored shards it overlaps, so a host never materializes more than it
needs plus a bounded cache of source shards.

Role: the device-tier half of SURVEY §5 checkpoint/resume. The native
keystone already persists object *metadata* durably; this persists device
*bytes* — e.g. model weights sharded over a v5e slice checkpointed into
the DRAM/NVMe tiers and restored after a preemption onto a different
topology. Operational runbook: docs/OPERATIONS.md §checkpointing.
"""

from __future__ import annotations

import json
import time
from typing import TYPE_CHECKING, Any, Sequence

import numpy as np
import numpy.typing as npt

if TYPE_CHECKING:
    from blackbird_tpu.client import Client
    from blackbird_tpu.fabric import FabricClient
    from blackbird_tpu.placement import PodPlacement

_MANIFEST_DIR = "/manifest/"
_DATA_DIR = "/data/"
_ATTEMPT_DIR = "/attempt/"
# Pre-manifest layout (single meta object, read-modify-write overwrite):
# still readable, reclaimed by the first committed save over the prefix.
_LEGACY_META_SUFFIX = "/meta"
_LEGACY_SHARD_SUFFIX = "/shard/"


def _index_to_boxes(index: Sequence[slice]) -> list[list[int]]:
    """A shard index (tuple of slices) -> [[start, stop], ...] per dim."""
    boxes: list[list[int]] = []
    for sl in index:
        boxes.append([int(sl.start or 0), int(sl.stop) if sl.stop is not None else -1])
    return boxes


def _boxes_to_index(boxes: Sequence[Sequence[int]],
                    shape: Sequence[int]) -> tuple[slice, ...]:
    return tuple(
        slice(start, stop if stop >= 0 else dim)
        for (start, stop), dim in zip(boxes, shape)
    )


def _box_name(boxes: list[list[int]]) -> str:
    """Deterministic shard-key suffix derived from the index box."""
    return "x".join(f"{a}-{b}" for a, b in boxes) if boxes else "scalar"


def _save_id_str(save_id: int) -> str:
    # Zero-padded so lexicographic listing order == numeric order; parsing
    # stays numeric everywhere regardless.
    return f"{save_id:08d}"


def _ids_under(client: Client, prefix: str) -> list[int]:
    """Numeric save ids present under `<prefix>` (a /manifest/ or /attempt/
    directory prefix), ascending. Only COMMITTED objects are listed, which
    is exactly the visibility the id scheme wants."""
    ids = []
    for obj in client.list(prefix):
        tail = obj["key"][len(prefix):]
        if tail.isdigit():
            ids.append(int(tail))
    return sorted(ids)


def committed_save_id(client: Client, prefix: str) -> int | None:
    """Highest committed manifest id under `prefix` (None: no checkpoint).
    THE commit point: a save is visible exactly when its manifest is."""
    ids = _ids_under(client, prefix + _MANIFEST_DIR)
    return ids[-1] if ids else None


def read_manifest(client: Client, prefix: str) -> dict[str, Any]:
    """The committed manifest readers resolve: highest id wins. Falls back
    to the legacy single-meta layout for pre-manifest checkpoints."""
    sid = committed_save_id(client, prefix)
    if sid is not None:
        return dict(json.loads(bytes(client.get(
            prefix + _MANIFEST_DIR + _save_id_str(sid)))))
    return dict(json.loads(bytes(client.get(prefix + _LEGACY_META_SUFFIX))))


def _is_device_class(preferred_class: Any) -> bool:
    name = (preferred_class.name.lower() if hasattr(preferred_class, "name")
            else str(preferred_class or "")).lower()
    return name == "hbm_tpu"


def _class_name(preferred_class: Any) -> str:
    return (preferred_class.name.lower() if hasattr(preferred_class, "name")
            else str(preferred_class or ""))


def _already_exists(exc: Exception) -> bool:
    from blackbird_tpu.native import ErrorCode

    return getattr(exc, "code", None) == int(ErrorCode.OBJECT_ALREADY_EXISTS)


def _shard_plan(array: Any) -> tuple[list[dict[str, Any]], dict[str, Any], Any]:
    """Global layout from the sharding, identical on every host: per-box
    meta entries (name/boxes/shape, sorted by name so every process agrees
    on box ordinals), box -> owner device (lowest device id among the
    replicas of that box), and the meta/commit owner (lowest device id in
    the sharding). One writer per object, by construction."""
    index_map = array.sharding.devices_indices_map(array.shape)
    entries: dict[str, dict[str, Any]] = {}
    box_owner: dict[str, Any] = {}
    for device, index in index_map.items():
        boxes = _index_to_boxes(index)
        name = _box_name(boxes)
        if name not in entries:
            shape = [
                (b if b >= 0 else dim) - a for (a, b), dim in zip(boxes, array.shape)
            ]
            entries[name] = {"name": name, "boxes": boxes, "shape": shape}
        if name not in box_owner or device.id < box_owner[name].id:
            box_owner[name] = device
    plan = [entries[name] for name in sorted(entries)]
    return plan, box_owner, min(index_map, key=lambda d: d.id)


def _layout_fingerprint(array: Any, plan: list[dict[str, Any]],
                        ec: tuple[int, int] | None,
                        preferred_class: Any) -> str:
    """Identity of a save's layout: shard reuse across attempts is only
    safe between saves that would write byte-identical objects to the same
    box names with the same durability shape."""
    return json.dumps({
        "global_shape": list(array.shape),
        "dtype": np.dtype(array.dtype).str,
        "boxes": [s["name"] for s in plan],
        "ec": list(ec) if ec else None,
        "class": _class_name(preferred_class),
    }, sort_keys=True)


def _claim_attempt(client: Client, prefix: str, fingerprint: str) -> int:
    """Claims a fresh save id by atomically creating its attempt marker.

    put_start rejects existing keys, so two concurrent savers computing the
    same next id race on the marker put and the loser moves to id+1:
    attempts are disjoint WITHOUT any read-modify-write (this is the
    versioned-put fix for the old single-meta overwrite race — concurrent
    savers never touch each other's objects, and readers take the highest
    committed manifest)."""
    used = set(_ids_under(client, prefix + _MANIFEST_DIR))
    used.update(_ids_under(client, prefix + _ATTEMPT_DIR))
    sid = (max(used) + 1) if used else 1
    claim = json.dumps({"layout": fingerprint}).encode()
    while True:
        try:
            client.put(prefix + _ATTEMPT_DIR + _save_id_str(sid), claim,
                       replicas=1)
            return sid
        except Exception as exc:  # noqa: BLE001 - duck-typed client
            if not _already_exists(exc):
                raise
            sid += 1  # lost the race to a concurrent saver


def _resume_candidate(client: Client, prefix: str, my_sid: int,
                      fingerprint: str) -> int | None:
    """Newest UNFINISHED attempt whose layout matches ours: its committed
    shard objects are reuse candidates. Committed attempts are excluded
    (their data is a complete checkpoint, not a partial to salvage), as is
    anything at or above our own id (concurrent savers, not predecessors)."""
    committed = committed_save_id(client, prefix) or 0
    for sid in reversed(_ids_under(client, prefix + _ATTEMPT_DIR)):
        if sid >= my_sid or sid <= committed:
            continue
        try:
            claim = json.loads(bytes(client.get(
                prefix + _ATTEMPT_DIR + _save_id_str(sid))))
        except Exception:  # noqa: BLE001 - marker gone mid-scan
            continue
        if claim.get("layout") == fingerprint:
            return sid
    return None


def _stored_crc(client: Client, key: str) -> int | None:
    """content crc32c of a COMMITTED object (None: missing, pending, or
    stored without a crc — e.g. striped multi-worker copies on an old
    build). Placements of a PENDING object fail, which is exactly the
    partial-write filter the resume path needs."""
    try:
        copies = client.placements(key)
    except Exception:  # noqa: BLE001 - not found / pending
        return None
    for copy in copies:
        crc = copy.get("crc")
        if crc:
            return int(crc)
    return None


def _local_crc(data: npt.NDArray[Any]) -> int | None:
    """crc32c of the shard bytes via the native export (None: library too
    old — resume then rewrites instead of reusing, which is always safe)."""
    from blackbird_tpu import native
    from blackbird_tpu.native import lib

    if not native.have("btpu_crc32c"):
        return None
    import ctypes

    return int(lib.btpu_crc32c(
        data.ctypes.data_as(ctypes.c_void_p), data.nbytes, 0))


def _fabric_put(client: Client, fabric: FabricClient, key: str,
                shard_data: Any, kwargs: dict[str, Any]) -> bool:
    """Fabric leg of the checkpoint writer: True when the shard landed over
    the transfer fabric, False = use the staged byte path."""
    from blackbird_tpu.fabric import FabricUnavailable

    pc = kwargs.get("preferred_class")
    name = pc.name.lower() if hasattr(pc, "name") else (pc or "hbm_tpu")
    try:
        fabric.put(key, shard_data, replicas=kwargs.get("replicas", 1),
                   preferred_class=name)
        return True
    except FabricUnavailable:
        return False


def _sync_reuse_bits(reuse: npt.NDArray[np.int32], multi_process: bool) -> \
        npt.NDArray[np.int32]:
    """Agrees the per-box reuse decisions across the pod: each box owner
    knows only its OWN boxes' bits; the manifest writer needs all of them.
    Rides the jax.distributed runtime (max-reduce over the gathered bits) —
    also a barrier, so when it returns every process's synchronous shard
    puts have committed and the manifest can be written immediately."""
    if not multi_process:
        return reuse
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(reuse)
    return np.asarray(gathered).reshape(-1, reuse.size).max(axis=0)


def save_sharded(client: Client, prefix: str, array: Any, *, replicas: int = 1,
                 preferred_class: Any = None, ec: tuple[int, int] | None = None,
                 fabric: FabricClient | None = None,
                 placement: PodPlacement | None = None) -> int:
    """Saves `array` (sharded or single-device) under `prefix`; returns the
    committed save id.

    Layout and crash semantics are described at module level: claim marker
    first, one object per distinct shard box (replicated shards are
    deduplicated), manifest last. Every object has exactly ONE writer —
    each box is written by the process owning the lowest device id
    replicating it, the claim/manifest by the process owning the lowest
    device id overall; other processes never touch those keys, so no host
    trips on another's put. A save interrupted anywhere before the manifest
    put leaves nothing visible; rerunning it claims a fresh id and reuses
    the interrupted attempt's bit-verified shards (crc-compared against the
    local bytes) instead of rewriting them.

    With `fabric` (a `blackbird_tpu.FabricClient`), device-resident shard
    bytes move over the transfer fabric — this process offers each shard
    from its own runtime and the worker pulls it straight into device
    memory, no host staging (the production TPU checkpoint shape). Shards
    the fabric cannot take (no fabric endpoints, EC requested) fall back to
    the staged byte path transparently.

    With `placement` (default: discovered from the client's pool registry),
    each shard put carries the owning device's (slice, host) affinity hint,
    and the placement scoreboard records how many bytes stayed host-local.

    `ec=(k, m)` erasure-codes each shard object (any m worker losses at
    (k+m)/k overhead); the manifest and claim are stored at ec=(1, m) — the
    same loss tolerance for the metadata as for the data, via m+1
    single-shard copies on distinct workers. EC placements are anti-affine
    by design, so host-affinity hints are skipped.

    Per-shard save durations and placed workers land in the manifest
    (`shards[*].duration_ms` / `workers`): the slow-shard triage hooks —
    every shard put is its own traced op, so `bb-trace` around a slow
    shard's window shows where its bytes stalled (docs/OPERATIONS.md).
    """
    import jax  # local: keep module import-light for non-JAX users

    if not isinstance(array, jax.Array):
        array = jax.numpy.asarray(array)
    kwargs: dict[str, Any] = {"replicas": replicas}
    if ec is not None:
        # Checkpoints are the natural erasure-coding consumer: large, cold,
        # durability-critical. replicas is ignored by the store when ec is
        # set.
        k, m = ec
        if k < 1 or m < 1:
            raise ValueError(f"ec needs k >= 1 and m >= 1, got {ec}")
        kwargs["ec"] = ec
    if preferred_class is not None:
        kwargs["preferred_class"] = preferred_class
    my_process = jax.process_index()
    multi_process = jax.process_count() > 1

    plan, box_owner, meta_owner = _shard_plan(array)
    fingerprint = _layout_fingerprint(array, plan, ec, preferred_class)
    i_commit = meta_owner.process_index == my_process

    if placement is None and ec is None:
        from blackbird_tpu.placement import PodPlacement

        try:
            placement = PodPlacement(client)
        except Exception:  # noqa: BLE001 - registry listing unavailable
            placement = None

    # Claim the save id on the commit owner; the other processes learn it
    # through the distributed runtime (one tiny broadcast), never by
    # guessing from store listings that concurrent savers may be mutating.
    if i_commit:
        sid = _claim_attempt(client, prefix, fingerprint)
    else:
        sid = 0
    if multi_process:
        from jax.experimental import multihost_utils

        sid = int(multihost_utils.broadcast_one_to_all(
            np.int32(sid), is_source=i_commit))
    data_dir = f"{prefix}{_DATA_DIR}{_save_id_str(sid)}/"

    # Resume: the newest unfinished attempt with OUR layout donates its
    # bit-verified shards. The candidate is resolved once, under the fresh
    # claim, so every process sees the same predecessor.
    prior = _resume_candidate(client, prefix, sid, fingerprint)
    prior_dir = (f"{prefix}{_DATA_DIR}{_save_id_str(prior)}/"
                 if prior is not None else None)

    box_index = {s["name"]: i for i, s in enumerate(plan)}
    reuse = np.zeros(len(plan), dtype=np.int32)
    durations: dict[str, int] = {}
    for shard in array.addressable_shards:
        name = _box_name(_index_to_boxes(shard.index))
        if shard.device != box_owner[name]:
            continue  # another device/host owns this box
        host = np.ascontiguousarray(np.asarray(shard.data))
        flat = host.reshape(-1).view(np.uint8)
        if prior_dir is not None:
            stored = _stored_crc(client, prior_dir + name)
            if stored is not None and stored == _local_crc(flat):
                reuse[box_index[name]] = 1  # verified: reference, don't move
                continue
        key = data_dir + name
        started = time.monotonic()
        # Fabric attempt only for device-tier targets: a host-tier placement
        # can never carry fabric endpoints, and probing it would cost a
        # reserve+cancel keystone round trip per shard.
        if not (fabric is not None and ec is None
                and _is_device_class(preferred_class)
                and _fabric_put(client, fabric, key, shard.data, kwargs)):
            # No affinity hint for EC: coded shards are anti-affine by design.
            hint = (placement.hint_for(shard.device)
                    if placement is not None and ec is None else {})
            if "preferred_host" in hint:
                # Host-affine shards pin to ONE worker: striping the object
                # across workers would reintroduce cross-host bytes.
                hint["max_workers"] = 1
            client.put(key, flat, **kwargs, **hint)
        durations[name] = int((time.monotonic() - started) * 1000)
        if placement is not None:
            from blackbird_tpu.placement import device_coord

            placement.record(key, device_coord(shard.device))

    # Barrier + decision exchange: after this, every process's shard puts
    # have committed and everyone knows which boxes were reused.
    reuse = _sync_reuse_bits(reuse, multi_process)
    if not i_commit:
        return sid

    shards_meta: list[dict[str, Any]] = []
    for i, s in enumerate(plan):
        key = (prior_dir if reuse[i] else data_dir) + s["name"]
        entry: dict[str, Any] = {"key": key, "boxes": s["boxes"],
                                 "shape": s["shape"]}
        if reuse[i]:
            entry["reused"] = True
        elif s["name"] in durations:
            entry["duration_ms"] = durations[s["name"]]
        try:  # slow-shard triage: where each shard's bytes actually live
            entry["workers"] = sorted(
                {sh["worker"] for copy in client.placements(key)
                 for sh in copy["shards"]})
        except Exception:  # noqa: BLE001 - placement listing is advisory
            pass
        shards_meta.append(entry)

    manifest = {
        "save_id": sid,
        "global_shape": list(array.shape),
        "dtype": np.dtype(array.dtype).str,
        "shards": shards_meta,
    }
    meta_kwargs = {k: v for k, v in kwargs.items() if k != "ec"}
    if ec is not None:
        # The manifest must survive what the coded shards survive (m
        # losses). ec=(1, m) degenerates to m+1 single-shard copies (any ONE
        # reconstructs it) on distinct workers — unlike `replicas`, not
        # clamped by the keystone's max_replicas, so the tolerance matches.
        meta_kwargs["ec"] = (1, ec[1])
    # THE commit: everything before this line is invisible to readers.
    client.put(prefix + _MANIFEST_DIR + _save_id_str(sid),
               json.dumps(manifest).encode(), **meta_kwargs)
    _reclaim_superseded(client, prefix, sid,
                        keep={s["key"] for s in shards_meta})
    return sid


def _reclaim_superseded(client: Client, prefix: str, sid: int,
                        keep: set[str]) -> None:
    """Post-commit garbage collection: manifests, attempt markers, and data
    of every save id below the just-committed one — except objects the new
    manifest references (resumed shards live in their original attempt's
    data directory) — plus any legacy single-meta layout under the prefix.
    Strictly `< sid`: a concurrent saver that claimed a higher id is mid-
    flight, not garbage. All best-effort: a failed removal leaks bytes the
    next committed save reclaims, never correctness."""
    doomed: set[str] = set()
    for old in _ids_under(client, prefix + _MANIFEST_DIR):
        if old < sid:
            doomed.add(prefix + _MANIFEST_DIR + _save_id_str(old))
    for old in _ids_under(client, prefix + _ATTEMPT_DIR):
        if old <= sid:
            doomed.add(prefix + _ATTEMPT_DIR + _save_id_str(old))
    for obj in client.list(prefix + _DATA_DIR):
        tail = obj["key"][len(prefix + _DATA_DIR):]
        sid_part = tail.split("/", 1)[0]
        if sid_part.isdigit() and int(sid_part) < sid:
            doomed.add(obj["key"])
    if client.exists(prefix + _LEGACY_META_SUFFIX):
        try:
            legacy = json.loads(bytes(client.get(prefix + _LEGACY_META_SUFFIX)))
            doomed.update(s["key"] for s in legacy.get("shards", []))
        except Exception:  # noqa: BLE001 - unreadable legacy meta
            pass
        doomed.add(prefix + _LEGACY_META_SUFFIX)
    doomed.update(obj["key"]
                  for obj in client.list(prefix + _LEGACY_SHARD_SUFFIX))
    for key in doomed - keep:
        try:
            client.remove(key)
        except Exception:  # noqa: BLE001 - lost race / already gone
            pass


def load_sharded(client: Client, prefix: str, *, sharding: Any = None,
                 fabric: FabricClient | None = None,
                 placement: PodPlacement | None = None) -> Any:
    """Restores the checkpoint committed under `prefix` (highest manifest).

    With `sharding` (any `jax.sharding.Sharding`), returns a `jax.Array`
    laid out accordingly — the target does not need to match the sharding
    the array was saved with. Without it, returns a host `numpy` array.

    With `fabric` (a `blackbird_tpu.FabricClient`), device-tier shards are
    pulled over the transfer fabric by THIS process's runtime instead of
    the worker's staged host lane; host-tier shards fall back to the
    staged path transparently.

    With `placement`, every fetched shard is scored against this process's
    pod coordinate on the placement scoreboard — restoring under the save
    sharding reads purely host-locally.
    """
    meta = read_manifest(client, prefix)
    global_shape = tuple(meta["global_shape"])
    dtype = np.dtype(meta["dtype"])

    my_coord: tuple[int, int] | None = None
    if placement is not None:
        import jax

        local = jax.local_devices()
        if local:
            from blackbird_tpu.placement import device_coord

            my_coord = device_coord(local[0])

    # Source shards fetched lazily, at most once each.
    cache: dict[str, npt.NDArray[Any]] = {}

    def fetch(shard_meta: dict[str, Any]) -> npt.NDArray[Any]:
        key = shard_meta["key"]
        if key not in cache:
            if fabric is not None:
                raw = np.frombuffer(fabric.get_bytes(key), dtype=np.uint8)
            else:
                raw = np.frombuffer(bytes(client.get(key)), dtype=np.uint8)
            cache[key] = raw.view(dtype).reshape(shard_meta["shape"])
            if placement is not None:
                placement.record(key, my_coord)
        return cache[key]

    def read_slice(index: tuple[slice, ...]) -> npt.NDArray[Any]:
        """Assembles [index] of the global array from overlapping shards."""
        starts = [sl.start or 0 for sl in index]
        stops = [sl.stop if sl.stop is not None else dim
                 for sl, dim in zip(index, global_shape)]
        out = np.empty([b - a for a, b in zip(starts, stops)], dtype=dtype)
        filled = 0
        for shard_meta in meta["shards"]:
            src_index = _boxes_to_index(shard_meta["boxes"], global_shape)
            # Overlap box between the request and this stored shard.
            o_starts: list[int] = []
            o_stops: list[int] = []
            for (a, b), sl in zip(zip(starts, stops), src_index):
                o_starts.append(max(a, sl.start))
                o_stops.append(min(b, sl.stop))
            if any(a >= b for a, b in zip(o_starts, o_stops)):
                continue
            src = fetch(shard_meta)
            src_sel: tuple[slice, ...] = tuple(
                slice(a - sl.start, b - sl.start)
                for a, b, sl in zip(o_starts, o_stops, src_index)
            )
            dst_sel = tuple(
                slice(a - s, b - s) for a, b, s in zip(o_starts, o_stops, starts)
            )
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in zip(o_starts, o_stops)]))
        if filled != out.size:
            raise ValueError(f"checkpoint {prefix!r} is missing data for {index}")
        return out

    if sharding is None:
        full = read_slice(tuple(slice(0, dim) for dim in global_shape))
        return full

    import jax

    return jax.make_array_from_callback(global_shape, sharding, read_slice)


def list_checkpoints(client: Client, root: str = "") -> list[str]:
    """COMMITTED checkpoint prefixes under `root`: prefixes holding at
    least one manifest (or a legacy single-meta object). Claimed attempts
    and data shards without a manifest — in-flight or interrupted saves —
    are not checkpoints and never appear here.

    Discovery for resume-after-preemption: a restarting trainer lists
    `ckpt/` and picks its checkpoint without tracking keys externally
    (uses the store's prefix listing, which the reference lacks). To pick
    the LATEST step, parse the step number — lexicographic max() breaks
    across digit-count boundaries ("step999" > "step1000") unless step
    names are zero-padded."""
    found: set[str] = set()
    for obj in client.list(root):
        key = obj["key"]
        if _MANIFEST_DIR in key:
            head, tail = key.rsplit(_MANIFEST_DIR, 1)
            if tail.isdigit():
                found.add(head)
        elif key.endswith(_LEGACY_META_SUFFIX):
            found.add(key[: -len(_LEGACY_META_SUFFIX)])
    return sorted(found)


def remove_checkpoint(client: Client, prefix: str) -> None:
    """Deletes every object of a checkpoint: manifests, attempt markers,
    data shards, and any legacy layout under the prefix.

    The manifests go FIRST: a removal interrupted halfway must not leave a
    discoverable-but-unloadable checkpoint for `list_checkpoints` resume.
    The data sweep then unions the prefix listing (orphans from interrupted
    saves) with every manifest's own shard list (shards stranded mid-put
    are PENDING and invisible to listing)."""
    shard_keys: set[str] = set()
    for sid in _ids_under(client, prefix + _MANIFEST_DIR):
        mkey = prefix + _MANIFEST_DIR + _save_id_str(sid)
        try:
            manifest = json.loads(bytes(client.get(mkey)))
            shard_keys.update(s["key"] for s in manifest.get("shards", []))
        except Exception:  # noqa: BLE001 - racing removal
            pass
        try:
            client.remove(mkey)
        except Exception:  # noqa: BLE001 - already gone
            pass
    try:
        legacy = json.loads(bytes(client.get(prefix + _LEGACY_META_SUFFIX)))
        shard_keys.update(s["key"] for s in legacy.get("shards", []))
    except Exception:  # noqa: BLE001 - no legacy meta (the common case)
        pass
    try:
        client.remove(prefix + _LEGACY_META_SUFFIX)
    except Exception:  # noqa: BLE001 - already gone
        pass
    for directory in (_ATTEMPT_DIR, _DATA_DIR, _LEGACY_SHARD_SUFFIX):
        shard_keys.update(obj["key"] for obj in client.list(prefix + directory))
    for key in shard_keys:
        try:
            client.remove(key)
        except Exception:  # noqa: BLE001 - lost race / already gone
            pass
