// Client write path: single-object put plus its two fast tiers — the
// keystone inline tier (one control RTT, no data plane) and pooled
// put slots (commit-with-refill). Split out of the monolithic
// client.cpp; see docs/BYTE_PATHS.md (client core).
#include "btpu/client/client.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <random>

#include "btpu/common/crc32c.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/wire.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/trace.h"
#include "btpu/coord/remote_coordinator.h"
#include "btpu/ec/rs.h"
#include "btpu/rpc/rpc.h"
#include "btpu/storage/hbm_provider.h"

#include "batch_engine.h"

namespace btpu::client {

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size) {
  return put(key, data, size, options_.default_config);
}

ErrorCode ObjectClient::put(const ObjectKey& key, const void* data, uint64_t size,
                            const WorkerConfig& config) {
  trace::OpScope op_trace("put");  // relabeled once the serving tier is known
  TRACE_SPAN("client.put");
  // The end-to-end budget covers every tier probe, transfer, and retry
  // below; a RETRY_LATER shed re-runs the whole body after jittered backoff
  // (safe: a shed provably did not execute, and put_many rolls back failed
  // reservations before reporting).
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  return with_shed_retry([&]() -> ErrorCode {
    // Tiny objects ride the inline tier when the keystone grants it: ONE
    // control RTT stores the bytes in the object map, and the first verified
    // read needs no data-plane hop at all. nullopt = not applicable — fall
    // through to slots/placed.
    if (auto inl = put_via_inline(key, data, size, config)) {
      op_trace.relabel("put_inline");
      return *inl;
    }
    // Small objects ride the pooled-slot path when possible: write into a
    // pre-allocated slot, then ONE control RTT commits it as `key` (and
    // refills the pool in the same round trip). nullopt = not applicable
    // (disabled, oversized, EC, embedded, slot reclaimed) — fall through.
    if (auto pooled = put_via_slot(key, data, size, config)) {
      op_trace.relabel("put_slot");
      return *pooled;
    }
    // One-item batch: put_many pipelines the wire shards of EVERY copy in a
    // single pass (a replicated put costs ~one round trip, not one per copy),
    // coalesces device shards, and rolls back failed reservations — the exact
    // single-object semantics (put_start -> transfer -> complete/cancel,
    // reference blackbird_client.cpp:87-117) with none of the code repeated.
    return put_many({{key, data, size}}, config)[0];
  });
}

std::optional<ErrorCode> ObjectClient::put_via_inline(const ObjectKey& key, const void* data,
                                                      uint64_t size,
                                                      const WorkerConfig& config) {
  // Explicit placement intent (replicas, EC, a tier or node preference)
  // means the caller wants bytes ON THE DATA PLANE — e.g. 2 KiB of HBM-tier
  // metadata read device-locally — so only default-placement puts are
  // offered to the inline tier.
  if (options_.inline_max_bytes == 0 || size == 0 || size > options_.inline_max_bytes ||
      config.replication_factor > 1 || config.ec_parity_shards > 0 ||
      !config.preferred_classes.empty() || !config.preferred_node.empty() || key.empty() ||
      key.find('\x01') != ObjectKey::npos)
    return std::nullopt;
  const int64_t now_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                             std::chrono::steady_clock::now().time_since_epoch())
                             .count();
  // ordering: relaxed — advisory backoff gate: a stale read just means one extra (harmless) inline probe.
  if (now_ms < inline_retry_after_ms_.load(std::memory_order_relaxed)) return std::nullopt;

  invalidate_placements(key);  // same re-created-key rule as the normal path
  const uint32_t crc = crc32c(data, size);
  std::string bytes(static_cast<const char*>(data), size);
  ErrorCode ec;
  if (embedded_) {
    ec = embedded_->put_inline(key, config, crc, std::move(bytes));
  } else {
    // Mutation: NOT_LEADER rotates, lost replies do not retry (matching
    // put_complete's stance — a resend could misreport ALREADY_EXISTS).
    ec = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
      return r.put_inline(key, config, crc, bytes);
    });
  }
  if (ec == ErrorCode::NOT_IMPLEMENTED) {
    // Refused: disabled, the server's limit is smaller than ours, or the
    // budget is spent. Budget refusals clear as objects expire, so re-probe
    // after a while rather than pinning the fallback forever. Jittered
    // around the configured backoff (was a fixed 60 s) so a fleet of
    // clients does not re-probe a recovering keystone in lockstep.
    const RetryPolicy probe{options_.inline_refusal_backoff_ms,
                            options_.inline_refusal_backoff_ms, 1.0, 1};
    inline_retry_after_ms_.store(now_ms + static_cast<int64_t>(probe.backoff_ms(0)),
                                 // ordering: relaxed — advisory backoff gate (see the read above).
                                 std::memory_order_relaxed);
    return std::nullopt;
  }
  return ec;
}

std::optional<ErrorCode> ObjectClient::put_via_slot(const ObjectKey& key, const void* data,
                                                    uint64_t size,
                                                    const WorkerConfig& config) {
  if (embedded_ || options_.put_slots == 0 || size == 0 ||
      size > options_.put_slot_max_bytes || config.ec_parity_shards > 0 || key.empty() ||
      key.find('\x01') != ObjectKey::npos)
    return std::nullopt;
  // Slot classes are exact-(size, config): the commit renames placements
  // verbatim, so shard geometry must match the bytes exactly. Repeat puts
  // of one class — the fixed-block serving pattern — hit the pool.
  std::string class_key;
  {
    wire::Writer w;
    wire::encode(w, config);
    const auto cfg = w.take();
    class_key.assign(reinterpret_cast<const char*>(cfg.data()), cfg.size());
    class_key += '/' + std::to_string(size);
  }

  invalidate_placements(key);  // same re-created-key rule as the normal path
  PutSlot slot;
  auto slot_granted_at = std::chrono::steady_clock::now();
  std::vector<ObjectKey> expired;
  {
    MutexLock lock(slot_mutex_);
    if (slots_unsupported_) return std::nullopt;
    auto& pool = slot_pool_[class_key];
    // Age gate: a slot the keystone may have reclaimed (slot TTL) must
    // never see a data-plane write — its ranges could already belong to
    // another object. Expired entries are cancelled below, not used.
    const auto now = std::chrono::steady_clock::now();
    const auto max_age = std::chrono::milliseconds(options_.put_slot_max_age_ms);
    while (!pool.empty()) {
      PooledSlot entry = std::move(pool.back());
      pool.pop_back();
      if (now - entry.granted_at > max_age) {
        expired.push_back(std::move(entry.slot.slot_key));
        continue;
      }
      slot = std::move(entry.slot);
      slot_granted_at = entry.granted_at;
      break;
    }
  }
  if (!expired.empty()) {
    // Best-effort release of the stale reservations (the TTL reclaims them
    // regardless); outside the pool lock, one batch RPC.
    (void)rpc_failover(/*idempotent=*/false,
                 [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(expired); });  // best-effort cancel; slot TTL reclaims
  }
  if (slot.slot_key.empty()) {
    // First put of this class pays the same two RTTs as the normal path,
    // but the grant covers this put AND the pool for the next ones.
    auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
      return c.put_start_pooled(size, config, options_.put_slots + 1, slot_tag_);
    });
    if (!r.ok() || r.value().empty()) {
      if (r.error() == ErrorCode::NOT_IMPLEMENTED) {
        // Old server or slots disabled server-side: stop asking.
        MutexLock lock(slot_mutex_);
        slots_unsupported_ = true;
      }
      return std::nullopt;  // the normal path reports the real outcome
    }
    auto slots = std::move(r).value();
    slot = std::move(slots.back());
    slots.pop_back();
    if (!slots.empty()) {
      const auto now = std::chrono::steady_clock::now();
      MutexLock lock(slot_mutex_);
      auto& pool = slot_pool_[class_key];
      for (auto& s : slots) pool.push_back({std::move(s), now});
    }
  }

  // Transfer into the slot's placements — the same jobs machinery as
  // put_many, for one item.
  auto* bytes = const_cast<uint8_t*>(static_cast<const uint8_t*>(data));
  uint32_t content_crc = 0;
  BatchJobs jobs;
  std::vector<ErrorCode> item_errors(1, ErrorCode::OK);
  std::vector<CopyShardCrcs> crcs;
  for (const auto& copy : slot.copies) {
    if (auto ec = append_copy_jobs(copy, bytes, size, 0, jobs, nullptr);
        ec != ErrorCode::OK) {
      item_errors[0] = ec;
      break;
    }
  }
  if (item_errors[0] == ErrorCode::OK) {
    TRACE_SPAN("client.put.transfer");
    std::vector<uint32_t> wire_crcs;
    run_device_jobs(*data_, jobs, /*is_write=*/true, item_errors);
    run_wire_jobs(*data_, jobs, /*is_write=*/true, options_.io_parallelism, item_errors,
                  &wire_crcs);
    if (item_errors[0] == ErrorCode::OK) {
      // Shard stamps come from the transport's fused write hashes; the
      // content stamp folds out of them — zero standalone passes for the
      // single-shard small-put norm. (Skipped entirely on transfer failure:
      // the fallback branch below discards them.)
      RangeCrcMap ranges;
      harvest_wire_ranges(jobs, wire_crcs, 0, bytes, ranges);
      crcs = stamp_copy_crcs(slot.copies, bytes, ranges);
      if (!crcs.empty() && !slot.copies.empty())
        content_crc = fold_content_crc(crcs[0], slot.copies[0]);
      if (!jobs.device.empty()) item_errors[0] = storage::hbm_flush();
    }
  }
  if (item_errors[0] != ErrorCode::OK) {
    // The slot's worker may be the problem (crashed after the grant): drop
    // the slot and FALL BACK — the normal path re-reserves on currently
    // healthy workers, preserving the pre-slot availability story.
    LOG_WARN << "put " << key << " slot transfer failed (" << to_string(item_errors[0])
             << "), cancelling slot and falling back";
    (void)rpc_failover(/*idempotent=*/false,
                 [&](rpc::KeystoneRpcClient& c) { return c.put_cancel(slot.slot_key); });  // best-effort cancel; slot TTL reclaims
    return std::nullopt;
  }

  PutCommitSlotRequest req;
  req.slot_key = slot.slot_key;
  req.key = key;
  req.content_crc = content_crc;
  req.shard_crcs = std::move(crcs);
  req.data_size = size;
  req.config = config;
  req.client_tag = slot_tag_;
  {
    MutexLock lock(slot_mutex_);
    const size_t have = slot_pool_[class_key].size();
    req.refill_count =
        have < options_.put_slots ? static_cast<uint32_t>(options_.put_slots - have) : 0;
  }
  std::vector<PutSlot> refills;
  const ErrorCode ec = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
    return c.put_commit_slot(req, &refills);
  });
  if (ec == ErrorCode::OK) {
    std::vector<ObjectKey> overflow;
    {
      const auto now = std::chrono::steady_clock::now();
      MutexLock lock(slot_mutex_);
      auto& pool = slot_pool_[class_key];
      for (auto& s : refills) {
        // Overflow (a concurrent put of this class refilled first) is
        // cancelled, not dropped: each refill reserves real capacity.
        if (pool.size() >= options_.put_slots) {
          overflow.push_back(std::move(s.slot_key));
        } else {
          pool.push_back({std::move(s), now});
        }
      }
    }
    if (!overflow.empty()) {
      (void)rpc_failover(/*idempotent=*/false,
                   [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(overflow); });  // best-effort cancel; slot TTL reclaims
    }
    return ErrorCode::OK;
  }
  if (ec == ErrorCode::OBJECT_NOT_FOUND) {
    // Slot reclaimed (TTL) or minted by a deposed leader: transparent
    // fallback — the normal path re-reserves and re-writes.
    return std::nullopt;
  }
  // Duplicate key, fail-closed persist, etc.: the slot survives server-side
  // (commit rolled it back), so it can serve the next put of this class.
  {
    MutexLock lock(slot_mutex_);
    slot_pool_[class_key].push_back({std::move(slot), slot_granted_at});
  }
  return ec;
}

void ObjectClient::cancel_pooled_slots() {
  std::vector<ObjectKey> keys;
  {
    MutexLock lock(slot_mutex_);
    for (auto& [cls, pool] : slot_pool_) {
      for (auto& s : pool) keys.push_back(std::move(s.slot.slot_key));
    }
    slot_pool_.clear();
  }
  // Only when already connected: the destructor must not pay a connect
  // timeout for a dead keystone — the slot TTL reclaims either way.
  std::shared_ptr<rpc::KeystoneRpcClient> rpc;
  if (!embedded_) rpc = rpc_snapshot();
  if (keys.empty() || !rpc || !rpc->connected()) return;
  (void)rpc->batch_put_cancel(keys);  // best-effort cancel; slot TTL reclaims
}

}  // namespace btpu::client
