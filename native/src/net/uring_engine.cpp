// io_uring event-loop data plane (see uring_engine.h for the design and
// docs/CORRECTNESS.md §8 for the ownership/locking argument).
//
// Raw io_uring syscalls — liburing is not in this image (the disk backend
// made the same call, iouring_disk_backend.cpp). Each loop owns one ring
// and every connection accepted on it; a connection is a small state
// machine with AT MOST ONE submission in flight, so a loop multiplexes
// thousands of connections with conns+3 outstanding entries and zero
// per-connection threads. Pool-direct reads answer with a single gather
// SENDMSG whose payload iovec points INTO the registered pool region — the
// worker never copies the bytes. At/above BTPU_ZC_THRESHOLD those sends
// upgrade to IORING_OP_SEND_ZC (kernel-probed; REPORT_USAGE notifs feed
// btpu_zerocopy_{sent,copied}_count): it pins pages and doubles
// completions per send, which loses below multi-MiB payloads — and always
// on loopback, where the kernel copies regardless — so the threshold
// defaults high and the copied counter is the regression alarm
// (docs/BYTE_PATHS.md, docs/OPERATIONS.md).
#include "uring_engine.h"

#include <linux/io_uring.h>
#include <linux/time_types.h>
#include <poll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <fcntl.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <string>
#include <thread>
#include <unordered_set>
#include <vector>

#include "btpu/common/deadline.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/transport/data_wire.h"

namespace btpu::transport {

using namespace datawire;

// This image builds against 5.12-era uapi headers, which predate the 6.x
// zero-copy send machinery. The KERNEL is what decides support (probed per
// ring via IORING_REGISTER_PROBE at init); these mirror the upstream
// values so the binary can use SEND_ZC on kernels that have it.
#ifndef IORING_OP_SEND_ZC
#define IORING_OP_SEND_ZC 47
#endif
#ifndef IORING_CQE_F_MORE
#define IORING_CQE_F_MORE (1U << 1)
#endif
#ifndef IORING_CQE_F_NOTIF
#define IORING_CQE_F_NOTIF (1U << 3)
#endif
#ifndef IORING_SEND_ZC_REPORT_USAGE
#define IORING_SEND_ZC_REPORT_USAGE (1U << 3) /* io_uring_sqe.ioprio flag */
#endif
#ifndef IORING_NOTIF_USAGE_ZC_COPIED
#define IORING_NOTIF_USAGE_ZC_COPIED (1U << 31) /* notif cqe.res bit */
#endif
#ifndef IORING_REGISTER_PROBE
#define IORING_REGISTER_PROBE 8
#endif

// TSan cannot see io_uring: bytes the ring moves over a socket carry none
// of the happens-before edges libtsan models for INTERCEPTED read/write
// syscalls, so every engine-served op would falsely race with its client
// (the kernel's socket ordering is the real edge; TSan just can't observe
// it). Under TSan builds only, mirror that ordering with zero-length
// intercepted syscalls on the same fd: recv(fd,·,0) when a ring recv
// completes (FdAcquire — pairs with the client's write release), and
// send(fd,·,0) before a response is submitted (FdRelease — pairs with the
// client's read acquire). Production builds compile these to nothing.
// Documented in docs/CORRECTNESS.md §8.
#if defined(__SANITIZE_THREAD__)
#define BTPU_URING_TSAN_FD_SYNC 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define BTPU_URING_TSAN_FD_SYNC 1
#endif
#endif

namespace {

std::atomic<size_t> g_active_loops{0};

inline void tsan_fd_acquire(int fd) {
#ifdef BTPU_URING_TSAN_FD_SYNC
  char b;
  (void)!::recv(fd, &b, 0, MSG_DONTWAIT);
#else
  (void)fd;
#endif
}
inline void tsan_fd_release(int fd) {
#ifdef BTPU_URING_TSAN_FD_SYNC
  (void)!::send(fd, "", 0, MSG_DONTWAIT | MSG_NOSIGNAL);
#else
  (void)fd;
#endif
}

int sys_io_uring_setup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(::syscall(__NR_io_uring_setup, entries, p));
}
int sys_io_uring_enter(int fd, unsigned to_submit, unsigned min_complete, unsigned flags) {
  return static_cast<int>(
      ::syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags, nullptr, 0));
}

// Kernel-side opcode support, asked of the ring itself (headers can't
// know): true when this kernel can serve IORING_OP_SEND_ZC.
bool ring_supports_send_zc(int ring_fd) {
  struct Probe {
    io_uring_probe head;
    io_uring_probe_op ops[256];
  } probe{};
  if (::syscall(__NR_io_uring_register, ring_fd, IORING_REGISTER_PROBE, &probe,
                256) < 0)
    return false;
  if (probe.head.ops_len <= IORING_OP_SEND_ZC) return false;
  return (probe.ops[IORING_OP_SEND_ZC].flags & IO_URING_OP_SUPPORTED) != 0;
}

// user_data encoding: values < 8 are loop-level ops; anything else is the
// owning Conn* (allocated, so 8-byte aligned — low bits are always clear).
constexpr uint64_t kUdAccept = 1;
constexpr uint64_t kUdEvent = 2;
constexpr uint64_t kUdTimeout = 3;
constexpr uint64_t kUdCancel = 4;  // completion of an ASYNC_CANCEL itself

io_uring_sqe make_sqe(uint8_t opcode, int fd, const void* addr, uint32_t len, uint64_t off,
                      uint64_t user_data) {
  io_uring_sqe s;
  std::memset(&s, 0, sizeof(s));
  s.opcode = opcode;
  s.fd = fd;
  s.addr = reinterpret_cast<uint64_t>(addr);
  s.len = len;
  s.off = off;
  s.user_data = user_data;
  return s;
}

// Single-thread io_uring wrapper: only the owning loop thread touches it.
// push() never fails — entries that don't fit the SQ wait in a local
// backlog and flush as the kernel consumes the ring.
class Ring {
 public:
  ~Ring() { close_ring(); }

  bool init(unsigned entries) {
    for (unsigned want = entries; want >= 16; want /= 2) {
      io_uring_params params{};
      // Deep CQ: with one outstanding op per connection, completions scale
      // with CONNECTIONS, not SQ depth. FEAT_NODROP (5.5+) buffers any
      // overflow past this in the kernel, so a shallow CQ degrades to
      // -EBUSY backpressure instead of lost completions.
      params.flags = IORING_SETUP_CQSIZE;
      params.cq_entries = want * 8 < 4096 ? 4096 : want * 8;
      int fd = sys_io_uring_setup(want, &params);
      if (fd < 0 && errno == EINVAL) {
        // Pre-CQSIZE kernel: retry plain before shrinking.
        io_uring_params plain{};
        fd = sys_io_uring_setup(want, &plain);
        params = plain;
      }
      if (fd < 0) continue;
      if (!(params.features & IORING_FEAT_NODROP)) {
        // A kernel that can silently drop completions would wedge the
        // outstanding-op accounting; let the thread server take over.
        ::close(fd);
        return false;
      }
      ring_fd_ = fd;
      if (map_rings(params)) return true;
      close_ring();
    }
    return false;
  }

  bool ok() const noexcept { return ring_fd_ >= 0; }

  int fd() const noexcept { return ring_fd_; }

  void push(const io_uring_sqe& sqe) {
    if (backlog_.empty() && try_place(sqe)) return;
    backlog_.push_back(sqe);
  }

  void flush() {
    while (!backlog_.empty() && try_place(backlog_.front())) backlog_.pop_front();
  }

  // Submits everything staged; blocks for >= wait_nr completions.
  // Returns >= 0 on success, -errno on failure (-EBUSY/-EINTR are benign:
  // drain completions and come back).
  int enter(unsigned wait_nr) {
    const unsigned to_submit = staged_;
    const int rc = sys_io_uring_enter(ring_fd_, to_submit, wait_nr, IORING_ENTER_GETEVENTS);
    if (rc < 0) return -errno;
    staged_ -= std::min(static_cast<unsigned>(rc), staged_);
    return rc;
  }

  unsigned drain(io_uring_cqe* out, unsigned max) {
    // ordering: relaxed head (only this thread advances it) + acquire tail — pairs with the kernel's release publish of new CQEs, so the entries read below are fully written.
    unsigned head = cq_head_->load(std::memory_order_relaxed);
    const unsigned tail = cq_tail_->load(std::memory_order_acquire);
    unsigned n = 0;
    while (head != tail && n < max) {
      out[n++] = cqes_[head & cq_mask_];
      ++head;
    }
    // ordering: release — returns the consumed slots to the kernel only after the copies above complete.
    cq_head_->store(head, std::memory_order_release);
    return n;
  }

 private:
  bool map_rings(const io_uring_params& params) {
    sq_entries_ = params.sq_entries;
    sq_ring_sz_ = params.sq_off.array + params.sq_entries * sizeof(unsigned);
    cq_ring_sz_ = params.cq_off.cqes + params.cq_entries * sizeof(io_uring_cqe);
    sq_ring_ = ::mmap(nullptr, sq_ring_sz_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, ring_fd_, IORING_OFF_SQ_RING);
    cq_ring_ = ::mmap(nullptr, cq_ring_sz_, PROT_READ | PROT_WRITE,
                      MAP_SHARED | MAP_POPULATE, ring_fd_, IORING_OFF_CQ_RING);
    sqes_sz_ = params.sq_entries * sizeof(io_uring_sqe);
    sqes_ = static_cast<io_uring_sqe*>(::mmap(nullptr, sqes_sz_, PROT_READ | PROT_WRITE,
                                              MAP_SHARED | MAP_POPULATE, ring_fd_,
                                              IORING_OFF_SQES));
    if (sq_ring_ == MAP_FAILED || cq_ring_ == MAP_FAILED ||
        sqes_ == reinterpret_cast<io_uring_sqe*>(MAP_FAILED))
      return false;
    auto* sq = static_cast<uint8_t*>(sq_ring_);
    sq_head_ = reinterpret_cast<std::atomic<unsigned>*>(sq + params.sq_off.head);
    sq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(sq + params.sq_off.tail);
    sq_mask_ = *reinterpret_cast<unsigned*>(sq + params.sq_off.ring_mask);
    sq_array_ = reinterpret_cast<unsigned*>(sq + params.sq_off.array);
    auto* cq = static_cast<uint8_t*>(cq_ring_);
    cq_head_ = reinterpret_cast<std::atomic<unsigned>*>(cq + params.cq_off.head);
    cq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(cq + params.cq_off.tail);
    cq_mask_ = *reinterpret_cast<unsigned*>(cq + params.cq_off.ring_mask);
    cqes_ = reinterpret_cast<io_uring_cqe*>(cq + params.cq_off.cqes);
    return true;
  }

  bool try_place(const io_uring_sqe& sqe) {
    // ordering: acquire head — pairs with the kernel's release as it frees SQ slots; relaxed tail (only this thread advances it).
    const unsigned head = sq_head_->load(std::memory_order_acquire);
    const unsigned tail = sq_tail_->load(std::memory_order_relaxed);
    if (tail - head >= sq_entries_) return false;
    const unsigned idx = tail & sq_mask_;
    sqes_[idx] = sqe;
    sq_array_[idx] = idx;
    // ordering: release — publishes the fully-written SQE before the kernel can observe the new tail.
    sq_tail_->store(tail + 1, std::memory_order_release);
    ++staged_;
    return true;
  }

  void close_ring() {
    if (sq_ring_ && sq_ring_ != MAP_FAILED) ::munmap(sq_ring_, sq_ring_sz_);
    if (cq_ring_ && cq_ring_ != MAP_FAILED) ::munmap(cq_ring_, cq_ring_sz_);
    if (sqes_ && sqes_ != reinterpret_cast<io_uring_sqe*>(MAP_FAILED))
      ::munmap(sqes_, sqes_sz_);
    sq_ring_ = cq_ring_ = nullptr;
    sqes_ = nullptr;
    if (ring_fd_ >= 0) ::close(ring_fd_);
    ring_fd_ = -1;
  }

  int ring_fd_{-1};
  unsigned sq_entries_{0};
  unsigned staged_{0};  // placed in the SQ, not yet submitted
  std::deque<io_uring_sqe> backlog_;
  void* sq_ring_{nullptr};
  void* cq_ring_{nullptr};
  io_uring_sqe* sqes_{nullptr};
  size_t sq_ring_sz_{0}, cq_ring_sz_{0}, sqes_sz_{0};
  std::atomic<unsigned>*sq_head_{}, *sq_tail_{}, *cq_head_{}, *cq_tail_{};
  unsigned sq_mask_{0}, cq_mask_{0};
  unsigned* sq_array_{nullptr};
  io_uring_cqe* cqes_{nullptr};
};

// Offload pool for BLOCKING work a loop thread must never run: virtual-
// region callbacks without a direct fd (device providers, mmap-disk) and
// fabric offer/pull (pull blocks until the device transfer lands). Threads
// are lazy up to the cap and exit at pool destruction.
class ExecPool {
 public:
  explicit ExecPool(unsigned max_threads) : max_threads_(max_threads ? max_threads : 1) {}

  ~ExecPool() {
    {
      MutexLock lock(mutex_);
      stop_ = true;
    }
    cv_.notify_all();
    std::vector<std::thread> joiners;
    {
      MutexLock lock(mutex_);
      joiners.swap(threads_);
    }
    for (auto& t : joiners)
      if (t.joinable()) t.join();
  }

  void submit(std::function<void()> task) {
    MutexLock lock(mutex_);
    tasks_.push_back(std::move(task));
    if (idle_ == 0 && threads_.size() < max_threads_)
      threads_.emplace_back([this] { worker(); });
    cv_.notify_one();
  }

 private:
  void worker() {
    MutexLock lock(mutex_);
    for (;;) {
      while (tasks_.empty() && !stop_) {
        ++idle_;
        cv_.wait(lock);
        --idle_;
      }
      if (tasks_.empty() && stop_) return;
      auto task = std::move(tasks_.front());
      tasks_.pop_front();
      lock.unlock();
      task();  // posts its completion to the owning loop itself
      lock.lock();
    }
  }

  Mutex mutex_;
  CondVarAny cv_;
  std::deque<std::function<void()>> tasks_ BTPU_GUARDED_BY(mutex_);
  std::vector<std::thread> threads_ BTPU_GUARDED_BY(mutex_);
  bool stop_ BTPU_GUARDED_BY(mutex_){false};
  unsigned idle_ BTPU_GUARDED_BY(mutex_){0};
  const size_t max_threads_;
};

class UringLoop;

// One connection's op state machine. Owned and mutated by exactly one loop
// thread; an exec-pool task may READ the fields frozen at submit time
// (offsets, scratch pointer) but the loop never touches the Conn while
// exec_out is set, so there is no concurrent access (CORRECTNESS §8).
struct Conn {
  int fd{-1};
  UringLoop* loop{nullptr};

  enum class S : uint8_t {
    kHeader,    // accumulating the fixed request header
    kTrailer,   // accumulating the op's trailer bytes (staged/hello/fabric)
    kPayload,   // write-op payload landing (pool-direct, scratch, or drain)
    kDiskRead,  // ring-submitted read from a region's backing file
    kExec,      // blocking callback in flight on the exec pool
    kSend,      // response (status [+ payload iovec]) going out
    kParked,    // admission-parked: no submission outstanding
  } state{S::kHeader};

  // Control-plane accumulation: header + largest trailer (fabric pull:
  // u64 id + u16 alen + 255 addr bytes).
  uint8_t ctl[sizeof(DataRequestHeader) + 8 + 2 + kMaxFabricAddrBytes]{};
  uint32_t ctl_have{0};
  uint32_t ctl_need{0};
  bool fabric_addr_extended{false};

  DataRequestHeader hdr{};
  Deadline deadline{};

  // Resolution result for the current op.
  bool valid{false};
  uint8_t* target{nullptr};  // flat-region pointer (null for virtual)
  Region virt;               // callbacks + direct fd when target == null
  uint64_t offset{0};        // offset within the region

  // Write-payload progress.
  uint64_t pay_done{0};
  bool drain_only{false};

  // Scratch for virtual payloads / drains / disk windows (512-aligned for
  // O_DIRECT ring reads).
  uint8_t* scratch{nullptr};
  uint64_t scratch_cap{0};

  // Disk-read window (O_DIRECT widening).
  uint64_t win_start{0}, win_len{0}, win_done{0};

  // Admission ticket held for the current op.
  bool ticket{false};
  uint64_t ticket_bytes{0};

  // Response.
  uint32_t status{0};
  const uint8_t* resp_payload{nullptr};
  uint64_t resp_len{0};
  uint64_t resp_done{0};
  bool pool_direct{false};  // payload went straight off pool pages
  iovec iov[2]{};
  msghdr msg{};  // stable storage for the SENDMSG sqe (points at iov)

  // Client-created staging segment (hello handshake).
  uint8_t* stg_base{nullptr};
  uint64_t stg_len{0};

  // Zero-copy send bookkeeping. zc_send_out marks the currently-submitted
  // send as a SEND_ZC (its main CQE needs F_MORE inspection);
  // zc_notif_pending counts kernel buffer-release notifications still due
  // — the kernel may DMA from the pool pages until each arrives, and every
  // notif CQE names this Conn, so destruction is deferred on it. Notifs
  // from a finished op can land while later ops are in flight, so this is
  // a counter, not a flag.
  bool zc_send_out{false};
  uint32_t zc_notif_pending{0};

  // Observability: op service window (header decoded -> response fully
  // sent) and the response-send window (first send submit -> final send
  // completion). Loop-owned like every other Conn field.
  uint64_t op_start_ns{0};
  uint64_t send_start_ns{0};

  // Lifecycle.
  bool sqe_out{false};
  bool exec_out{false};
  bool dead{false};

  ~Conn() {
    if (stg_base) ::munmap(stg_base, stg_len);
    if (scratch) std::free(scratch);
    if (fd >= 0) ::close(fd);
  }
};

constexpr uint64_t kDrainChunk = 64 * 1024;
// Free oversized per-connection scratch after each op: a thousand parked
// connections must not each pin a multi-MiB buffer.
constexpr uint64_t kScratchKeep = 256 * 1024;
constexpr uint64_t kOdirectAlign = 512;

class UringLoop {
 public:
  UringLoop(int listen_fd, RegionTable* regions, AdmissionGate* gate, ExecPool* exec,
            DataPlaneCounters counters, std::atomic<size_t>* conn_count,
            std::atomic<uint32_t>* parked_total, bool zc_want, uint64_t zc_threshold)
      : listen_fd_(listen_fd),
        regions_(regions),
        gate_(gate),
        exec_(exec),
        counters_(counters),
        conn_count_(conn_count),
        parked_total_(parked_total),
        zc_want_(zc_want),
        zc_threshold_(zc_threshold) {}

  ~UringLoop() {
    if (event_fd_ >= 0) ::close(event_fd_);
  }

  bool init(unsigned sq_entries) {
    event_fd_ = ::eventfd(0, EFD_CLOEXEC | EFD_NONBLOCK);
    if (event_fd_ < 0) return false;
    if (!ring_.init(sq_entries)) return false;
    // ZC is a per-ring capability: ask THIS ring, not the headers.
    zc_ok_ = zc_want_ && ring_supports_send_zc(ring_.fd());
    return true;
  }

  void start() {
    // Counted BEFORE the thread spawns so uring_active_loop_count() is
    // accurate the moment create() returns (benches/tests read it right
    // after server start); the loop decrements on exit.
    // ordering: relaxed — diagnostic loop counter (tests/benches poll it); no state is published through it.
    g_active_loops.fetch_add(1, std::memory_order_relaxed);
    thread_ = std::thread([this] {
      run();
      g_active_loops.fetch_sub(1, std::memory_order_relaxed);
    });
  }

  void request_stop() {
    // ordering: release — pairs with the loop's acquire poll so everything written before the stop request is visible when the loop observes it.
    stop_.store(true, std::memory_order_release);
    wake();
  }

  void join() {
    if (thread_.joinable()) thread_.join();
  }

  // Called from exec-pool threads: hand a finished callback's status back
  // to the loop.
  void post_exec(Conn* conn, uint32_t status) {
    {
      MutexLock lock(done_mutex_);
      done_.push_back({conn, status});
    }
    wake();
  }

 private:
  struct ExecDone {
    Conn* conn;
    uint32_t status;
  };

  void wake() {
    const uint64_t one = 1;
    // Non-blocking eventfd: a full counter still wakes the reader.
    (void)!::write(event_fd_, &one, sizeof(one));
  }

  void submit(const io_uring_sqe& sqe) {
    ring_.push(sqe);
    ++outstanding_;
  }

  // ---- arming ---------------------------------------------------------

  void arm_accept() {
#ifdef BTPU_URING_TSAN_FD_SYNC
    // TSan builds accept via POLL_ADD + the real accept4() SYSCALL instead
    // of IORING_OP_ACCEPT: libtsan only marks an fd as a socket (and wires
    // it to the global socket sync object the fd shims release/acquire on)
    // inside its accept interceptor — a ring-accepted fd would leave every
    // shim below releasing into the void. Accept is the cold path, so the
    // divergence costs nothing it measures.
    io_uring_sqe s = make_sqe(IORING_OP_POLL_ADD, listen_fd_, nullptr, 0, 0, kUdAccept);
    s.poll_events = POLLIN;
#else
    io_uring_sqe s = make_sqe(IORING_OP_ACCEPT, listen_fd_, nullptr, 0, 0, kUdAccept);
    s.accept_flags = SOCK_CLOEXEC;
#endif
    submit(s);
    accept_out_ = true;
  }

  void arm_event() {
    submit(make_sqe(IORING_OP_READ, event_fd_, &event_buf_, sizeof(event_buf_), 0, kUdEvent));
    event_out_ = true;
  }

  void arm_timeout() {
    ts_.tv_sec = 0;
    ts_.tv_nsec = 10 * 1000 * 1000;  // 10ms parked-op sweep tick
    submit(make_sqe(IORING_OP_TIMEOUT, -1, &ts_, 1, 0, kUdTimeout));
    timeout_armed_ = true;
  }

  void arm_recv_ctl(Conn* c) {
    submit(make_sqe(IORING_OP_RECV, c->fd, c->ctl + c->ctl_have, c->ctl_need - c->ctl_have, 0,
                    reinterpret_cast<uint64_t>(c)));
    c->sqe_out = true;
  }

  void arm_recv_payload(Conn* c) {
    uint8_t* dst;
    uint64_t want;
    if (c->target) {  // pool-direct landing: bytes go straight into the region
      dst = c->target + c->pay_done;
      want = c->hdr.len - c->pay_done;
    } else if (c->drain_only) {
      dst = c->scratch;
      want = std::min<uint64_t>(kDrainChunk, c->hdr.len - c->pay_done);
    } else {
      dst = c->scratch + c->pay_done;
      want = c->hdr.len - c->pay_done;
    }
    const uint32_t len = static_cast<uint32_t>(std::min<uint64_t>(want, 1u << 30));
    submit(make_sqe(IORING_OP_RECV, c->fd, dst, len, 0, reinterpret_cast<uint64_t>(c)));
    c->sqe_out = true;
  }

  void arm_send(Conn* c) {
    tsan_fd_release(c->fd);  // no-op outside TSan builds (see file header)
    if (c->send_start_ns == 0) c->send_start_ns = trace::now_ns();
    const uint64_t head_left = c->resp_done < 4 ? 4 - c->resp_done : 0;
    const uint64_t pay_sent = c->resp_done > 4 ? c->resp_done - 4 : 0;
    const uint64_t pay_left = c->resp_payload ? c->resp_len - pay_sent : 0;
    // Zero-copy eligibility, re-decided per submission: pool-direct
    // payloads at/above the threshold on a kernel whose ring probe said
    // yes. SEND_ZC takes one flat buffer, so the 4-byte status goes out on
    // its own writev first — one extra completion round, amortized over a
    // >= threshold payload. A partial ZC send that drops the remainder
    // below the threshold just finishes on the writev path.
    const bool zc = zc_ok_ && c->pool_direct && pay_left >= zc_threshold_;
    if (zc && head_left == 0) {
      io_uring_sqe s = make_sqe(
          IORING_OP_SEND_ZC, c->fd, c->resp_payload + pay_sent,
          static_cast<uint32_t>(std::min<uint64_t>(pay_left, 1u << 30)), 0,
          reinterpret_cast<uint64_t>(c));
      s.ioprio = IORING_SEND_ZC_REPORT_USAGE;  // notif reports copied-vs-zc
      s.msg_flags = MSG_NOSIGNAL;
      submit(s);
      // The kernel answers a SEND_ZC twice: the send result now, the
      // buffer-release notif later. Count BOTH up front (handle_cqe
      // decrements once per CQE); a failed send posts no notif and the
      // dispatch path refunds the second count there.
      ++outstanding_;
      ++c->zc_notif_pending;
      c->zc_send_out = true;
      c->sqe_out = true;
      c->state = Conn::S::kSend;
      return;
    }
    unsigned n = 0;
    if (head_left) {
      c->iov[n].iov_base = reinterpret_cast<uint8_t*>(&c->status) + c->resp_done;
      c->iov[n].iov_len = static_cast<size_t>(head_left);
      ++n;
    }
    if (c->resp_payload && !zc) {
      c->iov[n].iov_base = const_cast<uint8_t*>(c->resp_payload) + pay_sent;
      c->iov[n].iov_len = static_cast<size_t>(pay_left);
      ++n;
    }
    // SENDMSG + MSG_NOSIGNAL, NOT WRITEV: a ring WRITEV against a peer
    // that reset mid-response behaves like raw writev — the kernel raises
    // SIGPIPE in whichever thread sits in io_uring_enter, killing the
    // whole worker for one vanished client (net.cpp's "never raw
    // write/writev on sockets" rule applies on the ring too; caught by
    // RemoteLane.MidStreamPeerDeath). The gather behavior is identical.
    c->msg = msghdr{};
    c->msg.msg_iov = c->iov;
    c->msg.msg_iovlen = n;
    io_uring_sqe s = make_sqe(IORING_OP_SENDMSG, c->fd, &c->msg, 1, 0,
                              reinterpret_cast<uint64_t>(c));
    s.msg_flags = MSG_NOSIGNAL;
    submit(s);
    c->sqe_out = true;
    c->state = Conn::S::kSend;
  }

  void arm_disk_read(Conn* c) {
    const uint64_t left = c->win_len - c->win_done;
    const uint32_t len = static_cast<uint32_t>(std::min<uint64_t>(left, 1u << 30));
    submit(make_sqe(IORING_OP_READ, c->virt.direct_fd, c->scratch + c->win_done, len,
                    c->win_start + c->win_done, reinterpret_cast<uint64_t>(c)));
    c->sqe_out = true;
    c->state = Conn::S::kDiskRead;
  }

  // ---- op state machine ------------------------------------------------

  static uint32_t code(ErrorCode ec) { return static_cast<uint32_t>(ec); }

  void start_header(Conn* c) {
    c->ctl_have = 0;
    c->ctl_need = sizeof(DataRequestHeader);
    c->op_start_ns = 0;
    c->send_start_ns = 0;
    c->fabric_addr_extended = false;
    c->valid = false;
    c->target = nullptr;
    c->virt = Region{};
    c->offset = 0;
    c->pay_done = 0;
    c->drain_only = false;
    c->status = 0;
    c->resp_payload = nullptr;
    c->resp_len = 0;
    c->resp_done = 0;
    c->pool_direct = false;
    if (c->scratch && c->scratch_cap > kScratchKeep) {
      std::free(c->scratch);
      c->scratch = nullptr;
      c->scratch_cap = 0;
    }
    c->state = Conn::S::kHeader;
    arm_recv_ctl(c);
  }

  bool ensure_scratch(Conn* c, uint64_t len) {
    if (c->scratch_cap >= len) return true;
    void* p = nullptr;
    if (posix_memalign(&p, kOdirectAlign, static_cast<size_t>(len)) != 0) return false;
    if (c->scratch) std::free(c->scratch);
    c->scratch = static_cast<uint8_t*>(p);
    c->scratch_cap = len;
    return true;
  }

  void header_complete(Conn* c) {
    if (!decode_request_header(c->ctl, sizeof(DataRequestHeader), c->hdr)) {
      close_conn(c);  // poisoned stream: no frame boundary to resync on
      return;
    }
    c->deadline = Deadline::from_wire(c->hdr.deadline_ms);
    uint32_t trailer = 0;
    switch (c->hdr.op) {
      case kOpHello:
        trailer = static_cast<uint32_t>(c->hdr.len);  // decode pinned 1..255
        break;
      case kOpReadStaged:
      case kOpWriteStaged:
      case kOpFabricOffer:
        trailer = 8;
        break;
      case kOpFabricPull:
        trailer = 8 + 2;  // id + alen; addr bytes extend in trailer_complete
        break;
      default:
        break;
    }
    c->op_start_ns = trace::now_ns();
    flight::record_at(c->op_start_ns, flight::Ev::kUringSubmit, c->hdr.op, c->hdr.len,
                      c->hdr.trace_id);
    if (trailer == 0) {
      dispatch(c);
      return;
    }
    c->ctl_need += trailer;
    c->state = Conn::S::kTrailer;
    arm_recv_ctl(c);
  }

  void trailer_complete(Conn* c) {
    if (c->hdr.op == kOpFabricPull && !c->fabric_addr_extended) {
      uint16_t alen = 0;
      std::memcpy(&alen, c->ctl + sizeof(DataRequestHeader) + 8, sizeof(alen));
      if (!valid_fabric_addr_len(alen)) {
        close_conn(c);  // protocol violation, as in the thread server
        return;
      }
      c->fabric_addr_extended = true;
      c->ctl_need += alen;
      arm_recv_ctl(c);
      return;
    }
    dispatch(c);
  }

  // Op header (+ trailer) fully read: resolve, gate, serve.
  void dispatch(Conn* c) {
    switch (c->hdr.op) {
      case kOpHello:
        do_hello(c);
        return;
      case kOpReadStaged:
      case kOpWriteStaged: {
        // Re-validate through the exact checked decoder the fuzz corpus
        // drives (ctl holds header + shm_off contiguously = a StagedFrame).
        StagedFrame frame{};
        if (!decode_staged_frame(c->ctl, sizeof(StagedFrame), frame)) {
          close_conn(c);
          return;
        }
        ErrorCode resolved = regions_->resolve(
            c->hdr.addr, c->hdr.rkey, c->hdr.len, c->hdr.extent_gen,
            c->hdr.op == kOpWriteStaged ? poolspan::Access::kWrite : poolspan::Access::kRead,
            c->hdr.trace_id, c->target, c->virt, c->offset);
        c->valid = resolved == ErrorCode::OK;
        if (!c->valid) {
          // Mirrors the thread server: an unresolvable staged op answers
          // the resolve verdict (STALE_EXTENT for a poolsan conviction,
          // MEMORY_ACCESS_ERROR otherwise) without charging admission.
          finish(c, code(resolved));
          return;
        }
        gate_or_park(c);
        return;
      }
      case kOpFabricOffer:
      case kOpFabricPull:
        do_fabric(c);
        return;
      case kOpWrite: {
        const ErrorCode resolved = regions_->resolve(
            c->hdr.addr, c->hdr.rkey, c->hdr.len, c->hdr.extent_gen,
            poolspan::Access::kWrite, c->hdr.trace_id, c->target, c->virt, c->offset);
        c->valid = resolved == ErrorCode::OK;
        if (!c->valid) {
          // Must still drain the payload to keep the stream aligned.
          begin_drain(c, code(resolved));
          return;
        }
        gate_or_park(c);
        return;
      }
      case kOpRead: {
        const ErrorCode resolved = regions_->resolve(
            c->hdr.addr, c->hdr.rkey, c->hdr.len, c->hdr.extent_gen,
            poolspan::Access::kRead, c->hdr.trace_id, c->target, c->virt, c->offset);
        c->valid = resolved == ErrorCode::OK;
        if (!c->valid) {
          finish(c, code(resolved));
          return;
        }
        gate_or_park(c);
        return;
      }
      default:
        close_conn(c);  // decode_request_header whitelists ops; unreachable
        return;
    }
  }

  void do_hello(Conn* c) {
    char name[kMaxHelloNameBytes + 1] = {};
    std::memcpy(name, c->ctl + sizeof(DataRequestHeader), c->hdr.len);
    finish(c, code(map_staging_segment(name, c->stg_base, c->stg_len)));
  }

  void do_fabric(Conn* c) {
    const ErrorCode resolved =
        regions_->resolve(c->hdr.addr, c->hdr.rkey, c->hdr.len, c->hdr.extent_gen,
                          poolspan::Access::kRead, c->hdr.trace_id, c->target, c->virt,
                          c->offset);
    c->valid = resolved == ErrorCode::OK;
    if (!c->valid || c->target) {
      // Conviction verdicts (STALE_EXTENT) ride through verbatim, exactly
      // like the thread server's fabric branch.
      finish(c, code(!c->valid ? resolved : ErrorCode::MEMORY_ACCESS_ERROR));
      return;
    }
    uint64_t transfer_id = 0;
    std::memcpy(&transfer_id, c->ctl + sizeof(DataRequestHeader), sizeof(transfer_id));
    const uint64_t offset = c->offset;
    const uint64_t len = c->hdr.len;
    if (c->hdr.op == kOpFabricOffer && c->virt.offer_fn) {
      auto fn = c->virt.offer_fn;
      offload(c, [fn, offset, len, transfer_id] {
        return static_cast<uint32_t>(fn(offset, len, transfer_id));
      });
      return;
    }
    if (c->hdr.op == kOpFabricPull && c->virt.pull_fn) {
      uint16_t alen = 0;
      std::memcpy(&alen, c->ctl + sizeof(DataRequestHeader) + 8, sizeof(alen));
      std::string addr(reinterpret_cast<const char*>(c->ctl) + sizeof(DataRequestHeader) + 10,
                       alen);
      auto fn = c->virt.pull_fn;
      offload(c, [fn, addr, transfer_id, offset, len] {
        // Blocks until the bytes are in device memory — the status send
        // doubles as the completion, exactly like the thread server.
        return static_cast<uint32_t>(fn(addr, transfer_id, offset, len));
      });
      return;
    }
    finish(c, code(ErrorCode::NOT_IMPLEMENTED));
  }

  // ---- admission -------------------------------------------------------

  void gate_or_park(Conn* c) {
    if (gate_->try_enter(c->hdr.len)) {
      c->ticket = true;
      c->ticket_bytes = c->hdr.len;
      admitted(c);
      return;
    }
    // Same adaptive-LIFO shape as AdmissionGate's thread path: park the
    // newcomer, shed the OLDEST waiter once the queue is over watermark.
    // The watermark is judged against the SERVER-wide parked count
    // (parked_total_ is shared by every loop on this gate), so
    // BTPU_DATA_MAX_QUEUE bounds total queueing exactly like the thread
    // server — a multi-loop engine must not multiply it. Shed order under
    // pressure is oldest-of-THIS-loop (cross-loop oldest would need a
    // shared structure on the hot path; the bound is what operators tune).
    // ordering: relaxed — cross-loop advisory watermark; each deque is loop-owned, so the count only tunes shed pressure, never guards data.
    if (parked_total_->load(std::memory_order_relaxed) >= gate_->options().max_queue) {
      if (!parked_.empty()) {
        Conn* oldest = parked_.front();
        parked_.pop_front();
        // ordering: relaxed — advisory watermark (see try_park).
        parked_total_->fetch_sub(1, std::memory_order_relaxed);
        oldest->state = Conn::S::kHeader;  // leaves kParked
        shed(oldest);
      } else {
        shed(c);  // max_queue == 0 (or siblings hold the whole quota): never wait
        return;
      }
    }
    c->state = Conn::S::kParked;
    parked_.push_back(c);
    // ordering: relaxed — advisory watermark (see try_park).
    parked_total_->fetch_add(1, std::memory_order_relaxed);
  }

  void shed(Conn* c) {
    // ordering: relaxed — monotonic stat counter.
    robust_counters().shed.fetch_add(1, std::memory_order_relaxed);
    flight::record_at(trace::now_ns(), flight::Ev::kShed, /*a0=data plane*/ 2, 0,
                      c->hdr.trace_id);
    rejected(c, code(ErrorCode::RETRY_LATER));
  }

  void expire(Conn* c) {
    // ordering: relaxed — monotonic stat counter.
    robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
    flight::record_at(trace::now_ns(), flight::Ev::kDeadlineExceeded, /*a0=server*/ 1, 0,
                      c->hdr.trace_id);
    rejected(c, code(ErrorCode::DEADLINE_EXCEEDED));
  }

  // A gated op refused before service (shed or queue-expired deadline).
  void rejected(Conn* c, uint32_t status) {
    if (c->hdr.op == kOpWrite) {
      begin_drain(c, status);  // keep the stream aligned
      return;
    }
    if (c->hdr.op == kOpReadStaged || c->hdr.op == kOpWriteStaged) {
      // Thread-server parity: a bad segment outranks the rejection code.
      uint64_t shm_off = 0;
      std::memcpy(&shm_off, c->ctl + sizeof(DataRequestHeader), sizeof(shm_off));
      if (!staging_bounds_ok(c->stg_base, c->stg_len, shm_off, c->hdr.len))
        status = code(ErrorCode::MEMORY_ACCESS_ERROR);
    }
    finish(c, status);
  }

  // Ticket held: serve the op.
  void admitted(Conn* c) {
    if (c->deadline.expired()) {
      // ordering: relaxed — monotonic stat counter.
      robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
      rejected(c, code(ErrorCode::DEADLINE_EXCEEDED));
      return;
    }
    switch (c->hdr.op) {
      case kOpReadStaged:
      case kOpWriteStaged:
        serve_staged(c);
        return;
      case kOpWrite:
        c->drain_only = false;
        if (!c->target) {
          if (!ensure_scratch(c, c->hdr.len)) {
            begin_drain(c, code(ErrorCode::OUT_OF_MEMORY));
            return;
          }
        }
        if (c->hdr.len == 0) {
          write_payload_complete(c);
          return;
        }
        c->state = Conn::S::kPayload;
        arm_recv_payload(c);
        return;
      case kOpRead:
        serve_read(c);
        return;
      default:
        finish(c, code(ErrorCode::INTERNAL_ERROR));  // unreachable
        return;
    }
  }

  void serve_staged(Conn* c) {
    uint64_t shm_off = 0;
    std::memcpy(&shm_off, c->ctl + sizeof(DataRequestHeader), sizeof(shm_off));
    if (!staging_bounds_ok(c->stg_base, c->stg_len, shm_off, c->hdr.len)) {
      finish(c, code(ErrorCode::MEMORY_ACCESS_ERROR));
      return;
    }
    uint8_t* seg = c->stg_base + shm_off;
    const uint64_t len = c->hdr.len;
    if (c->target) {
      if (c->hdr.op == kOpWriteStaged) {
        std::memcpy(c->target, seg, len);
      } else {
        std::memcpy(seg, c->target, len);
      }
      finish(c, code(ErrorCode::OK));
      return;
    }
    // Virtual region: the callback moves bytes directly between the
    // backing store and the shared segment — possibly blocking (device
    // tier), so it runs on the exec pool.
    const uint64_t offset = c->offset;
    if (c->hdr.op == kOpWriteStaged) {
      auto fn = c->virt.write_fn;
      offload(c, [fn, offset, seg, len] { return static_cast<uint32_t>(fn(offset, seg, len)); });
    } else {
      auto fn = c->virt.read_fn;
      offload(c, [fn, offset, seg, len] { return static_cast<uint32_t>(fn(offset, seg, len)); });
    }
  }

  void serve_read(Conn* c) {
    if (c->target) {
      // Stream lane headline: ONE gather write whose payload iovec points
      // into the registered pool region. No staging copy exists server-side.
      c->status = code(ErrorCode::OK);
      c->resp_payload = c->target;
      c->resp_len = c->hdr.len;
      c->pool_direct = true;
      arm_send(c);
      return;
    }
    if (c->virt.direct_fd >= 0) {
      start_disk_read(c);
      return;
    }
    exec_read_fallback(c);
  }

  void exec_read_fallback(Conn* c) {
    if (!ensure_scratch(c, c->hdr.len)) {
      finish(c, code(ErrorCode::OUT_OF_MEMORY));
      return;
    }
    const uint64_t offset = c->offset;
    const uint64_t len = c->hdr.len;
    uint8_t* dst = c->scratch;
    auto fn = c->virt.read_fn;
    offload(c, [fn, offset, dst, len] { return static_cast<uint32_t>(fn(offset, dst, len)); });
  }

  void start_disk_read(Conn* c) {
    // Disk tier unified on the SAME ring as the network ops: the backing
    // file read is submitted as an IORING_OP_READ and the loop keeps
    // serving other connections while the NVMe completes it. O_DIRECT
    // files get 512-aligned window widening (scratch is always aligned).
    if (c->virt.direct_odirect) {
      c->win_start = c->offset & ~(kOdirectAlign - 1);
      c->win_len = ((c->offset + c->hdr.len + kOdirectAlign - 1) & ~(kOdirectAlign - 1)) -
                   c->win_start;
    } else {
      c->win_start = c->offset;
      c->win_len = c->hdr.len;
    }
    c->win_done = 0;
    if (!ensure_scratch(c, c->win_len)) {
      finish(c, code(ErrorCode::OUT_OF_MEMORY));
      return;
    }
    arm_disk_read(c);
  }

  void disk_read_cqe(Conn* c, int32_t res) {
    if (res < 0) {
      // O_DIRECT alignment quirk or transient I/O error: fall back to the
      // backend callback, which owns its own bounce machinery.
      exec_read_fallback(c);
      return;
    }
    if (res == 0) {
      // EOF inside capacity (sparse backing file): zero-fill, like raw_io.
      std::memset(c->scratch + c->win_done, 0, static_cast<size_t>(c->win_len - c->win_done));
      c->win_done = c->win_len;
    } else {
      c->win_done += static_cast<uint64_t>(res);
    }
    if (c->win_done < c->win_len) {
      arm_disk_read(c);
      return;
    }
    c->status = code(ErrorCode::OK);
    c->resp_payload = c->scratch + (c->offset - c->win_start);
    c->resp_len = c->hdr.len;
    arm_send(c);
  }

  // ---- write payload ---------------------------------------------------

  void begin_drain(Conn* c, uint32_t status) {
    c->status = status;
    if (c->hdr.len == 0) {
      finish(c, status);
      return;
    }
    c->drain_only = true;
    if (!ensure_scratch(c, kDrainChunk)) {
      close_conn(c);  // cannot even drain: drop the stream
      return;
    }
    c->state = Conn::S::kPayload;
    arm_recv_payload(c);
  }

  // read_exact/write_all retry EINTR (and EAGAIN can surface if fast-poll
  // raced a consumed wakeup); the state machines re-arm instead of killing
  // the connection, matching the thread server's loops.
  static bool retryable(int32_t res) { return res == -EINTR || res == -EAGAIN; }

  void payload_cqe(Conn* c, int32_t res) {
    if (retryable(res)) {
      arm_recv_payload(c);
      return;
    }
    if (res <= 0) {
      close_conn(c);
      return;
    }
    c->pay_done += static_cast<uint64_t>(res);
    if (c->pay_done < c->hdr.len) {
      arm_recv_payload(c);
      return;
    }
    write_payload_complete(c);
  }

  void write_payload_complete(Conn* c) {
    if (c->drain_only) {
      finish(c, c->status);
      return;
    }
    if (c->target) {
      // Bytes already landed in the region. Mid-service expiry answers
      // DEADLINE_EXCEEDED — one-sided writes are unacknowledged until this
      // status, so the client treats them as not-written.
      if (c->deadline.expired()) {
        // ordering: relaxed — monotonic stat counter.
        robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
        finish(c, code(ErrorCode::DEADLINE_EXCEEDED));
        return;
      }
      finish(c, code(ErrorCode::OK));
      return;
    }
    if (c->deadline.expired()) {
      // Budget spent during the drain: refuse the backing-store apply.
      // ordering: relaxed — monotonic stat counter.
      robust_counters().deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
      finish(c, code(ErrorCode::DEADLINE_EXCEEDED));
      return;
    }
    const uint64_t offset = c->offset;
    const uint64_t len = c->hdr.len;
    const uint8_t* src = c->scratch;
    auto fn = c->virt.write_fn;
    offload(c, [fn, offset, src, len] { return static_cast<uint32_t>(fn(offset, src, len)); });
  }

  // ---- completion plumbing --------------------------------------------

  void offload(Conn* c, std::function<uint32_t()> work) {
    c->state = Conn::S::kExec;
    c->exec_out = true;
    UringLoop* loop = this;
    exec_->submit([loop, c, work = std::move(work)] { loop->post_exec(c, work()); });
  }

  void exec_done(Conn* c, uint32_t status) {
    c->exec_out = false;
    if (c->dead || stopping_) {
      maybe_destroy(c);
      return;
    }
    if (c->hdr.op == kOpRead && status == code(ErrorCode::OK)) {
      c->status = status;
      c->resp_payload = c->scratch;
      c->resp_len = c->hdr.len;
      arm_send(c);
      return;
    }
    finish(c, status);
  }

  // Sends a bare status response (no payload).
  void finish(Conn* c, uint32_t status) {
    c->status = status;
    c->resp_payload = nullptr;
    c->resp_len = 0;
    arm_send(c);
  }

  void send_cqe(Conn* c, int32_t res) {
    if (retryable(res)) {
      arm_send(c);
      return;
    }
    if (res <= 0) {
      close_conn(c);
      return;
    }
    c->resp_done += static_cast<uint64_t>(res);
    const uint64_t total = 4 + (c->resp_payload ? c->resp_len : 0);
    if (c->resp_done < total) {
      arm_send(c);
      return;
    }
    // Lane accounting on COMPLETION only, like the client-side counters.
    if (c->pool_direct && c->status == code(ErrorCode::OK)) {
      if (counters_.pool_direct_ops) counters_.pool_direct_ops->add();
      if (counters_.pool_direct_bytes) counters_.pool_direct_bytes->add(c->resp_len);
    }
    observe_op_complete(c);
    release_ticket(c);
    start_header(c);
  }

  // Op fully answered: histogram samples always, span + flight completion
  // stamped with the header's trace id (ops interleave on one loop thread,
  // so there is no ambient context here — record_remote_span is the
  // explicit-ids path).
  void observe_op_complete(Conn* c) {
    if (c->op_start_ns == 0) return;
    const uint64_t t1 = trace::now_ns();
    hist::data_op(data_op_hist_name(c->hdr.op)).record_us((t1 - c->op_start_ns) / 1000);
    if (c->send_start_ns != 0 && t1 > c->send_start_ns)
      hist::uring_send().record_us((t1 - c->send_start_ns) / 1000);
    if (c->hdr.trace_id != 0)
      trace::record_remote_span(data_op_span_name(c->hdr.op), c->hdr.trace_id,
                                c->hdr.span_id, c->op_start_ns, t1);
    flight::record_at(t1, flight::Ev::kUringComplete, c->hdr.op, c->status,
                      c->hdr.trace_id);
    c->op_start_ns = 0;
    c->send_start_ns = 0;
  }

  void release_ticket(Conn* c) {
    if (!c->ticket) return;
    c->ticket = false;
    gate_->release(c->ticket_bytes);
    unpark();
  }

  // Admit parked ops newest-first while the gate has room.
  void unpark() {
    if (stopping_) return;  // shutdown destroys parked conns, never serves them
    while (!parked_.empty()) {
      Conn* newest = parked_.back();
      if (!gate_->try_enter(newest->hdr.len)) return;
      parked_.pop_back();
      // ordering: relaxed — advisory watermark (see try_park).
      parked_total_->fetch_sub(1, std::memory_order_relaxed);
      newest->state = Conn::S::kHeader;
      newest->ticket = true;
      newest->ticket_bytes = newest->hdr.len;
      admitted(newest);
    }
  }

  void sweep_parked() {
    // Queue-expired deadlines answer DEADLINE_EXCEEDED without service.
    for (size_t i = 0; i < parked_.size();) {
      Conn* c = parked_[i];
      if (!c->deadline.is_infinite() && c->deadline.expired()) {
        parked_.erase(parked_.begin() + static_cast<ptrdiff_t>(i));
        // ordering: relaxed — advisory watermark (see try_park).
        parked_total_->fetch_sub(1, std::memory_order_relaxed);
        c->state = Conn::S::kHeader;
        expire(c);
        continue;
      }
      ++i;
    }
    // Cross-loop capacity: releases on sibling loops don't wake this one,
    // so the sweep (every completion + the 10ms tick) retries the gate.
    unpark();
  }

  // ---- lifecycle -------------------------------------------------------

  void on_accept(int32_t res) {
    accept_out_ = false;
#ifdef BTPU_URING_TSAN_FD_SYNC
    // res is the poll mask; the actual accept happens through the
    // intercepted syscall (listener is O_NONBLOCK in tsan builds).
    if (res >= 0)
      res = ::accept4(listen_fd_, nullptr, nullptr, SOCK_CLOEXEC);
    if (res < 0 && (errno == EAGAIN || errno == EWOULDBLOCK) && !stopping_) {
      arm_accept();  // spurious readiness: re-arm the poll
      return;
    }
#endif
    if (stopping_) {
      if (res >= 0) ::close(res);
      return;
    }
    if (res < 0) {
      // EMFILE/ENFILE under fan-in pressure: back off one tick instead of
      // re-arming into a hot error loop.
      accept_rearm_ = true;
      return;
    }
    // IORING_OP_ACCEPT bypasses net::tcp_accept, so apply its socket
    // options here: without TCP_NODELAY the 4-byte status acks of the
    // staged lane serialize on delayed ACKs (measured 0.02 GB/s).
    net::set_nodelay(res);
    auto* c = new Conn();
    c->fd = res;
    c->loop = this;
    conns_.insert(c);
    // ordering: relaxed — diagnostic connection gauge; conn lifetime is loop-owned.
    conn_count_->fetch_add(1, std::memory_order_relaxed);
    start_header(c);
    arm_accept();
  }

  void close_conn(Conn* c) {
    release_ticket(c);
    if (c->state == Conn::S::kParked) {
      for (auto it = parked_.begin(); it != parked_.end(); ++it) {
        if (*it == c) {
          parked_.erase(it);
          // ordering: relaxed — advisory watermark (see try_park).
          parked_total_->fetch_sub(1, std::memory_order_relaxed);
          break;
        }
      }
    }
    // Peer-visible EOF right away, even if the fd must linger for an
    // in-flight completion (SocketShutdownGuard parity).
    ::shutdown(c->fd, SHUT_RDWR);
    c->dead = true;
    maybe_destroy(c);
  }

  void maybe_destroy(Conn* c) {
    // zc_notif_pending: the kernel still holds (and may DMA from) the send
    // buffer, and its notif CQE names this Conn — destruction waits.
    if (c->sqe_out || c->exec_out || c->zc_notif_pending > 0) return;
    conns_.erase(c);
    // ordering: relaxed — diagnostic connection gauge; conn lifetime is loop-owned.
    conn_count_->fetch_sub(1, std::memory_order_relaxed);
    delete c;
  }

  // ---- CQE dispatch ----------------------------------------------------

  void handle_cqe(const io_uring_cqe& cqe) {
    --outstanding_;
    const uint64_t ud = cqe.user_data;
    if (ud < 8) {
      switch (ud) {
        case kUdAccept:
          on_accept(cqe.res);
          break;
        case kUdEvent: {
          event_out_ = false;
          // Exec completions ride the eventfd; drain them now.
          drain_exec_done();
          if (!stopping_) {
            if (cqe.res >= 0) {
              arm_event();
            } else if (!event_broken_) {
              // A failing eventfd read must NOT be re-armed into a hot
              // -EINVAL spin. Degraded mode: the 10ms timeout tick keeps
              // stop/exec-drain latency bounded instead.
              event_broken_ = true;
              LOG_ERROR << "uring loop: eventfd read failed ("
                        << std::strerror(-cqe.res) << "); degrading to timer wakeups";
            }
          }
          break;
        }
        case kUdTimeout:
          timeout_armed_ = false;
          if (!stopping_ && accept_rearm_ && !accept_out_) {
            accept_rearm_ = false;
            arm_accept();
          }
          break;
        case kUdCancel:
        default:
          break;
      }
      return;
    }
    auto* c = reinterpret_cast<Conn*>(static_cast<uintptr_t>(ud));
    if (cqe.flags & IORING_CQE_F_NOTIF) {
      // SEND_ZC buffer-release notification: the kernel is done with the
      // pool pages. REPORT_USAGE classifies the completion — a kernel that
      // fell back to copying (loopback always does) is a perf-regression
      // signal the counters surface, not an error. Does NOT touch sqe_out:
      // the send's main CQE owns that.
      if (c->zc_notif_pending > 0) --c->zc_notif_pending;
      if (static_cast<uint32_t>(cqe.res) & IORING_NOTIF_USAGE_ZC_COPIED) {
        if (counters_.zerocopy_copied) counters_.zerocopy_copied->add();
      } else {
        if (counters_.zerocopy_sent) counters_.zerocopy_sent->add();
      }
      if (c->dead || stopping_) maybe_destroy(c);
      return;
    }
    c->sqe_out = false;
    bool zc_rejected = false;
    if (c->zc_send_out) {
      c->zc_send_out = false;
      if (!(cqe.flags & IORING_CQE_F_MORE)) {
        // Failed/degenerate SEND_ZC: the kernel posts no notif for it.
        // Refund the second completion counted at submit.
        --outstanding_;
        if (c->zc_notif_pending > 0) --c->zc_notif_pending;
      }
      // A kernel that probes SEND_ZC but rejects this submission shape
      // (6.0/6.1: opcode exists, REPORT_USAGE ioprio flag doesn't) answers
      // -EINVAL. That's a capability verdict, not a connection error:
      // disable ZC on this loop and finish the response on writev.
      zc_rejected = cqe.res == -EINVAL || cqe.res == -EOPNOTSUPP;
    }
    if (c->dead || stopping_) {
      maybe_destroy(c);
      return;
    }
    if (zc_rejected && c->state == Conn::S::kSend) {
      if (zc_ok_) {
        zc_ok_ = false;
        LOG_ERROR << "uring loop: kernel rejected SEND_ZC shape ("
                  << std::strerror(static_cast<int>(-cqe.res))
                  << "); zero-copy sends disabled on this loop";
      }
      arm_send(c);  // re-decides: zc_ok_ now false -> writev path
      return;
    }
    // Ring recv completed: take the client's write-side release edge
    // (no-op outside TSan builds, see file header).
    if (c->state == Conn::S::kHeader || c->state == Conn::S::kTrailer ||
        c->state == Conn::S::kPayload) {
      tsan_fd_acquire(c->fd);
    }
    switch (c->state) {
      case Conn::S::kHeader:
      case Conn::S::kTrailer: {
        if (retryable(cqe.res)) {
          arm_recv_ctl(c);
          return;
        }
        if (cqe.res <= 0) {
          close_conn(c);  // clean EOF or socket error
          return;
        }
        c->ctl_have += static_cast<uint32_t>(cqe.res);
        if (c->ctl_have < c->ctl_need) {
          arm_recv_ctl(c);
          return;
        }
        if (c->state == Conn::S::kHeader) {
          header_complete(c);
        } else {
          trailer_complete(c);
        }
        return;
      }
      case Conn::S::kPayload:
        payload_cqe(c, cqe.res);
        return;
      case Conn::S::kDiskRead:
        disk_read_cqe(c, cqe.res);
        return;
      case Conn::S::kSend:
        send_cqe(c, cqe.res);
        return;
      case Conn::S::kExec:
      case Conn::S::kParked:
        // No submission should be outstanding in these states.
        close_conn(c);
        return;
    }
  }

  void drain_exec_done() {
    std::deque<ExecDone> done;
    {
      MutexLock lock(done_mutex_);
      done.swap(done_);
    }
    for (const auto& d : done) exec_done(d.conn, d.status);
  }

  void process_cqes() {
    io_uring_cqe buf[64];
    for (;;) {
      const unsigned n = ring_.drain(buf, 64);
      if (n == 0) return;
      for (unsigned i = 0; i < n; ++i) handle_cqe(buf[i]);
    }
  }

  // ---- main loop -------------------------------------------------------

  void run() {
    arm_accept();
    arm_event();
    // ordering: acquire — pairs with request_stop's release store.
    while (!stop_.load(std::memory_order_acquire)) {
      if ((!parked_.empty() || accept_rearm_ || event_broken_) && !timeout_armed_)
        arm_timeout();
      ring_.flush();
      const int rc = ring_.enter(1);
      if (rc < 0 && rc != -EINTR && rc != -EBUSY && rc != -EAGAIN) {
        LOG_ERROR << "uring loop: io_uring_enter failed: " << std::strerror(-rc);
        break;
      }
      process_cqes();
      drain_exec_done();  // eventfd may coalesce several posts into one CQE
      sweep_parked();
    }
    shutdown_all();
  }

  void shutdown_all() {
    stopping_ = true;
    // Parked conns hold no submissions: destroy them now.
    for (Conn* c : std::vector<Conn*>(parked_.begin(), parked_.end())) close_conn(c);
    parked_.clear();
    // Wake every in-flight socket op with an error/EOF.
    for (Conn* c : conns_) ::shutdown(c->fd, SHUT_RDWR);
    // ASYNC_CANCEL targets are named by the victim's user_data in addr.
    auto cancel = [this](uint64_t target_ud) {
      io_uring_sqe s = make_sqe(IORING_OP_ASYNC_CANCEL, -1, nullptr, 0, 0, kUdCancel);
      s.addr = target_ud;
      submit(s);
    };
    if (accept_out_) cancel(kUdAccept);
    if (timeout_armed_) cancel(kUdTimeout);
    if (event_out_) cancel(kUdEvent);
    // Drain every outstanding completion (kernel writes into conn buffers
    // until then) and every exec task (pool threads reference the conns).
    while (outstanding_ > 0 || !conns_.empty()) {
      drain_exec_done();
      for (Conn* c : std::vector<Conn*>(conns_.begin(), conns_.end())) {
        if (!c->sqe_out && !c->exec_out && c->zc_notif_pending == 0) {
          conns_.erase(c);
          // ordering: relaxed — diagnostic connection gauge; conn lifetime is loop-owned.
          conn_count_->fetch_sub(1, std::memory_order_relaxed);
          delete c;
        }
      }
      if (outstanding_ == 0) {
        if (conns_.empty()) break;
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
        continue;
      }
      ring_.flush();
      const int rc = ring_.enter(1);
      if (rc < 0 && rc != -EINTR && rc != -EBUSY && rc != -EAGAIN) break;
      process_cqes();
    }
    // Normally conns_ is empty here. If the ring died fatally mid-drain,
    // exec tasks can still complete (wait for them — the pool threads
    // dereference these conns), but a conn with a submission the dead ring
    // will never complete is deliberately LEAKED: the kernel may still DMA
    // into its buffers, and a leak beats a use-after-free.
    for (;;) {
      drain_exec_done();
      bool exec_busy = false;
      for (Conn* c : std::vector<Conn*>(conns_.begin(), conns_.end())) {
        if (c->exec_out) {
          exec_busy = true;
          continue;
        }
        conns_.erase(c);
        // ordering: relaxed — diagnostic connection gauge; conn lifetime is loop-owned.
        conn_count_->fetch_sub(1, std::memory_order_relaxed);
        if (c->sqe_out || c->zc_notif_pending > 0) {
          // Undrainable submission or an un-notified ZC buffer the kernel
          // may still DMA from: a leak beats a use-after-free.
          LOG_ERROR << "uring loop: leaking connection with undrainable submission";
          continue;
        }
        delete c;
      }
      if (!exec_busy) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  const int listen_fd_;
  RegionTable* const regions_;
  AdmissionGate* const gate_;
  ExecPool* const exec_;
  const DataPlaneCounters counters_;
  std::atomic<size_t>* const conn_count_;

  Ring ring_;
  std::thread thread_;
  std::atomic<bool> stop_{false};
  bool stopping_{false};

  int event_fd_{-1};
  uint64_t event_buf_{0};
  __kernel_timespec ts_{};

  bool accept_out_{false};
  bool accept_rearm_{false};
  bool event_out_{false};
  bool event_broken_{false};  // eventfd read failed: timer-wakeup fallback
  bool timeout_armed_{false};
  uint64_t outstanding_{0};

  std::unordered_set<Conn*> conns_;
  std::deque<Conn*> parked_;
  std::atomic<uint32_t>* const parked_total_;  // server-wide, shared across loops

  const bool zc_want_;           // env said yes (kernel still gets a veto)
  const uint64_t zc_threshold_;  // min pool-direct payload for SEND_ZC
  bool zc_ok_{false};            // resolved at init() from the ring probe

  Mutex done_mutex_;
  std::deque<ExecDone> done_ BTPU_GUARDED_BY(done_mutex_);
};

}  // namespace

ErrorCode map_staging_segment(const char* name, uint8_t*& stg_base, uint64_t& stg_len) {
  const int seg = ::shm_open(name, O_RDWR, 0600);
  struct stat st {};
  void* mapped = MAP_FAILED;
  if (seg >= 0 && ::fstat(seg, &st) == 0 && st.st_size > 0) {
    mapped = ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                    MAP_SHARED, seg, 0);
  }
  if (seg >= 0) ::close(seg);
  if (mapped == MAP_FAILED) {
    // Different host (name unknown) or mapping failure: the client falls
    // back to streaming on this status.
    return ErrorCode::CONNECTION_FAILED;
  }
  if (stg_base) ::munmap(stg_base, stg_len);
  stg_base = static_cast<uint8_t*>(mapped);
  stg_len = static_cast<uint64_t>(st.st_size);
  return ErrorCode::OK;
}

// ---- UringDataPlane --------------------------------------------------------

struct UringDataPlane::Internals {
  net::Socket listener;
  std::unique_ptr<ExecPool> exec;
  std::vector<std::unique_ptr<UringLoop>> loops;
  std::atomic<size_t> conn_count{0};
  // Server-wide admission-parked op count: BTPU_DATA_MAX_QUEUE bounds the
  // TOTAL across loops, exactly like the thread server's single gate queue.
  std::atomic<uint32_t> parked_total{0};
  bool stopped{false};
};

std::unique_ptr<UringDataPlane> UringDataPlane::create(net::Socket& listener,
                                                       RegionTable* regions,
                                                       AdmissionGate* gate,
                                                       const Options& opts) {
  if (!uring_runtime_available()) return nullptr;
  unsigned nloops = opts.loops;
  if (nloops == 0) {
    const unsigned hw = std::thread::hardware_concurrency();
    nloops = hw > 1 ? std::min(hw, 4u) : 1u;
  }
  auto impl = std::make_unique<Internals>();
#ifdef BTPU_URING_TSAN_FD_SYNC
  // TSan builds accept via POLL_ADD + real accept4 (see arm_accept): a
  // blocking listener could then block the loop on a raced-away
  // connection, so make it non-blocking and treat EAGAIN as re-arm.
  {
    const int fl = ::fcntl(listener.fd(), F_GETFL, 0);
    ::fcntl(listener.fd(), F_SETFL, fl | O_NONBLOCK);
  }
#endif
  impl->exec = std::make_unique<ExecPool>(opts.exec_threads);
  // Zero-copy sends: BTPU_IOURING_ZC=auto|0|1 (0 disables; auto and 1 both
  // defer to the per-ring kernel probe) gated by BTPU_ZC_THRESHOLD — below
  // it the pin+notif overhead of SEND_ZC loses to plain writev (loopback
  // loses at ANY size: the kernel copies regardless and says so via the
  // btpu_zerocopy_copied_count signal). Default 4 MiB.
  const std::string zc_mode = env_str("BTPU_IOURING_ZC", "auto");
  const bool zc_want = zc_mode != "0";
  const uint64_t zc_threshold =
      std::max<uint64_t>(env_u32("BTPU_ZC_THRESHOLD", 4u << 20), 4096);
  for (unsigned i = 0; i < nloops; ++i) {
    // The fd NUMBER is stable across the later Socket move; the caller
    // keeps ownership until this function commits to success, so a null
    // return leaves the listener usable for the thread-server fallback.
    auto loop = std::make_unique<UringLoop>(listener.fd(), regions, gate,
                                            impl->exec.get(), opts.counters,
                                            &impl->conn_count, &impl->parked_total,
                                            zc_want, zc_threshold);
    if (!loop->init(opts.sq_entries)) {
      // First loop failing = io_uring effectively unavailable (memlock,
      // seccomp): report null so the caller runs the thread server. A
      // LATER loop failing just means fewer loops.
      if (i == 0) return nullptr;
      break;
    }
    impl->loops.push_back(std::move(loop));
  }
  if (impl->loops.empty()) return nullptr;
  impl->listener = std::move(listener);
  for (auto& loop : impl->loops) loop->start();
  auto engine = std::unique_ptr<UringDataPlane>(new UringDataPlane());
  engine->impl_ = std::move(impl);
  return engine;
}

UringDataPlane::~UringDataPlane() { stop(); }

void UringDataPlane::stop() {
  if (!impl_ || impl_->stopped) return;
  impl_->stopped = true;
  for (auto& loop : impl_->loops) loop->request_stop();
  for (auto& loop : impl_->loops) loop->join();
  // Exec pool last: loops wait on in-flight exec tasks before exiting.
  impl_->exec.reset();
  impl_->listener.close();
}

size_t UringDataPlane::connection_count() const noexcept {
  // ordering: relaxed — point-in-time gauge read.
  return impl_ ? impl_->conn_count.load(std::memory_order_relaxed) : 0;
}

bool uring_runtime_available() {
  // BTPU_IOURING_NET is the operator-facing dial (auto|0|1): 0 pins the
  // thread-per-connection fallback, 1 *requires* the engine (a kernel that
  // cannot run it logs once and still falls back — serving beats refusing,
  // and the CI probe-preflight is what turns "can't" into SKIP rather than
  // a silent downgrade), auto probes. BTPU_FORCE_NO_URING=1 remains as the
  // original spelling of =0.
  const std::string mode = env_str("BTPU_IOURING_NET", "auto");
  if (mode == "0") return false;
  if (mode != "1" && env_bool("BTPU_FORCE_NO_URING", false)) return false;
  io_uring_params params{};
  const int fd = sys_io_uring_setup(2, &params);
  if (fd < 0) {
    if (mode == "1") {
      static std::atomic<bool> warned{false};
      if (!warned.exchange(true)) {
        LOG_ERROR << "BTPU_IOURING_NET=1 but io_uring_setup failed ("
                  << std::strerror(errno)
                  << "); falling back to the thread-per-connection server";
      }
    }
    return false;
  }
  // NODROP (5.5): overflow CQEs buffer in the kernel instead of vanishing
  // — without it the outstanding-op accounting would wedge. FAST_POLL
  // (5.7): socket ops poll-arm inline instead of punting every recv/send
  // to an io-wq worker thread — without it the engine degrades to exactly
  // the thread-per-op shape it replaces. Requiring both also guarantees
  // every opcode the engine submits (RECV/SEND/READ/WRITEV/ACCEPT/
  // TIMEOUT/ASYNC_CANCEL, all <= 5.6) exists, so a probe-passing kernel
  // can actually serve — a 5.5 kernel would otherwise pass NODROP and
  // then fail every connection's first recv with -EINVAL.
  const bool ok = (params.features & IORING_FEAT_NODROP) != 0 &&
                  (params.features & IORING_FEAT_FAST_POLL) != 0;
  ::close(fd);
  if (!ok && mode == "1") {
    static std::atomic<bool> warned{false};
    if (!warned.exchange(true)) {
      LOG_ERROR << "BTPU_IOURING_NET=1 but this kernel cannot run the io_uring "
                   "data plane (missing NODROP/FAST_POLL); falling back to the "
                   "thread-per-connection server";
    }
  }
  return ok;
}

size_t uring_active_loop_count() noexcept {
  // ordering: relaxed — point-in-time gauge read.
  return g_active_loops.load(std::memory_order_relaxed);
}

}  // namespace btpu::transport
