"""Sharded-array checkpoint/restore through the object store: save on one
mesh layout, restore on another (resharding), replicated-shard dedup, and
the manifest-committed-last crash/concurrency contract (interrupted saves
invisible, resumed saves reuse verified shards, last committed wins)."""

import json

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from blackbird_tpu import EmbeddedCluster
from blackbird_tpu.checkpoint import (committed_save_id, list_checkpoints,
                                      load_sharded, read_manifest,
                                      remove_checkpoint, save_sharded)
from blackbird_tpu.parallel import make_mesh
from typing import Any, Generator


@pytest.fixture()
def store() -> Generator[Any, None, None]:
    with EmbeddedCluster(workers=4, pool_bytes=64 << 20) as cluster:
        yield cluster.client()


def _shard_keys(store: Any, prefix: str) -> list[str]:
    return [s["key"] for s in read_manifest(store, prefix)["shards"]]


def test_save_and_restore_same_sharding(store: Any) -> None:
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 16 * 32, dtype=np.float32).reshape(8 * 16, 32), sharding
    )
    save_sharded(store, "ckpt/a", arr)
    back = load_sharded(store, "ckpt/a", sharding=sharding)
    assert back.sharding == sharding
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_restore_onto_different_mesh_layout(store: Any) -> None:
    mesh8 = make_mesh(8)
    arr = jax.device_put(
        np.random.default_rng(5).normal(size=(64, 48)).astype(np.float32),
        NamedSharding(mesh8, P("workers", None)),
    )
    save_sharded(store, "ckpt/reshard", arr)

    # Restore sharded over the SECOND axis on a 4-device mesh.
    mesh4 = make_mesh(4)
    target = NamedSharding(mesh4, P(None, "workers"))
    back = load_sharded(store, "ckpt/reshard", sharding=target)
    assert back.sharding == target
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))

    # And to a plain host array.
    host = load_sharded(store, "ckpt/reshard")
    np.testing.assert_array_equal(host, np.asarray(arr))


def test_replicated_sharding_stores_one_copy(store: Any) -> None:
    mesh = make_mesh(8)
    replicated = NamedSharding(mesh, P())  # same bytes on every device
    arr = jax.device_put(np.arange(1024, dtype=np.int32), replicated)
    save_sharded(store, "ckpt/rep", arr)
    keys = _shard_keys(store, "ckpt/rep")
    assert len(keys) == 1  # deduplicated: one object for all 8 replicas
    assert store.exists(keys[0])
    back = load_sharded(store, "ckpt/rep", sharding=replicated)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_remove_checkpoint_cleans_all_objects(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.zeros((32, 8), dtype=np.float32), NamedSharding(mesh, P("workers", None))
    )
    save_sharded(store, "ckpt/tmp", arr)
    assert committed_save_id(store, "ckpt/tmp") is not None
    keys = _shard_keys(store, "ckpt/tmp")
    # An orphan from an interrupted save: written under a claimed attempt's
    # data directory, referenced by no manifest.
    store.put("ckpt/tmp/data/00000099/999-1000", b"orphan")
    remove_checkpoint(store, "ckpt/tmp")
    assert committed_save_id(store, "ckpt/tmp") is None
    for key in keys:
        assert not store.exists(key)
    assert not store.exists("ckpt/tmp/data/00000099/999-1000")
    assert store.list("ckpt/tmp") == []  # attempts + manifests swept too


def test_list_checkpoints_discovers_prefixes(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(np.zeros(64, dtype=np.float32), NamedSharding(mesh, P()))
    save_sharded(store, "ckpt/step999", arr)
    save_sharded(store, "ckpt/step1000", arr)
    save_sharded(store, "other/x", arr)
    assert list_checkpoints(store, "ckpt/") == ["ckpt/step1000", "ckpt/step999"]
    assert sorted(list_checkpoints(store)) == ["ckpt/step1000", "ckpt/step999", "other/x"]
    # Resume pattern: latest step by PARSED step number (lexicographic max
    # would wrongly pick step999 over step1000).
    latest = max(list_checkpoints(store, "ckpt/"),
                 key=lambda p: int(p.rsplit("step", 1)[1]))
    assert latest == "ckpt/step1000"


def test_int_dtypes_and_odd_shapes(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.random.default_rng(9).integers(-1000, 1000, size=(17, 13, 5),
                                          dtype=np.int16),
        NamedSharding(mesh, P(None)),
    )
    save_sharded(store, "ckpt/odd", arr)
    np.testing.assert_array_equal(load_sharded(store, "ckpt/odd"), np.asarray(arr))


def test_resave_replaces_and_reclaims_stale_shards(store: Any) -> None:
    mesh = make_mesh(8)
    arr8 = jax.device_put(
        np.arange(64 * 8, dtype=np.float32).reshape(64, 8),
        NamedSharding(mesh, P("workers", None)),
    )
    save_sharded(store, "ckpt/resave", arr8)
    first_keys = set(_shard_keys(store, "ckpt/resave"))
    assert len(first_keys) == 8

    # Re-save the (different) array replicated: 1 shard; the 8 old shard
    # objects must be reclaimed, and loads must see the NEW bytes.
    arr_new = jax.device_put(
        np.ones((64, 8), dtype=np.float32), NamedSharding(mesh, P())
    )
    save_sharded(store, "ckpt/resave", arr_new)
    second_keys = set(_shard_keys(store, "ckpt/resave"))
    assert len(second_keys) == 1
    for stale in first_keys - second_keys:
        assert not store.exists(stale)
    np.testing.assert_array_equal(
        load_sharded(store, "ckpt/resave"), np.asarray(arr_new)
    )


def test_scalar_and_zero_d_arrays(store: Any) -> None:
    step = jax.numpy.asarray(12345, dtype=jax.numpy.int32)  # 0-d
    save_sharded(store, "ckpt/step", step)
    assert int(load_sharded(store, "ckpt/step")) == 12345


def test_legacy_single_meta_layout_reads_and_migrates(store: Any) -> None:
    """Pre-manifest checkpoints (one `<prefix>/meta` object + `/shard/`
    keys) still load, still list, and the first committed save over the
    prefix reclaims the old layout wholesale."""
    data = np.arange(256, dtype=np.float32)
    store.put("ckpt/legacy/shard/0-256", data.view(np.uint8))
    store.put("ckpt/legacy/meta", json.dumps({
        "global_shape": [256], "dtype": "<f4",
        "shards": [{"key": "ckpt/legacy/shard/0-256", "boxes": [[0, 256]],
                    "shape": [256]}],
    }).encode())
    assert list_checkpoints(store, "ckpt/") == ["ckpt/legacy"]
    np.testing.assert_array_equal(load_sharded(store, "ckpt/legacy"), data)

    mesh = make_mesh(8)
    arr = jax.device_put(np.ones(256, dtype=np.float32),
                         NamedSharding(mesh, P()))
    save_sharded(store, "ckpt/legacy", arr)
    assert not store.exists("ckpt/legacy/meta")
    assert not store.exists("ckpt/legacy/shard/0-256")
    np.testing.assert_array_equal(load_sharded(store, "ckpt/legacy"),
                                  np.asarray(arr))


class _FailingPuts:
    """Client wrapper that fails put() after the first N data-shard puts —
    a saver crashing mid-save."""

    def __init__(self, inner: Any, fail_after: int) -> None:
        self._inner = inner
        self._left = fail_after

    def put(self, key: str, data: Any, **kw: Any) -> None:
        if "/data/" in key:
            if self._left <= 0:
                raise RuntimeError("injected saver crash")
            self._left -= 1
        return self._inner.put(key, data, **kw)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def test_interrupted_save_is_invisible_and_resumable(store: Any) -> None:
    """Manifest-committed-last: a save that dies after writing some shards
    leaves NOTHING visible — not to list_checkpoints, not to load. The
    rerun claims a fresh id, reuses the dead attempt's bit-verified shards,
    and commits; the restore is bit-exact."""
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 32 * 16, dtype=np.float32).reshape(8 * 32, 16), sharding
    )
    with pytest.raises(RuntimeError, match="injected saver crash"):
        save_sharded(_FailingPuts(store, fail_after=3), "ckpt/fault", arr)
    assert list_checkpoints(store, "ckpt/") == []
    assert committed_save_id(store, "ckpt/fault") is None

    sid = save_sharded(store, "ckpt/fault", arr)
    assert committed_save_id(store, "ckpt/fault") == sid
    manifest = read_manifest(store, "ckpt/fault")
    # The 3 shards the crashed attempt completed were verified + reused,
    # not rewritten; the rest were written fresh under the new id.
    reused = [s for s in manifest["shards"] if s.get("reused")]
    assert len(reused) == 3, manifest["shards"]
    back = load_sharded(store, "ckpt/fault", sharding=sharding)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_resume_rejects_changed_bytes(store: Any) -> None:
    """Shard reuse is crc-gated: when the array CHANGED between the crashed
    attempt and the rerun, every shard is rewritten — stale bytes from the
    dead attempt can never leak into the committed checkpoint."""
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr_a = jax.device_put(
        np.zeros((64, 16), dtype=np.float32), sharding)
    arr_b = jax.device_put(
        np.ones((64, 16), dtype=np.float32), sharding)
    with pytest.raises(RuntimeError):
        save_sharded(_FailingPuts(store, fail_after=4), "ckpt/chg", arr_a)
    save_sharded(store, "ckpt/chg", arr_b)
    manifest = read_manifest(store, "ckpt/chg")
    assert not any(s.get("reused") for s in manifest["shards"])
    np.testing.assert_array_equal(load_sharded(store, "ckpt/chg"),
                                  np.asarray(arr_b))


def test_concurrent_savers_last_commit_wins(store: Any) -> None:
    """The old single-meta layout overwrote via remove+retry — two
    concurrent savers could interleave into a meta pointing at the other
    saver's (deleted) shards. The claim/manifest scheme gives each saver a
    disjoint id and readers the HIGHEST committed manifest: run two savers
    truly concurrently, many times, and the surviving checkpoint must
    always be exactly one saver's array, bit-for-bit."""
    import threading

    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arrays = {
        "a": jax.device_put(
            np.full((64, 8), 7.0, dtype=np.float32), sharding),
        "b": jax.device_put(
            np.full((64, 8), 9.0, dtype=np.float32), sharding),
    }
    sids: dict[str, int] = {}
    errors: list[BaseException] = []

    def run(tag: str) -> None:
        try:
            sids[tag] = save_sharded(store, "ckpt/race", arrays[tag])
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(t,)) for t in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert sids["a"] != sids["b"]  # claims are disjoint by construction
    winner = max(sids, key=lambda t: sids[t])
    assert committed_save_id(store, "ckpt/race") == sids[winner]
    np.testing.assert_array_equal(load_sharded(store, "ckpt/race"),
                                  np.asarray(arrays[winner]))


def test_worker_crash_mid_save_resumes_cleanly() -> None:
    """Pod-scale fault drill (ISSUE satellite): SIGKILL the worker holding
    a save's first shards MID-SAVE — the saver dies with it. The
    interrupted attempt must be invisible (no checkpoint exists), and a
    restarted save over the same prefix must commit a checkpoint that
    restores bit-exact, rewriting the shards that died with the worker."""
    import time

    from blackbird_tpu.checkpoint import save_sharded as save
    from blackbird_tpu.procluster import ProcessCluster

    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 64 * 32, dtype=np.float32).reshape(8 * 64, 32), sharding)

    with ProcessCluster(workers=2, devices_per_worker=0, pool_mb=0,
                        dram_pool_mb=32) as cluster:
        client = cluster.wait_ready()

        class KillsWorkerMidSave:
            """Fails like a real preemption: after 4 shard puts, the worker
            the placement plane has been writing to is SIGKILLed and the
            saver process 'dies' (raises) in the same instant."""

            def __init__(self, inner: Any) -> None:
                self._inner = inner
                self._data_puts = 0

            def put(self, key: str, data: Any, **kw: Any) -> None:
                if "/data/" in key:
                    if self._data_puts == 4:
                        cluster.kill_worker(0)
                        raise RuntimeError("saver preempted")
                    self._data_puts += 1
                self._inner.put(key, data, **kw)

            def __getattr__(self, name: str) -> Any:
                return getattr(self._inner, name)

        with pytest.raises(RuntimeError, match="saver preempted"):
            save(KillsWorkerMidSave(client), "ckpt/crash", arr)
        # Nothing committed: the partial is invisible to discovery and load.
        assert list_checkpoints(client, "ckpt/") == []
        assert committed_save_id(client, "ckpt/crash") is None

        # Resume AFTER the keystone pruned the dead worker (heartbeat TTL):
        # reuse is placement-verified, and the dead worker's shards must
        # read as gone, not as reusable.
        deadline = time.time() + 60
        while client.stats()["workers"] != 1:
            assert time.time() < deadline, "dead worker never pruned"
            time.sleep(0.2)
        sid = save(client, "ckpt/crash", arr)
        assert committed_save_id(client, "ckpt/crash") == sid
        manifest = read_manifest(client, "ckpt/crash")
        # The first attempt's shards died with worker 0: nothing to reuse.
        assert not any(s.get("reused") for s in manifest["shards"])
        np.testing.assert_array_equal(load_sharded(client, "ckpt/crash"),
                                      np.asarray(arr))


def test_save_overwrites_orphaned_objects(store: Any) -> None:
    """Debris from crashed previous saves — orphaned data shards, a stale
    claim marker, a legacy meta listing shards never written — must neither
    fail a fresh save nor corrupt what it commits."""
    mesh = make_mesh(8)
    sharding = NamedSharding(mesh, P("workers", None))
    arr = jax.device_put(
        np.arange(8 * 4 * 4, dtype=np.float32).reshape(8 * 4, 4), sharding
    )
    # Orphan 1: a stale claim + data shard from a crashed attempt whose
    # layout does not match (no reuse possible).
    store.put("ckpt/orphan/attempt/00000001",
              json.dumps({"layout": "bogus"}).encode())
    store.put("ckpt/orphan/data/00000001/0-64", b"\x00" * 64)
    # Orphan 2: a legacy meta listing a shard that was never written.
    store.put("ckpt/orphan/meta", json.dumps({
        "global_shape": [1], "dtype": "<f4",
        "shards": [{"key": "ckpt/orphan/shard/never-written",
                    "boxes": [[0, 1]], "shape": [1]}],
    }).encode())
    save_sharded(store, "ckpt/orphan", arr)  # must not raise
    np.testing.assert_array_equal(load_sharded(store, "ckpt/orphan"), np.asarray(arr))
    # The committed save reclaimed all the debris.
    assert not store.exists("ckpt/orphan/attempt/00000001")
    assert not store.exists("ckpt/orphan/data/00000001/0-64")
    assert not store.exists("ckpt/orphan/meta")


def test_each_object_has_single_writer(store: Any) -> None:
    """Multi-host safety invariant (single-process proxy): every shard box
    is written by exactly one owner device, so replicated shards never
    double-put. With 8 devices replicating one box, a save must issue
    exactly one data put for it (verified via a counting client wrapper)."""
    mesh = make_mesh(8)
    replicated = NamedSharding(mesh, P())
    arr = jax.device_put(np.arange(256, dtype=np.int32), replicated)

    puts = []

    class Counting:
        def __init__(self, inner: Any) -> None:
            self._inner = inner

        def put(self, key: str, data: Any, **kw: Any) -> None:
            puts.append(key)
            return self._inner.put(key, data, **kw)

        def __getattr__(self, name: str) -> Any:
            return getattr(self._inner, name)

    save_sharded(Counting(store), "ckpt/single", arr)
    shard_puts = [k for k in puts if "/data/" in k]
    assert len(shard_puts) == 1, shard_puts


def test_checkpoint_onto_ici_device_mesh() -> None:
    """Sharded checkpoint whose bytes live ON the device mesh: save with
    preferred_class=HBM_TPU against an ICI cluster (one JAX device pool per
    chip), then restore under a different sharding. Ties together the
    checkpoint layer, keystone placement, and the ICI device tier."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blackbird_tpu import EmbeddedCluster, StorageClass
    from blackbird_tpu.hbm import JaxHbmProvider
    from blackbird_tpu.native import TransportKind
    from blackbird_tpu.parallel import make_mesh

    provider = JaxHbmProvider(page_bytes=64 * 1024).register()
    try:
        with EmbeddedCluster(workers=8, pool_bytes=8 << 20,
                             storage_class=StorageClass.HBM_TPU,
                             transport=TransportKind.ICI) as cluster:
            client = cluster.client()
            mesh = make_mesh(8)
            arr = jax.device_put(
                np.arange(8 * 64 * 16, dtype=np.float32).reshape(8 * 64, 16),
                NamedSharding(mesh, P("workers", None)),
            )
            save_sharded(client, "ckpt/mesh", arr,
                         preferred_class=StorageClass.HBM_TPU)

            # Every shard object landed on the device tier.
            for shard in read_manifest(client, "ckpt/mesh")["shards"]:
                for copy in client.placements(shard["key"]):
                    for s in copy["shards"]:
                        assert s["location"]["kind"] == "device", shard["key"]

            back = load_sharded(client, "ckpt/mesh",
                                sharding=NamedSharding(mesh, P(None, "workers")))
            np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
    finally:
        JaxHbmProvider.unregister()


def test_erasure_coded_checkpoint_roundtrip(store: Any) -> None:
    mesh = make_mesh(8)
    arr = jax.device_put(
        np.arange(8192, dtype=np.float32).reshape(64, 128),
        NamedSharding(mesh, P("workers", None)),
    )
    save_sharded(store, "ckpt/ec", arr, ec=(2, 1))
    # Every shard object is one coded copy; the manifest stays replicated.
    for key in _shard_keys(store, "ckpt/ec"):
        copies = store.placements(key)
        assert len(copies) == 1 and copies[0]["ec"]["data_shards"] == 2
    # The manifest is stored as a degenerate (1, m) code: m+1 single-shard
    # copies on distinct workers — the same loss tolerance as the shards.
    sid = committed_save_id(store, "ckpt/ec")
    manifest_key = f"ckpt/ec/manifest/{sid:08d}"
    meta_ec = store.placements(manifest_key)[0]["ec"]
    assert meta_ec["data_shards"] == 1 and meta_ec["parity_shards"] == 1
    back = load_sharded(store, "ckpt/ec", sharding=NamedSharding(mesh, P(None, "workers")))
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
