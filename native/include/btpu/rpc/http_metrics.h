// Minimal HTTP/1.1 server exposing Prometheus text metrics + /healthz.
//
// Parity target: the reference runs a coro_http metrics server but never
// registers the /metrics route (rpc_service.cpp:387-390, README claims
// notwithstanding) — here it is real.
#pragma once

#include <atomic>
#include <functional>
#include <string>
#include <thread>

#include "btpu/net/net.h"

namespace btpu::keystone {
class KeystoneService;
}

namespace btpu::rpc {

class MetricsHttpServer {
 public:
  MetricsHttpServer(keystone::KeystoneService& service, std::string host, uint16_t port);
  ~MetricsHttpServer();

  ErrorCode start();
  void stop();
  uint16_t port() const noexcept { return port_; }

  // Prometheus exposition text for the wrapped keystone (exposed for tests).
  std::string render_metrics() const;

 private:
  void accept_loop();

  keystone::KeystoneService& service_;
  std::string host_;
  uint16_t port_;
  net::Socket listener_;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
};

}  // namespace btpu::rpc
