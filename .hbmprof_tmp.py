import faulthandler; faulthandler.dump_traceback_later(120, exit=True)
import time, numpy as np, jax
from blackbird_tpu import EmbeddedCluster, StorageClass
from blackbird_tpu.hbm import JaxHbmProvider

iters, obj = 64, 1 << 20
payloads = {f"b/{i}": np.random.default_rng(i).integers(0, 256, obj, dtype=np.uint8).tobytes() for i in range(iters)}
prov = JaxHbmProvider().register()
try:
    with EmbeddedCluster(workers=1, pool_bytes=768 << 20, storage_class=StorageClass.HBM_TPU) as cluster:
        client = cluster.client()
        warm = {f"w/{i}": payloads[f"b/{i}"] for i in range(33)}
        t0 = time.perf_counter(); client.put_many(warm, max_workers=1)
        print(f"warm {1e3*(time.perf_counter()-t0):.0f} ms", flush=True)
        for r in range(3):
            t0 = time.perf_counter()
            a = jax.device_put(np.frombuffer(payloads["b/0"], np.uint8), jax.devices()[0]); a.block_until_ready()
            link = time.perf_counter() - t0
            batch = {f"p{r}/{i}": p for i, p in enumerate(payloads.values())}
            t0 = time.perf_counter()
            client.put_many(batch, max_workers=1)
            dt = time.perf_counter() - t0
            print(f"round {r}: link(1MiB) {obj/link/1e9:.2f} GB/s | put {iters*obj/dt/1e9:.2f} GB/s ({dt*1e3:.0f} ms)", flush=True)
finally:
    JaxHbmProvider.unregister()
