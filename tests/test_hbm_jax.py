"""JAX HBM provider: device buffers (cpu here, TPU in prod) as the top tier."""

import numpy as np
import pytest

from blackbird_tpu import EmbeddedCluster, StorageClass
from blackbird_tpu.hbm import JaxHbmProvider


@pytest.fixture()
def jax_provider():
    provider = JaxHbmProvider(chunk_bytes=64 * 1024).register()
    yield provider
    JaxHbmProvider.unregister()


def test_hbm_tier_backed_by_jax_buffers(jax_provider):
    with EmbeddedCluster(workers=2, pool_bytes=4 << 20,
                         storage_class=StorageClass.HBM_TPU) as cluster:
        assert jax_provider.region_count() == 2  # one region per worker pool
        client = cluster.client()
        payload = np.random.default_rng(11).bytes(300 * 1024)  # partial chunks too
        client.put("hbm/obj", payload, max_workers=2)
        assert client.get("hbm/obj") == payload

        # Overwrite-after-remove reuses device ranges.
        client.remove("hbm/obj")
        payload2 = np.random.default_rng(12).bytes(100 * 1024)
        client.put("hbm/obj2", payload2, max_workers=1)
        assert client.get("hbm/obj2") == payload2
    assert jax_provider.region_count() == 0  # regions freed on shutdown


def test_hbm_unaligned_edges(jax_provider):
    with EmbeddedCluster(workers=1, pool_bytes=1 << 20,
                         storage_class=StorageClass.HBM_TPU) as cluster:
        client = cluster.client()
        for size in (1, 13, 4096, 64 * 1024 + 7):
            payload = np.random.default_rng(size).bytes(size)
            client.put(f"hbm/sz{size}", payload)
            assert client.get(f"hbm/sz{size}") == payload
