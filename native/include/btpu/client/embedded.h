// Embedded cluster: keystone + N workers + client in one process. The
// hermetic harness used by tests, the benchmark, and the Python bindings —
// the reference has no equivalent (its distributed behavior is only
// exercised by a localhost shell script, SURVEY §4).
#pragma once

#include <memory>

#include "btpu/client/client.h"
#include "btpu/coord/mem_coordinator.h"
#include "btpu/worker/worker.h"

namespace btpu::client {

struct EmbeddedClusterOptions {
  KeystoneConfig keystone;
  std::vector<worker::WorkerServiceConfig> workers;
  bool use_coordinator{true};  // in-memory coordinator wiring vs direct feed
  TransportKind transport{TransportKind::LOCAL};
  // Coordinator persistence (WAL + snapshot under durability.dir): a new
  // cluster started on the SAME dir recovers every acked durable object —
  // inline-tier bytes ride the records; RAM pool bytes die with the
  // process by design. Requires use_coordinator. Empty dir = memory-only.
  coord::DurabilityOptions durability;

  // Convenience: n workers x one RAM pool of pool_bytes each.
  static EmbeddedClusterOptions simple(size_t n_workers, uint64_t pool_bytes,
                                       StorageClass cls = StorageClass::RAM_CPU);
};

class EmbeddedCluster {
 public:
  explicit EmbeddedCluster(EmbeddedClusterOptions options);
  ~EmbeddedCluster();

  ErrorCode start();
  void stop();

  keystone::KeystoneService& keystone() { return *keystone_; }
  worker::WorkerService& worker(size_t i) { return *workers_.at(i); }
  size_t worker_count() const { return workers_.size(); }
  coord::MemCoordinator* coordinator() { return coordinator_.get(); }
  // Shared handle for clients that subscribe to the invalidation watch lane
  // (ClientOptions::cache_coordinator in lease-mode cache tests).
  std::shared_ptr<coord::MemCoordinator> coordinator_shared() { return coordinator_; }

  // A client wired to this cluster (embedded keystone, local data plane).
  std::unique_ptr<ObjectClient> make_client(ClientOptions options = ClientOptions());

  // Kills worker i abruptly (no clean unregister): stops heartbeats and
  // drops its transport, as a preemption would.
  void kill_worker(size_t i);
  // Brings a killed worker back as a FRESH process would come back: same id
  // and pool ids, new memory (RAM pools lose their bytes — the keystone's
  // repair already re-replicated them). The chaos-soak restart primitive.
  ErrorCode revive_worker(size_t i);
  bool worker_alive(size_t i) const { return i < workers_.size() && workers_[i] != nullptr; }

 private:
  // Shared bring-up for start() and revive_worker (initialize + start +
  // direct-feed registration): revived workers must be indistinguishable
  // from originally-started ones.
  Result<std::unique_ptr<worker::WorkerService>> start_worker_instance(size_t i);
  EmbeddedClusterOptions options_;
  std::shared_ptr<coord::MemCoordinator> coordinator_;
  std::unique_ptr<keystone::KeystoneService> keystone_;
  std::vector<std::unique_ptr<worker::WorkerService>> workers_;
  bool running_{false};
};

}  // namespace btpu::client
