#include "btpu/coord/remote_coordinator.h"

#include "btpu/common/env.h"
#include "btpu/common/deadline.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/common/wire.h"
#include "btpu/coord/coord_proto.h"

namespace btpu::coord {

using wire::Reader;
using wire::Writer;

namespace {
ErrorCode open_channel(const std::string& endpoint, uint8_t kind, net::Socket& out) {
  auto hp = net::parse_host_port(endpoint);
  if (!hp) return ErrorCode::INVALID_ADDRESS;
  auto sock = net::tcp_connect(hp->host, hp->port);
  if (!sock.ok()) return sock.error();
  out = std::move(sock).value();
  uint8_t hello = kind;
  BTPU_RETURN_IF_ERROR(
      net::send_frame(out.fd(), static_cast<uint8_t>(Op::kHello), &hello, 1));
  uint8_t opcode = 0;
  std::vector<uint8_t> payload;
  BTPU_RETURN_IF_ERROR(net::recv_frame(out.fd(), opcode, payload));
  Reader r(payload);
  ErrorCode ec{};
  if (!r.get(ec)) return ErrorCode::RPC_FAILED;
  return ec;
}

// Pulls the leading ErrorCode off a response payload.
ErrorCode take_status(Reader& r) {
  ErrorCode ec{};
  if (!r.get(ec)) return ErrorCode::RPC_FAILED;
  return ec;
}
}  // namespace

RemoteCoordinator::RemoteCoordinator(std::string endpoint) {
  size_t start = 0;
  while (start <= endpoint.size()) {
    const size_t comma = endpoint.find(',', start);
    const std::string part =
        endpoint.substr(start, comma == std::string::npos ? std::string::npos : comma - start);
    if (!part.empty()) endpoints_.push_back(part);
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  if (endpoints_.empty()) endpoints_.push_back("");
  if (const uint32_t v = env_u32("BTPU_COORD_RESPONSE_TIMEOUT_MS", 0); v != 0)
    set_response_timeout_ms(v);
}

RemoteCoordinator::~RemoteCoordinator() { disconnect(); }

ErrorCode RemoteCoordinator::connect() {
  MutexLock lock(reconnect_mutex_);
  terminated_ = false;  // an explicit connect() revives a disconnected client
  return connect_locked();
}

ErrorCode RemoteCoordinator::connect_locked() {
  if (connected_) return ErrorCode::OK;
  if (terminated_) return ErrorCode::CLIENT_DISCONNECTED;
  if (event_reader_.joinable()) event_reader_.join();  // from a dead session
  // Dial endpoints starting at the current one; a dead primary rotates to
  // its standby here.
  ErrorCode dial_ec = ErrorCode::CONNECTION_FAILED;
  bool dialed = false;
  for (size_t attempt = 0; attempt < endpoints_.size(); ++attempt) {
    dial_ec = open_channel(endpoint(), 0, call_sock_);
    if (dial_ec == ErrorCode::OK) {
      dial_ec = open_channel(endpoint(), 1, event_sock_);
      if (dial_ec == ErrorCode::OK) {
        dialed = true;
        break;
      }
      call_sock_.close();
    }
    endpoint_index_ = (endpoint_index_ + 1) % endpoints_.size();
  }
  if (!dialed) return dial_ec;
  stopping_ = false;
  {
    MutexLock rlock(resp_mutex_);
    reader_dead_ = false;
  }
  connected_ = true;
  generation_.fetch_add(1);
  event_reader_ = std::thread([this] {
    reader_thread_id_.store(std::this_thread::get_id());
    event_reader_loop();
  });
  LOG_DEBUG << "coordinator client connected to " << endpoint();

  // Replay session state from a previous connection (no-op on first
  // connect): watches and election candidacies live in the server's memory
  // and died with it.
  std::vector<std::pair<int64_t, std::string>> watches;
  std::vector<std::tuple<std::string, std::string, int64_t>> campaigns;
  {
    MutexLock wlock(watch_mutex_);
    for (const auto& [id, prefix] : watch_prefixes_) watches.emplace_back(id, prefix);
    for (const auto& [key, meta] : campaigns_) campaigns.push_back(meta);
  }
  for (const auto& [id, prefix] : watches) {
    if (auto ec = send_watch(id, prefix); ec != ErrorCode::OK) {
      LOG_WARN << "watch replay failed for prefix " << prefix << ": " << to_string(ec);
    }
  }
  for (const auto& [election, candidate, ttl] : campaigns) {
    if (auto ec = send_campaign(election, candidate, ttl); ec != ErrorCode::OK) {
      LOG_WARN << "campaign replay failed for " << election << "/" << candidate << ": "
               << to_string(ec);
    }
  }
  return ErrorCode::OK;
}

void RemoteCoordinator::disconnect() {
  // Serialize against auto-reconnect: taking reconnect_mutex_ waits out any
  // in-flight redial, and terminated_ stops later ones from resurrecting
  // the connection after we tear it down.
  MutexLock lock(reconnect_mutex_);
  terminated_ = true;
  stopping_ = true;
  connected_ = false;
  call_sock_.shutdown();
  event_sock_.shutdown();  // wakes the event reader blocked in recv
  if (event_reader_.joinable()) event_reader_.join();
  call_sock_.close();
  event_sock_.close();
}

bool RemoteCoordinator::is_connection_error(ErrorCode ec) noexcept {
  return ec == ErrorCode::CLIENT_DISCONNECTED || ec == ErrorCode::NETWORK_ERROR ||
         ec == ErrorCode::CONNECTION_FAILED || ec == ErrorCode::OPERATION_TIMEOUT;
}

ErrorCode RemoteCoordinator::reconnect(uint64_t seen_generation) {
  // Never from the event reader thread: reconnect joins that thread, and a
  // user watch/leader callback issuing a coordinator op on it would
  // self-join through the mutex (deadlock). Fail fast; the next call from
  // any other thread redials.
  if (std::this_thread::get_id() == reader_thread_id_.load())
    return ErrorCode::CONNECTION_FAILED;
  MutexLock lock(reconnect_mutex_);
  if (terminated_) return ErrorCode::CLIENT_DISCONNECTED;
  if (generation_.load() != seen_generation) {
    // Another thread already reconnected since the failure was observed.
    return connected_ ? ErrorCode::OK : ErrorCode::CONNECTION_FAILED;
  }
  // Tear the dead session down fully before redialing. Shutdown ALWAYS runs
  // (even when the reader already cleared connected_): it is what wakes any
  // thread still blocked in recv on the old sockets. Then drain in-flight
  // RPCs by passing through their channel mutexes, so no recv can survive
  // into the new connection and read its bytes off a reused fd.
  stopping_ = true;
  connected_ = false;
  call_sock_.shutdown();
  event_sock_.shutdown();
  {
    MutexLock drain_call(call_mutex_);
    MutexLock drain_event(event_write_mutex_);
  }
  if (event_reader_.joinable()) event_reader_.join();
  call_sock_.close();
  event_sock_.close();
  LOG_WARN << "coordinator connection lost; redialing";
  return connect_locked();
}

ErrorCode RemoteCoordinator::rotate_endpoint(uint64_t seen_generation) {
  if (endpoints_.size() < 2) return ErrorCode::NOT_LEADER;
  if (std::this_thread::get_id() == reader_thread_id_.load())
    return ErrorCode::NOT_LEADER;  // see reconnect(): never from the reader
  MutexLock lock(reconnect_mutex_);
  if (terminated_) return ErrorCode::CLIENT_DISCONNECTED;
  if (generation_.load() != seen_generation) {
    // Another thread already rotated/reconnected since this NOT_LEADER was
    // observed — retry on the current connection instead of rotating away
    // from a freshly found primary.
    return connected_ ? ErrorCode::OK : ErrorCode::CONNECTION_FAILED;
  }
  endpoint_index_ = (endpoint_index_ + 1) % endpoints_.size();
  stopping_ = true;
  connected_ = false;
  call_sock_.shutdown();
  event_sock_.shutdown();
  {
    MutexLock drain_call(call_mutex_);
    MutexLock drain_event(event_write_mutex_);
  }
  if (event_reader_.joinable()) event_reader_.join();
  call_sock_.close();
  event_sock_.close();
  LOG_WARN << "coordinator answered NOT_LEADER; rotating to " << endpoint();
  return connect_locked();
}

// Peeks the op-level status that leads every response payload.
static ErrorCode peek_status(const std::vector<uint8_t>& resp) {
  Reader r(resp);
  ErrorCode ec{};
  return r.get(ec) ? ec : ErrorCode::RPC_FAILED;
}

ErrorCode RemoteCoordinator::call(uint8_t opcode, const std::vector<uint8_t>& req,
                                  std::vector<uint8_t>& resp, bool* retried) {
  // Under a traced keystone RPC this shows up as a child span — the
  // "keystone waited on the coordinator" slice of a slow mutation.
  TRACE_SPAN("keystone.coord_call");
  if (retried) *retried = false;
  // The generation of the connection each attempt ran on: a NOT_LEADER
  // answer only justifies rotating away from THAT connection (another
  // thread may have rotated to the primary since — rotate_endpoint no-ops
  // then and the retry lands on the fresh connection).
  uint64_t attempt_gen = 0;
  auto attempt = [&]() -> ErrorCode {
    attempt_gen = generation_.load();
    if (!connected_) return ErrorCode::CLIENT_DISCONNECTED;
    MutexLock lock(call_mutex_);
    BTPU_RETURN_IF_ERROR(net::send_frame(call_sock_.fd(), opcode, req.data(), req.size()));
    uint8_t resp_op = 0;
    BTPU_RETURN_IF_ERROR(net::recv_frame(call_sock_.fd(), resp_op, resp));
    if (resp_op != opcode) return ErrorCode::RPC_FAILED;
    return ErrorCode::OK;
  };
  const uint64_t gen = generation_.load();
  auto ec = attempt();
  if (is_connection_error(ec) && !stopping_) {
    if (reconnect(gen) == ErrorCode::OK) {
      if (retried) *retried = true;
      ec = attempt();
    }
  }
  // A standby answered: the op provably did NOT execute, so rotating and
  // re-sending is safe even for mutations. One full cycle at most.
  for (size_t hops = 0; ec == ErrorCode::OK && peek_status(resp) == ErrorCode::NOT_LEADER &&
                        hops + 1 < endpoints_.size();
       ++hops) {
    if (rotate_endpoint(attempt_gen) != ErrorCode::OK) break;
    ec = attempt();
  }
  return ec;
}

ErrorCode RemoteCoordinator::event_call_raw(uint8_t opcode, const std::vector<uint8_t>& req,
                                            std::vector<uint8_t>& resp) {
  if (!connected_) return ErrorCode::CLIENT_DISCONNECTED;
  // Response wait = the configured bound (was a hardcoded 10 s) tightened
  // by the caller's ambient per-op deadline; an already-spent budget fails
  // before the request is even framed.
  const Deadline ambient = current_op_deadline();
  if (ambient.expired()) {
    // ordering: relaxed — monotonic stat counter.
    robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
    return ErrorCode::DEADLINE_EXCEEDED;
  }
  const Deadline wait =
      Deadline::after_ms(static_cast<int64_t>(response_timeout_ms_)).min(ambient);
  MutexLock lock(event_write_mutex_);
  {
    MutexLock rlock(resp_mutex_);
    resp_ready_ = false;
  }
  BTPU_RETURN_IF_ERROR(net::send_frame(event_sock_.fd(), opcode, req.data(), req.size()));
  MutexLock rlock(resp_mutex_);
  // Explicit deadline loop instead of the predicate overload: a predicate
  // lambda is analyzed as an unannotated function and would flag the
  // guarded resp_ready_/reader_dead_ reads; this body is checked with
  // resp_mutex_ held.
  const auto deadline = wait.time_point();
  while (!resp_ready_ && !reader_dead_) {
    if (resp_cv_.wait_until(rlock, deadline) == std::cv_status::timeout &&
        !resp_ready_ && !reader_dead_)
      return ambient.expired() ? ErrorCode::DEADLINE_EXCEEDED
                               : ErrorCode::OPERATION_TIMEOUT;
  }
  if (!resp_ready_) return ErrorCode::CLIENT_DISCONNECTED;  // reader died
  if (resp_opcode_ != opcode) return ErrorCode::RPC_FAILED;
  resp = std::move(resp_payload_);
  return ErrorCode::OK;
}

ErrorCode RemoteCoordinator::event_call(uint8_t opcode, const std::vector<uint8_t>& req,
                                        std::vector<uint8_t>& resp) {
  // Captured BEFORE each attempt: a NOT_LEADER answer only justifies
  // rotating away from the connection that produced it (see call()).
  uint64_t attempt_gen = generation_.load();
  auto ec = event_call_raw(opcode, req, resp);
  if (is_connection_error(ec) && !stopping_) {
    if (reconnect(attempt_gen) == ErrorCode::OK) {
      attempt_gen = generation_.load();
      ec = event_call_raw(opcode, req, resp);
    }
  }
  // Standby rejection: rotate to the primary (see call()). Session state
  // (watches, campaigns) is replayed by connect_locked on the new endpoint.
  for (size_t hops = 0; ec == ErrorCode::OK && peek_status(resp) == ErrorCode::NOT_LEADER &&
                        hops + 1 < endpoints_.size();
       ++hops) {
    if (rotate_endpoint(attempt_gen) != ErrorCode::OK) break;
    attempt_gen = generation_.load();
    ec = event_call_raw(opcode, req, resp);
  }
  return ec;
}

ErrorCode RemoteCoordinator::send_watch(int64_t id, const std::string& prefix) {
  Writer w;
  w.put<int64_t>(id);
  wire::encode(w, prefix);
  std::vector<uint8_t> resp;
  auto ec = event_call_raw(static_cast<uint8_t>(Op::kWatchPrefix), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::send_campaign(const std::string& election,
                                           const std::string& candidate, int64_t ttl_ms) {
  Writer w;
  wire::encode_fields(w, election, candidate, ttl_ms);
  std::vector<uint8_t> resp;
  auto ec = event_call_raw(static_cast<uint8_t>(Op::kCampaign), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec == ErrorCode::CLIENT_ALREADY_EXISTS) {
    // The surviving candidacy belongs to a previous half-dead session; when
    // the server notices that session die it will resign it, silently
    // evicting us. Take the candidacy over: resign the stale one, then
    // re-register under THIS session.
    Writer rw;
    wire::encode_fields(rw, election, candidate);
    std::vector<uint8_t> rresp;
    if (auto rec = event_call_raw(static_cast<uint8_t>(Op::kResign), rw.buffer(), rresp);
        rec != ErrorCode::OK)
      return rec;
    std::vector<uint8_t> cresp;
    ec = event_call_raw(static_cast<uint8_t>(Op::kCampaign), w.buffer(), cresp);
    if (ec != ErrorCode::OK) return ec;
    Reader cr(cresp);
    ec = take_status(cr);
  }
  return ec;
}

void RemoteCoordinator::event_reader_loop() {
  uint8_t opcode = 0;
  std::vector<uint8_t> payload;
  while (!stopping_) {
    if (net::recv_frame(event_sock_.fd(), opcode, payload) != ErrorCode::OK) {
      // Server went away: flag the session dead so the next call redials,
      // and wake any event_call waiter so it fails fast instead of burning
      // its full timeout (leadership keepalives are TTL-sensitive).
      if (!stopping_) connected_ = false;
      {
        MutexLock rlock(resp_mutex_);
        reader_dead_ = true;
      }
      resp_cv_.notify_all();
      break;
    }
    const Op op = static_cast<Op>(opcode);
    if (op == Op::kEvent) {
      Reader r(payload);
      int64_t watch_id = 0;
      uint8_t type = 0;
      std::string key, value;
      if (!r.get(watch_id) || !r.get(type) || !wire::decode(r, key) || !wire::decode(r, value))
        continue;
      WatchCallback cb;
      {
        MutexLock lock(watch_mutex_);
        auto it = watch_cbs_.find(watch_id);
        if (it != watch_cbs_.end()) cb = it->second;
      }
      if (cb) {
        cb(WatchEvent{type == 0 ? WatchEvent::Type::kPut : WatchEvent::Type::kDelete, key,
                      value});
      }
    } else if (op == Op::kLeaderEvent) {
      Reader r(payload);
      std::string election, candidate;
      bool is_leader = false;
      if (!wire::decode_fields(r, election, candidate, is_leader)) continue;
      // Fencing epoch: appended by epoch-aware servers (tail-tolerant: 0
      // from older ones, malformed tail = discard the event — a torn epoch
      // must never masquerade as epoch 0).
      uint64_t epoch = 0;
      if (!wire::decode_fields_tail(r, epoch)) continue;
      CampaignCallback cb;
      {
        MutexLock lock(watch_mutex_);
        auto it = leader_cbs_.find(election + "/" + candidate);
        if (it != leader_cbs_.end()) cb = it->second;
      }
      if (cb) cb(is_leader, epoch);
    } else {
      // Response to an event-channel request.
      MutexLock lock(resp_mutex_);
      resp_opcode_ = opcode;
      resp_payload_ = std::move(payload);
      resp_ready_ = true;
      resp_cv_.notify_one();
    }
  }
}

Result<std::string> RemoteCoordinator::get(const std::string& key) {
  Writer w;
  wire::encode(w, key);
  std::vector<uint8_t> resp;
  auto ec = call(static_cast<uint8_t>(Op::kGet), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec != ErrorCode::OK) return ec;
  std::string value;
  if (!wire::decode(r, value)) return ErrorCode::RPC_FAILED;
  return value;
}

ErrorCode RemoteCoordinator::put(const std::string& key, const std::string& value) {
  Writer w;
  wire::encode_fields(w, key, value);
  std::vector<uint8_t> resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kPut), w.buffer(), resp));
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::put_with_ttl(const std::string& key, const std::string& value,
                                          int64_t ttl_ms) {
  Writer w;
  wire::encode_fields(w, key, value, ttl_ms);
  std::vector<uint8_t> resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kPutTtl), w.buffer(), resp));
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::del(const std::string& key) {
  Writer w;
  wire::encode(w, key);
  std::vector<uint8_t> resp;
  bool retried = false;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kDel), w.buffer(), resp, &retried));
  Reader r(resp);
  auto ec = take_status(r);
  // At-least-once: when the op was re-sent after a reconnect, the first
  // attempt may have deleted the key before the reply was lost — NOT_FOUND
  // on the retry then means "already done", not failure.
  if (retried && ec == ErrorCode::COORD_KEY_NOT_FOUND) return ErrorCode::OK;
  return ec;
}

Result<std::vector<KeyValue>> RemoteCoordinator::get_with_prefix(const std::string& prefix) {
  Writer w;
  wire::encode(w, prefix);
  std::vector<uint8_t> resp;
  auto ec = call(static_cast<uint8_t>(Op::kGetPrefix), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec != ErrorCode::OK) return ec;
  uint32_t count = 0;
  if (!r.get(count)) return ErrorCode::RPC_FAILED;
  std::vector<KeyValue> out;
  out.reserve(count);
  for (uint32_t i = 0; i < count; ++i) {
    KeyValue kv;
    if (!wire::decode(r, kv.key) || !wire::decode(r, kv.value)) return ErrorCode::RPC_FAILED;
    out.push_back(std::move(kv));
  }
  return out;
}

Result<LeaseId> RemoteCoordinator::lease_grant(int64_t ttl_ms) {
  Writer w;
  w.put<int64_t>(ttl_ms);
  std::vector<uint8_t> resp;
  auto ec = call(static_cast<uint8_t>(Op::kLeaseGrant), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec != ErrorCode::OK) return ec;
  int64_t lease = 0;
  if (!r.get(lease)) return ErrorCode::RPC_FAILED;
  return lease;
}

ErrorCode RemoteCoordinator::lease_keepalive(LeaseId lease) {
  Writer w;
  w.put<int64_t>(lease);
  std::vector<uint8_t> resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kLeaseKeepalive), w.buffer(), resp));
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::lease_revoke(LeaseId lease) {
  Writer w;
  w.put<int64_t>(lease);
  std::vector<uint8_t> resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kLeaseRevoke), w.buffer(), resp));
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::put_with_lease(const std::string& key, const std::string& value,
                                            LeaseId lease) {
  Writer w;
  wire::encode_fields(w, key, value, lease);
  std::vector<uint8_t> resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Op::kPutWithLease), w.buffer(), resp));
  Reader r(resp);
  return take_status(r);
}

Result<WatchId> RemoteCoordinator::watch_prefix(const std::string& prefix, WatchCallback cb) {
  const int64_t id = next_watch_++;
  {
    MutexLock lock(watch_mutex_);
    watch_cbs_[id] = std::move(cb);
    watch_prefixes_[id] = prefix;  // recorded first: a mid-call reconnect replays it
  }
  const uint64_t gen = generation_.load();
  auto ec = send_watch(id, prefix);
  if (is_connection_error(ec) && !stopping_) {
    // reconnect() replays watch_prefixes_ (including this one) on success.
    ec = reconnect(gen);
  }
  if (ec != ErrorCode::OK) {
    MutexLock lock(watch_mutex_);
    watch_cbs_.erase(id);
    watch_prefixes_.erase(id);
    return ec;
  }
  return static_cast<WatchId>(id);
}

ErrorCode RemoteCoordinator::unwatch(WatchId id) {
  Writer w;
  w.put<int64_t>(id);
  std::vector<uint8_t> resp;
  auto ec = event_call(static_cast<uint8_t>(Op::kUnwatch), w.buffer(), resp);
  if (ec == ErrorCode::OK) {
    Reader r(resp);
    ec = take_status(r);
  }
  MutexLock lock(watch_mutex_);
  watch_cbs_.erase(id);
  watch_prefixes_.erase(id);
  return ec;
}

ErrorCode RemoteCoordinator::register_service(const std::string& service_name,
                                              const std::string& id, const std::string& address,
                                              int64_t ttl_ms) {
  return put_with_ttl(services_prefix(service_name) + id, address, ttl_ms);
}

Result<std::vector<KeyValue>> RemoteCoordinator::discover_service(
    const std::string& service_name) {
  return get_with_prefix(services_prefix(service_name));
}

ErrorCode RemoteCoordinator::unregister_service(const std::string& service_name,
                                                const std::string& id) {
  return del(services_prefix(service_name) + id);
}

ErrorCode RemoteCoordinator::campaign(const std::string& election,
                                      const std::string& candidate_id, int64_t lease_ttl_ms,
                                      CampaignCallback cb) {
  const std::string key = election + "/" + candidate_id;
  {
    MutexLock lock(watch_mutex_);
    leader_cbs_[key] = std::move(cb);
    campaigns_[key] = {election, candidate_id, lease_ttl_ms};
  }
  uint64_t attempt_gen = generation_.load();
  auto ec = send_campaign(election, candidate_id, lease_ttl_ms);
  if (is_connection_error(ec) && !stopping_) {
    // reconnect() replays campaigns_ (including this one) on success.
    ec = reconnect(attempt_gen);
  }
  // A standby rejects candidacies: rotate to the primary and re-send
  // (send_campaign absorbs the ALREADY_EXISTS left by connect replay).
  for (size_t hops = 0;
       ec == ErrorCode::NOT_LEADER && !stopping_ && hops + 1 < endpoints_.size(); ++hops) {
    if (rotate_endpoint(attempt_gen) != ErrorCode::OK) break;
    attempt_gen = generation_.load();
    ec = send_campaign(election, candidate_id, lease_ttl_ms);
  }
  if (ec != ErrorCode::OK) {
    MutexLock lock(watch_mutex_);
    leader_cbs_.erase(key);
    campaigns_.erase(key);
  }
  return ec;
}

ErrorCode RemoteCoordinator::resign(const std::string& election,
                                    const std::string& candidate_id) {
  Writer w;
  wire::encode_fields(w, election, candidate_id);
  std::vector<uint8_t> resp;
  auto ec = event_call(static_cast<uint8_t>(Op::kResign), w.buffer(), resp);
  if (ec == ErrorCode::OK) {
    Reader r(resp);
    ec = take_status(r);
  }
  MutexLock lock(watch_mutex_);
  leader_cbs_.erase(election + "/" + candidate_id);
  campaigns_.erase(election + "/" + candidate_id);
  return ec;
}

ErrorCode RemoteCoordinator::campaign_keepalive(const std::string& election,
                                                const std::string& candidate_id) {
  Writer w;
  wire::encode_fields(w, election, candidate_id);
  std::vector<uint8_t> resp;
  auto ec = event_call(static_cast<uint8_t>(Op::kCampaignKeepalive), w.buffer(), resp);
  if (ec == ErrorCode::OK) {
    Reader r(resp);
    ec = take_status(r);
  }
  return ec;
}

Result<std::string> RemoteCoordinator::current_leader(const std::string& election) {
  Writer w;
  wire::encode(w, election);
  std::vector<uint8_t> resp;
  auto ec = call(static_cast<uint8_t>(Op::kCurrentLeader), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec != ErrorCode::OK) return ec;
  std::string leader;
  if (!wire::decode(r, leader)) return ErrorCode::RPC_FAILED;
  return leader;
}

Result<uint64_t> RemoteCoordinator::election_epoch(const std::string& election) {
  Writer w;
  wire::encode(w, election);
  std::vector<uint8_t> resp;
  auto ec = call(static_cast<uint8_t>(Op::kElectionEpoch), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  if (ec != ErrorCode::OK) return ec;
  uint64_t epoch = 0;
  if (!r.get(epoch)) return ErrorCode::RPC_FAILED;
  return epoch;
}

ErrorCode RemoteCoordinator::put_fenced(const std::string& key, const std::string& value,
                                        const std::string& election, uint64_t epoch) {
  Writer w;
  wire::encode_fields(w, key, value, election, epoch);
  std::vector<uint8_t> resp;
  // Fenced puts are safe to retry after a reconnect: re-executing is
  // idempotent (same value) and the fence re-checks the epoch server-side.
  auto ec = call(static_cast<uint8_t>(Op::kPutFenced), w.buffer(), resp);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  return take_status(r);
}

ErrorCode RemoteCoordinator::del_fenced(const std::string& key, const std::string& election,
                                        uint64_t epoch) {
  Writer w;
  wire::encode_fields(w, key, election, epoch);
  std::vector<uint8_t> resp;
  bool retried = false;
  auto ec = call(static_cast<uint8_t>(Op::kDelFenced), w.buffer(), resp, &retried);
  if (ec != ErrorCode::OK) return ec;
  Reader r(resp);
  ec = take_status(r);
  // At-least-once + replay: a retried delete that reports NOT_FOUND may
  // have executed on the first attempt (same contract as plain del()).
  if (ec == ErrorCode::COORD_KEY_NOT_FOUND && retried) return ErrorCode::OK;
  return ec;
}

}  // namespace btpu::coord
