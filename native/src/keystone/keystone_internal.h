// File-internal helpers shared by the keystone's translation units (core,
// persist, scrub, drain, repair, evict). Not part of the public API.
#pragma once

#include <atomic>
#include <optional>
#include <utility>
#include <vector>

#include "btpu/keystone/keystone.h"

namespace btpu::keystone::detail {

// Maps a shard placement back to (pool, offset-range) for allocator adoption.
std::optional<std::pair<MemoryPoolId, alloc::Range>> shard_to_range(
    const ShardPlacement& shard, const alloc::PoolMap& pools);

// All-or-nothing mapping of shards onto (pool, range) pairs.
bool append_copy_ranges(const CopyPlacement& copy, const alloc::PoolMap& pools,
                        std::vector<std::pair<MemoryPoolId, alloc::Range>>& out);

std::optional<std::vector<std::pair<MemoryPoolId, alloc::Range>>> map_copies_to_ranges(
    const std::vector<CopyPlacement>& copies, const alloc::PoolMap& pools);

// Shard CRCs are layout-bound: carries the source's stamps onto a
// destination only when it striped identically.
void carry_shard_crcs(const CopyPlacement& src, CopyPlacement& dst);

// Cross-process device fabric move (offer + pull between worker processes).
bool fabric_copy_object(transport::TransportClient& client, const CopyPlacement& src,
                        const CopyPlacement& dst, uint64_t size, const alloc::PoolMap& pools);

// Streams `size` bytes from `src` into every copy in `dsts` (bounded chunk
// buffer; device->device and fabric fast paths when available). See the
// definition for the CRC-gate contract and the `used_unchecked` report.
ErrorCode copy_object_bytes(transport::TransportClient& client, const CopyPlacement& src,
                            const std::vector<CopyPlacement>& dsts, uint64_t size,
                            const alloc::PoolMap* pools = nullptr,
                            std::atomic<uint64_t>* fabric_moves = nullptr,
                            bool* used_unchecked = nullptr);

}  // namespace btpu::keystone::detail
