// End-to-end deadlines, retry policies, and retry budgets — the
// overload-robustness primitives adopted by every layer that waits or
// retries (keystone RPC client/server, TCP data plane, remote coordinator,
// object client). The design follows Dean & Barroso's *The Tail at Scale*:
//   * a Deadline is ABSOLUTE (steady_clock) and propagates as a RELATIVE
//     remaining-budget field on the wire, so cross-host clock skew can
//     never expire a request spuriously — each hop restarts the clock from
//     the budget it received;
//   * retries use jittered exponential backoff (RetryPolicy) gated by a
//     per-client token-bucket RetryBudget, so a brownout's retry storm
//     self-extinguishes instead of amplifying the overload;
//   * servers reject work they cannot finish in budget (DEADLINE_EXCEEDED)
//     or cannot start at all (RETRY_LATER + backoff hint) instead of
//     queueing unboundedly — see btpu/common/admission.h.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <limits>

namespace btpu {

// Absolute per-operation deadline. Default-constructed = infinite (no
// deadline), which keeps every existing call site's behavior until a caller
// opts in. Cheap to copy; steady_clock only (never wall time).
class Deadline {
 public:
  using Clock = std::chrono::steady_clock;

  constexpr Deadline() = default;

  static Deadline infinite() noexcept { return Deadline{}; }
  static Deadline at(Clock::time_point tp) noexcept {
    Deadline d;
    d.tp_ = tp;
    return d;
  }
  // ms <= 0 = infinite (the "disabled" config value).
  static Deadline after_ms(int64_t ms) noexcept {
    if (ms <= 0) return infinite();
    return at(Clock::now() + std::chrono::milliseconds(ms));
  }
  // Reconstructs a deadline from a wire budget (remaining ms at the
  // sender): 0 = none. The receiver's clock starts at receipt, which is
  // the skew-free interpretation of a relative budget.
  static Deadline from_wire(uint32_t budget_ms) noexcept {
    return budget_ms == 0 ? infinite() : after_ms(budget_ms);
  }

  bool is_infinite() const noexcept { return tp_ == Clock::time_point::max(); }
  bool expired() const noexcept { return !is_infinite() && Clock::now() >= tp_; }
  Clock::time_point time_point() const noexcept { return tp_; }

  // Remaining budget, clamped to >= 0. Infinite reports INT64_MAX.
  int64_t remaining_ms() const noexcept {
    if (is_infinite()) return std::numeric_limits<int64_t>::max();
    const auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                          tp_ - Clock::now())
                          .count();
    return left > 0 ? left : 0;
  }

  // The relative budget stamped on the wire: 0 = no deadline. An expired
  // deadline reports... nothing useful — callers must fail locally instead
  // of sending (a 0 budget on the wire means "none", and an explicit
  // 0-remaining send would be doomed work for the server). Clamped to u32.
  uint32_t wire_budget_ms() const noexcept {
    if (is_infinite()) return 0;
    const int64_t left = remaining_ms();
    if (left <= 0) return 1;  // callers check expired() first; never send 0
    return left > std::numeric_limits<uint32_t>::max()
               ? std::numeric_limits<uint32_t>::max()
               : static_cast<uint32_t>(left);
  }

  // The tighter of two deadlines.
  Deadline min(const Deadline& other) const noexcept {
    return tp_ <= other.tp_ ? *this : other;
  }

 private:
  Clock::time_point tp_{Clock::time_point::max()};
};

// Jittered exponential backoff. backoff_ms(0) is the first retry's wait.
// The jitter is "equal jitter": wait = raw/2 + uniform(0, raw/2], so
// synchronized failures decorrelate while the floor keeps backoff honest.
struct RetryPolicy {
  uint32_t base_ms{5};
  uint32_t max_ms{2000};
  double multiplier{2.0};
  uint32_t max_attempts{4};  // total attempts including the first

  uint64_t backoff_ms(uint32_t attempt) const noexcept;
};

// Per-client retry *budget* (the gRPC retry-throttler shape): every retry
// spends one token, every success refunds `refund` tokens, and retries are
// only permitted while the bucket is above half capacity. Under a sustained
// brownout the bucket drains in O(capacity) retries and the client stops
// amplifying load until real successes refill it. Thread-safe, lock-free.
class RetryBudget {
 public:
  explicit RetryBudget(double capacity = 10.0, double refund = 0.5) noexcept
      : capacity_mil_(static_cast<int64_t>(capacity * 1000)),
        refund_mil_(static_cast<int64_t>(refund * 1000)),
        tokens_mil_(static_cast<int64_t>(capacity * 1000)) {}

  // True (and spends a token) when a retry is currently affordable.
  bool try_spend() noexcept {
    // ordering: relaxed CAS loop — the token count is the only shared word; no payload is transferred on spend/refund, so success needs no acquire edge.
    int64_t cur = tokens_mil_.load(std::memory_order_relaxed);
    while (true) {
      if (cur <= capacity_mil_ / 2) return false;
      if (tokens_mil_.compare_exchange_weak(cur, cur - 1000,
                                            std::memory_order_relaxed))
        return true;
    }
  }

  void on_success() noexcept {
    // ordering: relaxed CAS loop — same single-word argument as try_spend.
    int64_t cur = tokens_mil_.load(std::memory_order_relaxed);
    while (true) {
      const int64_t next = cur + refund_mil_ > capacity_mil_ ? capacity_mil_
                                                             : cur + refund_mil_;
      if (next == cur) return;
      if (tokens_mil_.compare_exchange_weak(cur, next, std::memory_order_relaxed))
        return;
    }
  }

  double tokens() const noexcept {
    // ordering: relaxed — point-in-time gauge read.
    return static_cast<double>(tokens_mil_.load(std::memory_order_relaxed)) / 1000.0;
  }

 private:
  const int64_t capacity_mil_;
  const int64_t refund_mil_;
  std::atomic<int64_t> tokens_mil_;
};

// ---- ambient per-operation deadline ----------------------------------------
// The object client opens an OpDeadlineScope at each public entry point;
// everything beneath it on the same thread (keystone RPC calls, wire-op
// construction, coordinator calls) inherits the deadline without threading
// a parameter through every signature. Fan-out worker threads do NOT
// inherit it — deadline-carrying state that crosses threads rides the
// WireOp itself (transport.h), which is stamped on the calling thread.
Deadline current_op_deadline() noexcept;

class OpDeadlineScope {
 public:
  explicit OpDeadlineScope(Deadline d) noexcept;
  // ms <= 0 = no deadline (scope still nests correctly).
  explicit OpDeadlineScope(int64_t ms) noexcept : OpDeadlineScope(Deadline::after_ms(ms)) {}
  ~OpDeadlineScope();
  OpDeadlineScope(const OpDeadlineScope&) = delete;
  OpDeadlineScope& operator=(const OpDeadlineScope&) = delete;

 private:
  Deadline saved_;
};

// ---- streaming latency estimate (hedging trigger) --------------------------
// Fixed ring of recent samples; quantile() copies + selects under the lock.
// Cheap enough for once-per-hedged-read use; the record path is O(1).
class LatencyTracker {
 public:
  void record_us(uint64_t us) noexcept;
  // 0 when fewer than min_samples recorded (callers fall back to a fixed
  // hedge delay or skip hedging).
  uint64_t quantile_us(double q, size_t min_samples = 16) const noexcept;
  // ordering: relaxed — sample-count gauge read.
  size_t samples() const noexcept { return count_.load(std::memory_order_relaxed); }

 private:
  static constexpr size_t kRing = 256;
  mutable std::atomic<uint64_t> ring_[kRing] = {};
  std::atomic<size_t> count_{0};
};

// ---- process-global robustness counters ------------------------------------
// One home for the overload-path scoreboard, exported through /metrics
// (keystone process) and the capi lane counters (client process). Embedded
// clusters share a process, so both views see the whole story there.
struct RobustCounters {
  // Server side (this process's keystone RPC server + data-plane server).
  std::atomic<uint64_t> deadline_exceeded{0};  // requests rejected: budget spent
  std::atomic<uint64_t> shed{0};               // requests shed: queue/bytes over watermark
  // Client side (this process's object/RPC clients).
  std::atomic<uint64_t> client_deadline_exceeded{0};  // ops failed locally on expiry
  std::atomic<uint64_t> retries{0};                   // backoff retries performed
  std::atomic<uint64_t> retry_budget_exhausted{0};    // retries suppressed by budget
  std::atomic<uint64_t> hedges_fired{0};              // secondary replica fetches started
  std::atomic<uint64_t> hedge_wins{0};                // hedge finished before the primary
  std::atomic<uint64_t> breaker_trips{0};             // breakers moved CLOSED -> OPEN
  std::atomic<uint64_t> breaker_skips{0};             // replica attempts skipped while open
};

RobustCounters& robust_counters() noexcept;

}  // namespace btpu
