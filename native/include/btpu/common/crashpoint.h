// Deterministic crash-point injection for the durability matrix.
//
// A crash point is a labeled site on the WAL / snapshot / persist / ack
// sequence. Arming one (BTPU_CRASHPOINT=<label>[:N]) makes the process
// _exit(kExitCode) the Nth time execution reaches that label — no atexit
// handlers, no stream flushing, no destructors: the closest a process can
// get to kill -9'ing itself at an exact instruction. bb-crash forks a child
// cluster per label, lets it die there under live traffic, restarts on the
// same data dir, and runs the recovery invariant checker
// (docs/CORRECTNESS.md §crash-point catalog).
//
// Disarmed cost is one pointer-load + compare per site: the env var is
// parsed once, and sites off the armed label return after a strcmp against
// a <=63-byte local buffer. Sites sit on durability slow paths (append,
// fsync, snapshot, persist), never on per-byte data paths.
#pragma once

#include <unistd.h>

#include <atomic>
#include <cstdlib>
#include <cstring>

#include "btpu/common/env.h"

namespace btpu::crashpoint {

// The child's exit code when a crash point fires (mirrors 128+SIGKILL so a
// harness can treat "crashed here" and "kill -9" the same way).
inline constexpr int kExitCode = 137;

// Every labeled site, in rough execution order along the durability path.
// Harnesses iterate this list — a new site MUST be added here or the matrix
// silently stops covering it (pinned by test_common.cpp CrashPointCatalog).
inline constexpr const char* kAll[] = {
    "wal.mid_append",         // record header written, payload not yet (torn tail)
    "wal.after_append",       // record fully in the file, not yet fdatasync'd
    "wal.before_sync",        // syncer about to fdatasync the batch
    "wal.after_sync",         // batch durable, waiters not yet released
    "snapshot.before_tmp",    // compaction about to write snapshot.bin.tmp
    "snapshot.before_rename", // tmp written + fsync'd, rename not yet issued
    "snapshot.after_rename",  // snapshot live, WAL not yet truncated
    "snapshot.after_truncate",// WAL reborn, fresh header written
    "persist.before_record",  // keystone about to write the durable object record
    "persist.after_record",   // durable record acked by the coordinator
    "persist.after_ack",      // mutation committed, ack about to reach the client
};

namespace detail {
struct Spec {
  bool armed{false};
  char label[64]{};
  std::atomic<long> remaining{1};
};

inline void parse(Spec& s) {
  s.armed = false;
  const char* v = env_str("BTPU_CRASHPOINT");
  if (!v) return;
  const char* colon = std::strchr(v, ':');
  const size_t n = colon ? static_cast<size_t>(colon - v) : std::strlen(v);
  if (n == 0 || n >= sizeof(s.label)) return;
  std::memcpy(s.label, v, n);
  s.label[n] = '\0';
  const long hits = colon ? std::strtol(colon + 1, nullptr, 10) : 1;
  s.remaining.store(hits > 0 ? hits : 1);
  s.armed = true;
}

inline Spec& spec() {
  static Spec s;
  static const bool parsed = [] {
    parse(s);
    return true;
  }();
  (void)parsed;
  return s;
}
}  // namespace detail

// Test-only: re-read BTPU_CRASHPOINT. The spec is parsed once per process,
// which is what production wants (harness children arm the env before
// anything touches a crash point) — but a TEST that forks a child after
// the parent suite already initialized the spec needs this to arm it.
// Not thread-safe; call before the child starts threads.
inline void reparse_for_test() { detail::parse(detail::spec()); }

// Dies at the armed label's Nth hit; free otherwise. Callable from any
// thread (the syncer, a keystone health loop, a client thread): whichever
// thread reaches the site dies with the whole process, exactly like a
// preemption would take it.
inline void hit(const char* label) {
  detail::Spec& s = detail::spec();
  if (!s.armed || std::strcmp(s.label, label) != 0) return;
  // ordering: relaxed — hit countdown; the _exit makes any cross-thread ordering moot, and overshoot by concurrent hits is impossible past the fetch_sub reaching 1 exactly once.
  if (s.remaining.fetch_sub(1, std::memory_order_relaxed) == 1) ::_exit(kExitCode);
}

}  // namespace btpu::crashpoint
