"""JAX-backed HBM provider: TPU device buffers as the top storage tier.

The native HbmBackend talks to a C ABI provider table (hbm_provider.h). This
module implements that table with JAX: a region is a list of fixed-size
device-resident uint8 chunks on one TPU chip; read/write are host<->device
transfers. Registering the provider flips every HBM_TPU pool in this process
from the built-in host-memory emulation to real device memory.

Granularity: writes/reads are chunk-based (default 1 MiB). Whole-chunk
writes cost one device_put; partial-chunk writes stage the payload on device
and apply `lax.dynamic_update_slice` there (no device->host readback), and
partial-chunk reads slice on device first so only the requested bytes cross
the host<->device link. Aligning shard sizes to the chunk size still gives
peak throughput by hitting the whole-chunk paths.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from blackbird_tpu.native import lib

_u64 = ctypes.c_uint64

_ALLOC_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, _u64,
                             ctypes.POINTER(_u64))
_FREE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64)
_WRITE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_READ_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_AVAIL_FN = ctypes.CFUNCTYPE(_u64, ctypes.c_void_p, ctypes.c_char_p)


class _ProviderStruct(ctypes.Structure):
    _fields_ = [
        ("ctx", ctypes.c_void_p),
        ("alloc_region", _ALLOC_FN),
        ("free_region", _FREE_FN),
        ("write", _WRITE_FN),
        ("read", _READ_FN),
        ("available", _AVAIL_FN),
    ]


class JaxHbmProvider:
    """Chunked device-buffer regions managed through JAX."""

    def __init__(self, chunk_bytes: int = 1 << 20, assemble_limit_bytes: int = 64 << 20):
        import jax

        self._jax = jax
        self.chunk_bytes = chunk_bytes
        # Reads up to this size are gathered into one device buffer for a
        # single D2H transfer; larger reads stream per chunk (no extra
        # device memory).
        self.assemble_limit_bytes = assemble_limit_bytes
        self._lock = threading.Lock()
        self._regions: dict[int, dict] = {}
        self._next_id = 1
        self._struct = None  # built in register()
        # jit caches: bucketed by power-of-two length so each holds at most
        # log2(chunk_bytes) executables; offsets/leads stay traced scalars so
        # varying positions reuse one executable.
        self._slice_fns: dict[int, object] = {}
        self._merge_fns: dict[int, object] = {}

    def _bucket_span(self, off: int, n: int):
        """Pow2 staging window for [off, off+n) within a chunk.

        Lengths round up to the next power of two (capped at the chunk size)
        so the jit caches hold at most log2(chunk_bytes) executables instead
        of one per distinct request length. When the bucket would run past
        the chunk end, the start is pulled back and `lead` bytes at the front
        are outside the requested range. Returns (bucket, start, lead) with
        the invariant [start+lead, start+lead+n) == [off, off+n); both the
        slice and merge paths MUST use this one mapping.
        """
        cb = self.chunk_bytes
        bucket = min(1 << max(0, (n - 1).bit_length()), cb)
        start = min(off, cb - bucket)
        return bucket, start, off - start

    def _device_slice(self, chunk, off: int, n: int):
        """Device-side byte-range slice, compile-bounded (see _bucket_span).

        Returns (device_array, lead) — the requested bytes are
        device_array[lead : lead + n].
        """
        bucket, start, lead = self._bucket_span(off, n)
        fn = self._slice_fns.get(bucket)
        if fn is None:
            lax = self._jax.lax
            fn = self._jax.jit(
                lambda c, o, _n=bucket: lax.dynamic_slice(c, (o,), (_n,))
            )
            self._slice_fns[bucket] = fn
        return fn(chunk, np.uint32(start)), lead

    def _device_merge(self, chunk, part_b, start: int, lead: int, n: int):
        """Writes part_b[lead:lead+n] into chunk at start+lead, on device.

        part_b is a host buffer padded to a power-of-two bucket; the merge
        masks in only the live [lead, lead+n) bytes against the current
        chunk contents, so — like _device_slice — the jit cache is bounded
        at one executable per bucket size, not per distinct write length.
        """
        jnp, lax = self._jax.numpy, self._jax.lax
        b = len(part_b)
        fn = self._merge_fns.get(b)
        if fn is None:
            def merge(c, p, s, l, m, _b=b):
                cur = lax.dynamic_slice(c, (s,), (_b,))
                idx = lax.iota(jnp.uint32, _b)
                merged = jnp.where((idx >= l) & (idx < l + m), p, cur)
                return lax.dynamic_update_slice(c, merged, (s,))

            fn = self._jax.jit(merge)
            self._merge_fns[b] = fn
        return fn(chunk, part_b, np.uint32(start), np.uint32(lead), np.uint32(n))

    # -- device helpers ----------------------------------------------------

    def _device_for(self, device_id: str):
        devices = self._jax.local_devices()
        if ":" in device_id:
            try:
                ordinal = int(device_id.split(":", 1)[1])
                if 0 <= ordinal < len(devices):
                    return devices[ordinal]
            except ValueError:
                pass
        return devices[0]

    # -- provider callbacks ------------------------------------------------

    def _alloc(self, _ctx, device_id, size, out_id):
        try:
            device = self._device_for(device_id.decode() if device_id else "tpu:0")
            n_chunks = (size + self.chunk_bytes - 1) // self.chunk_bytes
            zero = np.zeros(self.chunk_bytes, dtype=np.uint8)
            # One H2D transfer; chunks alias the same device buffer. Safe
            # because writes never mutate in place — they replace list slots
            # with freshly-built arrays (copy-on-write).
            shared_zero = self._jax.device_put(zero, device)
            chunks = [shared_zero] * n_chunks
            with self._lock:
                region_id = self._next_id
                self._next_id += 1
                self._regions[region_id] = {
                    "chunks": chunks,
                    "size": size,
                    "device": device,
                }
            out_id[0] = region_id
            return 0
        except Exception:  # noqa: BLE001 - must not raise through the C ABI
            return 1

    def _free(self, _ctx, region_id):
        with self._lock:
            return 0 if self._regions.pop(region_id, None) is not None else 1

    def _rw(self, region_id, offset, buf, length, is_write):
        try:
            with self._lock:
                region = self._regions.get(region_id)
            if region is None or offset + length > region["size"]:
                return 1
            jax = self._jax
            cb = self.chunk_bytes
            src = (
                np.ctypeslib.as_array(ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)),
                                      shape=(length,))
                if length
                else np.empty(0, np.uint8)
            )
            if not is_write and length:
                # Assemble the requested byte range ON DEVICE (slice partial
                # chunks, concatenate spans), then do exactly ONE
                # device->host transfer. One transfer per read beats
                # per-chunk pulls when the link is latency-bound, and
                # copy_to_host_async is deliberately avoided: on some
                # platforms (observed on tunneled dev TPUs) it does not share
                # its transfer with the later np.asarray, tripling the cost.
                spans = []  # (dst pos, n, device part, lead bytes to skip)
                pos = 0
                while pos < length:
                    chunk_idx = (offset + pos) // cb
                    chunk_off = (offset + pos) % cb
                    n = min(length - pos, cb - chunk_off)
                    chunk = region["chunks"][chunk_idx]
                    if n == cb:
                        spans.append((pos, n, chunk, 0))
                    else:
                        part, lead = self._device_slice(chunk, chunk_off, n)
                        spans.append((pos, n, part, lead))
                    pos += n
                # Assemble in batches of at most assemble_limit_bytes: one
                # D2H per batch, and the device never needs more than the
                # batch size of extra memory (an almost-full HBM can't spare
                # `length` bytes for one giant concatenation).
                def flush(batch):
                    if len(batch) == 1:
                        pos, n, part, lead = batch[0]
                        src[pos : pos + n] = np.asarray(part)[lead : lead + n]
                        return
                    joined = np.asarray(jax.numpy.concatenate([b[2] for b in batch]))
                    acc = 0
                    for pos, n, part, lead in batch:
                        src[pos : pos + n] = joined[acc + lead : acc + lead + n]
                        acc += part.shape[0]

                batch, batch_width = [], 0
                for span in spans:
                    width = span[2].shape[0]
                    if batch and batch_width + width > self.assemble_limit_bytes:
                        flush(batch)
                        batch, batch_width = [], 0
                    batch.append(span)
                    batch_width += width
                if batch:
                    flush(batch)
                return 0
            pos = 0
            while pos < length:
                chunk_idx = (offset + pos) // cb
                chunk_off = (offset + pos) % cb
                n = min(length - pos, cb - chunk_off)
                if chunk_off == 0 and n == cb:
                    new_chunk = jax.device_put(
                        np.array(src[pos : pos + n], copy=True), region["device"]
                    )
                else:
                    # Stage only the payload on device (padded to a pow2
                    # bucket), merge there — no device->host readback of the
                    # surrounding chunk, bounded jit cache.
                    bucket, start, lead = self._bucket_span(chunk_off, n)
                    part_b = np.zeros(bucket, dtype=np.uint8)
                    part_b[lead : lead + n] = src[pos : pos + n]
                    new_chunk = self._device_merge(
                        region["chunks"][chunk_idx], part_b, start, lead, n
                    )
                region["chunks"][chunk_idx] = new_chunk
                pos += n
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _write(self, _ctx, region_id, offset, buf, length):
        return self._rw(region_id, offset, buf, length, is_write=True)

    def _read(self, _ctx, region_id, offset, buf, length):
        return self._rw(region_id, offset, buf, length, is_write=False)

    def _available(self, _ctx, _device_id):
        return 0  # unknown

    # -- registration ------------------------------------------------------

    def register(self) -> "JaxHbmProvider":
        """Installs this provider process-wide for all HBM_TPU backends."""
        self._struct = _ProviderStruct(
            ctx=None,
            alloc_region=_ALLOC_FN(self._alloc),
            free_region=_FREE_FN(self._free),
            write=_WRITE_FN(self._write),
            read=_READ_FN(self._read),
            available=_AVAIL_FN(self._available),
        )
        lib.btpu_register_hbm_provider(ctypes.cast(ctypes.pointer(self._struct),
                                                   ctypes.c_void_p))
        return self

    @staticmethod
    def unregister() -> None:
        """Restores the built-in host-memory emulation."""
        lib.btpu_register_hbm_provider(None)

    def region_count(self) -> int:
        with self._lock:
            return len(self._regions)

    def synchronize(self) -> None:
        """Blocks until all in-flight device transfers have completed.

        jax.device_put is asynchronous, so a write that has returned may
        still be copying host->device; call this before timing-sensitive
        checkpoints (benchmarks, barrier points)."""
        with self._lock:
            chunks = [c for r in self._regions.values() for c in r["chunks"]]
        for chunk in chunks:
            if hasattr(chunk, "block_until_ready"):
                chunk.block_until_ready()
