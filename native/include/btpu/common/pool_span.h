// PoolSpan — the ONE sanctioned way to turn a registered pool region's base
// pointer plus an (offset, length) extent into a dereferenceable span.
//
// Every raw `base + offset` in the tree (serving engines, transports,
// storage backends) funnels through resolve() below, which
//   1. bounds-PROVES the access against the region length (overflow-safe:
//      no sum is formed before both operands are vetted), and
//   2. in -DBTPU_POOLSAN trees, consults the pool's shadow state
//      (btpu/common/poolsan.h): extent allocated? generation stamp on the
//      placement still the live one? not a red zone, not quarantine? —
//      convicting stale/wild accesses AT THE ACCESS SITE with a replayable
//      report instead of serving a neighbor object's bytes.
//
// The `pool-span-only` rule in scripts/btpu_lint.py fails `make lint` on
// any pool-base pointer arithmetic outside this header and the backends'
// own region setup — the chokepoint stays the chokepoint.
//
// Release builds compile step 2 out entirely; resolve() is then a handful
// of compares and one add (see bench.py's "poolsan overhead" guard row,
// PASS <= 1.05x on the cached-get and 1 MiB stream paths).
#pragma once

#include <cstdint>

#include "btpu/common/poolsan.h"
#include "btpu/common/result.h"

namespace btpu::poolspan {

using poolsan::Access;

// A bounds-proved window into a registered pool region. Constructible only
// by resolve() — holding a PoolSpan IS the proof the access was vetted.
class PoolSpan {
 public:
  PoolSpan() = default;  // empty (Result plumbing); data() == nullptr
  uint8_t* data() const noexcept { return data_; }
  uint64_t size() const noexcept { return len_; }

 private:
  PoolSpan(uint8_t* d, uint64_t n) noexcept : data_(d), len_(n) {}
  friend Result<PoolSpan> resolve(void*, uint64_t, uint64_t, uint64_t, uint64_t, Access,
                                  const char*, uint64_t) noexcept;

  uint8_t* data_{nullptr};
  uint64_t len_{0};
};

// Resolves extent [offset, offset+len) of the region [base, base+region_len)
// into a span. `gen` is the placement's generation stamp (0 = unstamped —
// bounds + shadow-state checks only, no generation comparison); `tag` is
// the pool id / region tag when the caller knows it (shadow lookup falls
// back to it when the base address is not the registered one, e.g. a
// client-side shm mapping); `trace_id` attributes convictions to the
// requesting op in the flight recorder.
BTPU_NODISCARD inline Result<PoolSpan> resolve(void* base, uint64_t region_len,
                                               uint64_t offset, uint64_t len,
                                               uint64_t gen = 0,
                                               Access access = Access::kRead,
                                               const char* tag = nullptr,
                                               uint64_t trace_id = 0) noexcept {
  if (base == nullptr) return ErrorCode::MEMORY_ACCESS_ERROR;
  // Overflow-safe bounds proof: compare before any sum is trusted.
  if (offset > region_len || len > region_len - offset)
    return ErrorCode::MEMORY_ACCESS_ERROR;
#if defined(BTPU_POOLSAN)
  if (poolsan::armed()) {
    const ErrorCode verdict =
        poolsan::check_access(base, tag, region_len, offset, len, gen, access, trace_id);
    if (verdict != ErrorCode::OK) return verdict;
  }
#else
  (void)gen;
  (void)access;
  (void)tag;
  (void)trace_id;
#endif
  return PoolSpan(static_cast<uint8_t*>(base) + offset, len);
}

}  // namespace btpu::poolspan
