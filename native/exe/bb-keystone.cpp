// bb-keystone: control-plane daemon (the reference planned this binary in
// src/executables/CMakeLists.txt but never shipped it; its role was filled by
// examples/keystone_example.cpp, whose flags this follows).
#include <csignal>
#include <cstdio>
#include <cstring>
#include <thread>

#include "btpu/common/flight_recorder.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/coord/remote_coordinator.h"
#include "btpu/rpc/rpc_server.h"

namespace {
volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  btpu::trace::set_process_name("bb-keystone");
  btpu::flight::install_fatal_dump();
  std::string config_path;
  std::string coord_override;
  std::string listen_override;
  std::string metrics_port_override;
  std::string service_id_override;
  bool ha_override = false;
  int stats_interval_sec = 60;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) config_path = argv[++i];
    else if (!std::strcmp(argv[i], "--coord") && i + 1 < argc) coord_override = argv[++i];
    else if (!std::strcmp(argv[i], "--listen") && i + 1 < argc) listen_override = argv[++i];
    else if (!std::strcmp(argv[i], "--stats-interval") && i + 1 < argc)
      stats_interval_sec = std::stoi(argv[++i]);
    else if (!std::strcmp(argv[i], "--metrics-port") && i + 1 < argc)
      metrics_port_override = argv[++i];
    else if (!std::strcmp(argv[i], "--service-id") && i + 1 < argc)
      service_id_override = argv[++i];
    else if (!std::strcmp(argv[i], "--ha"))
      ha_override = true;
    else if (!std::strcmp(argv[i], "--help")) {
      std::printf(
          "usage: bb-keystone [--config keystone.yaml] [--coord host:port]\n"
          "                   [--listen host:port] [--metrics-port port]\n"
          "                   [--service-id id] [--ha] [--stats-interval sec]\n");
      return 0;
    }
  }

  btpu::KeystoneConfig config;
  try {
    if (!config_path.empty()) config = btpu::KeystoneConfig::from_yaml(config_path);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "bb-keystone: %s\n", e.what());
    return 1;
  }
  if (!coord_override.empty()) config.coord_endpoints = coord_override;
  if (!listen_override.empty()) config.listen_address = listen_override;
  if (!metrics_port_override.empty()) config.http_metrics_port = metrics_port_override;
  if (!service_id_override.empty()) config.service_id = service_id_override;
  if (ha_override) config.enable_ha = true;

  std::shared_ptr<btpu::coord::Coordinator> coordinator;
  if (!config.coord_endpoints.empty()) {
    auto remote = std::make_shared<btpu::coord::RemoteCoordinator>(config.coord_endpoints);
    if (remote->connect() != btpu::ErrorCode::OK) {
      std::fprintf(stderr, "bb-keystone: cannot reach coordinator at %s\n",
                   config.coord_endpoints.c_str());
      return 1;
    }
    coordinator = remote;
  }

  auto stack = btpu::rpc::create_and_start_keystone(config, coordinator);
  if (!stack.ok()) {
    std::fprintf(stderr, "bb-keystone: start failed: %s\n",
                 std::string(btpu::to_string(stack.error())).c_str());
    return 1;
  }
  auto& keystone = *stack.value()->service;
  std::printf("bb-keystone up: rpc %s, metrics :%u\n",
              stack.value()->rpc->endpoint().c_str(), stack.value()->metrics->port());
  std::fflush(stdout);

  std::signal(SIGINT, handle_signal);
  std::signal(SIGTERM, handle_signal);
  auto last_stats = std::chrono::steady_clock::now();
  while (!g_stop) {
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
    if (std::chrono::steady_clock::now() - last_stats >=
        std::chrono::seconds(stats_interval_sec)) {
      last_stats = std::chrono::steady_clock::now();
      auto stats = keystone.get_cluster_stats();
      if (stats.ok()) {
        const auto& s = stats.value();
        std::printf("[stats] workers=%llu pools=%llu objects=%llu used=%llu/%llu (%.1f%%)\n",
                    (unsigned long long)s.total_workers,
                    (unsigned long long)s.total_memory_pools,
                    (unsigned long long)s.total_objects, (unsigned long long)s.used_capacity,
                    (unsigned long long)s.total_capacity, 100.0 * s.avg_utilization);
        std::fflush(stdout);
      }
    }
  }
  stack.value()->stop();
  return 0;
}
