// Low-level socket utilities shared by the coordination service, the keystone
// RPC server, the metrics HTTP server, and the TCP data-plane transport.
//
// Role parity: the reference leans on etcd-cpp-apiv3 + YLT coro_rpc for these
// layers; neither exists in this image, so the framework owns its sockets.
#pragma once

#include <cstdint>
#include <optional>
#include <string>
#include <vector>

#include "btpu/common/result.h"

namespace btpu::net {

// RAII fd wrapper.
class Socket {
 public:
  Socket() = default;
  explicit Socket(int fd) : fd_(fd) {}
  Socket(Socket&& o) noexcept : fd_(o.fd_) { o.fd_ = -1; }
  Socket& operator=(Socket&& o) noexcept;
  Socket(const Socket&) = delete;
  Socket& operator=(const Socket&) = delete;
  ~Socket();

  int fd() const noexcept { return fd_; }
  bool valid() const noexcept { return fd_ >= 0; }
  void close();
  // Wakes any thread blocked in read()/write() on this socket (close() alone
  // does not unblock readers on Linux).
  void shutdown();
  int release() noexcept {
    int f = fd_;
    fd_ = -1;
    return f;
  }

 private:
  int fd_{-1};
};

// Scope guard for server serve loops: on ANY exit (clean EOF, protocol
// violation, send failure) shut the socket down so the peer sees EOF at
// once instead of hanging on a half-dead connection — a poisoned-stream
// drop must be observable. The fd itself stays owned by the server's
// connection registry until stop(): closing here would race stop()'s
// shutdown() against a reused descriptor.
struct SocketShutdownGuard {
  Socket& s;
  ~SocketShutdownGuard() { s.shutdown(); }
};

struct HostPort {
  std::string host;
  uint16_t port{0};
};
std::optional<HostPort> parse_host_port(const std::string& endpoint);

// Listening socket bound to host:port (port 0 = ephemeral). Returns the socket
// and the actually bound port.
Result<Socket> tcp_listen(const std::string& host, uint16_t port, uint16_t* bound_port);
// bulk_buffers: apply data-plane socket buffer sizing BEFORE connect() so the
// receive window scale is negotiated with the deep buffer (tcp(7): setting
// SO_RCVBUF after the handshake is too late).
Result<Socket> tcp_connect(const std::string& host, uint16_t port, int timeout_ms = 5000,
                           bool bulk_buffers = false);
// Accept with optional timeout; CONNECTION_FAILED on error, OPERATION_TIMEOUT
// when the poll expires.
Result<Socket> tcp_accept(const Socket& listener, int timeout_ms = -1);

ErrorCode read_exact(int fd, void* buf, size_t n);
ErrorCode write_all(int fd, const void* buf, size_t n);
// write_all for callers that KNOW fd is a regular file (WAL appends,
// snapshot dumps): plain write(2) loop, skipping the send()-ENOTSOCK
// probe write_all pays per call to stay SIGPIPE-safe on sockets — that
// probe is a guaranteed-failing syscall on every file append otherwise.
ErrorCode file_write_all(int fd, const void* buf, size_t n);
// Scatter-gather write of header + payload without copying the payload.
ErrorCode write_iov2(int fd, const void* h, size_t hn, const void* p, size_t pn);

void set_nodelay(int fd);
// Fixed-size socket buffers for bulk transfers; disables kernel autotuning,
// so apply to data-plane sockets only — and before connect()/listen() so the
// window scaling reflects them. BTPU_SOCK_BUFS=auto skips the pinning
// entirely (WAN autotuning); =N pins both directions to N bytes.
void set_bulk_buffers(int fd, int bytes = 4 << 20);
void set_keepalive(int fd);

// Frame layout: [u32 payload_len][u8 opcode][payload]. Max 1 GiB payload.
inline constexpr uint32_t kMaxFrameBytes = 1u << 30;

ErrorCode send_frame(int fd, uint8_t opcode, const void* payload, size_t n);
ErrorCode recv_frame(int fd, uint8_t& opcode, std::vector<uint8_t>& payload);

}  // namespace btpu::net
