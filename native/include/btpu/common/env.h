// Shared environment-knob parsing. Every operator override in the native
// tree reads through these, so empty-string / garbage handling stays
// uniform: unset OR empty falls back, non-numeric parses as 0 (strtoul
// semantics) — a deliberate "explicitly off" escape hatch.
#pragma once

#include <cstdint>
#include <cstdlib>

namespace btpu {

inline uint64_t env_u64(const char* name, uint64_t fallback) {
  const char* v = std::getenv(name);
  if (!v || !v[0]) return fallback;
  return std::strtoull(v, nullptr, 10);
}

inline uint32_t env_u32(const char* name, uint32_t fallback) {
  const char* v = std::getenv(name);
  if (!v || !v[0]) return fallback;
  return static_cast<uint32_t>(std::strtoul(v, nullptr, 10));
}

}  // namespace btpu
