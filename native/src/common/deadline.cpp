#include "btpu/common/deadline.h"

#include <algorithm>
#include <random>
#include <vector>

namespace btpu {

namespace {
thread_local Deadline t_op_deadline;  // infinite by default

uint64_t jitter_below(uint64_t n) noexcept {
  if (n == 0) return 0;
  thread_local std::mt19937_64 rng{std::random_device{}()};
  return rng() % n;
}
}  // namespace

uint64_t RetryPolicy::backoff_ms(uint32_t attempt) const noexcept {
  double raw = static_cast<double>(base_ms);
  for (uint32_t i = 0; i < attempt && raw < static_cast<double>(max_ms); ++i)
    raw *= multiplier;
  const uint64_t capped = std::min<uint64_t>(static_cast<uint64_t>(raw), max_ms);
  if (capped <= 1) return capped;
  return capped / 2 + 1 + jitter_below(capped / 2);
}

Deadline current_op_deadline() noexcept { return t_op_deadline; }

OpDeadlineScope::OpDeadlineScope(Deadline d) noexcept : saved_(t_op_deadline) {
  // Nested scopes tighten, never loosen: a sub-operation cannot outlive the
  // deadline its caller is already bound by.
  t_op_deadline = d.min(saved_);
}

OpDeadlineScope::~OpDeadlineScope() { t_op_deadline = saved_; }

void LatencyTracker::record_us(uint64_t us) noexcept {
  // ordering: relaxed — lossy sampling ring: the claim only spreads writers across slots, and samples are single-word; a racing quantile fold reading a mix of generations is the accepted statistics of a sliding window.
  const size_t i = count_.fetch_add(1, std::memory_order_relaxed) % kRing;
  ring_[i].store(us == 0 ? 1 : us, std::memory_order_relaxed);
}

uint64_t LatencyTracker::quantile_us(double q, size_t min_samples) const noexcept {
  // ordering: relaxed — quantile fold over the lossy ring (see record_us); any torn-free snapshot is a valid sample set.
  const size_t n = std::min(count_.load(std::memory_order_relaxed), kRing);
  if (n < min_samples || n == 0) return 0;
  uint64_t local[kRing];
  size_t m = 0;
  for (size_t i = 0; i < n; ++i) {
    const uint64_t v = ring_[i].load(std::memory_order_relaxed);
    if (v != 0) local[m++] = v;
  }
  if (m == 0) return 0;
  const size_t k = std::min(m - 1, static_cast<size_t>(q * static_cast<double>(m)));
  std::nth_element(local, local + k, local + m);
  return local[k];
}

RobustCounters& robust_counters() noexcept {
  static RobustCounters counters;
  return counters;
}

}  // namespace btpu
