// Coordinator WAL v2 on-disk format: CRC-chained, length-prefixed records
// behind a file header, torn-write-proof by construction.
//
//   [FileHeader: u32 magic "BTWL" | u32 version=2]
//   [RecordHeader: u32 len | u32 chain_crc][len payload bytes]  ...repeated
//
// chain_crc is CRC32C of the payload SEEDED with the previous record's
// chain_crc (kChainSeed for the first record after a header/compaction), so
// a record is only valid in its exact position: torn appends, spliced
// records, and bit rot all break the chain. Recovery classifies the first
// bad byte (scan() below):
//
//   * torn tail   — the damage is a PARTIAL final append (short header, or
//                   a record whose extent runs past EOF). The only writes
//                   that can end mid-record are the crash-interrupted last
//                   one, so truncating at the last intact record loses
//                   nothing that was ever acked (acks wait for fdatasync,
//                   which never covers a partial record).
//   * corruption  — a COMPLETE record body fails its chain CRC, or a
//                   complete header carries a length the writer could never
//                   have produced, with bytes beyond it. That is mid-log
//                   damage (bit rot, external truncation+append, a spliced
//                   file): records AFTER the damage may include acked
//                   mutations, so recovery must hard-fail, never silently
//                   truncate (docs/OPERATIONS.md crash-recovery runbook).
//
// Files without the magic are pre-chain legacy WALs ([u32 len][payload]
// with no integrity check); MemCoordinator replays them with the legacy
// rules once, then compacts so the reborn WAL is v2. The raw header
// layouts are frozen in wire_layout_check.h and the golden table
// (wal/file_header, wal/record rows) — append-only rules apply.
//
// Header-only so the fuzz target (fuzz_targets.h run_wal_record) drives the
// EXACT scanner recovery uses, not a copy.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <utility>
#include <vector>

#include "btpu/common/crc32c.h"

namespace btpu::coord::wal {

inline constexpr uint32_t kFileMagic = 0x4C575442u;  // "BTWL" little-endian
inline constexpr uint32_t kFileVersion = 2;
inline constexpr uint32_t kChainSeed = 0xB7C0FFEEu;  // chain value before record 1
inline constexpr uint32_t kMaxRecordBytes = 64u << 20;

struct FileHeader {
  uint32_t magic;
  uint32_t version;
};
struct RecordHeader {
  uint32_t len;        // payload bytes following this header
  uint32_t chain_crc;  // crc32c(payload, seed = previous record's chain_crc)
};
static_assert(sizeof(FileHeader) == 8 && sizeof(RecordHeader) == 8);

inline uint32_t chain_next(uint32_t chain, const uint8_t* payload, size_t len) {
  return crc32c(payload, len, chain);
}

// True when the bytes begin with the v2 magic (any version). A legacy WAL
// cannot collide: its first 4 bytes are a record length the legacy writer
// capped at kMaxRecordBytes, and the magic value is ~1.28e9.
inline bool has_v2_magic(const uint8_t* data, size_t size) {
  if (size < sizeof(uint32_t)) return false;
  uint32_t magic = 0;
  std::memcpy(&magic, data, sizeof(magic));
  return magic == kFileMagic;
}

enum class ScanStatus : uint8_t {
  kClean,     // every byte accounted for
  kTornTail,  // intact prefix + a partial final append: truncate at valid_end
  kCorrupt,   // mid-log damage: REFUSE to serve (valid_end = first bad byte)
  kLegacy,    // no v2 magic: replay with the pre-chain legacy rules
  kFuture,    // v2 magic, newer version byte: unusable here, refuse
};

struct ScanResult {
  ScanStatus status{ScanStatus::kClean};
  size_t valid_end{0};          // bytes of intact prefix (incl. file header)
  uint32_t chain{kChainSeed};   // chain value after the last intact record
  // (payload offset, payload length) of every intact record, in order.
  std::vector<std::pair<size_t, uint32_t>> records;
};

inline ScanResult scan(const uint8_t* data, size_t size) {
  ScanResult out;
  if (size == 0) return out;  // fresh file: clean, header written on open
  if (!has_v2_magic(data, size)) {
    out.status = ScanStatus::kLegacy;
    return out;
  }
  if (size < sizeof(FileHeader)) {
    // The 8-byte header write itself tore. Nothing after it can exist.
    out.status = ScanStatus::kTornTail;
    return out;
  }
  FileHeader fh;
  std::memcpy(&fh, data, sizeof(fh));
  if (fh.version != kFileVersion) {
    out.status = ScanStatus::kFuture;
    return out;
  }
  size_t pos = sizeof(FileHeader);
  out.valid_end = pos;
  while (pos < size) {
    if (size - pos < sizeof(RecordHeader)) {
      out.status = ScanStatus::kTornTail;
      return out;
    }
    RecordHeader rh;
    std::memcpy(&rh, data + pos, sizeof(rh));
    if (rh.len == 0 || rh.len > kMaxRecordBytes) {
      // A complete header with a length the writer could never emit: the
      // length field itself rotted. A torn append cannot produce this (a
      // tear leaves a SHORT header, caught above).
      out.status = ScanStatus::kCorrupt;
      return out;
    }
    const size_t extent = pos + sizeof(RecordHeader) + rh.len;
    if (extent > size) {
      out.status = ScanStatus::kTornTail;
      return out;
    }
    const uint32_t want = chain_next(out.chain, data + pos + sizeof(RecordHeader), rh.len);
    if (want != rh.chain_crc) {
      // Complete body, broken chain: in-place damage (or splicing), not a
      // torn append — a tear leaves the record short, never wrong.
      out.status = ScanStatus::kCorrupt;
      return out;
    }
    out.records.emplace_back(pos + sizeof(RecordHeader), rh.len);
    out.chain = want;
    pos = extent;
    out.valid_end = pos;
  }
  return out;
}

// Legacy (pre-chain) WAL: [u32 len][payload] repeated, no header, no CRC.
// The historical recovery rule: stop at the first short/oversized length
// and truncate there (indistinguishable from a torn tail by design — this
// is exactly the blind spot the v2 chain closes).
inline ScanResult scan_legacy(const uint8_t* data, size_t size) {
  ScanResult out;
  out.status = ScanStatus::kLegacy;
  size_t pos = 0;
  while (pos + sizeof(uint32_t) <= size) {
    uint32_t len = 0;
    std::memcpy(&len, data + pos, sizeof(len));
    if (len == 0 || len > kMaxRecordBytes || pos + sizeof(len) + len > size) break;
    out.records.emplace_back(pos + sizeof(len), len);
    pos += sizeof(len) + len;
    out.valid_end = pos;
  }
  return out;
}

// Appends one v2-framed record to `file`, advancing `chain` — the byte-
// building half of the round-trip the fuzz target pins against scan().
inline void append_record(std::vector<uint8_t>& file, uint32_t& chain,
                          const uint8_t* payload, size_t len) {
  RecordHeader rh;
  rh.len = static_cast<uint32_t>(len);
  rh.chain_crc = chain_next(chain, payload, len);
  const uint8_t* h = reinterpret_cast<const uint8_t*>(&rh);
  file.insert(file.end(), h, h + sizeof(rh));
  file.insert(file.end(), payload, payload + len);
  chain = rh.chain_crc;
}

inline void append_file_header(std::vector<uint8_t>& file) {
  FileHeader fh{kFileMagic, kFileVersion};
  const uint8_t* h = reinterpret_cast<const uint8_t*>(&fh);
  file.insert(file.end(), h, h + sizeof(fh));
}

}  // namespace btpu::coord::wal
