"""ctypes bindings to the native core (libbtpu.so), with build-on-demand."""

from __future__ import annotations

import ctypes
import enum
import os
import shutil
import subprocess
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent
_BUILD_DIR = _REPO_ROOT / "build"
_LIB_PATH = _BUILD_DIR / "libbtpu.so"


class ErrorCode(enum.IntEnum):
    """Mirror of btpu::ErrorCode domain bases + common codes (error.h)."""

    OK = 0
    INTERNAL_ERROR = 1000
    NOT_IMPLEMENTED = 1005
    MEMORY_POOL_NOT_FOUND = 2002
    INSUFFICIENT_SPACE = 2006
    MEMORY_ACCESS_ERROR = 2007
    CONNECTION_FAILED = 3001
    TRANSFER_FAILED = 3002
    OBJECT_NOT_FOUND = 5000
    OBJECT_ALREADY_EXISTS = 5001
    NO_COMPLETE_WORKER = 5005
    INVALID_PARAMETERS = 7002


class StorageClass(enum.IntEnum):
    RAM_CPU = 1
    HBM_TPU = 2
    NVME = 3
    SSD = 4
    HDD = 5
    CXL_MEMORY = 6


class TransportKind(enum.IntEnum):
    LOCAL = 1
    SHM = 2
    TCP = 3
    ICI = 4
    HBM = 5


def _needs_build() -> bool:
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    native_dir = _REPO_ROOT / "native"
    for path in native_dir.rglob("*"):
        if path.suffix in (".cpp", ".h") and path.stat().st_mtime > lib_mtime:
            return True
    return False


def build_native(force: bool = False) -> None:
    """(Re)builds libbtpu.so when sources are newer than the artifact.

    Prefers the cmake/ninja build; containers that ship only gcc+make fall
    back to the mirror Makefile (same artifacts in the same build/ layout).
    """
    if not force and not _needs_build():
        return
    if shutil.which("cmake") and shutil.which("ninja"):
        subprocess.run(
            ["cmake", "-B", str(_BUILD_DIR), "-G", "Ninja"],
            cwd=_REPO_ROOT,
            check=True,
            capture_output=True,
        )
        subprocess.run(
            ["ninja", "-C", str(_BUILD_DIR)],
            cwd=_REPO_ROOT,
            check=True,
            capture_output=True,
        )
        return
    jobs = str(max(2, os.cpu_count() or 1))
    subprocess.run(
        ["make", "-j", jobs, "native"],
        cwd=_REPO_ROOT,
        check=True,
        capture_output=True,
    )


def _load() -> ctypes.CDLL:
    build_native()
    handle = ctypes.CDLL(str(_LIB_PATH))

    c = ctypes.c_void_p
    u32, u64, i32 = ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int32
    sig = {
        "btpu_cluster_create": (c, [u32, u64, u32, u32]),
        "btpu_cluster_create_tiered": (c, [u32, u64, u64]),
        "btpu_cluster_destroy": (None, [c]),
        "btpu_cluster_kill_worker": (i32, [c, u32]),
        "btpu_cluster_worker_count": (u32, [c]),
        "btpu_cluster_counters": (None, [c, ctypes.POINTER(u64)]),
        "btpu_client_create_embedded": (c, [c]),
        "btpu_client_create_remote": (c, [ctypes.c_char_p]),
        "btpu_client_destroy": (None, [c]),
        "btpu_client_set_verify": (None, [c, i32]),
        "btpu_put": (i32, [c, ctypes.c_char_p, ctypes.c_void_p, u64, u32, u32, u32]),
        "btpu_get": (i32, [c, ctypes.c_char_p, ctypes.c_void_p, u64, ctypes.POINTER(u64)]),
        "btpu_put_many": (i32, [c, u32, ctypes.POINTER(ctypes.c_char_p),
                                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(u64),
                                u32, u32, u32, ctypes.POINTER(i32)]),
        "btpu_get_many": (i32, [c, u32, ctypes.POINTER(ctypes.c_char_p),
                                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(u64),
                                ctypes.POINTER(u64), ctypes.POINTER(i32)]),
        "btpu_sizes_many": (i32, [c, u32, ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.POINTER(u64), ctypes.POINTER(i32)]),
        "btpu_exists": (i32, [c, ctypes.c_char_p, ctypes.POINTER(i32)]),
        "btpu_remove": (i32, [c, ctypes.c_char_p]),
        "btpu_stats": (i32, [c, ctypes.POINTER(u64)]),
        "btpu_pvm_op_count": (u64, []),
        "btpu_error_name": (ctypes.c_char_p, [i32]),
        "btpu_register_hbm_provider_v3": (None, [ctypes.c_void_p]),
        "btpu_placements_json": (i32, [c, ctypes.c_char_p, ctypes.c_char_p, u64,
                                       ctypes.POINTER(u64)]),
        "btpu_list_json": (i32, [c, ctypes.c_char_p, u64, ctypes.c_char_p, u64,
                                 ctypes.POINTER(u64)]),
        "btpu_put_ex2": (i32, [c, ctypes.c_char_p, ctypes.c_void_p, u64, u32, u32,
                               u32, ctypes.c_int64, i32, i32]),
        "btpu_put_ec2": (i32, [c, ctypes.c_char_p, ctypes.c_void_p, u64, u32, u32,
                               u32, ctypes.c_int64, i32, i32]),
        "btpu_drain_worker": (i32, [c, ctypes.c_char_p, ctypes.POINTER(u64)]),
        "btpu_put_start_json": (i32, [c, ctypes.c_char_p, u64, u32, u32,
                                      ctypes.c_char_p, ctypes.c_char_p, u64,
                                      ctypes.POINTER(u64)]),
        "btpu_put_complete": (i32, [c, ctypes.c_char_p]),
        "btpu_put_cancel": (i32, [c, ctypes.c_char_p]),
        "btpu_fabric_offer": (i32, [c, ctypes.c_char_p, ctypes.c_char_p, u64, u64,
                                    u64, u64]),
        "btpu_fabric_pull": (i32, [c, ctypes.c_char_p, ctypes.c_char_p, u64, u64,
                                   u64, u64, ctypes.c_char_p]),
        "btpu_worker_create": (c, [ctypes.c_char_p, ctypes.c_char_p]),
        "btpu_worker_pool_count": (u32, [c]),
        "btpu_worker_id": (ctypes.c_char_p, [c]),
        "btpu_worker_destroy": (None, [c]),
    }
    for name, (restype, argtypes) in sig.items():
        fn = getattr(handle, name)
        fn.restype = restype
        fn.argtypes = argtypes
    # Newer provider-registration entry points are OPTIONAL: hbm.py probes
    # with hasattr() and falls back down the version chain, so a prebuilt
    # older library must not fail the whole import here.
    for name in ("btpu_register_hbm_provider_v4", "btpu_register_hbm_provider_v5"):
        if hasattr(handle, name):
            fn = getattr(handle, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p]
    # Lane scoreboard counters (optional for the same prebuilt-library reason).
    for name in ("btpu_pvm_byte_count", "btpu_tcp_staged_op_count",
                 "btpu_tcp_staged_byte_count", "btpu_tcp_stream_op_count",
                 "btpu_tcp_stream_byte_count", "btpu_tcp_pool_direct_op_count",
                 "btpu_tcp_pool_direct_byte_count", "btpu_tcp_zerocopy_sent_count",
                 "btpu_tcp_zerocopy_copied_count", "btpu_uring_loop_count",
                 "btpu_wire_pool_threads", "btpu_cached_op_count",
                 "btpu_cached_byte_count", "btpu_persist_retry_backlog",
                 "btpu_op_get_count", "btpu_op_get_p50_us", "btpu_op_get_p99_us",
                 "btpu_flight_event_count", "btpu_trace_span_count"):
        if hasattr(handle, name):
            fn = getattr(handle, name)
            fn.restype = u64
            fn.argtypes = []
    # Observability exports (optional, same prebuilt-library reason):
    # histogram/trace/flight JSON dumps + the tracing master switch.
    if hasattr(handle, "btpu_histograms_json"):
        handle.btpu_histograms_json.restype = i32
        handle.btpu_histograms_json.argtypes = [ctypes.c_char_p, u64,
                                                ctypes.POINTER(u64)]
        handle.btpu_trace_spans_json.restype = i32
        handle.btpu_trace_spans_json.argtypes = [u64, ctypes.c_char_p, u64,
                                                 ctypes.POINTER(u64)]
        handle.btpu_flight_json.restype = i32
        handle.btpu_flight_json.argtypes = [ctypes.c_char_p, u64, ctypes.POINTER(u64)]
        handle.btpu_set_tracing.restype = None
        handle.btpu_set_tracing.argtypes = [i32]
    # Durable embedded cluster (optional, same prebuilt-library reason):
    # cluster.py probes hasattr before offering data_dir.
    if hasattr(handle, "btpu_cluster_create_ex"):
        handle.btpu_cluster_create_ex.restype = c
        handle.btpu_cluster_create_ex.argtypes = [u32, u64, u32, u32, ctypes.c_char_p,
                                                  ctypes.c_int64]
    # Client object cache (optional, same prebuilt-library reason): config +
    # stats for the lease-coherent cache (native/src/cache/object_cache.cpp).
    if hasattr(handle, "btpu_client_cache_configure"):
        handle.btpu_client_cache_configure.restype = None
        handle.btpu_client_cache_configure.argtypes = [c, u64]
        handle.btpu_client_cache_stats.restype = i32
        handle.btpu_client_cache_stats.argtypes = [c, ctypes.POINTER(u64)]
    return handle


lib = _load()


class BtpuError(RuntimeError):
    def __init__(self, code: int, operation: str):
        self.code = code
        name = lib.btpu_error_name(code).decode()
        super().__init__(f"{operation} failed: {name} ({code})")


def check(code: int, operation: str) -> None:
    if code != 0:
        raise BtpuError(code, operation)
