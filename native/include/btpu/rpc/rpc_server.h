// RPC server wrapping a KeystoneService, plus the bootstrap helper.
// Parity target: reference RpcService (rpc_service.h:28-274,
// create_and_start_keystone rpc_service.cpp:434-467).
#pragma once

#include <atomic>
#include <memory>
#include <thread>

#include "btpu/common/admission.h"
#include "btpu/common/deadline.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/keystone/keystone.h"
#include "btpu/net/net.h"
#include "btpu/rpc/http_metrics.h"

namespace btpu::rpc {

class KeystoneRpcServer {
 public:
  KeystoneRpcServer(keystone::KeystoneService& service, std::string host, uint16_t port);
  ~KeystoneRpcServer();

  ErrorCode start();
  void stop();
  uint16_t port() const noexcept { return port_; }
  std::string endpoint() const { return host_ + ":" + std::to_string(port_); }
  // Observability for tests/metrics.
  const AdmissionGate& gate() const noexcept { return *gate_; }

 private:
  void accept_loop();
  void serve(std::shared_ptr<net::Socket> sock);
  std::vector<uint8_t> dispatch(uint8_t opcode, const std::vector<uint8_t>& payload);

  keystone::KeystoneService& service_;
  std::string host_;
  uint16_t port_;
  // Admission gate for non-control ops (see AdmissionGate). Control ops —
  // ping, view version, cluster stats, drain — bypass it so the control
  // plane stays observable exactly when the gate is closed.
  std::unique_ptr<AdmissionGate> gate_;
  // Test hook: per-request service delay (BTPU_RPC_TEST_DELAY_MS at
  // construction) so admission/deadline behavior is deterministically
  // testable without a genuinely slow keystone.
  uint32_t test_delay_ms_{0};
  net::Socket listener_;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  Mutex conns_mutex_;
  std::vector<std::thread> conn_threads_ BTPU_GUARDED_BY(conns_mutex_);
  std::vector<std::shared_ptr<net::Socket>> conns_ BTPU_GUARDED_BY(conns_mutex_);
};

// Bundled keystone + RPC + metrics, one call to boot a control plane
// (reference create_and_start_keystone).
struct KeystoneStack {
  std::unique_ptr<keystone::KeystoneService> service;
  std::unique_ptr<KeystoneRpcServer> rpc;
  std::unique_ptr<MetricsHttpServer> metrics;

  ~KeystoneStack();
  void stop();
};

Result<std::unique_ptr<KeystoneStack>> create_and_start_keystone(
    const KeystoneConfig& config, std::shared_ptr<coord::Coordinator> coordinator);

}  // namespace btpu::rpc
