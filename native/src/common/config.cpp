#include "btpu/common/config.h"

#include <algorithm>
#include <cctype>
#include <charconv>
#include <fstream>
#include <sstream>

#include "btpu/common/log.h"
#include "btpu/common/types.h"

namespace btpu::yaml {

NodePtr Node::make_null() {
  auto n = std::make_shared<Node>();
  n->kind_ = Kind::kNull;
  return n;
}
NodePtr Node::make_scalar(std::string value, bool quoted) {
  auto n = std::make_shared<Node>();
  n->kind_ = Kind::kScalar;
  n->scalar_ = std::move(value);
  n->quoted_ = quoted;
  return n;
}
NodePtr Node::make_map() {
  auto n = std::make_shared<Node>();
  n->kind_ = Kind::kMap;
  return n;
}
NodePtr Node::make_list() {
  auto n = std::make_shared<Node>();
  n->kind_ = Kind::kList;
  return n;
}

NodePtr Node::get(const std::string& key) const {
  if (!is_map()) return nullptr;
  auto it = map_.find(key);
  return it == map_.end() ? nullptr : it->second;
}

NodePtr Node::get_path(const std::string& dotted) const {
  size_t start = 0;
  const Node* cur = this;
  NodePtr result;
  while (start <= dotted.size()) {
    size_t dot = dotted.find('.', start);
    std::string part = dotted.substr(start, dot == std::string::npos ? std::string::npos : dot - start);
    result = cur->get(part);
    if (!result) return nullptr;
    if (dot == std::string::npos) return result;
    cur = result.get();
    start = dot + 1;
  }
  return result;
}

std::optional<std::string> Node::as_string() const {
  if (!is_scalar()) return std::nullopt;
  return scalar_;
}

std::optional<int64_t> Node::as_int() const {
  if (!is_scalar()) return std::nullopt;
  int64_t v = 0;
  auto [p, ec] = std::from_chars(scalar_.data(), scalar_.data() + scalar_.size(), v);
  if (ec != std::errc{} || p != scalar_.data() + scalar_.size()) return std::nullopt;
  return v;
}

std::optional<uint64_t> Node::as_uint() const {
  if (!is_scalar()) return std::nullopt;
  uint64_t v = 0;
  auto [p, ec] = std::from_chars(scalar_.data(), scalar_.data() + scalar_.size(), v);
  if (ec != std::errc{} || p != scalar_.data() + scalar_.size()) return std::nullopt;
  return v;
}

std::optional<double> Node::as_double() const {
  if (!is_scalar()) return std::nullopt;
  try {
    size_t pos = 0;
    double v = std::stod(scalar_, &pos);
    if (pos != scalar_.size()) return std::nullopt;
    return v;
  } catch (...) {
    return std::nullopt;
  }
}

std::optional<bool> Node::as_bool() const {
  if (!is_scalar()) return std::nullopt;
  if (scalar_ == "true" || scalar_ == "True" || scalar_ == "yes" || scalar_ == "on") return true;
  if (scalar_ == "false" || scalar_ == "False" || scalar_ == "no" || scalar_ == "off") return false;
  return std::nullopt;
}

namespace {

struct Line {
  int indent;
  std::string content;  // stripped of indentation and trailing comment
  size_t number;
};

// Strip a trailing comment that is not inside quotes.
std::string strip_comment(const std::string& s) {
  bool in_single = false, in_double = false;
  for (size_t i = 0; i < s.size(); ++i) {
    char c = s[i];
    if (c == '\'' && !in_double) in_single = !in_single;
    else if (c == '"' && !in_single) in_double = !in_double;
    else if (c == '#' && !in_single && !in_double && (i == 0 || s[i - 1] == ' ' || s[i - 1] == '\t'))
      return s.substr(0, i);
  }
  return s;
}

std::string rstrip(std::string s) {
  while (!s.empty() && (s.back() == ' ' || s.back() == '\t' || s.back() == '\r')) s.pop_back();
  return s;
}

// Parse a scalar token: strip quotes, detect null.
NodePtr scalar_node(std::string tok) {
  if (tok.empty() || tok == "~" || tok == "null") return Node::make_null();
  if (tok.size() >= 2 && ((tok.front() == '"' && tok.back() == '"') ||
                          (tok.front() == '\'' && tok.back() == '\''))) {
    return Node::make_scalar(tok.substr(1, tok.size() - 2), /*quoted=*/true);
  }
  return Node::make_scalar(std::move(tok));
}

class Parser {
 public:
  explicit Parser(std::vector<Line> lines) : lines_(std::move(lines)) {}

  Result<NodePtr> run() {
    if (lines_.empty()) return Node::make_map();
    auto node = parse_block(lines_[0].indent);
    if (!node.ok()) return node;
    if (pos_ != lines_.size()) {
      LOG_ERROR << "yaml: unexpected content at line " << lines_[pos_].number;
      return ErrorCode::INVALID_CONFIGURATION;
    }
    return node;
  }

 private:
  // Parses a block (map or list) whose items sit at `indent`.
  Result<NodePtr> parse_block(int indent) {
    if (pos_ >= lines_.size()) return Node::make_null();
    const bool is_list = lines_[pos_].content.rfind("- ", 0) == 0 || lines_[pos_].content == "-";
    return is_list ? parse_list(indent) : parse_map(indent);
  }

  Result<NodePtr> parse_map(int indent) {
    auto map = Node::make_map();
    while (pos_ < lines_.size() && lines_[pos_].indent == indent) {
      const Line& line = lines_[pos_];
      if (line.content.rfind("- ", 0) == 0 || line.content == "-") break;  // list item at map level: stop
      size_t colon = find_key_colon(line.content);
      if (colon == std::string::npos) {
        LOG_ERROR << "yaml: expected 'key: value' at line " << line.number;
        return ErrorCode::INVALID_CONFIGURATION;
      }
      std::string key = rstrip(line.content.substr(0, colon));
      std::string rest = line.content.substr(colon + 1);
      size_t first = rest.find_first_not_of(" \t");
      rest = first == std::string::npos ? "" : rest.substr(first);
      ++pos_;
      if (!rest.empty()) {
        map->map_set(key, scalar_node(rest));
      } else if (pos_ < lines_.size() && lines_[pos_].indent > indent) {
        auto child = parse_block(lines_[pos_].indent);
        if (!child.ok()) return child;
        map->map_set(key, child.value());
      } else {
        map->map_set(key, Node::make_null());
      }
    }
    if (pos_ < lines_.size() && lines_[pos_].indent > indent) {
      LOG_ERROR << "yaml: bad indentation at line " << lines_[pos_].number;
      return ErrorCode::INVALID_CONFIGURATION;
    }
    return map;
  }

  Result<NodePtr> parse_list(int indent) {
    auto list = Node::make_list();
    while (pos_ < lines_.size() && lines_[pos_].indent == indent &&
           (lines_[pos_].content.rfind("- ", 0) == 0 || lines_[pos_].content == "-")) {
      Line line = lines_[pos_];
      std::string rest = line.content == "-" ? "" : line.content.substr(2);
      size_t first = rest.find_first_not_of(" \t");
      rest = first == std::string::npos ? "" : rest.substr(first);
      if (rest.empty()) {
        ++pos_;
        if (pos_ < lines_.size() && lines_[pos_].indent > indent) {
          auto child = parse_block(lines_[pos_].indent);
          if (!child.ok()) return child;
          list->list_append(child.value());
        } else {
          list->list_append(Node::make_null());
        }
      } else if (find_key_colon(rest) != std::string::npos) {
        // Inline first pair of a map item: rewrite "- k: v" as a map whose
        // first line is at the rest's indentation, then continue that map.
        int item_indent = line.indent + 2;
        lines_[pos_] = Line{item_indent, rest, line.number};
        auto child = parse_map(item_indent);
        if (!child.ok()) return child;
        list->list_append(child.value());
      } else {
        list->list_append(scalar_node(rest));
        ++pos_;
      }
    }
    return list;
  }

  // Finds the ':' separating key from value (not inside quotes; must be at
  // end or followed by whitespace).
  static size_t find_key_colon(const std::string& s) {
    bool in_single = false, in_double = false;
    for (size_t i = 0; i < s.size(); ++i) {
      char c = s[i];
      if (c == '\'' && !in_double) in_single = !in_single;
      else if (c == '"' && !in_single) in_double = !in_double;
      else if (c == ':' && !in_single && !in_double &&
               (i + 1 == s.size() || s[i + 1] == ' ' || s[i + 1] == '\t'))
        return i;
    }
    return std::string::npos;
  }

  std::vector<Line> lines_;
  size_t pos_{0};
};

}  // namespace

Result<NodePtr> parse(const std::string& text) {
  std::vector<Line> lines;
  std::istringstream in(text);
  std::string raw;
  size_t number = 0;
  while (std::getline(in, raw)) {
    ++number;
    std::string no_comment = rstrip(strip_comment(raw));
    size_t indent = no_comment.find_first_not_of(' ');
    if (indent == std::string::npos) continue;  // blank line
    std::string content = no_comment.substr(indent);
    if (content == "---") continue;  // document marker
    if (content.find('\t') == 0) {
      LOG_ERROR << "yaml: tab indentation at line " << number;
      return ErrorCode::INVALID_CONFIGURATION;
    }
    lines.push_back({static_cast<int>(indent), content, number});
  }
  return Parser(std::move(lines)).run();
}

Result<NodePtr> parse_file(const std::string& path) {
  std::ifstream f(path);
  if (!f) {
    LOG_ERROR << "yaml: cannot open " << path;
    return ErrorCode::CONFIG_ERROR;
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  return parse(ss.str());
}

std::optional<uint64_t> parse_byte_size(const std::string& text) {
  if (text.empty()) return std::nullopt;
  size_t i = 0;
  uint64_t value = 0;
  bool any = false;
  while (i < text.size() && std::isdigit(static_cast<unsigned char>(text[i]))) {
    value = value * 10 + (text[i] - '0');
    any = true;
    ++i;
  }
  if (!any) return std::nullopt;
  std::string suffix = text.substr(i);
  std::transform(suffix.begin(), suffix.end(), suffix.begin(),
                 [](unsigned char c) { return std::toupper(c); });
  if (suffix.empty() || suffix == "B") return value;
  if (suffix == "K" || suffix == "KB" || suffix == "KIB") return value << 10;
  if (suffix == "M" || suffix == "MB" || suffix == "MIB") return value << 20;
  if (suffix == "G" || suffix == "GB" || suffix == "GIB") return value << 30;
  if (suffix == "T" || suffix == "TB" || suffix == "TIB") return value << 40;
  return std::nullopt;
}

}  // namespace yaml

// ---------------------------------------------------------------------------
// KeystoneConfig::from_yaml — parity with reference src/common/types.cpp:20-101
// (throws std::runtime_error on unreadable/invalid config).
// ---------------------------------------------------------------------------
namespace btpu {

KeystoneConfig KeystoneConfig::from_yaml(const std::string& file_path) {
  auto parsed = yaml::parse_file(file_path);
  if (!parsed.ok()) {
    throw std::runtime_error("failed to parse keystone config " + file_path + ": " +
                             std::string(to_string(parsed.error())));
  }
  const auto& root = *parsed.value();
  KeystoneConfig cfg;
  if (auto n = root.get("cluster_id")) cfg.cluster_id = n->str_or(cfg.cluster_id);
  if (auto n = root.get("coord_endpoints")) cfg.coord_endpoints = n->str_or("");
  if (auto n = root.get("etcd_endpoints")) cfg.coord_endpoints = n->str_or("");  // reference key
  if (auto n = root.get("listen_address")) cfg.listen_address = n->str_or(cfg.listen_address);
  if (auto n = root.get("http_metrics_port")) cfg.http_metrics_port = n->str_or(cfg.http_metrics_port);
  if (auto n = root.get("service_id")) cfg.service_id = n->str_or("");

  if (auto n = root.get("enable_gc")) cfg.enable_gc = n->bool_or(cfg.enable_gc);
  if (auto n = root.get("enable_ha")) cfg.enable_ha = n->bool_or(cfg.enable_ha);
  if (auto n = root.get("eviction_ratio")) cfg.eviction_ratio = n->double_or(cfg.eviction_ratio);
  if (auto n = root.get("high_watermark")) cfg.high_watermark = n->double_or(cfg.high_watermark);
  if (auto n = root.get("client_ttl_sec")) cfg.client_ttl_sec = n->int_or(cfg.client_ttl_sec);
  if (auto n = root.get("worker_heartbeat_ttl_sec"))
    cfg.worker_heartbeat_ttl_sec = n->int_or(cfg.worker_heartbeat_ttl_sec);
  if (auto n = root.get("service_registration_ttl_sec"))
    cfg.service_registration_ttl_sec = n->int_or(cfg.service_registration_ttl_sec);
  if (auto n = root.get("service_refresh_interval_sec"))
    cfg.service_refresh_interval_sec = n->int_or(cfg.service_refresh_interval_sec);
  if (auto n = root.get("gc_interval_sec")) cfg.gc_interval_sec = n->int_or(cfg.gc_interval_sec);
  if (auto n = root.get("scrub_interval_sec"))
    cfg.scrub_interval_sec = n->int_or(cfg.scrub_interval_sec);
  if (auto n = root.get("scrub_objects_per_pass"))
    cfg.scrub_objects_per_pass = static_cast<uint32_t>(n->int_or(cfg.scrub_objects_per_pass));
  if (auto n = root.get("inline_max_bytes"))
    cfg.inline_max_bytes = static_cast<uint64_t>(n->int_or(cfg.inline_max_bytes));
  if (auto n = root.get("inline_total_bytes"))
    cfg.inline_total_bytes = static_cast<uint64_t>(n->int_or(cfg.inline_total_bytes));
  if (auto n = root.get("health_check_interval_sec"))
    cfg.health_check_interval_sec = n->int_or(cfg.health_check_interval_sec);
  if (auto n = root.get("pending_put_timeout_sec"))
    cfg.pending_put_timeout_sec = n->int_or(cfg.pending_put_timeout_sec);
  if (auto n = root.get("slot_ttl_sec"))
    cfg.slot_ttl_sec = n->int_or(cfg.slot_ttl_sec);
  if (auto n = root.get("max_replicas")) cfg.max_replicas = static_cast<int32_t>(n->int_or(cfg.max_replicas));
  if (auto n = root.get("default_replicas"))
    cfg.default_replicas = static_cast<int32_t>(n->int_or(cfg.default_replicas));
  if (auto n = root.get("enable_repair")) cfg.enable_repair = n->bool_or(cfg.enable_repair);
  if (auto n = root.get("tier_aware_eviction"))
    cfg.tier_aware_eviction = n->bool_or(cfg.tier_aware_eviction);
  if (auto n = root.get("enable_tier_demotion"))
    cfg.enable_tier_demotion = n->bool_or(cfg.enable_tier_demotion);
  if (auto n = root.get("persist_objects"))
    cfg.persist_objects = n->bool_or(cfg.persist_objects);
  if (auto n = root.get("metadata_shards"))
    cfg.metadata_shards = static_cast<uint32_t>(n->int_or(cfg.metadata_shards));
  if (auto n = root.get("rpc_max_inflight"))
    cfg.rpc_max_inflight = static_cast<uint32_t>(n->int_or(cfg.rpc_max_inflight));
  if (auto n = root.get("rpc_max_queue"))
    cfg.rpc_max_queue = static_cast<uint32_t>(n->int_or(cfg.rpc_max_queue));
  if (auto n = root.get("rpc_shed_backoff_hint_ms"))
    cfg.rpc_shed_backoff_hint_ms =
        static_cast<uint32_t>(n->int_or(cfg.rpc_shed_backoff_hint_ms));

  if (auto ec = cfg.validate(); ec != ErrorCode::OK) {
    throw std::runtime_error("invalid keystone config " + file_path + ": " +
                             std::string(to_string(ec)));
  }
  return cfg;
}

}  // namespace btpu
