"""blackbird_tpu: TPU-native distributed object store.

A from-scratch rebuild of blackbird-io/blackbird for TPU deployments: a C++20
core (control plane, allocator, transports, tiered storage backends) plus a
JAX-backed TPU HBM tier and mesh/collective helpers for the intra-slice (ICI)
data plane.

Layout:
    blackbird_tpu.native    ctypes bindings to libbtpu.so (auto-builds)
    blackbird_tpu.cluster   embedded in-process cluster harness
    blackbird_tpu.client    object client (put/get bytes or numpy arrays)
    blackbird_tpu.hbm       JAX HBM provider: device buffers as the top tier
    blackbird_tpu.topology  TPU pod/slice topology discovery from jax.devices()
    blackbird_tpu.parallel  mesh/sharding helpers for the ICI data plane
    blackbird_tpu.checkpoint sharded-array checkpoint/restore via the store
    blackbird_tpu.ops       pallas/jnp kernels (checksums, shard repacking)
    blackbird_tpu.worker    standalone TPU-VM worker host (python -m ...)
    blackbird_tpu.procluster multi-controller process-cluster launcher
    blackbird_tpu.distributed jax.distributed bridge: derive this host's
                            worker from the runtime (pods)
"""

from blackbird_tpu.native import ErrorCode, StorageClass, TransportKind, lib
from blackbird_tpu.cluster import EmbeddedCluster
from blackbird_tpu.client import Client
from blackbird_tpu.fabric import FabricClient, FabricUnavailable

# Explicit export surface (mypy runs with no_implicit_reexport).
__all__ = [
    "Client",
    "EmbeddedCluster",
    "ErrorCode",
    "FabricClient",
    "FabricUnavailable",
    "StorageClass",
    "TransportKind",
    "lib",
]

__version__ = "0.1.0"
