#include "btpu/rpc/rpc_client.h"

#include <algorithm>
#include <thread>

#include "btpu/common/flight_recorder.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/common/wire.h"
#include "btpu/rpc/rpc.h"

namespace btpu::rpc {

KeystoneRpcClient::KeystoneRpcClient(std::string endpoint) : endpoint_(std::move(endpoint)) {}

KeystoneRpcClient::~KeystoneRpcClient() { disconnect(); }

ErrorCode KeystoneRpcClient::connect() {
  MutexLock lock(mutex_);
  return ensure_connected_locked(current_op_deadline());
}

void KeystoneRpcClient::disconnect() {
  MutexLock lock(mutex_);
  sock_.shutdown();
  sock_.close();
}

bool KeystoneRpcClient::connected() const {
  // Non-blocking probe: destructor-path callers (cancel_pooled_slots) use
  // this precisely to AVOID paying a connect timeout an in-flight call may
  // be stuck in — parking behind mutex_ here would defeat that. A busy
  // client reports "not idle-connected" and best-effort work is skipped
  // (the server-side slot TTL covers it either way).
  MutexLock lock(mutex_, std::try_to_lock);
  if (!lock) return false;
  return sock_.valid();
}

ErrorCode KeystoneRpcClient::ensure_connected_locked(const Deadline& deadline) {
  if (sock_.valid()) return ErrorCode::OK;
  auto hp = net::parse_host_port(endpoint_);
  if (!hp) return ErrorCode::INVALID_ADDRESS;
  // The dial itself honors the op deadline: a dead keystone must not cost a
  // caller with 50 ms of budget a 5 s connect timeout.
  int timeout_ms = 5000;
  if (!deadline.is_infinite()) {
    const int64_t left = deadline.remaining_ms();
    if (left <= 0) return ErrorCode::DEADLINE_EXCEEDED;
    timeout_ms = static_cast<int>(std::min<int64_t>(timeout_ms, left));
  }
  auto sock = net::tcp_connect(hp->host, hp->port, timeout_ms);
  if (!sock.ok()) return sock.error();
  sock_ = std::move(sock).value();
  return ErrorCode::OK;
}

ErrorCode KeystoneRpcClient::call_raw(uint8_t opcode, const std::vector<uint8_t>& req,
                                      std::vector<uint8_t>& resp) {
  const Deadline deadline = current_op_deadline();
  // The RPC round trip as a span under the caller's op (the keystone-side
  // dispatch span stitches under it by the propagated ids), plus the wire
  // context snapshot — read ONCE here on the calling thread (retry attempts
  // reuse it; backoff sleeps must not re-read another op's context).
  TRACE_SPAN("client.rpc");
  const trace::TraceContext tctx =
      trace::enabled() ? trace::current() : trace::TraceContext{};
  flight::record(flight::Ev::kRpcStart, opcode);
  if (deadline.expired()) {
    // ordering: relaxed — monotonic stat counter.
    robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
    flight::record(flight::Ev::kDeadlineExceeded, /*a0=client*/ 0);
    return ErrorCode::DEADLINE_EXCEEDED;
  }
  MutexLock lock(mutex_);
  // CONNECTION_FAILED is a *contract*: it may only be returned when no whole
  // frame was ever delivered, so callers (client failover) can safely replay
  // the call against another keystone. Once a mutation frame went out, a
  // lost reply is RPC_FAILED and the request is never re-sent — it may have
  // executed. Read-only methods ARE re-sent after a lost reply (stale
  // pooled connection, keystone restart): replaying them is harmless and
  // keeps single-endpoint clients transparent across restarts. RETRY_LATER
  // sheds are retryable for EVERY method: the server rejects before
  // dispatch, so the request provably did not execute.
  const bool read_only = opcode == static_cast<uint8_t>(Method::kObjectExists) ||
                         opcode == static_cast<uint8_t>(Method::kGetWorkers) ||
                         opcode == static_cast<uint8_t>(Method::kGetClusterStats) ||
                         opcode == static_cast<uint8_t>(Method::kGetViewVersion) ||
                         opcode == static_cast<uint8_t>(Method::kBatchObjectExists) ||
                         opcode == static_cast<uint8_t>(Method::kBatchGetWorkers) ||
                         opcode == static_cast<uint8_t>(Method::kPing);
  // max_attempts counts TOTAL attempts; 1 = fail-fast (no retry, no replay)
  // as the storm tests configure. The default policy (4) keeps single-
  // endpoint clients transparent across keystone restarts via the read-only
  // replay contract above. 0 is nonsense — treat as 1.
  const uint32_t max_attempts = std::max<uint32_t>(1, retry_policy_.max_attempts);
  uint32_t shed_hint_ms = 0;
  ErrorCode last = ErrorCode::CONNECTION_FAILED;
  for (uint32_t attempt = 0; attempt < max_attempts; ++attempt) {
    if (attempt > 0) {
      // Jittered exponential backoff, stretched to any server-provided
      // backoff hint, bounded by the retry BUDGET (token bucket: a retry
      // storm drains it and the client stops amplifying the overload) and
      // by the caller's remaining deadline.
      if (deadline.expired()) {
        // ordering: relaxed — monotonic stat counter.
        robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
        flight::record(flight::Ev::kDeadlineExceeded, /*a0=client*/ 0);
        return ErrorCode::DEADLINE_EXCEEDED;
      }
      if (!retry_budget_.try_spend()) {
        // ordering: relaxed — monotonic stat counter.
        robust_counters().retry_budget_exhausted.fetch_add(1, std::memory_order_relaxed);
        flight::record(flight::Ev::kRetryBudgetOut);
        break;
      }
      uint64_t wait_ms = retry_policy_.backoff_ms(attempt - 1);
      if (shed_hint_ms > 0) {
        const RetryPolicy hint{shed_hint_ms, shed_hint_ms, 1.0, 1};
        wait_ms = std::max(wait_ms, hint.backoff_ms(0));
      }
      if (!deadline.is_infinite())
        wait_ms = std::min<uint64_t>(wait_ms, static_cast<uint64_t>(deadline.remaining_ms()));
      if (wait_ms > 0) {
        // Sleep UNLOCKED: sibling threads sharing this client must not stall
        // behind one caller's backoff series. The loop revalidates the
        // connection after relocking, so concurrent close/rotate is safe.
        lock.unlock();
        std::this_thread::sleep_for(std::chrono::milliseconds(wait_ms));
        lock.lock();
      }
      // ordering: relaxed — monotonic stat counter.
      robust_counters().retries.fetch_add(1, std::memory_order_relaxed);
      flight::record(flight::Ev::kRetry, attempt);
    }
    if (auto cec = ensure_connected_locked(deadline); cec != ErrorCode::OK) {
      last = cec == ErrorCode::DEADLINE_EXCEEDED ? cec : ErrorCode::CONNECTION_FAILED;
      if (last == ErrorCode::DEADLINE_EXCEEDED) return last;
      continue;
    }
    const std::vector<uint8_t>* framed = &req;
    std::vector<uint8_t> with_trailer;
    if (!deadline.is_infinite() || tctx.trace_id != 0) {
      if (!deadline.is_infinite() && deadline.expired()) {
        // ordering: relaxed — monotonic stat counter.
        robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
        flight::record(flight::Ev::kDeadlineExceeded, /*a0=client*/ 0);
        return ErrorCode::DEADLINE_EXCEEDED;
      }
      with_trailer = req;
      // Order is the v4<->v5 compat contract (rpc.h): trace INSIDE,
      // deadline OUTERMOST so a pre-v5 server still finds its magic at the
      // payload tail.
      if (tctx.trace_id != 0)
        append_trace_trailer(with_trailer, tctx.trace_id, tctx.span_id);
      if (!deadline.is_infinite())
        append_deadline_trailer(with_trailer, deadline.wire_budget_ms());
      framed = &with_trailer;
    }
    if (net::send_frame(sock_.fd(), opcode, framed->data(), framed->size()) !=
        ErrorCode::OK) {
      // Stale connection discovered at send time (keystone restarted): at
      // most a partial frame left this socket, which the server discards
      // without executing — safe to reconnect and try again.
      sock_.close();
      last = ErrorCode::CONNECTION_FAILED;
      continue;
    }
    uint8_t resp_op = 0;
    if (net::recv_frame(sock_.fd(), resp_op, resp) == ErrorCode::OK) {
      if (resp_op == opcode) {
        retry_budget_.on_success();
        return ErrorCode::OK;
      }
      if (resp_op == kControlErrorOpcode) {
        // Overload/deadline rejection before dispatch: the connection is
        // still aligned (the server answered cleanly), so keep it.
        ErrorCode code{};
        uint32_t hint = 0;
        if (decode_control_error(resp, code, hint)) {
          if (code == ErrorCode::RETRY_LATER) {
            shed_hint_ms = hint ? hint : 50;
            last = ErrorCode::RETRY_LATER;
            continue;  // provably not executed: safe for every method
          }
          return code;  // DEADLINE_EXCEEDED / RESOURCE_EXHAUSTED: not retryable here
        }
      }
    }
    sock_.close();
    if (!read_only) return ErrorCode::RPC_FAILED;  // delivered, outcome unknown
    last = ErrorCode::CONNECTION_FAILED;
  }
  return last;
}

template <typename Req, typename Resp>
ErrorCode KeystoneRpcClient::call(uint8_t opcode, const Req& req, Resp& resp) {
  std::vector<uint8_t> resp_bytes;
  BTPU_RETURN_IF_ERROR(call_raw(opcode, wire::to_bytes(req), resp_bytes));
  if (!wire::from_bytes_lax(resp_bytes, resp)) return ErrorCode::RPC_FAILED;
  return ErrorCode::OK;
}

Result<bool> KeystoneRpcClient::object_exists(const ObjectKey& key) {
  ObjectExistsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kObjectExists),
                            ObjectExistsRequest{key}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.exists;
}

Result<std::vector<CopyPlacement>> KeystoneRpcClient::get_workers(const ObjectKey& key) {
  GetWorkersResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetWorkers), GetWorkersRequest{key},
                            resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.copies);
}

Result<std::vector<CopyPlacement>> KeystoneRpcClient::put_start(const ObjectKey& key,
                                                                uint64_t size,
                                                                const WorkerConfig& config,
                                                                uint32_t content_crc) {
  PutStartResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutStart),
                            PutStartRequest{key, size, config, content_crc}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.copies);
}

ErrorCode KeystoneRpcClient::put_complete(const ObjectKey& key,
                                          const std::vector<CopyShardCrcs>& shard_crcs,
                                          uint32_t content_crc) {
  PutCompleteResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutComplete),
                            PutCompleteRequest{key, shard_crcs, content_crc}, resp));
  return resp.error_code;
}

Result<std::vector<PutSlot>> KeystoneRpcClient::put_start_pooled(uint64_t size,
                                                                 const WorkerConfig& config,
                                                                 uint32_t count,
                                                                 const std::string& client_tag) {
  PutStartPooledResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutStartPooled),
                            PutStartPooledRequest{size, config, count, client_tag}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.slots);
}

ErrorCode KeystoneRpcClient::put_commit_slot(const PutCommitSlotRequest& request,
                                             std::vector<PutSlot>* refill_slots) {
  PutCommitSlotResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutCommitSlot), request, resp));
  if (refill_slots && resp.error_code == ErrorCode::OK) *refill_slots = std::move(resp.slots);
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::put_inline(const ObjectKey& key, const WorkerConfig& config,
                                        uint32_t content_crc, std::string data) {
  PutInlineResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutInline),
                            PutInlineRequest{key, config, content_crc, std::move(data)},
                            resp));
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::put_cancel(const ObjectKey& key) {
  PutCancelResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutCancel), PutCancelRequest{key},
                            resp));
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::remove_object(const ObjectKey& key) {
  RemoveObjectResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kRemoveObject),
                            RemoveObjectRequest{key}, resp));
  return resp.error_code;
}

Result<uint64_t> KeystoneRpcClient::remove_all_objects() {
  RemoveAllObjectsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kRemoveAllObjects),
                            RemoveAllObjectsRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.objects_removed;
}

Result<uint64_t> KeystoneRpcClient::drain_worker(const NodeId& worker_id) {
  DrainWorkerResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kDrainWorker),
                            DrainWorkerRequest{worker_id}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.copies_migrated;
}

Result<std::vector<ObjectSummary>> KeystoneRpcClient::list_objects(const std::string& prefix,
                                                                   uint64_t limit) {
  ListObjectsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kListObjects),
                            ListObjectsRequest{prefix, limit}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.objects);
}

Result<std::vector<MemoryPool>> KeystoneRpcClient::list_pools() {
  ListPoolsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kListPools), ListPoolsRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.pools);
}

Result<ClusterStats> KeystoneRpcClient::get_cluster_stats() {
  GetClusterStatsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetClusterStats),
                            GetClusterStatsRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.stats;
}

Result<ViewVersionId> KeystoneRpcClient::get_view_version() {
  GetViewVersionResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetViewVersion),
                            GetViewVersionRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.view_version;
}

Result<ViewVersionId> KeystoneRpcClient::ping() {
  std::vector<uint8_t> resp_bytes;
  BTPU_RETURN_IF_ERROR(call_raw(static_cast<uint8_t>(Method::kPing),
                                wire::to_bytes(PingRequest{kProtocolVersion}), resp_bytes));
  PingResponse resp;
  if (!wire::from_bytes_lax(resp_bytes, resp)) return ErrorCode::RPC_FAILED;
  // ordering: relaxed — advisory protocol-version cache; any torn-free value is fine and the caller re-pings on mismatch.
  server_proto_version_.store(resp.proto_version, std::memory_order_relaxed);
  return resp.view_version;
}

Result<std::vector<Result<bool>>> KeystoneRpcClient::batch_object_exists(
    const std::vector<ObjectKey>& keys) {
  BatchObjectExistsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchObjectExists),
                            BatchObjectExistsRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<Result<std::vector<CopyPlacement>>>> KeystoneRpcClient::batch_get_workers(
    const std::vector<ObjectKey>& keys) {
  BatchGetWorkersResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchGetWorkers),
                            BatchGetWorkersRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<Result<std::vector<CopyPlacement>>>> KeystoneRpcClient::batch_put_start(
    const std::vector<BatchPutStartItem>& items) {
  BatchPutStartResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutStart),
                            BatchPutStartRequest{items}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<ErrorCode>> KeystoneRpcClient::batch_put_complete(
    const std::vector<ObjectKey>& keys,
    const std::vector<std::vector<CopyShardCrcs>>& shard_crcs,
    const std::vector<uint32_t>& content_crcs) {
  BatchPutCompleteResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutComplete),
                            BatchPutCompleteRequest{keys, shard_crcs, content_crcs}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<ErrorCode>> KeystoneRpcClient::batch_put_cancel(
    const std::vector<ObjectKey>& keys) {
  BatchPutCancelResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutCancel),
                            BatchPutCancelRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

}  // namespace btpu::rpc
