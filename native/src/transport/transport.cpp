// Transport factory + the mux client routing on descriptor kind.
#include <algorithm>
#include <atomic>
#include <charconv>
#include <chrono>
#include <cstdio>
#include <thread>

#include "btpu/common/crc32c.h"
#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/storage/hbm_provider.h"
#include "btpu/transport/transport.h"

namespace btpu::transport {

// Implemented in the per-kind translation units.
std::unique_ptr<TransportServer> make_local_transport_server();
std::unique_ptr<TransportServer> make_tcp_transport_server();
std::unique_ptr<TransportServer> make_shm_transport_server();
ErrorCode local_access(uint64_t remote_addr, uint64_t rkey, void* buf, uint64_t len,
                       bool is_write, uint32_t* crc_out = nullptr, uint64_t extent_gen = 0);
ErrorCode shm_access(const std::string& name, uint64_t offset, void* buf, uint64_t len,
                     bool is_write, uint32_t* crc_out = nullptr, uint64_t extent_gen = 0);
ErrorCode tcp_read(const std::string& endpoint, uint64_t addr, uint64_t rkey, void* dst,
                   uint64_t len, uint64_t extent_gen = 0);
ErrorCode tcp_write(const std::string& endpoint, uint64_t addr, uint64_t rkey, const void* src,
                    uint64_t len, uint64_t extent_gen = 0);
ErrorCode tcp_fabric_offer(const std::string& endpoint, uint64_t addr, uint64_t rkey,
                           uint64_t len, uint64_t transfer_id);
ErrorCode tcp_fabric_pull(const std::string& endpoint, uint64_t addr, uint64_t rkey,
                          uint64_t len, uint64_t transfer_id,
                          const std::string& src_fabric_addr);
ErrorCode tcp_batch(WireOp* ops, size_t n, bool is_write,
                    size_t max_concurrency);  // pipelined, tcp_transport.cpp

std::string rkey_to_hex(uint64_t rkey) {
  char buf[17];
  std::snprintf(buf, sizeof(buf), "%llx", static_cast<unsigned long long>(rkey));
  return buf;
}

namespace {

// ICI transport: the data plane for device-resident (HBM) pools on a TPU
// mesh WITHIN one process. There is no listener and no flat remote address
// space — regions ARE device buffers owned by the HBM provider, placements
// are DeviceLocation {device, region, offset}, and transfers go through the
// provider ABI: host<->device for client put/get, device-to-device (riding
// ICI, no host staging) for keystone repair/demotion via provider.copy.
// The reference's analog is the UCX engine's registered-region + rkey
// contract (ucx_engine.cpp:150-180); here the "registration" is the
// provider region advertised by the worker (worker.cpp HBM branch) and the
// "rkey" is the region id.
//
// ACROSS processes (the multi-controller pod shape: one worker process per
// host, blackbird_tpu/procluster.py) device pools are served instead by the
// worker's TCP transport as shm-STAGED virtual regions — the provider moves
// bytes device<->shared-segment directly, headers ride the socket
// (tcp_transport.cpp staged lane), and keystone repair streams DCN-style
// between processes. So this class intentionally registers nothing: host
// memory has no ICI path, and cross-process device traffic belongs to the
// staged TCP lane, not here.
class IciTransportServer final : public TransportServer {
 public:
  TransportKind kind() const noexcept override { return TransportKind::ICI; }
  ErrorCode start(const std::string&, uint16_t) override { return ErrorCode::OK; }
  void stop() override {}
  Result<RemoteDescriptor> register_region(void*, uint64_t, const std::string&) override {
    // Host memory has no ICI path; workers route host tiers to the TCP
    // virtual transport (worker.cpp fallback chain).
    return ErrorCode::NOT_IMPLEMENTED;
  }
  ErrorCode unregister_region(const RemoteDescriptor&) override { return ErrorCode::OK; }
};

}  // namespace

std::unique_ptr<TransportServer> make_transport_server(TransportKind kind) {
  switch (kind) {
    case TransportKind::LOCAL: return make_local_transport_server();
    case TransportKind::TCP: return make_tcp_transport_server();
    case TransportKind::SHM: return make_shm_transport_server();
    case TransportKind::ICI: return std::make_unique<IciTransportServer>();
    default:
      LOG_ERROR << "no transport server for kind " << transport_kind_name(kind);
      return nullptr;
  }
}

namespace {

class MuxTransportClient : public TransportClient {
 public:
  ErrorCode read(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey, void* dst,
                 uint64_t len) override {
    return access(remote, remote_addr, rkey, dst, len, /*is_write=*/false);
  }

  ErrorCode write(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey,
                  const void* src, uint64_t len) override {
    return access(remote, remote_addr, rkey, const_cast<void*>(src), len, /*is_write=*/true);
  }

  // TCP ops pipeline (one round trip for the whole batch); memory-backed
  // kinds (LOCAL/SHM) are memcpy-bound and run inline — parallel memcpy
  // buys nothing the memory bus doesn't already give.
  ErrorCode read_batch(WireOp* ops, size_t n, size_t max_concurrency) override {
    return batch(ops, n, false, max_concurrency);
  }
  ErrorCode write_batch(WireOp* ops, size_t n, size_t max_concurrency) override {
    return batch(ops, n, true, max_concurrency);
  }

  ErrorCode fabric_offer(const RemoteDescriptor& remote, uint64_t addr, uint64_t rkey,
                         uint64_t len, uint64_t transfer_id) override {
    if (remote.transport != TransportKind::TCP) return ErrorCode::NOT_IMPLEMENTED;
    return tcp_fabric_offer(remote.endpoint, addr, rkey, len, transfer_id);
  }
  ErrorCode fabric_pull(const RemoteDescriptor& remote, uint64_t addr, uint64_t rkey,
                        uint64_t len, uint64_t transfer_id,
                        const std::string& src_fabric_addr) override {
    if (remote.transport != TransportKind::TCP) return ErrorCode::NOT_IMPLEMENTED;
    return tcp_fabric_pull(remote.endpoint, addr, rkey, len, transfer_id, src_fabric_addr);
  }

 private:
  static ErrorCode batch(WireOp* ops, size_t n, bool is_write, size_t max_concurrency) {
    // Memory-lane ops (LOCAL/SHM memcpy, pvm syscall) of a large batch run
    // shard-parallel across the wire worker pool: a striped get's shards
    // previously copied one after another on the calling thread even though
    // each shard is an independent one-sided copy. Below the threshold (or
    // on a single-core box) the inline loop stays — fan-out wakeups cost
    // more than a few hundred KiB of memcpy returns.
    constexpr uint64_t kParallelMemBytes = 512ull << 10;
    uint64_t mem_bytes = 0;
    size_t mem_ops = 0;
    for (size_t i = 0; i < n; ++i) {
      if (ops[i].len == 0) continue;
      ++mem_ops;  // pvm-eligible TCP ops count too; the lane IS a memcpy
      mem_bytes += ops[i].len;
    }
    // to_tcp[i] marks ops the socket pipeline must carry (TCP descriptors
    // the pvm lane declined); set by run_one, consumed after the barrier.
    std::vector<uint8_t> to_tcp(n, 0);
    auto run_one = [&](size_t i) {
      WireOp& op = ops[i];
      op.status = ErrorCode::OK;
      if (op.len == 0) return;
      if (op.remote->transport == TransportKind::TCP) {
        // Same-host one-sided lane first: the client moves the bytes itself
        // (one kernel copy, zero worker CPU) instead of the two-copy staged
        // pipeline. Only TCP descriptors consult it — LOCAL is already an
        // in-process memcpy and SHM a direct segment copy, both cheaper
        // than a process_vm syscall. false = op proceeds on the pipeline —
        // UNLESS the lane convicted the descriptor (poolsan): a stale
        // placement fails HERE with the conviction code rather than paying
        // a socket round trip to be re-convicted by the server.
        ErrorCode convicted = ErrorCode::OK;
        if (!pvm_access(*op.remote, op.addr, op.buf, op.len, is_write,
                        op.want_crc ? &op.crc : nullptr, op.extent_gen, &convicted)) {
          if (convicted != ErrorCode::OK) {
            op.status = convicted;
          } else {
            to_tcp[i] = 1;
          }
        }
        return;
      }
      op.status = access(*op.remote, op.addr, op.rkey, op.buf, op.len, is_write,
                         op.want_crc ? &op.crc : nullptr, op.extent_gen);
    };
    // The wrapper (not run_one itself) owns exception containment: on a
    // pool worker an escaped exception is swallowed by the pool and the op
    // would otherwise read as success for unmoved bytes.
    auto run_one_contained = [&](size_t i) {
      try {
        run_one(i);
      } catch (...) {
        ops[i].status = ErrorCode::INTERNAL_ERROR;
      }
    };
    if (mem_ops > 1 && mem_bytes >= kParallelMemBytes && wire_parallel_capacity() > 0 &&
        max_concurrency != 1) {
      wire_parallel_for(n, run_one_contained);
    } else {
      for (size_t i = 0; i < n; ++i) run_one(i);
    }
    ErrorCode first = ErrorCode::OK;
    std::vector<WireOp*> tcp_ops;
    for (size_t i = 0; i < n; ++i) {
      if (to_tcp[i]) {
        tcp_ops.push_back(&ops[i]);
      } else if (ops[i].status != ErrorCode::OK && first == ErrorCode::OK) {
        first = ops[i].status;
      }
    }
    if (!tcp_ops.empty()) {
      // Compact the TCP subset so the pipeline sees a contiguous array.
      std::vector<WireOp> subset(tcp_ops.size());
      for (size_t i = 0; i < tcp_ops.size(); ++i) subset[i] = *tcp_ops[i];
      const ErrorCode ec = tcp_batch(subset.data(), subset.size(), is_write, max_concurrency);
      for (size_t i = 0; i < tcp_ops.size(); ++i) {
        tcp_ops[i]->status = subset[i].status;
        tcp_ops[i]->crc = subset[i].crc;
      }
      if (ec != ErrorCode::OK && first == ErrorCode::OK) first = ec;
    }
    return first;
  }

  static ErrorCode access(const RemoteDescriptor& remote, uint64_t addr, uint64_t rkey,
                          void* buf, uint64_t len, bool is_write,
                          uint32_t* crc_out = nullptr, uint64_t extent_gen = 0) {
    if (len == 0) {
      if (crc_out) *crc_out = 0;
      return ErrorCode::OK;
    }
    switch (remote.transport) {
      case TransportKind::LOCAL:
        return local_access(addr, rkey, buf, len, is_write, crc_out, extent_gen);
      case TransportKind::SHM:
        return shm_access(remote.endpoint, addr, buf, len, is_write, crc_out, extent_gen);
      case TransportKind::TCP: {
        // Same-host one-sided lane first (see batch()); then the sockets.
        // A poolsan conviction in the lane fails the op outright — the
        // server would only re-convict the same stale descriptor.
        ErrorCode convicted = ErrorCode::OK;
        if (pvm_access(remote, addr, buf, len, is_write, crc_out, extent_gen, &convicted))
          return ErrorCode::OK;
        if (convicted != ErrorCode::OK) return convicted;
        // Raw-framing dialect guard (socket lanes only — pvm above never
        // frames): refuse a POSITIVE version mismatch before any byte goes
        // out; 0 = pre-versioned metadata, served as today (transport.h).
        if (remote.data_wire_version != 0 &&
            remote.data_wire_version != kTcpDataWireVersion)
          return ErrorCode::REMOTE_ENDPOINT_ERROR;
        // The single-op helpers route through tcp_batch, which fills crc
        // for want_crc ops; plain single ops hash post-hoc when asked.
        const ErrorCode ec =
            is_write ? tcp_write(remote.endpoint, addr, rkey, buf, len, extent_gen)
                     : tcp_read(remote.endpoint, addr, rkey, buf, len, extent_gen);
        if (ec == ErrorCode::OK && crc_out) *crc_out = crc32c(buf, len);
        return ec;
      }
      default:
        return ErrorCode::TRANSPORT_ERROR;
    }
  }
};

}  // namespace

// Default: attempt every op through the virtual single-op path (keeps
// wrappers like the fault injector in the loop for each op).
ErrorCode TransportClient::read_batch(WireOp* ops, size_t n, size_t) {
  ErrorCode first = ErrorCode::OK;
  for (size_t i = 0; i < n; ++i) {
    WireOp& op = ops[i];
    op.status = op.len == 0 ? ErrorCode::OK
                            : read(*op.remote, op.addr, op.rkey, op.buf, op.len);
    // Wrappers that route per-op (fault injector) still honor the CRC
    // contract, post-hoc.
    if (op.status == ErrorCode::OK && op.want_crc) op.crc = crc32c(op.buf, op.len);
    if (op.status != ErrorCode::OK && first == ErrorCode::OK) first = op.status;
  }
  return first;
}

ErrorCode TransportClient::write_batch(WireOp* ops, size_t n, size_t) {
  ErrorCode first = ErrorCode::OK;
  for (size_t i = 0; i < n; ++i) {
    WireOp& op = ops[i];
    op.status = op.len == 0 ? ErrorCode::OK
                            : write(*op.remote, op.addr, op.rkey, op.buf, op.len);
    // Wrappers that route per-op (fault injector) still honor the CRC
    // contract, post-hoc.
    if (op.status == ErrorCode::OK && op.want_crc) op.crc = crc32c(op.buf, op.len);
    if (op.status != ErrorCode::OK && first == ErrorCode::OK) first = op.status;
  }
  return first;
}

bool make_wire_op(const ShardPlacement& shard, uint64_t in_off, uint8_t* buf, uint64_t len,
                  WireOp& op) {
  const auto* mem = std::get_if<MemoryLocation>(&shard.location);
  if (!mem) return false;
  op = {&shard.remote, mem->remote_addr + in_off, mem->rkey, buf, len, ErrorCode::OK};
  // Ops are built on the calling thread, so the ambient per-op deadline and
  // trace context are in scope here; fan-out workers read them from the op
  // from now on.
  op.deadline = current_op_deadline();
  const auto ctx = trace::current();
  op.trace_id = ctx.trace_id;
  op.span_id = ctx.span_id;
  // Poolsan generation stamp rides every lane this op takes (TCP header,
  // local/shm/pvm resolve): a placement held across a free is convicted at
  // the access site, never served as a neighbor object's bytes.
  op.extent_gen = mem->extent_gen;
  return true;
}

bool append_range_wire_ops(const CopyPlacement& copy, uint64_t obj_off, uint64_t len,
                           uint8_t* buf, std::vector<WireOp>& ops) {
  uint64_t shard_start = 0, cur = obj_off, remaining = len;
  for (const auto& shard : copy.shards) {
    const uint64_t shard_end = shard_start + shard.length;
    if (cur < shard_end && remaining > 0) {
      const uint64_t in_off = cur - shard_start;
      const uint64_t n = std::min(remaining, shard.length - in_off);
      WireOp op;
      if (!make_wire_op(shard, in_off, buf + (cur - obj_off), n, op)) return false;
      ops.push_back(op);
      cur += n;
      remaining -= n;
    }
    shard_start = shard_end;
    if (remaining == 0) break;
  }
  return remaining == 0;
}

std::unique_ptr<TransportClient> make_transport_client() {
  return std::make_unique<MuxTransportClient>();
}

namespace {
class FaultyTransportClient final : public TransportClient {
 public:
  FaultyTransportClient(std::unique_ptr<TransportClient> inner, FaultSpec spec)
      : inner_(std::move(inner)), spec_(std::move(spec)) {}

  ErrorCode read(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey,
                 void* dst, uint64_t len) override {
    inject_latency(remote);
    if (!spec_.fail_endpoint.empty() && remote.endpoint == spec_.fail_endpoint)
      return spec_.error;
    if (spec_.fail_nth_read != 0 &&
        reads_.fetch_add(1) + 1 == spec_.fail_nth_read)
      return spec_.error;
    return inner_->read(remote, remote_addr, rkey, dst, len);
  }
  ErrorCode write(const RemoteDescriptor& remote, uint64_t remote_addr, uint64_t rkey,
                  const void* src, uint64_t len) override {
    inject_latency(remote);
    if (!spec_.fail_endpoint.empty() && remote.endpoint == spec_.fail_endpoint)
      return spec_.error;
    if (spec_.fail_nth_write != 0 &&
        writes_.fetch_add(1) + 1 == spec_.fail_nth_write)
      return spec_.error;
    return inner_->write(remote, remote_addr, rkey, src, len);
  }

 private:
  void inject_latency(const RemoteDescriptor& remote) {
    if (!spec_.latency_endpoint.empty() && remote.endpoint != spec_.latency_endpoint)
      return;
    uint32_t ms = spec_.latency_override_ms
                      // ordering: relaxed — chaos latency dial: a single word read each op; stale values just shift when the injected latency starts.
                      ? spec_.latency_override_ms->load(std::memory_order_relaxed)
                      : spec_.latency_ms;
    if (ms == 0 && spec_.latency_jitter_ms == 0) return;
    if (spec_.latency_jitter_ms > 0) {
      // Cheap per-op jitter; determinism is not a goal for latency faults.
      ms += static_cast<uint32_t>(jitter_rng_.fetch_add(0x9E3779B97F4A7C15ull) >> 40) %
            (spec_.latency_jitter_ms + 1);
    }
    if (ms > 0) std::this_thread::sleep_for(std::chrono::milliseconds(ms));
  }

  std::unique_ptr<TransportClient> inner_;
  FaultSpec spec_;
  std::atomic<uint32_t> reads_{0};
  std::atomic<uint32_t> writes_{0};
  std::atomic<uint64_t> jitter_rng_{0x6C617465ull};
};
}  // namespace

std::unique_ptr<TransportClient> make_faulty_transport_client(
    std::unique_ptr<TransportClient> inner, FaultSpec spec) {
  return std::make_unique<FaultyTransportClient>(std::move(inner), spec);
}

ErrorCode shard_io(TransportClient& client, const ShardPlacement& shard, uint64_t in_off,
                   uint8_t* buf, uint64_t len, bool is_write) {
  if (in_off + len > shard.length) return ErrorCode::INVALID_PARAMETERS;
  if (const auto* mem = std::get_if<MemoryLocation>(&shard.location)) {
    return is_write
               ? client.write(shard.remote, mem->remote_addr + in_off, mem->rkey, buf, len)
               : client.read(shard.remote, mem->remote_addr + in_off, mem->rkey, buf, len);
  }
  if (const auto* dev = std::get_if<DeviceLocation>(&shard.location)) {
    const auto& provider = storage::hbm_provider();
    const int rc = is_write
                       ? provider.write(provider.ctx, dev->region_id, dev->offset + in_off,
                                        buf, len)
                       : provider.read(provider.ctx, dev->region_id, dev->offset + in_off,
                                       buf, len);
    return rc == 0 ? ErrorCode::OK : ErrorCode::MEMORY_ACCESS_ERROR;
  }
  // FileLocation shards are served by the worker via virtual regions and
  // should never surface on a client data path.
  return ErrorCode::NOT_IMPLEMENTED;
}

ErrorCode copy_range_io(TransportClient& client, const CopyPlacement& copy, uint64_t obj_off,
                        uint8_t* buf, uint64_t len, bool is_write) {
  uint64_t shard_start = 0;
  uint64_t cur = obj_off, remaining = len;
  uint8_t* p = buf;
  for (const auto& shard : copy.shards) {
    const uint64_t shard_end = shard_start + shard.length;
    if (cur < shard_end && remaining > 0) {
      const uint64_t in_off = cur - shard_start;
      const uint64_t n = std::min(remaining, shard.length - in_off);
      if (auto ec = shard_io(client, shard, in_off, p, n, is_write); ec != ErrorCode::OK)
        return ec;
      p += n;
      cur += n;
      remaining -= n;
    }
    shard_start = shard_end;
    if (remaining == 0) break;
  }
  return remaining == 0 ? ErrorCode::OK : ErrorCode::INVALID_PARAMETERS;
}

ErrorCode shard_io_batch(TransportClient& client, const ShardJob* jobs, size_t n,
                         bool is_write) {
  std::vector<BtpuHbmIoVec> device_vecs;
  for (size_t i = 0; i < n; ++i) {
    const ShardJob& job = jobs[i];
    if (job.len == 0) continue;
    if (job.in_off + job.len > job.shard->length) return ErrorCode::INVALID_PARAMETERS;
    if (const auto* dev = std::get_if<DeviceLocation>(&job.shard->location)) {
      device_vecs.push_back(
          {dev->region_id, dev->offset + job.in_off, job.buf, job.len});
    } else {
      if (auto ec = shard_io(client, *job.shard, job.in_off, job.buf, job.len, is_write);
          ec != ErrorCode::OK)
        return ec;
    }
  }
  return storage::hbm_batch_io(device_vecs.data(), device_vecs.size(), is_write);
}

}  // namespace btpu::transport
