#include "btpu/rpc/rpc_client.h"

#include "btpu/common/log.h"
#include "btpu/common/wire.h"
#include "btpu/rpc/rpc.h"

namespace btpu::rpc {

KeystoneRpcClient::KeystoneRpcClient(std::string endpoint) : endpoint_(std::move(endpoint)) {}

KeystoneRpcClient::~KeystoneRpcClient() { disconnect(); }

ErrorCode KeystoneRpcClient::connect() {
  MutexLock lock(mutex_);
  return ensure_connected_locked();
}

void KeystoneRpcClient::disconnect() {
  MutexLock lock(mutex_);
  sock_.shutdown();
  sock_.close();
}

bool KeystoneRpcClient::connected() const {
  // Non-blocking probe: destructor-path callers (cancel_pooled_slots) use
  // this precisely to AVOID paying a connect timeout an in-flight call may
  // be stuck in — parking behind mutex_ here would defeat that. A busy
  // client reports "not idle-connected" and best-effort work is skipped
  // (the server-side slot TTL covers it either way).
  MutexLock lock(mutex_, std::try_to_lock);
  if (!lock) return false;
  return sock_.valid();
}

ErrorCode KeystoneRpcClient::ensure_connected_locked() {
  if (sock_.valid()) return ErrorCode::OK;
  auto hp = net::parse_host_port(endpoint_);
  if (!hp) return ErrorCode::INVALID_ADDRESS;
  auto sock = net::tcp_connect(hp->host, hp->port);
  if (!sock.ok()) return sock.error();
  sock_ = std::move(sock).value();
  return ErrorCode::OK;
}

ErrorCode KeystoneRpcClient::call_raw(uint8_t opcode, const std::vector<uint8_t>& req,
                                      std::vector<uint8_t>& resp) {
  MutexLock lock(mutex_);
  // CONNECTION_FAILED is a *contract*: it may only be returned when no whole
  // frame was ever delivered, so callers (client failover) can safely replay
  // the call against another keystone. Once a mutation frame went out, a
  // lost reply is RPC_FAILED and the request is never re-sent — it may have
  // executed. Read-only methods ARE re-sent after a lost reply (stale
  // pooled connection, keystone restart): replaying them is harmless and
  // keeps single-endpoint clients transparent across restarts.
  const bool read_only = opcode == static_cast<uint8_t>(Method::kObjectExists) ||
                         opcode == static_cast<uint8_t>(Method::kGetWorkers) ||
                         opcode == static_cast<uint8_t>(Method::kGetClusterStats) ||
                         opcode == static_cast<uint8_t>(Method::kGetViewVersion) ||
                         opcode == static_cast<uint8_t>(Method::kBatchObjectExists) ||
                         opcode == static_cast<uint8_t>(Method::kBatchGetWorkers) ||
                         opcode == static_cast<uint8_t>(Method::kPing);
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (ensure_connected_locked() != ErrorCode::OK) continue;
    if (net::send_frame(sock_.fd(), opcode, req.data(), req.size()) != ErrorCode::OK) {
      // Stale connection discovered at send time (keystone restarted): at
      // most a partial frame left this socket, which the server discards
      // without executing — safe to reconnect and try again.
      sock_.close();
      continue;
    }
    uint8_t resp_op = 0;
    if (net::recv_frame(sock_.fd(), resp_op, resp) == ErrorCode::OK && resp_op == opcode) {
      return ErrorCode::OK;
    }
    sock_.close();
    if (!read_only) return ErrorCode::RPC_FAILED;  // delivered, outcome unknown
  }
  return ErrorCode::CONNECTION_FAILED;
}

template <typename Req, typename Resp>
ErrorCode KeystoneRpcClient::call(uint8_t opcode, const Req& req, Resp& resp) {
  std::vector<uint8_t> resp_bytes;
  BTPU_RETURN_IF_ERROR(call_raw(opcode, wire::to_bytes(req), resp_bytes));
  if (!wire::from_bytes_lax(resp_bytes, resp)) return ErrorCode::RPC_FAILED;
  return ErrorCode::OK;
}

Result<bool> KeystoneRpcClient::object_exists(const ObjectKey& key) {
  ObjectExistsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kObjectExists),
                            ObjectExistsRequest{key}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.exists;
}

Result<std::vector<CopyPlacement>> KeystoneRpcClient::get_workers(const ObjectKey& key) {
  GetWorkersResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetWorkers), GetWorkersRequest{key},
                            resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.copies);
}

Result<std::vector<CopyPlacement>> KeystoneRpcClient::put_start(const ObjectKey& key,
                                                                uint64_t size,
                                                                const WorkerConfig& config,
                                                                uint32_t content_crc) {
  PutStartResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutStart),
                            PutStartRequest{key, size, config, content_crc}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.copies);
}

ErrorCode KeystoneRpcClient::put_complete(const ObjectKey& key,
                                          const std::vector<CopyShardCrcs>& shard_crcs,
                                          uint32_t content_crc) {
  PutCompleteResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutComplete),
                            PutCompleteRequest{key, shard_crcs, content_crc}, resp));
  return resp.error_code;
}

Result<std::vector<PutSlot>> KeystoneRpcClient::put_start_pooled(uint64_t size,
                                                                 const WorkerConfig& config,
                                                                 uint32_t count,
                                                                 const std::string& client_tag) {
  PutStartPooledResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutStartPooled),
                            PutStartPooledRequest{size, config, count, client_tag}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.slots);
}

ErrorCode KeystoneRpcClient::put_commit_slot(const PutCommitSlotRequest& request,
                                             std::vector<PutSlot>* refill_slots) {
  PutCommitSlotResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutCommitSlot), request, resp));
  if (refill_slots && resp.error_code == ErrorCode::OK) *refill_slots = std::move(resp.slots);
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::put_inline(const ObjectKey& key, const WorkerConfig& config,
                                        uint32_t content_crc, std::string data) {
  PutInlineResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutInline),
                            PutInlineRequest{key, config, content_crc, std::move(data)},
                            resp));
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::put_cancel(const ObjectKey& key) {
  PutCancelResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kPutCancel), PutCancelRequest{key},
                            resp));
  return resp.error_code;
}

ErrorCode KeystoneRpcClient::remove_object(const ObjectKey& key) {
  RemoveObjectResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kRemoveObject),
                            RemoveObjectRequest{key}, resp));
  return resp.error_code;
}

Result<uint64_t> KeystoneRpcClient::remove_all_objects() {
  RemoveAllObjectsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kRemoveAllObjects),
                            RemoveAllObjectsRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.objects_removed;
}

Result<uint64_t> KeystoneRpcClient::drain_worker(const NodeId& worker_id) {
  DrainWorkerResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kDrainWorker),
                            DrainWorkerRequest{worker_id}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.copies_migrated;
}

Result<std::vector<ObjectSummary>> KeystoneRpcClient::list_objects(const std::string& prefix,
                                                                   uint64_t limit) {
  ListObjectsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kListObjects),
                            ListObjectsRequest{prefix, limit}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.objects);
}

Result<ClusterStats> KeystoneRpcClient::get_cluster_stats() {
  GetClusterStatsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetClusterStats),
                            GetClusterStatsRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.stats;
}

Result<ViewVersionId> KeystoneRpcClient::get_view_version() {
  GetViewVersionResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kGetViewVersion),
                            GetViewVersionRequest{}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return resp.view_version;
}

Result<ViewVersionId> KeystoneRpcClient::ping() {
  std::vector<uint8_t> resp_bytes;
  BTPU_RETURN_IF_ERROR(call_raw(static_cast<uint8_t>(Method::kPing),
                                wire::to_bytes(PingRequest{kProtocolVersion}), resp_bytes));
  PingResponse resp;
  if (!wire::from_bytes_lax(resp_bytes, resp)) return ErrorCode::RPC_FAILED;
  server_proto_version_.store(resp.proto_version, std::memory_order_relaxed);
  return resp.view_version;
}

Result<std::vector<Result<bool>>> KeystoneRpcClient::batch_object_exists(
    const std::vector<ObjectKey>& keys) {
  BatchObjectExistsResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchObjectExists),
                            BatchObjectExistsRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<Result<std::vector<CopyPlacement>>>> KeystoneRpcClient::batch_get_workers(
    const std::vector<ObjectKey>& keys) {
  BatchGetWorkersResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchGetWorkers),
                            BatchGetWorkersRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<Result<std::vector<CopyPlacement>>>> KeystoneRpcClient::batch_put_start(
    const std::vector<BatchPutStartItem>& items) {
  BatchPutStartResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutStart),
                            BatchPutStartRequest{items}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<ErrorCode>> KeystoneRpcClient::batch_put_complete(
    const std::vector<ObjectKey>& keys,
    const std::vector<std::vector<CopyShardCrcs>>& shard_crcs,
    const std::vector<uint32_t>& content_crcs) {
  BatchPutCompleteResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutComplete),
                            BatchPutCompleteRequest{keys, shard_crcs, content_crcs}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

Result<std::vector<ErrorCode>> KeystoneRpcClient::batch_put_cancel(
    const std::vector<ObjectKey>& keys) {
  BatchPutCancelResponse resp;
  BTPU_RETURN_IF_ERROR(call(static_cast<uint8_t>(Method::kBatchPutCancel),
                            BatchPutCancelRequest{keys}, resp));
  if (resp.error_code != ErrorCode::OK) return resp.error_code;
  return std::move(resp.results);
}

}  // namespace btpu::rpc
